# Convenience targets. `make verify` is the pre-ship gate: it runs the
# ROADMAP tier-1 suite and fails if the pass count drops below the
# recorded floor (tools/check_tier1.py — the floor lives there).

.PHONY: verify test bench lint serve-smoke prefix-smoke chaos-smoke \
	kernel-smoke stats-smoke fleet-smoke observe-smoke elastic-smoke \
	spec-smoke mem-smoke disagg-smoke cascade-smoke \
	cascade-decode-smoke tiered-smoke install-hooks

verify: lint cascade-smoke cascade-decode-smoke tiered-smoke
	python tools/check_tier1.py

# graft-lint: AST static analysis proving the engine's JAX/XLA
# invariants — donation-safety, trace-hazard, host-sync,
# lock-discipline, config-drift (lir_tpu/lint, DEPLOY.md §1i). Fails on
# any finding outside tools/lint_baseline.json; runs in ~2 s with no
# jax import, so it gates verify and the pre-push hook first.
lint:
	python -m lir_tpu.lint

# The raw tier-1 suite without the floor gate (interactive debugging).
test:
	JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' \
		--continue-on-collection-errors -p no:cacheprovider \
		-p no:xdist -p no:randomly

bench:
	python bench.py

# Online-serving smoke: boot the server on the fake backend, push 50
# requests (incl. duplicate re-asks), assert zero sheds + nonzero dedup
# hit rate + all-ok (tools/serve_smoke.py).
serve-smoke:
	JAX_PLATFORMS=cpu python tools/serve_smoke.py

# Prefix-cache smoke: serve the shared-prefix workload (variations of 5
# long bases) on the fake backend with the cross-request radix prefix
# cache ON vs OFF — assert nonzero prefill-tokens-avoided on the warm
# pass, per-request payloads bitwise-identical to the unpaged path, and
# page refcounts sane after drain (tools/prefix_smoke.py).
prefix-smoke:
	JAX_PLATFORMS=cpu python tools/prefix_smoke.py

# Chaos smoke: seeded fault schedule on the fake backend — a sweep under
# injected device errors + a mid-sweep kill + a torn manifest tail must
# resume bitwise-identical (zero lost/duplicated rows); the serve
# circuit breaker must trip and recover via its half-open probe; the
# degradation ladder must isolate a poison row; a SIGTERM-style state
# checkpoint must hand every pending request to a fresh server; an
# injected HANG must be stalled-out by the watchdog within its deadline
# and recovered via the ladder; injected-NaN rows must quarantine as
# error:numerics with every clean row bitwise-identical (zero corrupted
# rows); a simulated dead peer must raise HostDesyncError within the
# liveness timeout instead of hanging (tools/chaos_smoke.py).
chaos-smoke:
	JAX_PLATFORMS=cpu python tools/chaos_smoke.py

# Kernel smoke: the PR-7 fused layer vs its references on CPU — the
# Pallas flash-decode kernel under interpret mode must be greedy
# argmax-identical to the dense decode path, the fused s8xs8 matmul must
# match the dequantized reference (static + dynamic + shared-quant), and
# a piggybacked dispatch chain must reproduce the sequential sweep's
# rows exactly while its chain counters move (tools/kernel_smoke.py).
kernel-smoke:
	JAX_PLATFORMS=cpu python tools/kernel_smoke.py

# Streaming-statistics smoke: the grid -> CIs device pipeline on the
# fake backend — the accumulator finalize must equal the csv-reload
# pipeline (counts/kappa bitwise, moments/CIs within FLOAT_TOL), a
# streaming-only pass must fold every row on device with zero result
# rows written (host-sync lint clean over the sink module), and the
# serve `stats` endpoint must answer live mid-workload
# (tools/stats_smoke.py).
stats-smoke:
	JAX_PLATFORMS=cpu python tools/stats_smoke.py

# Fleet smoke: the multi-model fleet layer on the fake backend — a
# 3-model sweep must book nonzero prefetch overlap (swap_s_hidden > 0,
# exactly one exposed load), per-model rows must be bitwise-identical
# to standalone single-model engines, and a fleet_score serve fan-out
# must answer per-model P(yes)/P(no) with kappa exactly equal to the
# analysis layer's within_group_kappa (tools/fleet_smoke.py).
fleet-smoke:
	JAX_PLATFORMS=cpu python tools/fleet_smoke.py

# Observatory smoke: the reliability observatory + telemetry spine on
# the fake backend — a 2-model fleet re-scores a sentinel grid across 3
# time windows; the two clean windows raise no alert, a seeded
# fault-plan NaN injection in window 3 raises EXACTLY one drift alert
# naming window 3 and the injected model, per-window kappa is bitwise
# the analysis layer's within_group_kappa, and the unified metrics
# snapshot is non-empty for every registered stats source
# (tools/observe_smoke.py).
observe-smoke:
	JAX_PLATFORMS=cpu python tools/observe_smoke.py

# Speculative-decode smoke: confidence-tail grid on the fake backend,
# scored twice — pass 2 drafts each row's whole continuation from the
# radix tree's token history and verifies it in one multi-query
# forward. Asserts nonzero accepted tokens, >= 2x fewer decode
# dispatches per row on the warm pass, and speculation-ON == OFF
# payloads bitwise (tools/spec_smoke.py; DEPLOY.md §1n).
spec-smoke:
	JAX_PLATFORMS=cpu python tools/spec_smoke.py

# Memory-governance smoke: the unified HBM governor under a seeded
# hbm_squeeze on the fake backend — the degradation ladder must walk
# down during the squeeze and back up after it (rung_downs == rung_ups,
# level 0) in BOTH the sweep and serve paths, with zero crashed
# dispatches and rows/payloads bitwise-identical to unpressured runs;
# governor gauges must ride the metrics snapshot (tools/mem_smoke.py;
# DEPLOY.md §1o).
mem-smoke:
	JAX_PLATFORMS=cpu python tools/mem_smoke.py

# Elastic-serving smoke: 3 in-process replicas behind the failover
# router on the fake backend — a seeded replica_kill mid-run must lose
# and duplicate ZERO requests (in-flight re-admitted to survivors,
# zombie payloads dropped by resolve-once + content dedup), the killed
# replica's breaker must walk open -> half_open -> closed across the
# rejoin, and a shard lease abandoned by a dead holder must be stolen
# within one TTL with the stolen shard's lattice merge bitwise-
# identical (tools/elastic_smoke.py).
elastic-smoke:
	JAX_PLATFORMS=cpu python tools/elastic_smoke.py

# Cascade-prefill smoke: shared-trunk grid (3 long bases x 8 tail
# rephrasings) served on the fake backend with cascade prefill ON vs
# OFF — the trunk's attention must be computed once per dispatch
# (nonzero cascade dispatches / trunk rows deduped / analytic prefix
# FLOPs saved in CascadeStats), every argmax-derived payload field
# identical between the two servers and float probabilities within
# tolerance (the PR-7 parity bar), and the dense server must never
# cascade (tools/cascade_smoke.py; DEPLOY.md §1q).
cascade-smoke:
	JAX_PLATFORMS=cpu python tools/cascade_smoke.py

# Cascade-decode smoke: the same shared-trunk grid served with cascade
# DECODE on vs off (prefill dense on both) — nonzero trunk-aware decode
# dispatches AND analytic trunk bytes deduped in CascadeStats, every
# payload field BITWISE-identical between the two servers (the trunk
# kernels compute the flat kernels' exact partials), and the flat
# server never counting a cascade-decode dispatch
# (tools/cascade_decode_smoke.py; DEPLOY.md §1r).
cascade-decode-smoke:
	JAX_PLATFORMS=cpu python tools/cascade_decode_smoke.py

# Disaggregated-serving smoke: 1 prefill-role + 2 decode-role replicas
# behind the router on the fake backend — scoring lands only on decode
# replicas, a nonzero number of KV pages migrates (prefill -> export ->
# transfer -> import), every payload is bitwise-identical to a
# colocated single server's, and a replica killed mid-migration falls
# back to local re-prefill with nothing dropped (tools/disagg_smoke.py;
# DEPLOY.md §1p).
disagg-smoke:
	JAX_PLATFORMS=cpu python tools/disagg_smoke.py

# Tiered-memory smoke: a shared-prefix working set larger than the HBM
# page budget on the HBM -> host DRAM -> disk KV ladder — nonzero
# demotions AND promotions, every payload bitwise-identical to the
# untiered server's, and a restarted server re-seeds its radix tree
# from the disk index with nonzero prefill tokens avoided
# (tools/tiered_smoke.py; DEPLOY.md §1s).
tiered-smoke:
	JAX_PLATFORMS=cpu python tools/tiered_smoke.py

# Run graft-lint (seconds) then the tier-1 guard before every
# `git push` — lint first so an invariant break fails in two seconds,
# not after the full suite.
install-hooks:
	printf '#!/bin/sh\npython -m lir_tpu.lint || exit 1\nexec python tools/check_tier1.py\n' > .git/hooks/pre-push
	chmod +x .git/hooks/pre-push
	@echo "pre-push hook installed: graft-lint + tier-1 guard run before every push"
