"""Central configuration for lir_tpu.

The reference scatters configuration across module-level CAPITALIZED constants,
``.env`` secrets, and hard-coded personal paths (reference:
analysis/perturb_prompts.py:19-65, analysis/config.py:1-16,
analysis/compare_base_vs_instruct.py:129-132). Here all of it is one dataclass
tree with a single ``backend`` switch ("tpu" | "api") as mandated by the north
star (BASELINE.json). No secrets live in code: the optional API backend reads
keys from the environment at call time.
"""

from __future__ import annotations

import dataclasses
import os
from pathlib import Path
from typing import Optional, Sequence, Tuple


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """Device-mesh shape for pjit sharding.

    Axis names follow the scaling-book convention: ``data`` for batch/grid
    parallelism, ``model`` for tensor parallelism (attention heads / MLP
    columns), ``seq`` for sequence (ring/context) parallelism. Any axis can be
    1. The product must equal the number of devices used.
    """

    data: int = 1
    model: int = 1
    seq: int = 1

    @property
    def axis_names(self) -> Tuple[str, ...]:
        return ("data", "model", "seq")

    @property
    def shape(self) -> Tuple[int, ...]:
        return (self.data, self.model, self.seq)

    @property
    def n_devices(self) -> int:
        return self.data * self.model * self.seq


@dataclasses.dataclass(frozen=True)
class RuntimeConfig:
    """Numerics + execution knobs for the inference engine."""

    dtype: str = "bfloat16"           # parameter/activation dtype on TPU
    logits_dtype: str = "float32"     # final logits always accumulated in fp32
    max_new_tokens: int = 50          # reference: compare_base_vs_instruct.py:253
    scan_positions: int = 10          # MAX_LOOK_AHEAD, compare_base_vs_instruct.py:187
    topk_match: int = 2               # top-2 yes/no match rule, :270-273
    batch_size: int = 32              # padded scoring batch per device step
    max_seq_len: int = 1024           # legal prompt + format ≲ 700 tokens (SURVEY §5)
    remat: bool = False               # jax.checkpoint the blocks for big models

    # Perturbation-sweep decode budget. The sweep's numeric readouts consume
    # ONLY position 0 (Token_1/2_Prob, top-20 map, E[v] — perturb_prompts.py:
    # 474-526), so by default each binary cell decodes a few tokens instead
    # of the full `max_new_tokens`=50 — a ~10x cut in decode-step compute.
    # The confidence call keeps a larger budget: its *parsed* integer may sit
    # several tokens into a verbose reply ("I am about 85% sure"), and a
    # truncated decode would silently null 'Confidence Value'. The 8-token
    # default is measured, not guessed: across the reference's committed
    # real-model outputs (18 base/instruct + 10 instruct models,
    # data/*_comparison_results.csv), the answer token sits at word 0-1 for
    # every perturbation-zoo family (96.4% of base rows and 100% of
    # instruct rows inside 8 words — SCALE.md "confidence decode budget").
    # A truncated integer is never recorded wrong (the parse rejects
    # budget-edge integers), and the C26 confidence-compliance gate flags a
    # model that needs a bigger budget; with `sweep_early_stop` a generous
    # re-run budget costs only actual response length.
    # `sweep_full_completions=True` restores 50-token 'Model Response' /
    # 'Model Confidence Response' text parity with the reference.
    sweep_decode_tokens: int = 4
    sweep_confidence_tokens: int = 8
    # Stop the confidence decode scan once every row has emitted EOS or a
    # complete first integer (a digit token followed by a digit-free one) —
    # the only thing the confidence parse reads. Needs per-token strings
    # (HF tokenizers) + an EOS id; silently off otherwise.
    sweep_early_stop: bool = True
    sweep_full_completions: bool = False

    # Ragged sweep scheduler (engine/scheduler.py). ON: grid cells are
    # tokenized up front, sorted into a ~sqrt(2) prompt-length bucket
    # ladder (engine/tokens.bucket_ladder), drained per-bucket with slot
    # refill, and cells sharing a long token prefix score through one
    # shared prefill (cross-cell prefix reuse). OFF restores the legacy
    # todo-order batching whose every mixed-length batch pads to its
    # longest row (the bench's single-bucket baseline). Per-cell results
    # are identical either way — left/right padding is masked out of
    # every readout (pinned by tests/test_scheduler.py).
    ragged_scheduler: bool = True
    # Cross-cell prefix grouping engages for >= min_cells cells agreeing
    # on >= min_prefix leading tokens AND on at least half their prefill
    # (see scheduler.RaggedScheduler). 0 cells disables grouping.
    sweep_group_min_prefix: int = 16
    sweep_group_min_cells: int = 4

    # Compile plan (engine/compile_plan.py). With the ragged scheduler the
    # whole sweep's dispatch shapes are known before the first dispatch,
    # so every bucket executable is lowered + compiled CONCURRENTLY in
    # background threads while the first bucket streams, and dispatches
    # consume precompiled executables instead of paying trace-on-first-
    # call serially inside the sweep. 0 workers = one per CPU core
    # (capped at the shape count). OFF restores lazy per-shape jit.
    aot_precompile: bool = True       # host-only (plan policy, not shapes)
    precompile_workers: int = 0       # host-only
    # Persistent XLA compilation cache (utils/compile_cache.py): compiled
    # executables survive process restarts, so a restarted worker / model
    # swap / autoscale event deserializes instead of recompiling. None
    # resolves $LIR_TPU_COMPILE_CACHE then ~/.cache/lir_tpu/xla; the CLI
    # and bench enable it by default (--no-compile-cache opts out).
    compile_cache_dir: Optional[str] = None   # host-only

    # Cross-request radix prefix cache over the paged KV allocator
    # (models/paged.py + engine/prefix_tree.py). ON: the engine keeps a
    # device-resident pool of `prefix_cache_pages` KV pages of
    # `prefix_page_size` token positions each, indexed by a per-bucket
    # radix tree over tokenized prefixes; a warm dispatch gathers its
    # rows' cached prefix pages into the exact slots the left-padded
    # prefill would fill and recomputes only a small remainder window
    # (across requests AND across batches — the production workload
    # re-asks variations of ~5 legal prompts, so warm traffic prefills
    # suffixes only). Results are bitwise-identical to the unpaged path
    # (pinned by tests/test_prefix_cache.py). Pool HBM = pages x
    # models/paged.kv_page_bytes (512 pages x 16 tokens covers the 5
    # legal prompts at ~700 tokens several times over; DEPLOY.md §1g).
    # Offline sweeps default OFF (the ragged scheduler's prefix groups
    # already share within a plan; opt in for repeated grids on one
    # engine via --prefix-cache); serving defaults ON
    # (ServeConfig.prefix_cache).
    prefix_cache: bool = False
    prefix_cache_pages: int = 512
    prefix_page_size: int = 16

    # Fused decode kernels (ops/flash_decode.py). ON: single-query decode
    # steps run the Pallas flash-decode kernel — K-split online softmax
    # over the cache with a log-sum-exp combine, so the score row, the
    # fp32 softmax, and the probability row never round-trip HBM between
    # XLA kernels. Greedy decode stays argmax-identical to the dense path
    # (pinned by tests/test_kernels.py); OFF (--no-fused-decode) restores
    # the dense decode lowering exactly. The engine threads this onto
    # ModelConfig.fused_decode; CPU runs keep the dense path either way
    # (Pallas lowers on TPU; the interpreter hook is test-only).
    fused_decode: bool = True

    # Chunked prefill/decode piggybacking (Sarathi-Serve-style): the
    # ragged sweep fuses the pending decode scan of the in-flight
    # dispatch into the NEXT same-shape dispatch's prefill call
    # (engine/generate.py shared_piggyback_*), so the dispatch stream
    # pays one device round-trip per dispatch instead of two and decode
    # never waits on a host gap behind a full prefill. Results are
    # identical per row to the sequential path (pinned by tests/
    # test_kernels.py). Piggybacking keeps TWO dispatch caches live, so
    # the engine engages it only when params + 2 caches fit the device
    # memory budget; --no-piggyback opts out entirely.
    piggyback_prefill: bool = True

    # Guard layer (lir_tpu/guard): silent-failure detection.
    # Dispatch watchdog — every device dispatch runs on a watched
    # executor whose deadline is floor + multiple * predicted seconds,
    # where "predicted" comes from the scheduler.bucket_cost() price
    # model calibrated against this engine's own observed dispatch rate
    # (guard/watchdog.py). A dispatch that outlives its deadline is
    # abandoned with a full thread-stack dump and surfaces
    # DispatchStalled into the ordinary recovery machinery (ladder
    # retry -> breaker), so a wedged runtime call costs one deadline
    # instead of the run. multiple <= 0 disables; the floor is a hard
    # minimum so a fast calibration can never produce a hair-trigger
    # deadline. The first (uncalibrated) dispatch is observe-only — a
    # legitimate cold compile must never be shot. The same deadline
    # (floor * multiple) bounds how long a dispatch waits on a
    # background AOT compile before falling back to lazy jit.
    watchdog_multiple: float = 20.0   # host-only (deadline policy)
    watchdog_floor_s: float = 30.0    # host-only; cli: --watchdog-floor
    # Numerics guard — validate every row's readouts at score-extraction
    # time (probs finite and in [0,1], P(Yes)+P(No) <= 1, weighted
    # confidence in [0,100], logprob map NaN-free) and quarantine
    # offenders as error:numerics instead of writing garbage
    # (guard/numerics.py).
    numerics_guard: bool = True       # host-only (validates host readouts)
    # Streaming statistics (engine/stream_stats.py + stats/streaming):
    # every scoring dispatch folds its position-0 readouts into a
    # device-resident accumulator lattice with ONE fused update (no
    # per-row device->host transfer), checkpointed at flush boundaries
    # and merged across hosts at the shard fences; grid -> percentile/
    # kappa/bootstrap-CI estimates come straight off the accumulator
    # (live mid-run via the serve `stats` endpoint, final via
    # StreamSink.finalize). The bootstrap key is recorded in the sweep
    # manifest so CIs reproduce across resume and re-runs. OFF restores
    # the csv-reload-only pipeline (which always remains available for
    # parity — DEPLOY.md §1j).
    streaming_stats: bool = True      # host-only (sink policy, not shapes)
    # With streaming stats ON, the per-row results artifact (csv/xlsx
    # rows + manifest union resume) becomes OPTIONAL schema parity:
    # row_artifact=False skips materializing rows entirely — the
    # dispatch loop then transfers NO per-row payloads through the host
    # (resume runs off the manifest + accumulator checkpoint alone).
    # Ignored (rows always written) when streaming_stats is off.
    row_artifact: bool = True         # host-only

    # Multihost liveness — sweep shard boundaries run a heartbeat
    # allgather + barrier bounded by this timeout; a dead peer host
    # then raises HostDesyncError on the survivors (manifest already
    # flushed -> resumable) instead of parking them in ICI/DCN forever
    # (parallel/multihost.py). <= 0 restores unbounded barriers.
    barrier_timeout_s: float = 900.0  # host-only; cli: --barrier-timeout

    # Leased sweep shards (engine/lease.py; DEPLOY.md §1m). ON: the
    # pending grid is split into small shards whose ownership is a
    # LEASE record riding the manifest's {"__meta__": ...} lines
    # ({holder, expiry, seq}; renewed at every flush) in a shared
    # <results>.leases.jsonl log, instead of the static host_shard
    # partition. A live host claims unclaimed shards, then STEALS
    # shards whose lease expired (holder dead or straggling) — re-done
    # rows fold into the streaming accumulator as bitwise no-ops (slot
    # idempotence), so rebalancing can never corrupt the merged
    # lattice, and the shard fence drains leases instead of waiting on
    # the slowest static shard. Single-process runs work identically
    # (one holder claims every shard in order).
    lease_shards: bool = False        # host-only
    # Speculative scoring decode (engine/spec.py + generate.
    # greedy_decode_fused_shared_spec; DEPLOY.md §1n). ON: shared-path
    # dispatches draft up to spec_k tokens ahead (prompt-lookup from
    # the radix tree's token history + n-gram self-lookup, or a small
    # fleet draft model when spec_draft_model names one) and VERIFY
    # them in one multi-query pass through the decode attention path —
    # the ≤10-token sequential scan collapses to ~T/k verify forwards
    # when drafts land. Greedy acceptance keeps every consumed result
    # (scored rows, serve payloads: position-0 readouts + generated
    # text) BITWISE identical to the sequential scan (pinned by
    # tests/test_spec_decode.py); a rejected draft only costs
    # re-verification. Piggyback chains take precedence offline
    # (--no-piggyback makes every shared dispatch eligible); the
    # drafting-policy knobs live on Config.spec (SpecConfig).
    spec_decode: bool = True
    # Verify window: tokens checked per verify forward (1 emission + up
    # to spec_k-1 accepted drafts). < 2 disables speculation.
    spec_k: int = 4
    # Fleet model id that drafts for this engine (acquired through the
    # PR-10 WeightCache so drafting never evicts the verifier
    # mid-dispatch). Empty = self-drafting (tree + n-gram lookup).
    spec_draft_model: str = ""
    # Shared-prefix cascade prefill (ops/cascade_prefill + generate.
    # greedy_decode_fused_shared_cascade; DEPLOY.md §1q). ON: a shared
    # dispatch whose rows all begin with the same trunk (LCP across the
    # dispatch, snapped to CascadeConfig.trunk_quantum) prefills that
    # trunk ONCE at batch 1 — or gathers it warm from the radix page
    # pool at zero recompute — and extends the per-row remainders over
    # it via cascade attention: prefix leg = one dense GEMM per kv head
    # against the shared trunk KV (optionally int8 QK^T fused in-kernel),
    # suffix leg = causal window, exact log-sum-exp merge. Results are
    # argmax-identical to the dense shared path (tolerance-bound interior
    # floats — the PR-7 bar, pinned by tests/test_cascade.py);
    # --no-cascade-prefill restores the dense path exactly. Cascade
    # takes precedence over speculation and piggybacking for eligible
    # dispatches (it removes the prefill those paths would chain/draft
    # around); ineligible dispatches fall back dense and count
    # CascadeStats.dense_fallbacks. Eligibility knobs live on
    # Config.cascade (CascadeConfig).
    cascade_prefill: bool = True      # cli: --no-cascade-prefill
    # Cascade DECODE (ops/flash_decode trunk variants; DEPLOY.md §1r):
    # on a shared-trunk dispatch, every decode step's trunk-key splits
    # compute as ONE batched GEMM per kv head against cache row 0's
    # trunk K/V — the trunk tiles stream from HBM once per step instead
    # of once per row — and only the per-row suffix splits run the
    # split-K path; the log-sum-exp merge makes the result BITWISE the
    # flat kernel's (tests/test_cascade.py pins it, speculative verify
    # windows ride flash_decode_mq_trunk the same way). Independent of
    # cascade_prefill: a dense-prefill or paged-warm dispatch dedups its
    # decode too. --no-cascade-decode restores the flat kernels exactly
    # (the flag mirrors into the static ModelConfig, re-keying every
    # decode executable). Trunk eligibility shares CascadeConfig.
    cascade_decode: bool = True       # cli: --no-cascade-decode
    # Fused single-kernel cascade prefill (ops/cascade_prefill): prefix
    # leg + suffix leg + log-sum-exp merge in ONE Pallas launch — no HBM
    # round-trip for the per-leg partials. BITWISE the two-leg path at
    # every trunk extent (tests/test_cascade.py); --no-cascade-fused-
    # suffix restores the two-leg lowering exactly (mirrored into the
    # static ModelConfig like cascade_decode). float QK^T only — the
    # int8_qk cascade keeps the two-leg path.
    cascade_fused_suffix: bool = True  # cli: --no-cascade-fused-suffix
    # Lease time-to-live in WALL-CLOCK seconds (leases compare across
    # hosts, so the shared clock is time.time, not monotonic). A holder
    # renews on every flush; a lease older than this is stealable.
    lease_ttl_s: float = 300.0        # host-only; cli: --lease-ttl
    # Grid cells per leased shard (the stealing granularity): smaller
    # shards rebalance finer but renew/claim more often. <= 0 derives
    # ~4 shards per host from the grid.
    lease_cells_per_shard: int = 0    # host-only; cli: --lease-cells


@dataclasses.dataclass(frozen=True)
class SpecConfig:
    """Speculative-decode DRAFTING policy (engine/spec.py; DEPLOY.md
    §1n). These knobs steer where draft tokens come from — they can
    change speed, never results (greedy acceptance keeps every accepted
    token identical to the sequential scan's, so outputs are bitwise
    regardless of draft quality). The on/off switch and verify-window
    size live on RuntimeConfig (``spec_decode``/``spec_k``/
    ``spec_draft_model``) because those change compiled shapes."""

    # N-gram match length for the prompt-lookup fallback drafter: the
    # verify scan drafts the tokens that followed the most recent
    # earlier occurrence of the last `ngram` context tokens (prompt +
    # already-accepted emissions).
    ngram: int = 2                    # cli: --spec-ngram
    # Probe the radix prefix tree's token history for a whole-window
    # draft of the dispatch's continuation (prefix_tree.continuation)
    # before falling back to n-gram matching. Needs the prefix cache
    # (the tree) to be enabled on the engine; silently off otherwise.
    tree_probe: bool = True           # cli: --no-spec-tree-probe
    # Continuation tails recorded per radix node (host memory only, LRU
    # beyond this): each completed dispatch records its prompt's
    # observed continuation so a repeat visit drafts the whole reply.
    tree_tails_per_node: int = 32     # cli: --spec-tree-tails


@dataclasses.dataclass(frozen=True)
class CascadeConfig:
    """Cascade-prefill ELIGIBILITY policy (ops/cascade_prefill +
    engine/runner cascade routing; DEPLOY.md §1q). These knobs steer
    WHICH shared dispatches take the cascade split — they can change
    speed, never results (the cascade is argmax-identical to the dense
    path it replaces, and an ineligible dispatch runs the dense path
    verbatim). The on/off switch lives on RuntimeConfig
    (``cascade_prefill``) because it changes compiled shapes."""

    # Minimum shared-trunk length (tokens, post-snap) worth the split:
    # below this the prefix-leg GEMM is too thin to beat the dense
    # prefill's one fused pass, so short-LCP dispatches fall back dense
    # (counted in CascadeStats.dense_fallbacks).
    min_trunk: int = 32               # cli: --cascade-min-trunk
    # Trunk lengths snap DOWN to this grid before compilation: the trunk
    # extent is a STATIC shape (compile_plan keys executables on it), so
    # a coarse quantum keeps the executable population bounded while a
    # few unshared tail tokens just ride the per-row remainder.
    trunk_quantum: int = 16           # cli: --cascade-trunk-quantum
    # Minimum REAL rows in the dispatch: the cascade dedups trunk work
    # across rows, so a 1-row dispatch has nothing to dedup and the
    # dense path wins on dispatch overhead alone.
    min_rows: int = 2                 # cli: --cascade-min-rows
    # Fuse int8 QK^T inside the prefix-leg kernel (models/quant.py's
    # dynamic rule applied to q/trunk-k blocks in VMEM; softmax and PV
    # stay fp32). Halves the kernel's VMEM read traffic on the score
    # matmul; scores are tolerance-bound, argmax parity is pinned by
    # tests/test_cascade.py. OFF by default: exact-fp32 scores unless
    # opted in.
    int8_qk: bool = False             # cli: --cascade-int8-qk


@dataclasses.dataclass(frozen=True)
class PerturbationConfig:
    """Perturbation-sweep scale parameters (reference: perturb_prompts.py)."""

    sessions_per_prompt: int = 100      # :787-788
    rephrasings_per_session: int = 20   # numbered 1..20
    rephrase_temperature: float = 0.9   # :802
    reasoning_model_runs: int = 10      # REASONING_MODEL_RUNS, :47
    max_batch_size: int = 50_000        # MAX_BATCH_SIZE, :29
    subset_size: Optional[int] = None   # PROCESS_RANDOM_SUBSET/SUBSET_SIZE, :31-33
    seed: int = 42                      # RANDOM_SEED, :34


@dataclasses.dataclass(frozen=True)
class StatsConfig:
    """Bootstrap / MC budgets (BASELINE.md table)."""

    bootstrap_large: int = 10_000   # simulated-individual CIs, diff CIs, family MC
    bootstrap_standard: int = 1_000 # Pearson CIs, corr matrices, kappa CIs, QQ bands
    bootstrap_small: int = 100      # cross-prompt, respondent-resample
    truncnorm_samples: int = 100_000  # analyze_perturbation_results.py:113
    truncnorm_max_iter: int = 30
    truncnorm_damping: float = 0.5
    truncnorm_tol: float = 1e-4
    seed: int = 42


@dataclasses.dataclass(frozen=True)
class RetryConfig:
    """Exponential-backoff policy (reference: perturb_prompts.py:72-106).

    ``full_jitter=True`` switches the multiplicative 0.8-1.2 jitter to
    AWS-style full jitter (wait ~ U[0, delay]) — the right mode when many
    clients retry against one contended resource (the serve supervisor's
    device retries). ``max_elapsed`` caps the TOTAL time spent inside the
    retry loop (attempts + sleeps): once another sleep would cross it, the
    last failure is re-raised instead — so a retried call can never
    overrun its caller's deadline. None keeps the reference's unbounded
    behavior (the API backend's 24 h batch windows don't want a cap).
    """

    max_retries: int = 10
    initial_delay: float = 60.0
    max_delay: float = 300.0
    backoff_factor: float = 1.5
    jitter: Tuple[float, float] = (0.8, 1.2)
    full_jitter: bool = False
    max_elapsed: Optional[float] = None


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    """Online serving layer knobs (lir_tpu/serve).

    - ``queue_depth``: admission-control bound. A submit into a full queue
      either sheds the incoming request or (deadline-aware) evicts the
      queued request with the LATEST deadline when the newcomer is more
      urgent — bounded memory and bounded worst-case queueing delay.
    - ``classes``: (name, deadline seconds) pairs. A request names its
      class; its deadline defaults to the class deadline unless it carries
      an explicit ``deadline_s``. Unknown classes fall back to
      ``default_class``.
    - ``linger_s``: continuous-batching window — a partially filled bucket
      dispatches once its oldest request has waited this long (a full
      batch dispatches immediately).
    - ``cache_entries``: content-addressed result-cache capacity (LRU).
      0 disables dedup.
    - ``max_consecutive_failures``: after this many back-to-back dispatch
      failures (each already retried per ``retry``) the circuit breaker
      OPENS (faults/breaker.py): the queue drains with error results and
      submits shed until the breaker recovers — but unlike the pre-PR4
      one-way health flag, after ``breaker_cooldown_s`` the breaker goes
      HALF-OPEN and lets one probe dispatch through; probe success closes
      it (healthy again), probe failure re-opens it for another cooldown.
      A transient device outage costs one cheap probe per cooldown
      instead of the whole process.
    - ``breaker_cooldown_s``: how long the breaker stays open before the
      half-open probe. Tune to the expected outage shape: ~30 s covers
      driver restarts and preempted-neighbor wobbles; sub-second values
      are for tests and chaos drivers (DEPLOY.md §1e).
    - ``degrade_ladder``: on a dispatch that fails all its retries,
      degrade instead of erroring the whole batch — drop the AOT
      registry (lazy jit re-trace excludes a corrupt executable), retry
      once, then bisect the batch to isolate poison rows; only the
      culprit rows resolve as errors (faults/ladder.py).
    - ``retry``: device-dispatch retry policy. Short, full-jitter, and
      elapsed-capped — a transient XLA/runtime hiccup is retried inside
      the request deadlines; a persistent fault fails fast into the
      breaker path.
    """

    queue_depth: int = 256
    # Live streaming-statistics window (engine/stream_stats.py
    # ServeStreamSink): the `stats` endpoint reports percentile/kappa
    # estimates over the last `stream_window` resolved rows, grouped by
    # target pair; folded idempotently by content address so SIGTERM
    # checkpoint/resume never double-counts a row. Gated on
    # RuntimeConfig.streaming_stats; 0 disables the ring.
    stream_window: int = 4096
    # Cross-request radix prefix cache (engine/prefix_tree.py over
    # models/paged.py): ON by default for serving — an arriving request
    # whose tokenized prefix is already resident pays prefill only for
    # its unshared suffix, across requests and across batches. The pool
    # is sized by RuntimeConfig.prefix_cache_pages; results stay
    # bitwise-identical to the unpaged path. OFF restores the PR-3
    # behavior (exact-match dedup only).
    prefix_cache: bool = True
    classes: Tuple[Tuple[str, float], ...] = (
        ("interactive", 10.0), ("batch", 300.0))
    # Fallback CLASS name for unknown request classes — set through
    # --deadline CLASS=SECS entries, not a flag of its own.
    default_class: str = "batch"    # lint: allow(config-drift)
    linger_s: float = 0.02
    # Pad every dispatch to the FULL configured batch instead of the
    # offline sweep's power-of-two tail: serving wants shape stability
    # more than tail FLOP savings — one executable per (bucket, suffix)
    # pair means no mid-traffic compiles, and degenerate tiny-batch
    # programs are avoided (measured on the CPU smoke: a warm batch-1
    # shared decode runs ~2.5x SLOWER than the warm batch-4 program).
    # The batcher's online slot-refill promotion (serve/batcher.py)
    # keeps the padding waste bounded the same way the offline
    # planner's does.
    pad_full: bool = True
    cache_entries: int = 4096
    max_consecutive_failures: int = 3
    breaker_cooldown_s: float = 30.0
    degrade_ladder: bool = True
    # Composite policy object (utils/retry.RetryConfig): tuned in code
    # next to the failure-domain story, not flag-by-flag.
    retry: RetryConfig = dataclasses.field(  # lint: allow(config-drift)
        default_factory=lambda: RetryConfig(
        max_retries=2, initial_delay=0.25, max_delay=2.0,
        backoff_factor=2.0, full_jitter=True, max_elapsed=8.0))

    def deadline_for(self, klass: str) -> float:
        table = dict(self.classes)
        if klass in table:
            return table[klass]
        return table.get(self.default_class,
                         max(table.values()) if table else 300.0)


@dataclasses.dataclass(frozen=True)
class ObserveConfig:
    """Reliability-observatory + telemetry knobs (lir_tpu/observe;
    DEPLOY.md §1l).

    The observatory re-scores a sentinel grid on a schedule (and on
    weight-cache residency change), folds results into time-windowed
    accumulator lattices, and raises σ-threshold drift alerts on
    per-window κ / per-model mean / valid-fraction movement — all
    queryable live through the serve ``stats``/``metrics`` endpoints.
    """

    # Seconds between scheduled sentinel re-scorings. A weight-cache
    # residency change (model evicted/re-streamed) forces an immediate
    # sweep regardless of the interval.
    sentinel_interval_s: float = 60.0    # cli: --sentinel-interval
    # Drift-window width in seconds: sweeps landing in the same window
    # fold into one lattice; κ/CI/mean are compared ACROSS windows.
    sentinel_window_s: float = 600.0     # cli: --sentinel-window
    # Lattice capacity per window (columns = sweeps x sentinels); a
    # window that fills logs and skips further sweeps rather than
    # silently overwriting slots.
    max_sweeps_per_window: int = 32      # cli: --sentinel-max-sweeps
    # Alert threshold: |window metric - baseline mean| > drift_sigma *
    # max(baseline std, floor). 3σ is the classic control-chart bound.
    drift_sigma: float = 3.0             # cli: --drift-sigma
    # Clean windows required before drift detection arms (a baseline of
    # one window has no variance to threshold against).
    drift_min_windows: int = 2           # cli: --drift-min-windows
    # Window lattices kept on device / summaries kept queryable; the
    # oldest drop beyond this (their summaries persist in history).
    history_windows: int = 64            # cli: --observe-history
    # Trace-span ring capacity for --trace-out recording.
    trace_buffer: int = 65536            # cli: --trace-buffer


@dataclasses.dataclass(frozen=True)
class GovernorConfig:
    """Unified HBM governor knobs (engine/hbm.py; DEPLOY.md §1o).

    Every HBM consumer (weight cache, KV page pool, dispatch/handoff
    caches, spec-draft pins, accumulator lattice) registers projected
    bytes into ONE ledger; sustained pressure against the budget walks
    a reversible degradation ladder (evict idle weights → evict cold
    radix pages → disable piggyback chaining → disable spec drafting →
    step the batch ladder down → shed), each rung re-arming with
    hysteresis once pressure clears. Real device OOMs route through
    the governor's reclaim-and-retry instead of killing the run or
    feeding the circuit breaker.
    """

    # Master switch: OFF leaves every consumer self-governed exactly as
    # before the governor existed (measurement baseline).
    enabled: bool = True                 # cli: --no-hbm-governor
    # Governed HBM budget in GiB. 0 derives the budget from the
    # device's reported bytes_limit (with `hbm_reserve_frac` held
    # back); on backends without memory stats (CPU) 0 means unbounded
    # — the ladder then never engages and behavior is identical to
    # governor-off.
    hbm_budget_gb: float = 0.0           # cli: --hbm-budget-gb
    # Fraction of the device bytes_limit held back from a derived
    # budget (runtime scratch, fragmentation slack).
    hbm_reserve_frac: float = 0.08       # cli: --hbm-reserve-frac
    # Ledger pressure (ledger_bytes / budget) at which the ladder
    # engages its next rung, and the hysteresis band below it at which
    # the most recent rung re-arms (releases). engage 0.9 / hysteresis
    # 0.15 means: walk down above 0.9, walk back up below 0.75 — a
    # rung can never flap on the threshold itself.
    engage_pressure: float = 0.9         # cli: --hbm-engage-pressure
    hysteresis: float = 0.15             # cli: --hbm-hysteresis
    # Consecutive over-pressure ticks (one tick per dispatch) before a
    # rung engages — transient spikes (one oversized dispatch) don't
    # walk the ladder; sustained pressure does. The same count of
    # under-pressure ticks releases.
    sustain_ticks: int = 2               # cli: --hbm-sustain-ticks
    # Radix pages evicted per evict_pages rung engagement.
    evict_pages_per_step: int = 32       # cli: --hbm-evict-pages

    @property
    def budget_bytes(self) -> Optional[int]:
        return (int(self.hbm_budget_gb * 2**30)
                if self.hbm_budget_gb > 0 else None)


@dataclasses.dataclass(frozen=True)
class RouterConfig:
    """Elastic multi-replica serving knobs (serve/router.py;
    DEPLOY.md §1m).

    The router is a front process spreading one request stream over N
    replica servers. Placement reads three live signals per replica:
    queue depth (queue + bucketed rows), the router-side circuit
    breaker (one per replica — a replica that keeps erroring stops
    receiving traffic until its cooldown probe), and — for fleet
    replicas — WEIGHT RESIDENCY (WeightCache listener events feed a
    router-side residency map, so a model's requests land on the
    replica already holding its weights). Failover re-admits a dead or
    erroring replica's in-flight requests to survivors exactly once
    (ServeFuture first-resolution-wins + the content-address dedup
    key), and requests inside the deadline whisker are HEDGED to a
    second replica with first-payload-wins resolution.
    """

    # In-process replica count for `lir_tpu serve --replicas N`
    # (single-model serving only; each replica is a full ScoringServer
    # with its own breaker/ladder). 1 = no router.
    replicas: int = 1                      # cli: --replicas
    # Hedge whisker in seconds: an in-flight request whose deadline is
    # closer than this is duplicated onto a second replica
    # (first-payload-wins; the loser is dropped by resolve-once).
    # 0 disables hedging.
    hedge_s: float = 0.0                   # cli: --hedge-threshold
    # Router-side per-replica breaker: consecutive error results from
    # one replica before its breaker OPENS (routing avoids it), and how
    # long it stays open before the half-open probe (the next routed
    # request). Timed on time.monotonic — wall steps can't hold a
    # breaker open.
    replica_failure_threshold: int = 2     # cli: --replica-failure-threshold
    replica_cooldown_s: float = 5.0        # cli: --replica-cooldown
    # Placement score bonus (in queue-row equivalents) for a replica
    # whose WeightCache already holds the request's model — weight
    # residency as a first-class routing signal.
    residency_bonus: float = 8.0           # cli: --residency-bonus
    # Memory-pressure placement penalty (queue-row equivalents per unit
    # of HBM-governor pressure): a replica whose ledger is squeezed
    # reads as a worse home than an equally-loaded replica with
    # headroom — the governor's pressure gauge as a routing signal,
    # the seam ROADMAP item 2's page migration stands on. 0 disables.
    pressure_weight: float = 6.0           # cli: --pressure-weight
    # SLO-aware placement: weight on a replica's oldest queued-row wait
    # relative to the request's remaining deadline, so deadline-tight
    # requests avoid replicas with stale backlogs. 0 disables.
    slo_wait_weight: float = 4.0           # cli: --slo-wait-weight
    # Router supervisor tick (hedging scans + breaker promotion).
    tick_s: float = 0.02                   # cli: --router-tick
    # Router-level content-addressed dedup cache (the exactly-once
    # backstop: a late payload from a zombie replica can never
    # double-resolve a content address). 0 disables.
    cache_entries: int = 4096              # cli: --router-cache-entries


@dataclasses.dataclass(frozen=True)
class MigrationConfig:
    """Disaggregated prefill/decode serving knobs (serve/migrate.py;
    DEPLOY.md §1p).

    The router splits its replica pool into PREFILL-role and
    DECODE-role replicas: a long prompt prefills on a prefill replica,
    its KV pages stream to a decode replica as chunked double-buffered
    transfers (the weight-streaming discipline of models/weights.
    stream_params applied to the §1g page pool), and decode resumes
    there bitwise-identically to a colocated run. The cluster-wide
    prefix index (engine/prefix_tree.ClusterPrefixIndex) makes a
    prefix prefilled ANYWHERE warm EVERYWHERE: page residency joins
    weight residency and HBM pressure as a placement signal, and a
    migration pulls matching pages instead of re-prefilling. A stalled
    or corrupted transfer falls back to local re-prefill on the decode
    replica — never a wrong answer, never a dropped request.
    """

    # Master switch for page migration + disaggregated placement. OFF
    # restores the PR-12 role-less router exactly.
    enabled: bool = True                # cli: --no-migrate
    # Replicas (of `--replicas N`) dedicated to the PREFILL role: they
    # absorb long-prompt prefills and never serve decode traffic while
    # a decode-role replica survives. 0 = colocated (every replica
    # does both phases — the pre-disaggregation behavior).
    prefill_replicas: int = 0           # cli: --migrate-prefill-replicas
    # KV pages per transfer chunk: the unit of the double-buffered
    # device<->host hop (page bytes: models/paged.kv_page_bytes).
    chunk_pages: int = 8                # cli: --migrate-chunk-pages
    # Transfer chunks kept in flight (2 = classic double buffering:
    # chunk i+1 streams while chunk i lands).
    inflight_chunks: int = 2            # cli: --migrate-inflight-chunks
    # Minimum tokenized shared-prefix length worth a remote prefill +
    # migration; shorter prompts score colocated on a decode replica
    # (the handoff overhead would exceed the prefill saved).
    min_prefix_tokens: int = 32         # cli: --migrate-min-prefix
    # Placement bonus (queue-row equivalents) per cluster-index-matched
    # PAGE a replica already holds for the request's prefix — page
    # residency as a first-class routing signal beside weight residency
    # and hbm_pressure (serve/router.ReplicaRouter._pick).
    page_bonus: float = 0.5             # cli: --migrate-page-bonus
    # Verify a per-chunk checksum at import: a corrupted transfer is
    # detected BEFORE its pages enter the decode replica's radix tree
    # and falls back to local re-prefill (chaos kind
    # ``migration_corrupt``). Disabling trades the integrity check for
    # one CRC pass per chunk.
    verify: bool = True                 # cli: --no-migrate-verify
    # Wall-clock budget for one whole migration chain (prefill ->
    # export -> transfer -> import). Past it the router abandons the
    # chain and the decode replica re-prefills locally (chaos kind
    # ``migration_stall``); a late-landing import is harmless (it only
    # warms the pool with verified pages).
    timeout_s: float = 30.0             # cli: --migrate-timeout


@dataclasses.dataclass(frozen=True)
class TierConfig:
    """Tiered KV + weight store knobs (serve/tiers.py; DEPLOY.md §1s).

    Mooncake's observation applied to this engine: HBM pressure should
    DEMOTE cached state down a tier ladder (HBM -> pinned host DRAM ->
    local disk), not delete it. The governor's reclaim rungs become
    reversible — ``evict_weights`` records the victim's staged host
    tree to the disk tier before eviction, ``evict_pages`` exports the
    coldest radix leaves (serve/migrate.py's chunked checksummed
    transfer discipline) into a byte-budgeted host pool whose own LRU
    overflow spills to an on-disk page store with an append-only JSONL
    index (the manifest kill-mid-append discipline). Promotion back to
    HBM runs through the ordinary paged-warm import path, so payloads
    stay bitwise; a corrupt or stalled tier read falls back to local
    re-prefill — never a wrong answer. The disk tier survives process
    death: a restarted server re-seeds its radix tree and weight cache
    from it (restart-warm).
    """

    # Master switch. OFF restores the PR-14 delete-on-pressure rungs
    # exactly (and serve restarts start cold).
    enabled: bool = False               # cli: --tiered
    # Pinned-host-DRAM pool budget for demoted KV pages, MiB. LRU
    # overflow spills to the disk tier (or is dropped when no disk_dir
    # is configured). Size against models/paged.kv_page_bytes.
    host_budget_mb: float = 256.0       # cli: --tier-host-mb
    # Disk tier root directory ("" disables the disk leg: demotions
    # stop at host DRAM and restart-warm is off). One page store +
    # one weight store per serving process live under it.
    disk_dir: str = ""                  # cli: --tier-disk-dir
    # Disk tier budget, MiB; oldest spilled entries are dropped past it
    # (tombstoned in the index, file unlinked).
    disk_budget_mb: float = 1024.0      # cli: --tier-disk-mb
    # Radix pages demoted per evict_pages rung engagement — replaces
    # GovernorConfig.evict_pages_per_step deletions when tiering is ON.
    demote_pages_per_step: int = 32     # cli: --tier-demote-pages
    # Verify per-chunk checksums at promote: a corrupted host/disk
    # chunk is refused BEFORE its pages enter the radix tree and the
    # request re-prefills (chaos kind ``tier_corrupt``).
    verify: bool = True                 # cli: --no-tier-verify
    # Wall-clock budget for one disk-tier read; past it the promote is
    # abandoned and the request re-prefills locally (chaos kind
    # ``disk_stall``). The entry stays — a transient stall is not
    # corruption.
    disk_timeout_s: float = 10.0        # cli: --tier-disk-timeout
    # Re-seed the radix tree + weight cache from the disk tier at
    # server construction (restart-warm serving). Needs disk_dir.
    restart_warm: bool = True           # cli: --no-restart-warm
    # Placement bonus per HOST-tier-matched page as a fraction of
    # MigrationConfig.page_bonus ("warm on host at replica 2" prices
    # between HBM-warm and cold in ReplicaRouter._pick).
    host_bonus: float = 0.5             # cli: --tier-host-bonus
    # Same for DISK-tier-matched pages (cheaper than host, dearer
    # than a cold re-prefill).
    disk_bonus: float = 0.25            # cli: --tier-disk-bonus

    @property
    def host_budget_bytes(self) -> int:
        return int(self.host_budget_mb * 2**20)

    @property
    def disk_budget_bytes(self) -> int:
        return int(self.disk_budget_mb * 2**20)


@dataclasses.dataclass(frozen=True)
class FleetConfig:
    """Multi-model fleet knobs (engine/fleet.py over models/weights.py).

    The fleet layer serves/sweeps N co-resident models off one engine
    cluster: an HBM-budgeted LRU weight cache holds as many model param
    trees as fit, an async streamer prefetches the next model's weights
    behind the current model's compute, and serve grows the
    ``fleet_score`` request class (one question across every resident
    model, answered with per-model P(yes)/P(no) + pairwise
    kappa/disagreement). DEPLOY.md §1k has the sizing arithmetic.

    - ``fleet_models``: the model ids served by ``lir_tpu serve
      --fleet-models`` (comma-separated on the CLI). Empty = single-
      model serving (the pre-fleet ScoringServer path).
    - ``weight_cache_gb``: HBM budget for co-resident model weights.
      0 = unbounded (every model stays resident — correct whenever the
      fleet fits; the CPU smoke default). When a model would not fit,
      the LRU model with no in-flight dispatch is evicted; a budget
      smaller than the single largest model is a loud error.
    - ``weight_prefetch``: stream the next model's weights on a
      background worker while the current model scores
      (``--no-weight-prefetch`` serializes every swap — measurement
      baseline, the pre-fleet drop-and-reload behavior).
    - ``fleet_deadline_s``: default deadline for fleet_score fan-outs
      (each per-model sub-request inherits it unless the request
      carries an explicit ``deadline_s``).
    """

    fleet_models: Tuple[str, ...] = ()
    weight_cache_gb: float = 0.0
    weight_prefetch: bool = True
    fleet_deadline_s: float = 60.0   # cli: --fleet-deadline

    @property
    def weight_cache_bytes(self) -> Optional[int]:
        return (int(self.weight_cache_gb * 2**30)
                if self.weight_cache_gb > 0 else None)


@dataclasses.dataclass(frozen=True)
class Config:
    """Top-level framework configuration."""

    backend: str = "tpu"  # "tpu" (local JAX inference) | "api" (remote, optional)
    mesh: MeshConfig = dataclasses.field(default_factory=MeshConfig)
    runtime: RuntimeConfig = dataclasses.field(default_factory=RuntimeConfig)
    spec: SpecConfig = dataclasses.field(default_factory=SpecConfig)
    cascade: CascadeConfig = dataclasses.field(default_factory=CascadeConfig)
    perturbation: PerturbationConfig = dataclasses.field(default_factory=PerturbationConfig)
    stats: StatsConfig = dataclasses.field(default_factory=StatsConfig)
    retry: RetryConfig = dataclasses.field(default_factory=RetryConfig)
    serve: ServeConfig = dataclasses.field(default_factory=ServeConfig)
    fleet: FleetConfig = dataclasses.field(default_factory=FleetConfig)
    observe: ObserveConfig = dataclasses.field(
        default_factory=ObserveConfig)
    router: RouterConfig = dataclasses.field(
        default_factory=RouterConfig)
    migrate: MigrationConfig = dataclasses.field(
        default_factory=MigrationConfig)
    governor: GovernorConfig = dataclasses.field(
        default_factory=GovernorConfig)
    tiers: TierConfig = dataclasses.field(default_factory=TierConfig)

    # Paths: everything under one results root; no personal gdrive paths.
    results_dir: Path = Path("results")
    data_dir: Path = Path("data")
    checkpoint_dir: Path = Path("checkpoints")

    # Models under test (HF repo ids or registry names).
    models: Sequence[str] = ()

    def __post_init__(self) -> None:
        if self.backend not in ("tpu", "api"):
            raise ValueError(f"backend must be 'tpu' or 'api', got {self.backend!r}")

    @staticmethod
    def api_key(name: str) -> str:
        """Read a secret from the environment (reference: analysis/config.py:6-16).

        Raised lazily, only when the optional API backend is actually used.
        """
        val = os.environ.get(name, "")
        if not val:
            raise RuntimeError(
                f"{name} not set. The 'api' backend needs it; the default 'tpu' "
                "backend performs zero external API calls."
            )
        return val


DEFAULT_CONFIG = Config()
