"""Fake inference backend for hermetic tests (SURVEY.md §4).

The reference has no test suite; its committed CSVs double as golden outputs.
Our upgrade: a deterministic tokenizer + tiny-model stand-in so the engine
(L2) and stats (L4) layers are testable with zero network, zero weights, and
zero TPU time. The FakeTokenizer implements exactly the slice of the HF
tokenizer protocol the engine touches (``__call__ -> .input_ids``,
``decode``, ``pad_token_id``).
"""

from __future__ import annotations

import dataclasses
import hashlib
from typing import List, Sequence


@dataclasses.dataclass
class _Encoding:
    input_ids: List[int]


class FakeTokenizer:
    """Whitespace word tokenizer with a stable hashed vocab.

    Ids are stable across runs/processes (md5, not Python hash). ' Yes' and
    ' No' map to dedicated reserved ids so yes/no readout tests are exact.

    ``vocab`` MUST cover the model config it is paired with
    (``vocab <= cfg.vocab_size``): an out-of-vocab id reads an
    out-of-range embedding row, whose NaN readouts the numerics guard
    quarantines as error:numerics (the historical
    __graft_entry__.dryrun_multichip harness bug — default 1000 vs the
    tiny flagship's 512). Pass ``vocab=cfg.vocab_size`` whenever the
    model's vocab is smaller than the default.
    """

    VOCAB = 1000
    PAD, YES, NO = 0, 1, 2
    _RESERVED = 3

    pad_token_id = PAD
    eos_token_id = PAD

    def __init__(self, vocab: int = VOCAB):
        if vocab <= self._RESERVED:
            raise ValueError(f"FakeTokenizer vocab {vocab} leaves no room "
                             f"past the {self._RESERVED} reserved ids")
        self.VOCAB = int(vocab)   # instance override; class default kept

    def _word_id(self, w: str) -> int:
        if w == "Yes":
            return self.YES
        if w == "No":
            return self.NO
        h = int(hashlib.md5(w.encode()).hexdigest(), 16)
        return self._RESERVED + h % (self.VOCAB - self._RESERVED)

    def __call__(self, text: str, add_special_tokens: bool = True) -> _Encoding:
        return _Encoding([self._word_id(w) for w in text.split()])

    def decode(self, ids: Sequence[int], skip_special_tokens: bool = True) -> str:
        out = []
        for i in ids:
            i = int(i)
            if i == self.YES:
                out.append("Yes")
            elif i == self.NO:
                out.append("No")
            elif i != self.PAD or not skip_special_tokens:
                out.append(f"<{i}>")
        return " ".join(out)

    def __len__(self) -> int:
        return self.VOCAB
