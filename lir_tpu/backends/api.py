"""Optional remote-API backend (C7-C9), preserved behind the config switch.

Parity target: the OpenAI Batch API client of analysis/perturb_prompts.py —
request building with custom_id metadata (:190-269), JSONL save/upload
(:271-292), batch create/poll/download (:294-345), >50,000-request chunking
(:578-600), and the result decoder that recovers Token_1/2_Prob from
first-token top_logprobs, the odds ratio, and the probability-weighted
confidence E[v] over integer tokens (:398-549).

The default 'tpu' backend performs zero external API calls; this module
exists for capability parity (BASELINE.json's ``backend: "api" | "tpu"``
switch). Network access is abstracted behind the BatchTransport protocol:
production wires the OpenAI client (lazily, keys from the environment via
Config.api_key), tests inject a fake transport. Nothing here imports an SDK
at module import time.
"""

from __future__ import annotations

import dataclasses
import json
import math
import re
import time
from typing import Dict, Iterable, List, Optional, Protocol, Sequence, Tuple

from ..config import Config, RetryConfig
from ..engine.grid import GridCell
from ..utils.logging import get_logger
from ..utils.retry import retry_with_exponential_backoff

log = get_logger(__name__)

MAX_BATCH_SIZE = 50_000     # perturb_prompts.py:29
POLL_INTERVAL_S = 60.0      # :313-330
TERMINAL_FAILURES = ("failed", "cancelled", "expired")


class BatchTransport(Protocol):
    """The five remote operations the batch pipeline needs."""

    def upload_jsonl(self, lines: Sequence[str]) -> str:
        """Upload request lines; return a file id."""

    def create_batch(self, file_id: str) -> str:
        """Create a batch over the uploaded file; return a batch id."""

    def batch_status(self, batch_id: str) -> str:
        """Return current status string (completed/failed/...)."""

    def batch_output_file(self, batch_id: str) -> Optional[str]:
        """Return the output file id once completed."""

    def download_jsonl(self, file_id: str) -> List[str]:
        """Download result lines."""


def openai_transport(config: Optional[Config] = None) -> BatchTransport:
    """Production transport over the OpenAI SDK (lazy import; the key is
    read from the environment only when this is constructed)."""
    config = config or Config(backend="api")
    api_key = config.api_key("OPENAI_API_KEY")
    import openai  # imported here so the tpu backend never needs the SDK

    client = openai.OpenAI(api_key=api_key)

    class _Transport:
        def upload_jsonl(self, lines: Sequence[str]) -> str:
            data = ("\n".join(lines) + "\n").encode("utf-8")
            f = client.files.create(file=("batch.jsonl", data), purpose="batch")
            return f.id

        def create_batch(self, file_id: str) -> str:
            b = client.batches.create(
                input_file_id=file_id,
                endpoint="/v1/chat/completions",
                completion_window="24h",
            )
            return b.id

        def batch_status(self, batch_id: str) -> str:
            return client.batches.retrieve(batch_id).status

        def batch_output_file(self, batch_id: str) -> Optional[str]:
            return client.batches.retrieve(batch_id).output_file_id

        def download_jsonl(self, file_id: str) -> List[str]:
            content = client.files.content(file_id)
            return content.text.splitlines()

    return _Transport()


# ---------------------------------------------------------------------------
# Request building (C4 parity for the remote path)
# ---------------------------------------------------------------------------


def build_batch_requests(
    cells: Sequence[GridCell],
    model: str,
    reasoning_model: bool = False,
    reasoning_runs: int = 10,
    skip_reasoning_logprobs: bool = True,
) -> Tuple[List[Dict[str, object]], Dict[str, GridCell]]:
    """Expand grid cells into chat-completion batch requests with a
    custom_id -> cell map (perturb_prompts.py:190-269). Non-reasoning
    bodies carry temperature 0 / max_tokens 500 / logprobs top-20 on BOTH
    formats — the confidence request's logprobs feed the weighted E[v]
    readout (:504-526) — plus the reference's response_format field.
    Reasoning models (no logprobs exposed) default to the reference's
    SKIP_REASONING_MODEL_LOGPROBS=True mode (confidence request only,
    :211); with skip_reasoning_logprobs=False each binary request repeats
    ``reasoning_runs`` times and the decoder averages answer counts
    (REASONING_MODEL_RUNS, perturb_prompts.py:47,220,412-446). Body
    fields pinned against the EXECUTED reference
    (tools/reference_perturb_oracle.py)."""
    requests: List[Dict[str, object]] = []
    id_map: Dict[str, GridCell] = {}

    def add(custom_id: str, cell: GridCell, prompt: str) -> None:
        body: Dict[str, object] = {
            "model": model,
            "messages": [{"role": "user", "content": prompt}],
            "response_format": {"type": "text"},
        }
        if reasoning_model:
            body["max_completion_tokens"] = 2000
        else:
            body["max_tokens"] = 500
            body["temperature"] = 0.0
            body["logprobs"] = True
            body["top_logprobs"] = 20
        requests.append(
            {
                "custom_id": custom_id,
                "method": "POST",
                "url": "/v1/chat/completions",
                "body": body,
            }
        )
        id_map[custom_id] = cell

    for cell in cells:
        base = f"p{cell.prompt_idx}_r{cell.rephrase_idx}"
        if reasoning_model:
            if not skip_reasoning_logprobs:
                for run in range(reasoning_runs):
                    add(f"{base}_binary_run{run}", cell, cell.binary_prompt)
        else:
            add(f"{base}_binary", cell, cell.binary_prompt)
        add(f"{base}_confidence", cell, cell.confidence_prompt)
    return requests, id_map


def chunk_requests(
    requests: Sequence[Dict[str, object]],
    max_batch_size: int = MAX_BATCH_SIZE,
) -> List[List[Dict[str, object]]]:
    """Split oversized request lists (perturb_prompts.py:578-600)."""
    return [
        list(requests[i : i + max_batch_size])
        for i in range(0, len(requests), max_batch_size)
    ]


# ---------------------------------------------------------------------------
# Batch lifecycle (C7)
# ---------------------------------------------------------------------------


def run_batch(
    transport: BatchTransport,
    requests: Sequence[Dict[str, object]],
    poll_interval: float = POLL_INTERVAL_S,
    max_wait: float = 24 * 3600,
    sleep=time.sleep,
    retry: Optional[RetryConfig] = None,
) -> Optional[List[Dict[str, object]]]:
    """Upload -> create -> poll -> download one batch. Returns decoded
    result objects, or None on a terminal failure (the caller skips the
    model, perturb_prompts.py:324-328).

    Every remote call runs under ``retry`` (utils/retry.py; default: the
    reference's 10-retry/60 s policy capped to this call's ``max_wait`` so
    retries can never outlive the batch window) — the reference wraps its
    client calls in the same exponential-backoff helper."""
    retry = retry if retry is not None else RetryConfig(max_elapsed=max_wait)

    def _call(op, what):
        return retry_with_exponential_backoff(
            op, (Exception,), retry, sleep=sleep,
            log=lambda msg: log.warning("%s: %s", what, msg))

    lines = [json.dumps(r) for r in requests]
    file_id = _call(lambda: transport.upload_jsonl(lines), "upload_jsonl")
    batch_id = _call(lambda: transport.create_batch(file_id), "create_batch")
    log.info("batch %s created (%d requests)", batch_id, len(requests))

    waited = 0.0
    while waited < max_wait:
        status = _call(lambda: transport.batch_status(batch_id),
                       "batch_status")
        if status == "completed":
            break
        if status in TERMINAL_FAILURES:
            log.error("batch %s terminal status: %s", batch_id, status)
            return None
        sleep(poll_interval)
        waited += poll_interval
    else:
        log.error("batch %s timed out after %.0fs", batch_id, max_wait)
        return None

    out_file = _call(lambda: transport.batch_output_file(batch_id),
                     "batch_output_file")
    if out_file is None:
        return None
    return [json.loads(line)
            for line in _call(lambda: transport.download_jsonl(out_file),
                              "download_jsonl")]


# ---------------------------------------------------------------------------
# Result decoding (C8)
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class ApiScore:
    """Decoded per-cell measurement from batch results."""

    custom_id: str
    response_text: str = ""
    confidence_text: str = ""
    token_1_prob: float = 0.0
    token_2_prob: float = 0.0
    log_probabilities: str = ""
    confidence_value: Optional[int] = None
    weighted_confidence: Optional[float] = None
    run_responses: List[str] = dataclasses.field(default_factory=list)
    reasoning_skipped: bool = False
    binary_seen: bool = False

    @property
    def odds_ratio(self) -> float:
        if self.reasoning_skipped:
            return 0.0               # perturb_prompts.py:453 (skip mode)
        if self.token_2_prob > 0:
            return self.token_1_prob / self.token_2_prob
        return math.inf


def _first_token_probs(
    logprob_content: List[Dict[str, object]],
    target_tokens: Tuple[str, str],
) -> Tuple[float, float]:
    """Scan the first position's top_logprobs for the two target tokens
    (perturb_prompts.py:474-490); a missing target scores 0. Matching is
    RAW string equality — the executed reference never strips, so a
    leading-space ' Covered' token does NOT match target 'Covered'
    (pinned by the oracle's lookalike entries)."""
    if not logprob_content:
        return 0.0, 0.0
    top = logprob_content[0].get("top_logprobs", [])
    p1 = p2 = 0.0
    for entry in top:
        token = str(entry.get("token", ""))
        lp = float(entry.get("logprob", -math.inf))
        if token == target_tokens[0]:
            p1 = math.exp(lp)
        elif token == target_tokens[1]:
            p2 = math.exp(lp)
    return p1, p2


def _weighted_confidence(
    logprob_content: List[Dict[str, object]]
) -> Optional[float]:
    """E[v] over integer-bearing tokens 0-100 across EVERY generated
    confidence position's top_logprobs (perturb_prompts.py:504-526: the
    reference iterates the full content list, and extracts integers with
    the same \\b(\\d+)\\b search the text parse uses — '85%' contributes
    85, '150' is range-excluded)."""
    num, den = 0.0, 0.0
    for token_info in logprob_content:
        for entry in token_info.get("top_logprobs", []) or []:
            m = re.search(r"\b(\d+)\b", str(entry.get("token", "")))
            if not m:
                continue
            v = int(m.group(1))
            if not 0 <= v <= 100:
                continue
            p = math.exp(float(entry.get("logprob", -math.inf)))
            num += v * p
            den += p
    return num / den if den > 0 else None


def decode_batch_results(
    results: Iterable[Dict[str, object]],
    id_map: Dict[str, GridCell],
    reasoning_skip: bool = False,
) -> Dict[str, ApiScore]:
    """Re-key raw batch result objects by custom_id and extract the
    measurement fields (perturb_prompts.py:352-549). With
    ``reasoning_skip`` (the reference's SKIP_REASONING_MODEL_LOGPROBS
    mode, a confidence-only grid) rows carry the reference's literal
    placeholders and odds_ratio 0.0 (:448-466)."""
    scores: Dict[str, ApiScore] = {}
    id_pattern = re.compile(r"^(p\d+_r\d+)_(binary(?:_run\d+)?|confidence)$")
    for obj in results:
        custom_id = str(obj.get("custom_id", ""))
        m_id = id_pattern.match(custom_id)
        cell = id_map.get(custom_id)
        if cell is None or m_id is None:
            continue
        base_id, fmt = m_id.group(1), m_id.group(2)
        # The reference creates the per-cell entry for every KNOWN
        # custom_id, but extracts fields only from lines that carry a
        # response body — errored lines leave their leg empty
        # (perturb_prompts.py:370-396).
        score = scores.setdefault(base_id, ApiScore(custom_id=base_id))
        response = obj.get("response")
        body = (response.get("body", {})
                if isinstance(response, dict) else {})
        if not body:
            log.warning("no response body for %s: %s", custom_id,
                        (obj.get("error") or {}).get("message", "unknown"))
            continue
        choices = body.get("choices") or [{}]
        message = choices[0].get("message", {}) or {}
        text = str(message.get("content", "") or "")
        raw_logprobs = choices[0].get("logprobs", {})
        content = (raw_logprobs or {}).get("content") or []

        if fmt == "binary":
            score.binary_seen = True
            score.response_text = text.strip()
            score.token_1_prob, score.token_2_prob = _first_token_probs(
                content, cell.target_tokens
            )
            # D6 "Log Probabilities" stores the reference's exact string:
            # str() of the full logprobs object (:540) — the format the
            # compliance checker (C25) parses.
            score.log_probabilities = str(raw_logprobs)
        elif fmt.startswith("binary_run"):
            # Reasoning-model run: counted later in _finalize_reasoning.
            score.run_responses.append(text.strip())
        else:
            score.confidence_text = text.strip()
            m = re.search(r"\b(\d+)\b", text)
            score.confidence_value = int(m.group(1)) if m else None
            score.weighted_confidence = _weighted_confidence(content)

    if reasoning_skip:
        # Skip-mode rows are emitted even when their confidence line
        # errored (values stay None) and always carry the reference's
        # literal placeholders (:448-466).
        for score in scores.values():
            score.reasoning_skipped = True
            score.response_text = "N/A (skipped for reasoning model)"
            score.log_probabilities = "N/A for reasoning models"
            score.weighted_confidence = score.confidence_value
    else:
        # A cell with no successful binary leg (single errored binary, or
        # zero successful reasoning runs) is dropped with a warning
        # (:408-410).
        for base_id in [b for b, s in scores.items()
                        if not s.binary_seen and not s.run_responses]:
            log.warning("no binary results for %s — row dropped", base_id)
            del scores[base_id]

    _finalize_reasoning(scores, id_map)
    return scores


def _finalize_reasoning(
    scores: Dict[str, ApiScore], id_map: Dict[str, GridCell]
) -> None:
    """Average answer counts over reasoning runs (perturb_prompts.py:412-446):
    Token_i_Prob = (runs whose text contains target_i) / n_runs; the stored
    response is the most common run text."""
    cells_by_base = {
        cid.rsplit("_", 2)[0] if "_run" in cid else cid.rsplit("_", 1)[0]: cell
        for cid, cell in id_map.items()
    }
    for base_id, score in scores.items():
        if not score.run_responses:
            continue
        cell = cells_by_base.get(base_id)
        if cell is None:
            continue
        t1, t2 = cell.target_tokens
        # Shared with the local sampled scorer (engine/score.py) so the two
        # reasoning paths cannot drift on the if/elif counting order or the
        # most-common tie-break.
        from ..engine.score import count_averaged_responses

        (score.token_1_prob, score.token_2_prob,
         score.response_text) = count_averaged_responses(
            score.run_responses, t1, t2)
        # Reasoning models expose no logprobs; weighted confidence falls
        # back to the parsed integer and the D6 logprob column carries the
        # reference's literal placeholder (perturb_prompts.py:446,540).
        if score.weighted_confidence is None:
            score.weighted_confidence = score.confidence_value
        score.log_probabilities = "N/A for reasoning models"
