"""Content-addressed result cache: dedup for near-identical probes.

Perturbation-style traffic re-asks near-identical questions constantly —
a sweep client retrying a timed-out cell, two analyses probing the same
(model, prompt) pair, the unperturbed original scored once per session.
The cache is keyed by a sha256 content address over everything that
determines a score: the serving model's manifest key (utils/compile_cache
.manifest_key via the engine — model config, runtime budgets, quant, mesh,
ladder) plus both prompts and both target strings. Two requests with the
same address would dispatch byte-identical device programs on byte-
identical inputs, so replaying the stored measurement IS the fresh score
(bitwise — pinned by tests/test_serve.py); anything that could change the
numbers (a different checkpoint, budget, or quant mode) changes the
manifest key and misses.

LRU-bounded; entries are plain measurement dicts (no futures, no device
arrays), so the cache is cheap to hold at depth and safe to share across
threads.
"""

from __future__ import annotations

import hashlib
import threading
from collections import OrderedDict
from typing import Dict, Optional

from ..utils.profiling import ServeStats
from .queue import ServeRequest


def content_key(engine_key: str, request: ServeRequest) -> str:
    """Content address of one probe under one engine configuration."""
    h = hashlib.sha256()
    for part in (engine_key, request.binary_prompt,
                 request.confidence_prompt, *request.targets):
        h.update(part.encode("utf-8"))
        h.update(b"\x1f")
    return h.hexdigest()


class ResultCache:
    """Thread-safe LRU of measurement payloads keyed by content address.

    ``max_entries <= 0`` disables the cache (every lookup misses and puts
    are dropped) — the stats still count misses so the dedup hit rate
    reads 0, not NaN."""

    def __init__(self, max_entries: int,
                 stats: Optional[ServeStats] = None):
        self.max_entries = int(max_entries)
        self.stats = stats if stats is not None else ServeStats()
        self._od: "OrderedDict[str, Dict]" = OrderedDict()  # guarded-by: _lock
        self._lock = threading.Lock()

    def __len__(self) -> int:
        with self._lock:
            return len(self._od)

    def get(self, key: str) -> Optional[Dict]:
        with self._lock:
            payload = self._od.get(key)
            if payload is not None:
                self._od.move_to_end(key)
        if payload is None:
            self.stats.count("dedup_misses")
            return None
        self.stats.count("dedup_hits")
        return dict(payload)

    def put(self, key: str, payload: Dict) -> None:
        if self.max_entries <= 0:
            return
        with self._lock:
            self._od[key] = dict(payload)
            self._od.move_to_end(key)
            while len(self._od) > self.max_entries:
                self._od.popitem(last=False)
