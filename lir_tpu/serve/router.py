"""Elastic multi-replica serving: the failover router (ROADMAP item 1).

A single :class:`~lir_tpu.serve.server.ScoringServer` (or fleet server)
is a single point of failure: PR 5's heartbeat machinery *detects* a
dead peer, but a lost server still costs the run. Production
disaggregated stacks (Mooncake-style separation of placement from
execution, Orca-style continuous batching behind a router) treat replica
death and stragglers as the steady state. This module is that front
process: a :class:`ReplicaRouter` spreads one request stream over N
replica servers, each wrapped in its own router-side
:class:`~lir_tpu.faults.breaker.CircuitBreaker`.

Placement reads three live signals per replica:

- **queue depth** (queue + bucketed rows) — the load signal;
- **breaker state** — a replica that keeps erroring (or was observed
  dead) stops receiving traffic until its cooldown probe;
- **weight residency** — for fleet replicas, the WeightCache's
  ``add_listener`` insert/evict events feed a router-side residency
  map, so a model's requests land on the replica already holding its
  weights (weight residency as a first-class routing signal), with an
  SLO term (the replica's oldest queued-row wait against the request's
  remaining deadline) keeping deadline-tight requests away from stale
  backlogs.

Failover is the headline contract:

- a replica that answers ``error`` (or sheds) triggers re-admission to
  the next-best replica while the deadline allows — ``failovers``;
- a replica KILLED mid-dispatch (:meth:`ReplicaRouter.kill_replica`, or
  a ``replica_kill`` fault schedule) has its in-flight requests
  re-admitted to survivors immediately — ``re_admitted`` — and its
  breaker force-opens (``trip``), so recovery after a rejoin flows
  through the ordinary open -> half_open -> closed probe;
- EXACTLY-ONCE resolution: every request resolves through one
  :class:`~lir_tpu.serve.queue.ServeFuture` (first resolution wins) and
  payloads are content-addressed with the existing ResultCache key, so
  a late payload from a zombie replica can never double-resolve — it is
  counted (``zombie_payloads``) and dropped. Because every replica runs
  the same engine configuration, the winning payload is bitwise the
  payload any replica would have produced (pinned by
  tests/test_router.py) — PAPER.md's axis results cannot depend on
  which replica scored a row;
- requests inside the deadline whisker (``RouterConfig.hedge_s``) are
  HEDGED onto a second replica with first-payload-wins resolution.

Everything here is host-side; replicas are ordinary servers (in-process
today — the JSONL/network hop is a transport detail the router's
contract does not depend on).
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Set, Tuple

from ..config import RouterConfig
from ..faults import CircuitBreaker
from ..observe import registry as metrics_mod
from ..observe import tracing
from ..utils.logging import get_logger
from ..utils.profiling import RouterStats, ServeStats
from .cache import ResultCache, content_key
from .queue import (STATUS_ERROR, STATUS_OK, STATUS_SHED, ServeFuture,
                    ServeRequest, ServeResult)

log = get_logger(__name__)

# The measurement fields a payload carries — what the router's dedup
# cache stores and what ServeResult(**payload) re-expands (the same
# projection ScoringServer._resolve_ok caches).
PAYLOAD_FIELDS = ("model_response", "model_confidence_response",
                  "token_1_prob", "token_2_prob", "log_probabilities",
                  "confidence_value", "weighted_confidence")


def _payload_of(res: ServeResult) -> Dict:
    return {f: getattr(res, f) for f in PAYLOAD_FIELDS}


class _Replica:
    """Router-side state for one replica server."""

    def __init__(self, replica_id: str, server, breaker: CircuitBreaker):
        self.replica_id = replica_id
        self.server = server
        self.breaker = breaker
        self.alive = True
        self.is_fleet = hasattr(server, "fleet")
        self._lock = threading.Lock()
        # Requests currently attempted on this replica, by pending id —
        # the re-admission set when this replica dies.
        self.inflight: Dict[int, "_Pending"] = {}  # guarded-by: _lock
        # Residency map fed by WeightCache listener events (may fire
        # under the cache lock: cheap set ops only).
        self.resident: Set[str] = set()  # guarded-by: _lock

    def seed_resident(self, models) -> None:
        with self._lock:
            self.resident = set(models)

    def on_weight_event(self, event: str, model_id: str) -> None:
        with self._lock:
            if event == "insert":
                self.resident.add(model_id)
            elif event == "evict":
                self.resident.discard(model_id)

    def resident_view(self) -> Set[str]:
        with self._lock:
            return set(self.resident)

    def track(self, pending: "_Pending") -> None:
        with self._lock:
            self.inflight[id(pending)] = pending

    def untrack(self, pending: "_Pending") -> None:
        with self._lock:
            self.inflight.pop(id(pending), None)

    def take_inflight(self) -> List["_Pending"]:
        with self._lock:
            victims = list(self.inflight.values())
            self.inflight.clear()
        return victims

    @property
    def depth(self) -> int:
        try:
            return int(self.server.queue_depth)
        except Exception:  # noqa: BLE001 — a dying replica reads as deep
            return 1 << 20

    def oldest_wait(self, now: float) -> float:
        fn = getattr(self.server, "oldest_wait", None)
        if fn is None:
            return 0.0
        try:
            return float(fn(now))
        except Exception:  # noqa: BLE001
            return 0.0

    @property
    def pressure(self) -> float:
        """The replica's HBM-governor ledger pressure (engine/hbm.py) —
        memory as a placement signal beside queue depth and weight
        residency: a squeezed replica is a worse home for new work even
        when its queue looks shallow. 0 when ungoverned/unbounded."""
        try:
            return float(getattr(self.server, "hbm_pressure", 0.0))
        except Exception:  # noqa: BLE001
            return 0.0


class _Pending:
    """One routed request's lifecycle across attempts."""

    __slots__ = ("request", "model_id", "future", "key", "t_submit",
                 "t_deadline", "tried", "hedged", "resolved", "lock")

    def __init__(self, request: ServeRequest, model_id: str, key: str,
                 t_submit: float, t_deadline: float):
        self.request = request
        self.model_id = model_id
        self.future = ServeFuture()
        self.key = key
        self.t_submit = t_submit
        self.t_deadline = t_deadline
        self.tried: Set[str] = set()   # guarded-by: lock
        self.hedged = False            # guarded-by: lock
        self.resolved = False          # guarded-by: lock
        self.lock = threading.Lock()

    def claim_resolution(self) -> bool:
        """True exactly once — the winning attempt's right to resolve."""
        with self.lock:
            if self.resolved:
                return False
            self.resolved = True
            return True


class ReplicaRouter:
    """Failover router over N replica servers (module docstring).

    ``replicas`` is ``[(replica_id, server), ...]`` — servers are
    started/stopped by the caller (they may be shared with other
    routers or direct clients); :meth:`start`/:meth:`stop` only own the
    router's tick thread (hedging scans + breaker promotion).
    """

    def __init__(self, replicas: Sequence[Tuple[str, object]],
                 config: Optional[RouterConfig] = None,
                 stats: Optional[RouterStats] = None,
                 clock: Callable[[], float] = time.monotonic):
        assert replicas, "a router needs at least one replica"
        self.config = config or RouterConfig()
        self.stats = stats if stats is not None else RouterStats()
        self.clock = clock
        self._lock = threading.Lock()
        self._handles: Dict[str, _Replica] = {}
        self._pending: Dict[int, _Pending] = {}  # guarded-by: _lock
        self._rr = 0                             # guarded-by: _lock
        for rid, server in replicas:
            assert rid not in self._handles, f"duplicate replica {rid}"
            breaker = CircuitBreaker(
                failure_threshold=self.config.replica_failure_threshold,
                cooldown_s=self.config.replica_cooldown_s, clock=clock)
            handle = _Replica(str(rid), server, breaker)
            # Residency map: seed from the current resident set, then
            # ride the WeightCache's insert/evict listener events.
            cache = getattr(getattr(server, "fleet", None), "cache", None)
            if cache is not None and hasattr(cache, "add_listener"):
                resident = getattr(server, "resident_models", None)
                if callable(resident):
                    handle.seed_resident(resident())
                cache.add_listener(handle.on_weight_event)
            # Sentinel gating (observe/sentinel.py): a fleet replica
            # exposes the ROUTER-side breaker so the scheduler pauses
            # sentinel sweeps while the replica is failing over.
            if getattr(server, "breaker", "absent") is None:
                server.breaker = breaker
            self._handles[handle.replica_id] = handle
        # Router-level content-addressed dedup: the exactly-once
        # backstop. The cache's own ServeStats is private; RouterStats
        # carries the router-visible dedup counter.
        self.cache = ResultCache(self.config.cache_entries, ServeStats())
        self._engine_key = self._derive_engine_key()
        self.metrics = metrics_mod.MetricsRegistry()
        self.metrics.register("router", self.stats)
        for rid, handle in self._handles.items():
            rstats = getattr(handle.server, "stats", None)
            if rstats is not None:
                self.metrics.register(f"replica:{rid}:serve", rstats)
        rec = tracing.get_recorder()
        if rec is not None:
            self.metrics.register("trace", rec)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _derive_engine_key(self) -> str:
        for handle in self._handles.values():
            key = getattr(handle.server, "_engine_key", None)
            if key is None:
                eng = getattr(handle.server, "engine", None)
                key = getattr(eng, "cache_manifest_key", None)
            if key is None and handle.is_fleet:
                key = "fleet:" + ",".join(handle.server.model_ids)
            if key:
                return str(key)
        return "router"

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "ReplicaRouter":
        assert self._thread is None, "router already started"
        self._thread = threading.Thread(target=self._loop,
                                        name="replica-router",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self, timeout: Optional[float] = None) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout)
            self._thread = None

    def _loop(self) -> None:
        while not self._stop.wait(self.config.tick_s):
            try:
                self._tick()
            except Exception:  # noqa: BLE001 — the tick is advisory
                # (hedges/promotion); it must never take routing down.
                log.exception("router tick failed; continuing")

    # -- introspection -------------------------------------------------------

    @property
    def replica_ids(self) -> List[str]:
        return list(self._handles)

    def handle(self, replica_id: str) -> _Replica:
        return self._handles[replica_id]

    def breaker_of(self, replica_id: str) -> CircuitBreaker:
        return self._handles[replica_id].breaker

    def alive_replicas(self) -> List[str]:
        return [rid for rid, h in self._handles.items() if h.alive]

    def stats_summary(self) -> Dict:
        now = self.clock()
        return {
            "router": self.stats.summary(),
            "replicas": {
                rid: {
                    "alive": h.alive,
                    "breaker": h.breaker.state,
                    "queue_depth": h.depth,
                    "oldest_wait_s": round(h.oldest_wait(now), 4),
                    "hbm_pressure": round(h.pressure, 4),
                    "resident": sorted(h.resident_view()),
                }
                for rid, h in self._handles.items()
            },
        }

    # -- placement -----------------------------------------------------------

    def _pick(self, model_id: str, exclude: Set[str],
              remaining_s: Optional[float] = None) -> Optional[_Replica]:
        """The placement decision: among live replicas whose breaker
        admits traffic (and not in ``exclude``), the lowest-scoring one
        — queue depth, minus the residency bonus when the model's
        weights are already there, plus the SLO term (oldest queued-row
        wait against the request's remaining deadline). Round-robin
        rotation breaks ties so equal replicas share load."""
        now = self.clock()
        with self._lock:
            self._rr += 1
            order = list(self._handles.values())
            order = order[self._rr % len(order):] \
                + order[:self._rr % len(order)]
        cands = [h for h in order
                 if h.alive and h.replica_id not in exclude
                 and h.breaker.allow()]
        if not cands:
            return None

        def score(h: _Replica) -> float:
            s = float(h.depth)
            if model_id and model_id in h.resident_view():
                s -= self.config.residency_bonus
            if self.config.slo_wait_weight > 0 and remaining_s:
                s += (self.config.slo_wait_weight * h.oldest_wait(now)
                      / max(remaining_s, 0.1))
            if self.config.pressure_weight > 0:
                # Memory pressure as a placement input (the HBM
                # governor's gauge): a replica mid-squeeze — ladder
                # walking, batches halved — should absorb LESS new
                # work than an equally-deep replica with headroom.
                s += self.config.pressure_weight * h.pressure
            return s

        return min(cands, key=score)

    def _deadline_for(self, request: ServeRequest) -> float:
        if request.deadline_s is not None:
            return float(request.deadline_s)
        for h in self._handles.values():
            cfg = getattr(h.server, "config", None)
            if cfg is not None and hasattr(cfg, "deadline_for"):
                return float(cfg.deadline_for(request.klass))
        return 300.0

    # -- client side ---------------------------------------------------------

    def submit(self, request: ServeRequest,
               model_id: str = "") -> ServeFuture:
        """Route one request: dedup, place, attempt. The returned
        future resolves exactly once with the first winning payload
        (primary, failover, hedge, or re-admission — whichever answers
        first)."""
        now = self.clock()
        key = content_key(
            self._engine_key if not model_id
            else f"{self._engine_key}|{model_id}", request)
        if self.cache.max_entries > 0:
            hit = self.cache.get(key)
            if hit is not None:
                self.stats.count("dedup_hits")
                self.stats.count("completed")
                fut = ServeFuture()
                fut.resolve(ServeResult(
                    request_id=request.request_id, status=STATUS_OK,
                    cached=True, latency_s=self.clock() - now, **hit))
                return fut
        deadline_s = self._deadline_for(request)
        pending = _Pending(request, model_id, key, now,
                           now + deadline_s)
        with tracing.span("router/route",
                          request_id=request.request_id):
            handle = self._pick(model_id, exclude=set(),
                                remaining_s=deadline_s)
            if handle is None:
                self.stats.count("no_replica_sheds")
                pending.claim_resolution()
                pending.future.resolve(ServeResult(
                    request_id=request.request_id, status=STATUS_SHED,
                    note="no live replica available (all dead or "
                         "breaker-open)"))
                return pending.future
            self.stats.count("routed")
            if model_id and model_id in handle.resident_view():
                self.stats.count("routed_resident")
            with self._lock:
                self._pending[id(pending)] = pending
            self._attempt(pending, handle, "primary")
        return pending.future

    # -- attempt machinery ---------------------------------------------------

    def _attempt(self, pending: _Pending, handle: _Replica,
                 kind: str) -> None:
        with pending.lock:
            pending.tried.add(handle.replica_id)
        handle.track(pending)
        self.stats.placed(handle.replica_id)
        try:
            if handle.is_fleet and pending.model_id:
                inner = handle.server.submit(pending.request,
                                             pending.model_id)
            else:
                inner = handle.server.submit(pending.request)
        except Exception as err:  # noqa: BLE001 — a replica whose
            # submit path itself raises is as dead as one that errors.
            handle.untrack(pending)
            self._on_result(pending, handle, kind, ServeResult(
                request_id=pending.request.request_id,
                status=STATUS_ERROR,
                note=f"replica {handle.replica_id} submit raised: "
                     f"{err!r}"))
            return
        inner.add_done_callback(
            lambda res, p=pending, h=handle, k=kind:
            self._on_result(p, h, k, res))

    def _forget(self, pending: _Pending) -> None:
        with self._lock:
            self._pending.pop(id(pending), None)

    def _on_result(self, pending: _Pending, handle: _Replica,
                   kind: str, res: ServeResult) -> None:
        """One attempt resolved on ``handle`` (runs on the replica's
        resolving thread). Winner resolves the router future and feeds
        the dedup cache; losers are classified (zombie payload / hedge
        loss) and dropped — resolve-once is the double-resolution
        proof."""
        handle.untrack(pending)
        if res.status == STATUS_OK:
            if handle.alive:
                # A DEAD replica's late success must not move its
                # breaker: recovery is the revive + half-open probe's
                # job, not a zombie payload's.
                handle.breaker.record_success()
            if not pending.claim_resolution():
                # A payload for an already-resolved request: the hedge
                # race's loser, or a zombie — late from a replica that
                # was killed (possibly since revived) after the work
                # was re-admitted. Either way it is dropped here —
                # never double-resolved — and the cache.put below is
                # idempotent by content address (replicas are
                # config-identical, so the payload is bitwise the
                # winner's).
                with pending.lock:
                    was_hedged = pending.hedged
                self.stats.count("hedge_losses"
                                 if handle.alive and was_hedged
                                 else "zombie_payloads")
                self.cache.put(pending.key, _payload_of(res))
                return
            self.cache.put(pending.key, _payload_of(res))
            self.stats.count("completed")
            if kind == "hedge":
                self.stats.count("hedge_wins")
            pending.future.resolve(dataclasses.replace(
                res, latency_s=self.clock() - pending.t_submit))
            self._forget(pending)
            return
        if res.status in (STATUS_ERROR, STATUS_SHED):
            if res.status == STATUS_ERROR:
                self.stats.count("replica_errors")
                opened = (handle.breaker.record_failure()
                          if handle.alive else False)
                if opened:
                    log.warning("router: replica %s breaker OPEN "
                                "(cooldown %.1fs)", handle.replica_id,
                                self.config.replica_cooldown_s)
            else:
                self.stats.count("replica_sheds")
            with pending.lock:
                if pending.resolved:
                    return
            now = self.clock()
            remaining = pending.t_deadline - now
            if remaining > 0:
                nxt = self._pick(pending.model_id,
                                 exclude=set(pending.tried),
                                 remaining_s=remaining)
                if nxt is not None:
                    self.stats.count("failovers")
                    tracing.add_span("router/failover", now,
                                     self.clock(),
                                     request_id=pending.request.request_id,
                                     frm=handle.replica_id,
                                     to=nxt.replica_id)
                    self._attempt(pending, nxt, "failover")
                    return
            if not pending.claim_resolution():
                return
            self.stats.count("errors")
            pending.future.resolve(res)
            self._forget(pending)
            return
        # expired/partial statuses resolve through: the deadline is
        # gone — another replica could only answer later still.
        if pending.claim_resolution():
            pending.future.resolve(res)
            self._forget(pending)

    # -- failover ------------------------------------------------------------

    def kill_replica(self, replica_id: str) -> int:
        """A replica observed DEAD (process gone, host lost, chaos
        schedule): force its breaker open, stop placing traffic on it,
        and re-admit its unresolved in-flight requests to survivors —
        exactly once each (the zombie's late payloads are dropped by
        resolve-once + content dedup). Returns how many were
        re-admitted."""
        handle = self._handles[replica_id]
        handle.alive = False
        handle.breaker.trip()
        self.stats.count("kills")
        victims = handle.take_inflight()
        n = 0
        t0 = self.clock()
        for p in victims:
            with p.lock:
                if p.resolved:
                    continue
            nxt = self._pick(p.model_id, exclude={replica_id},
                             remaining_s=max(p.t_deadline - t0, 0.0))
            if nxt is None:
                if p.claim_resolution():
                    self.stats.count("errors")
                    p.future.resolve(ServeResult(
                        request_id=p.request.request_id,
                        status=STATUS_ERROR,
                        note=f"replica {replica_id} died with no "
                             f"survivor to re-admit to"))
                    self._forget(p)
                continue
            n += 1
            self.stats.count("re_admitted")
            self._attempt(p, nxt, "re_admit")
        tracing.add_span("router/replica_kill", t0, self.clock(),
                         replica=replica_id, re_admitted=n)
        log.warning("router: replica %s killed; %d in-flight request(s) "
                    "re-admitted to survivors", replica_id, n)
        return n

    def revive_replica(self, replica_id: str) -> None:
        """The replica rejoined: mark it placeable again. Its breaker
        stays OPEN until the cooldown elapses, so the first request it
        sees is the ordinary half-open probe — success closes the
        breaker, failure re-opens it."""
        handle = self._handles[replica_id]
        handle.alive = True
        self.stats.count("revives")
        log.info("router: replica %s revived (breaker %s; probe after "
                 "cooldown)", replica_id, handle.breaker.state)

    # -- the tick (hedging) --------------------------------------------------

    def _tick(self) -> None:
        now = self.clock()
        # Reading state lazily promotes OPEN -> HALF_OPEN breakers.
        for h in self._handles.values():
            h.breaker.state  # noqa: B018 — promotion side effect
        if self.config.hedge_s <= 0:
            return
        with self._lock:
            pendings = list(self._pending.values())
        for p in pendings:
            remaining = p.t_deadline - now
            if remaining > self.config.hedge_s:
                continue
            with p.lock:
                if p.resolved or p.hedged:
                    continue
                tried = set(p.tried)
            nxt = self._pick(p.model_id, exclude=tried,
                             remaining_s=max(remaining, 0.0))
            if nxt is None:
                continue
            with p.lock:
                if p.resolved or p.hedged:
                    continue
                p.hedged = True
            self.stats.count("hedged")
            tracing.add_span("router/hedge", now, self.clock(),
                             request_id=p.request.request_id,
                             to=nxt.replica_id)
            self._attempt(p, nxt, "hedge")
