"""Elastic multi-replica serving: the failover router (ROADMAP item 1).

A single :class:`~lir_tpu.serve.server.ScoringServer` (or fleet server)
is a single point of failure: PR 5's heartbeat machinery *detects* a
dead peer, but a lost server still costs the run. Production
disaggregated stacks (Mooncake-style separation of placement from
execution, Orca-style continuous batching behind a router) treat replica
death and stragglers as the steady state. This module is that front
process: a :class:`ReplicaRouter` spreads one request stream over N
replica servers, each wrapped in its own router-side
:class:`~lir_tpu.faults.breaker.CircuitBreaker`.

Placement reads three live signals per replica:

- **queue depth** (queue + bucketed rows) — the load signal;
- **breaker state** — a replica that keeps erroring (or was observed
  dead) stops receiving traffic until its cooldown probe;
- **weight residency** — for fleet replicas, the WeightCache's
  ``add_listener`` insert/evict events feed a router-side residency
  map, so a model's requests land on the replica already holding its
  weights (weight residency as a first-class routing signal), with an
  SLO term (the replica's oldest queued-row wait against the request's
  remaining deadline) keeping deadline-tight requests away from stale
  backlogs.

Failover is the headline contract:

- a replica that answers ``error`` (or sheds) triggers re-admission to
  the next-best replica while the deadline allows — ``failovers``;
- a replica KILLED mid-dispatch (:meth:`ReplicaRouter.kill_replica`, or
  a ``replica_kill`` fault schedule) has its in-flight requests
  re-admitted to survivors immediately — ``re_admitted`` — and its
  breaker force-opens (``trip``), so recovery after a rejoin flows
  through the ordinary open -> half_open -> closed probe;
- EXACTLY-ONCE resolution: every request resolves through one
  :class:`~lir_tpu.serve.queue.ServeFuture` (first resolution wins) and
  payloads are content-addressed with the existing ResultCache key, so
  a late payload from a zombie replica can never double-resolve — it is
  counted (``zombie_payloads``) and dropped. Because every replica runs
  the same engine configuration, the winning payload is bitwise the
  payload any replica would have produced (pinned by
  tests/test_router.py) — PAPER.md's axis results cannot depend on
  which replica scored a row;
- requests inside the deadline whisker (``RouterConfig.hedge_s``) are
  HEDGED onto a second replica with first-payload-wins resolution.

Disaggregated prefill/decode (ROADMAP item 2; serve/migrate.py): with
``roles`` splitting the pool into PREFILL-role and DECODE-role replicas
and ``MigrationConfig.enabled``, a long prompt prefills on a prefill
replica, its KV pages stream to the chosen decode replica (chunked,
double-buffered, checksummed), and decode resumes there bitwise-
identically to a colocated run. The cluster-wide prefix index
(engine/prefix_tree.ClusterPrefixIndex, fed by every replica tree's
page listener events exactly like the residency map above) adds PAGE
residency to ``_pick``'s signals — a prefix prefilled anywhere is warm
everywhere, and a request whose pages sit on some replica PULLS them
instead of re-prefilling. A stalled or corrupted transfer falls back to
local re-prefill on the decode replica (``refetch_fallbacks``) — never
a wrong answer.

Everything here is host-side; replicas are ordinary servers (in-process
today — the JSONL/network hop is a transport detail the router's
contract does not depend on).
"""

from __future__ import annotations

import dataclasses
import functools
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Set, Tuple

from ..config import MigrationConfig, RouterConfig
from ..engine import prefix_tree
from ..engine import tokens as tok
from ..faults import CircuitBreaker
from ..observe import registry as metrics_mod
from ..observe import tracing
from ..utils.logging import get_logger
from ..utils.profiling import MigrationStats, RouterStats, ServeStats
from . import migrate as migrate_mod
from .cache import ResultCache, content_key
from .queue import (STATUS_ERROR, STATUS_OK, STATUS_SHED, ServeFuture,
                    ServeRequest, ServeResult)

log = get_logger(__name__)

# The measurement fields a payload carries — what the router's dedup
# cache stores and what ServeResult(**payload) re-expands (the same
# projection ScoringServer._resolve_ok caches).
PAYLOAD_FIELDS = ("model_response", "model_confidence_response",
                  "token_1_prob", "token_2_prob", "log_probabilities",
                  "confidence_value", "weighted_confidence")


def _payload_of(res: ServeResult) -> Dict:
    return {f: getattr(res, f) for f in PAYLOAD_FIELDS}


class _Replica:
    """Router-side state for one replica server."""

    def __init__(self, replica_id: str, server, breaker: CircuitBreaker,
                 role: str = "both"):
        assert role in ("prefill", "decode", "both"), role
        self.replica_id = replica_id
        self.server = server
        self.breaker = breaker
        self.alive = True
        # Disaggregated serving (serve/migrate.py): "prefill" replicas
        # absorb long-prompt prefill-only dispatches and receive decode
        # traffic only as a last resort (every decode-capable replica
        # dead); "decode"/"both" replicas serve scoring traffic.
        self.role = role
        self.is_fleet = hasattr(server, "fleet")
        self._lock = threading.Lock()
        # Requests currently attempted on this replica, by pending id —
        # the re-admission set when this replica dies.
        self.inflight: Dict[int, "_Pending"] = {}  # guarded-by: _lock
        # Residency map fed by WeightCache listener events (may fire
        # under the cache lock: cheap set ops only).
        self.resident: Set[str] = set()  # guarded-by: _lock

    def seed_resident(self, models) -> None:
        with self._lock:
            self.resident = set(models)

    def on_weight_event(self, event: str, model_id: str) -> None:
        with self._lock:
            if event == "insert":
                self.resident.add(model_id)
            elif event == "evict":
                self.resident.discard(model_id)

    def resident_view(self) -> Set[str]:
        with self._lock:
            return set(self.resident)

    def track(self, pending: "_Pending") -> None:
        with self._lock:
            self.inflight[id(pending)] = pending

    def untrack(self, pending: "_Pending") -> None:
        with self._lock:
            self.inflight.pop(id(pending), None)

    def take_inflight(self) -> List["_Pending"]:
        with self._lock:
            victims = list(self.inflight.values())
            self.inflight.clear()
        return victims

    @property
    def depth(self) -> int:
        try:
            return int(self.server.queue_depth)
        except Exception:  # noqa: BLE001 — a dying replica reads as deep
            return 1 << 20

    def oldest_wait(self, now: float) -> float:
        fn = getattr(self.server, "oldest_wait", None)
        if fn is None:
            return 0.0
        try:
            return float(fn(now))
        except Exception:  # noqa: BLE001
            return 0.0

    @property
    def pressure(self) -> float:
        """The replica's HBM-governor ledger pressure (engine/hbm.py) —
        memory as a placement signal beside queue depth and weight
        residency: a squeezed replica is a worse home for new work even
        when its queue looks shallow. 0 when ungoverned/unbounded."""
        try:
            return float(getattr(self.server, "hbm_pressure", 0.0))
        except Exception:  # noqa: BLE001
            return 0.0


class _Pending:
    """One routed request's lifecycle across attempts."""

    __slots__ = ("request", "model_id", "future", "key", "t_submit",
                 "t_deadline", "tried", "hedged", "resolved", "lock")

    def __init__(self, request: ServeRequest, model_id: str, key: str,
                 t_submit: float, t_deadline: float):
        self.request = request
        self.model_id = model_id
        self.future = ServeFuture()
        self.key = key
        self.t_submit = t_submit
        self.t_deadline = t_deadline
        self.tried: Set[str] = set()   # guarded-by: lock
        self.hedged = False            # guarded-by: lock
        self.resolved = False          # guarded-by: lock
        self.lock = threading.Lock()

    def claim_resolution(self) -> bool:
        """True exactly once — the winning attempt's right to resolve."""
        with self.lock:
            if self.resolved:
                return False
            self.resolved = True
            return True


class _Migration:
    """One disaggregated handoff chain's lifecycle (prefill -> export
    -> transfer -> import -> score), claimable exactly once: whichever
    of {chain completion, failure fallback, tick timeout, replica
    kill} claims first decides where the request scores — the others
    become no-ops (a late-landing import merely warms the pool with
    verified pages)."""

    __slots__ = ("pending", "dst", "src", "bucket", "prefix_ids",
                 "dst_tokens", "t_deadline", "_claimed", "_lock")

    def __init__(self, pending: _Pending, dst: "_Replica",
                 src: "_Replica", bucket: int,
                 prefix_ids: Tuple[int, ...], dst_tokens: int,
                 t_deadline: float):
        self.pending = pending
        self.dst = dst
        self.src = src
        self.bucket = int(bucket)
        self.prefix_ids = prefix_ids
        self.dst_tokens = int(dst_tokens)
        self.t_deadline = t_deadline
        self._claimed = False          # guarded-by: _lock
        self._lock = threading.Lock()

    @property
    def claimed(self) -> bool:
        with self._lock:
            return self._claimed

    def claim(self) -> bool:
        with self._lock:
            if self._claimed:
                return False
            self._claimed = True
            return True


class ReplicaRouter:
    """Failover router over N replica servers (module docstring).

    ``replicas`` is ``[(replica_id, server), ...]`` — servers are
    started/stopped by the caller (they may be shared with other
    routers or direct clients); :meth:`start`/:meth:`stop` only own the
    router's tick thread (hedging scans + breaker promotion +
    migration timeouts).

    ``roles`` maps replica ids to "prefill" / "decode" / "both"
    (default "both" — the role-less PR-12 router exactly). With at
    least one prefill-role and one decode-capable replica and
    ``migrate.enabled``, the router serves DISAGGREGATED: long prompts
    prefill on a prefill replica, their KV pages migrate to the chosen
    decode replica (serve/migrate.py), and decode resumes there
    bitwise-identically to a colocated run. The cluster-wide prefix
    index (engine/prefix_tree.ClusterPrefixIndex) is fed by every
    replica tree's page listener events, so a prefix prefilled
    anywhere is warm everywhere — page residency joins weight
    residency and hbm_pressure in :meth:`_pick`, and a request whose
    pages already sit on some replica PULLS them instead of
    re-prefilling.
    """

    def __init__(self, replicas: Sequence[Tuple[str, object]],
                 config: Optional[RouterConfig] = None,
                 stats: Optional[RouterStats] = None,
                 clock: Callable[[], float] = time.monotonic,
                 roles: Optional[Dict[str, str]] = None,
                 migrate: Optional[MigrationConfig] = None,
                 migrate_stats: Optional[MigrationStats] = None):
        assert replicas, "a router needs at least one replica"
        self.config = config or RouterConfig()
        self.stats = stats if stats is not None else RouterStats()
        self.migrate_config = migrate or MigrationConfig()
        self.migrate_stats = (migrate_stats if migrate_stats is not None
                              else MigrationStats())
        self.migrator = migrate_mod.PageMigrator(
            self.migrate_config, self.migrate_stats, clock=clock)
        self.clock = clock
        self._lock = threading.Lock()
        self._handles: Dict[str, _Replica] = {}
        self._pending: Dict[int, _Pending] = {}  # guarded-by: _lock
        self._migrations: Dict[int, _Migration] = {}  # guarded-by: _lock
        self._rr = 0                             # guarded-by: _lock
        roles = dict(roles or {})
        for rid, server in replicas:
            assert rid not in self._handles, f"duplicate replica {rid}"
            breaker = CircuitBreaker(
                failure_threshold=self.config.replica_failure_threshold,
                cooldown_s=self.config.replica_cooldown_s, clock=clock)
            handle = _Replica(str(rid), server, breaker,
                              role=roles.get(str(rid), "both"))
            # Residency map: seed from the current resident set, then
            # ride the WeightCache's insert/evict listener events.
            cache = getattr(getattr(server, "fleet", None), "cache", None)
            if cache is not None and hasattr(cache, "add_listener"):
                resident = getattr(server, "resident_models", None)
                if callable(resident):
                    handle.seed_resident(resident())
                cache.add_listener(handle.on_weight_event)
            # Sentinel gating (observe/sentinel.py): a fleet replica
            # exposes the ROUTER-side breaker so the scheduler pauses
            # sentinel sweeps while the replica is failing over.
            if getattr(server, "breaker", "absent") is None:
                server.breaker = breaker
            self._handles[handle.replica_id] = handle
        # Cluster-wide prefix index (engine/prefix_tree.py): every
        # replica engine's radix tree feeds page insert/evict listener
        # events into ONE router-side index — fed exactly the way the
        # weight-residency map above is fed by WeightCache events — so
        # placement and migration can ask "who holds this prefix's
        # pages?" without touching any replica.
        page_size = 16
        for handle in self._handles.values():
            tree = getattr(getattr(handle.server, "engine", None),
                           "prefix_cache", None)
            if tree is not None:
                page_size = tree.page_size
                break
        self.cluster_tree = prefix_tree.ClusterPrefixIndex(page_size)
        self._have_page_index = False
        for rid, handle in self._handles.items():
            tree = getattr(getattr(handle.server, "engine", None),
                           "prefix_cache", None)
            if tree is not None:
                self._have_page_index = True
                tree.add_listener(
                    functools.partial(self.cluster_tree.on_event, rid))
        # Tier-residency feed (serve/tiers.py): each replica's tiered
        # page store reports host/disk movement into the SAME cluster
        # index under its tier dimension, and a restart-warm replica
        # joining the router announces what its surviving host/disk
        # entries can re-serve (emit_residency) — so placement sees
        # "demoted but promotable here" as warmer than cold.
        self._tier_bonus: Dict[str, float] = {"host": 0.5, "disk": 0.25}
        for rid, handle in self._handles.items():
            store = getattr(handle.server, "tiers", None)
            if store is None:
                continue
            self._tier_bonus = {"host": store.cfg.host_bonus,
                                "disk": store.cfg.disk_bonus}
            store.add_listener(
                functools.partial(self.cluster_tree.on_tier_event, rid))
            store.emit_residency()
        # Router-level content-addressed dedup: the exactly-once
        # backstop. The cache's own ServeStats is private; RouterStats
        # carries the router-visible dedup counter.
        self.cache = ResultCache(self.config.cache_entries, ServeStats())
        self._engine_key = self._derive_engine_key()
        self.metrics = metrics_mod.MetricsRegistry()
        self.metrics.register("router", self.stats)
        self.metrics.register("migrate", self.migrate_stats)
        for rid, handle in self._handles.items():
            rstats = getattr(handle.server, "stats", None)
            if rstats is not None:
                self.metrics.register(f"replica:{rid}:serve", rstats)
        rec = tracing.get_recorder()
        if rec is not None:
            self.metrics.register("trace", rec)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _derive_engine_key(self) -> str:
        for handle in self._handles.values():
            key = getattr(handle.server, "_engine_key", None)
            if key is None:
                eng = getattr(handle.server, "engine", None)
                key = getattr(eng, "cache_manifest_key", None)
            if key is None and handle.is_fleet:
                key = "fleet:" + ",".join(handle.server.model_ids)
            if key:
                return str(key)
        return "router"

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "ReplicaRouter":
        assert self._thread is None, "router already started"
        self._thread = threading.Thread(target=self._loop,
                                        name="replica-router",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self, timeout: Optional[float] = None) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout)
            self._thread = None

    def _loop(self) -> None:
        while not self._stop.wait(self.config.tick_s):
            try:
                self._tick()
            except Exception:  # noqa: BLE001 — the tick is advisory
                # (hedges/promotion); it must never take routing down.
                log.exception("router tick failed; continuing")

    # -- introspection -------------------------------------------------------

    @property
    def replica_ids(self) -> List[str]:
        return list(self._handles)

    def handle(self, replica_id: str) -> _Replica:
        return self._handles[replica_id]

    def breaker_of(self, replica_id: str) -> CircuitBreaker:
        return self._handles[replica_id].breaker

    def alive_replicas(self) -> List[str]:
        return [rid for rid, h in self._handles.items() if h.alive]

    def stats_summary(self) -> Dict:
        now = self.clock()
        return {
            "router": self.stats.summary(),
            "migrate": self.migrate_stats.summary(),
            "replicas": {
                rid: {
                    "alive": h.alive,
                    "role": h.role,
                    "breaker": h.breaker.state,
                    "queue_depth": h.depth,
                    "oldest_wait_s": round(h.oldest_wait(now), 4),
                    "hbm_pressure": round(h.pressure, 4),
                    "resident": sorted(h.resident_view()),
                    "tiers": (h.server.tiers.summary()
                              if getattr(h.server, "tiers", None)
                              is not None else None),
                }
                for rid, h in self._handles.items()
            },
        }

    # -- placement -----------------------------------------------------------

    def _tier_priced(self, bucket: int, prefix: Tuple[int, ...],
                     hbm_match: Dict[str, int]) -> Dict[str, float]:
        """Effective page-equivalents per replica: HBM pages at full
        price plus host/disk tier pages discounted by the tier bonuses
        (TierConfig.host_bonus / disk_bonus) — a demoted prefix is
        warmer than a cold replica, but a promote is not free. Feeds
        :meth:`_pick` only; pull/prefill decisions keep the exact HBM
        match."""
        priced: Dict[str, float] = dict(hbm_match)
        for rid, by_tier in self.cluster_tree.match_tiers(
                bucket, prefix).items():
            for tier, pages in by_tier.items():
                bonus = self._tier_bonus.get(tier, 0.0)
                if bonus:
                    priced[rid] = priced.get(rid, 0) + bonus * pages
        return priced

    def _pick(self, model_id: str, exclude: Set[str],
              remaining_s: Optional[float] = None,
              page_match: Optional[Dict[str, float]] = None
              ) -> Optional[_Replica]:
        """The placement decision: among live replicas whose breaker
        admits traffic (and not in ``exclude``), the lowest-scoring one
        — queue depth, minus the residency bonus when the model's
        weights are already there, MINUS the page-residency bonus per
        cluster-index-matched prefix page (``page_match``, pages per
        replica id — a decode replica already holding the prompt's
        pages wins placement over an equally-loaded cold one), plus the
        SLO term (oldest queued-row wait against the request's
        remaining deadline) and the HBM-pressure penalty. Prefill-role
        replicas receive scoring traffic only when no decode-capable
        replica survives (never a dropped request over role purity).
        Round-robin rotation breaks ties so equal replicas share
        load."""
        now = self.clock()
        with self._lock:
            self._rr += 1
            order = list(self._handles.values())
            order = order[self._rr % len(order):] \
                + order[:self._rr % len(order)]
        cands = [h for h in order
                 if h.alive and h.replica_id not in exclude
                 and h.breaker.allow()]
        decode_capable = [h for h in cands if h.role != "prefill"]
        cands = decode_capable or cands
        if not cands:
            return None

        def score(h: _Replica) -> float:
            s = float(h.depth)
            if model_id and model_id in h.resident_view():
                s -= self.config.residency_bonus
            if page_match:
                # Cluster prefix-tree match as a placement signal
                # (serve/migrate.py): every page already resident on
                # the replica is prefill the dispatch never re-pays.
                s -= (self.migrate_config.page_bonus
                      * page_match.get(h.replica_id, 0))
            if self.config.slo_wait_weight > 0 and remaining_s:
                s += (self.config.slo_wait_weight * h.oldest_wait(now)
                      / max(remaining_s, 0.1))
            if self.config.pressure_weight > 0:
                # Memory pressure as a placement input (the HBM
                # governor's gauge): a replica mid-squeeze — ladder
                # walking, batches halved — should absorb LESS new
                # work than an equally-deep replica with headroom.
                s += self.config.pressure_weight * h.pressure
            return s

        return min(cands, key=score)

    def _pick_prefill(self, exclude: Set[str]) -> Optional[_Replica]:
        """Least-loaded live prefill-role replica (with a page pool to
        export from), or None — the migration chain's prefill leg."""
        cands = [h for h in self._handles.values()
                 if h.alive and h.role == "prefill"
                 and h.replica_id not in exclude and h.breaker.allow()
                 and getattr(getattr(h.server, "engine", None),
                             "prefix_cache", None) is not None]
        if not cands:
            return None
        return min(cands, key=lambda h: h.depth)

    def _disagg_active(self) -> bool:
        """Disaggregated placement is live: migration enabled, a page
        index exists, and both a live prefill-role and a live
        decode-capable replica are present."""
        if not (self.migrate_config.enabled and self._have_page_index):
            return False
        have_prefill = any(h.alive and h.role == "prefill"
                           for h in self._handles.values())
        have_decode = any(h.alive and h.role != "prefill"
                          for h in self._handles.values())
        return have_prefill and have_decode

    def _tokenize_prefix(self, request: ServeRequest
                         ) -> Optional[Tuple[Tuple[int, ...], int]]:
        """(shared token prefix, ladder bucket) for the placement /
        migration probes — computed EXACTLY the way the replica's own
        admission computes them (ScoringServer._submit: shared prefix
        of the two format prompts, snapped to the engine's ladder), so
        the cluster index, the migrated pages, and the eventual
        dispatch all speak the same (bucket, ids) namespace. Uses the
        first replica engine with a page pool (replicas are
        config-identical); None when tokenization is unavailable."""
        for h in self._handles.values():
            eng = getattr(h.server, "engine", None)
            if eng is None or getattr(eng, "prefix_cache", None) is None:
                continue
            try:
                with eng._tok_lock:
                    bin_ids = [int(i) for i in eng.tokenizer(
                        request.binary_prompt).input_ids]
                    conf_ids = [int(i) for i in eng.tokenizer(
                        request.confidence_prompt).input_ids]
            except Exception:  # noqa: BLE001 — probe only; the replica
                # will tokenize (and fail loudly) at admission.
                return None
            lcp = tok.shared_prefix_len(bin_ids, conf_ids)
            if lcp <= 0:
                return None
            bucket = tok.assign_bucket(max(lcp, 1), eng.buckets)
            return tuple(bin_ids[:lcp]), int(bucket)
        return None

    def _deadline_for(self, request: ServeRequest) -> float:
        if request.deadline_s is not None:
            return float(request.deadline_s)
        for h in self._handles.values():
            cfg = getattr(h.server, "config", None)
            if cfg is not None and hasattr(cfg, "deadline_for"):
                return float(cfg.deadline_for(request.klass))
        return 300.0

    # -- client side ---------------------------------------------------------

    def submit(self, request: ServeRequest,
               model_id: str = "") -> ServeFuture:
        """Route one request: dedup, place, attempt. The returned
        future resolves exactly once with the first winning payload
        (primary, failover, hedge, or re-admission — whichever answers
        first)."""
        now = self.clock()
        key = content_key(
            self._engine_key if not model_id
            else f"{self._engine_key}|{model_id}", request)
        if self.cache.max_entries > 0:
            hit = self.cache.get(key)
            if hit is not None:
                self.stats.count("dedup_hits")
                self.stats.count("completed")
                fut = ServeFuture()
                fut.resolve(ServeResult(
                    request_id=request.request_id, status=STATUS_OK,
                    cached=True, latency_s=self.clock() - now, **hit))
                return fut
        deadline_s = self._deadline_for(request)
        pending = _Pending(request, model_id, key, now,
                           now + deadline_s)
        with tracing.span("router/route",
                          request_id=request.request_id):
            # Cluster prefix-tree probe: which replicas already hold
            # this prompt's prefix pages (single-model traffic only —
            # the fleet path keeps its own per-model trees colocated).
            prefix: Optional[Tuple[int, ...]] = None
            bucket = 0
            page_match: Dict[str, int] = {}
            pick_match: Dict[str, float] = {}
            if self._have_page_index and not model_id:
                info = self._tokenize_prefix(request)
                if info is not None:
                    prefix, bucket = info
                    page_match = self.cluster_tree.match_pages(bucket,
                                                               prefix)
                    # Placement prices host/disk-tier pages at a
                    # discount (promotable, not free); migration
                    # decisions below keep the exact HBM-only match —
                    # only HBM pages are exportable.
                    pick_match = self._tier_priced(bucket, prefix,
                                                   page_match)
            handle = self._pick(model_id, exclude=set(),
                                remaining_s=deadline_s,
                                page_match=pick_match or page_match)
            if handle is None:
                self.stats.count("no_replica_sheds")
                pending.claim_resolution()
                pending.future.resolve(ServeResult(
                    request_id=request.request_id, status=STATUS_SHED,
                    note="no live replica available (all dead or "
                         "breaker-open)"))
                return pending.future
            self.stats.count("routed")
            if model_id and model_id in handle.resident_view():
                self.stats.count("routed_resident")
            with self._lock:
                self._pending[id(pending)] = pending
            if prefix is not None and self._disagg_active() \
                    and handle.role != "prefill":
                if self._route_disaggregated(pending, handle, bucket,
                                             prefix, page_match):
                    return pending.future
            self._attempt(pending, handle, "primary")
        return pending.future

    def _route_disaggregated(self, pending: _Pending, dst: _Replica,
                             bucket: int, prefix: Tuple[int, ...],
                             page_match: Dict[str, int]) -> bool:
        """The disaggregation decision for one request (True = a
        migration chain owns it now):

        - prefix fully page-resident on the chosen decode replica —
          route straight there (``cluster_tree_hits``: warm anywhere
          became warm HERE without re-prefilling);
        - some OTHER replica holds at least as many pages as the
          prompt needs — PULL them (export -> transfer -> import), no
          prefill anywhere;
        - prefix long enough (``min_prefix_tokens``) and a prefill
          replica lives — prefill THERE, then pull;
        - otherwise: colocated scoring on the decode replica (the
          handoff would cost more than the prefill it saves)."""
        ps = self.cluster_tree.page_size
        want_pages = len(prefix) // ps
        have = page_match.get(dst.replica_id, 0)
        if want_pages <= 0:
            return False
        if have >= want_pages:
            self.migrate_stats.count("cluster_tree_hits")
            return False                 # already warm on dst: just score
        src: Optional[_Replica] = None
        need_prefill = False
        src_rid, src_pages = self.cluster_tree.best_holder(
            bucket, prefix, exclude=(dst.replica_id,))
        if (src_rid is not None and src_pages >= want_pages
                and self._handles[src_rid].alive
                and getattr(getattr(self._handles[src_rid].server,
                                    "engine", None),
                            "prefix_cache", None) is not None):
            src = self._handles[src_rid]   # warm elsewhere: pure pull
        elif len(prefix) >= self.migrate_config.min_prefix_tokens:
            src = self._pick_prefill(exclude={dst.replica_id})
            need_prefill = src is not None
        if src is None:
            return False
        self._start_migration(pending, dst, src, bucket, prefix,
                              dst_tokens=have * ps,
                              need_prefill=need_prefill)
        return True

    # -- attempt machinery ---------------------------------------------------

    def _attempt(self, pending: _Pending, handle: _Replica,
                 kind: str) -> None:
        with pending.lock:
            pending.tried.add(handle.replica_id)
        handle.track(pending)
        self.stats.placed(handle.replica_id)
        try:
            if handle.is_fleet and pending.model_id:
                inner = handle.server.submit(pending.request,
                                             pending.model_id)
            else:
                inner = handle.server.submit(pending.request)
        except Exception as err:  # noqa: BLE001 — a replica whose
            # submit path itself raises is as dead as one that errors.
            handle.untrack(pending)
            self._on_result(pending, handle, kind, ServeResult(
                request_id=pending.request.request_id,
                status=STATUS_ERROR,
                note=f"replica {handle.replica_id} submit raised: "
                     f"{err!r}"))
            return
        inner.add_done_callback(
            lambda res, p=pending, h=handle, k=kind:
            self._on_result(p, h, k, res))

    def _forget(self, pending: _Pending) -> None:
        with self._lock:
            self._pending.pop(id(pending), None)

    def _on_result(self, pending: _Pending, handle: _Replica,
                   kind: str, res: ServeResult) -> None:
        """One attempt resolved on ``handle`` (runs on the replica's
        resolving thread). Winner resolves the router future and feeds
        the dedup cache; losers are classified (zombie payload / hedge
        loss) and dropped — resolve-once is the double-resolution
        proof."""
        handle.untrack(pending)
        if res.status == STATUS_OK:
            if handle.alive:
                # A DEAD replica's late success must not move its
                # breaker: recovery is the revive + half-open probe's
                # job, not a zombie payload's.
                handle.breaker.record_success()
            if not pending.claim_resolution():
                # A payload for an already-resolved request: the hedge
                # race's loser, or a zombie — late from a replica that
                # was killed (possibly since revived) after the work
                # was re-admitted. Either way it is dropped here —
                # never double-resolved — and the cache.put below is
                # idempotent by content address (replicas are
                # config-identical, so the payload is bitwise the
                # winner's).
                with pending.lock:
                    was_hedged = pending.hedged
                self.stats.count("hedge_losses"
                                 if handle.alive and was_hedged
                                 else "zombie_payloads")
                self.cache.put(pending.key, _payload_of(res))
                return
            self.cache.put(pending.key, _payload_of(res))
            self.stats.count("completed")
            if kind == "hedge":
                self.stats.count("hedge_wins")
            pending.future.resolve(dataclasses.replace(
                res, latency_s=self.clock() - pending.t_submit))
            self._forget(pending)
            return
        if res.status in (STATUS_ERROR, STATUS_SHED):
            if res.status == STATUS_ERROR:
                self.stats.count("replica_errors")
                opened = (handle.breaker.record_failure()
                          if handle.alive else False)
                if opened:
                    log.warning("router: replica %s breaker OPEN "
                                "(cooldown %.1fs)", handle.replica_id,
                                self.config.replica_cooldown_s)
            else:
                self.stats.count("replica_sheds")
            with pending.lock:
                if pending.resolved:
                    return
            now = self.clock()
            remaining = pending.t_deadline - now
            if remaining > 0:
                nxt = self._pick(pending.model_id,
                                 exclude=set(pending.tried),
                                 remaining_s=remaining)
                if nxt is not None:
                    self.stats.count("failovers")
                    tracing.add_span("router/failover", now,
                                     self.clock(),
                                     request_id=pending.request.request_id,
                                     frm=handle.replica_id,
                                     to=nxt.replica_id)
                    self._attempt(pending, nxt, "failover")
                    return
            if not pending.claim_resolution():
                return
            self.stats.count("errors")
            pending.future.resolve(res)
            self._forget(pending)
            return
        # expired/partial statuses resolve through: the deadline is
        # gone — another replica could only answer later still.
        if pending.claim_resolution():
            pending.future.resolve(res)
            self._forget(pending)

    # -- the migration chain (disaggregated handoff; serve/migrate.py) -------

    def _start_migration(self, pending: _Pending, dst: _Replica,
                         src: _Replica, bucket: int,
                         prefix: Tuple[int, ...], dst_tokens: int,
                         need_prefill: bool) -> None:
        """Launch one handoff chain: [prefill on src ->] export(src) ->
        transfer -> import(dst) -> score(dst). Every hop is a page op
        on the owning replica's supervisor thread, linked by completion
        callbacks; the chain deadline (`MigrationConfig.timeout_s`,
        policed by the tick) and every failure path end in
        :meth:`_mig_fallback` — local re-prefill on a decode replica,
        never a wrong or dropped answer."""
        mig = _Migration(pending, dst, src, bucket, prefix, dst_tokens,
                         self.clock() + self.migrate_config.timeout_s)
        with self._lock:
            self._migrations[id(mig)] = mig
        tracing.add_span("router/migrate_start", self.clock(),
                         self.clock(),
                         request_id=pending.request.request_id,
                         src=src.replica_id, dst=dst.replica_id,
                         prefill=need_prefill)
        if need_prefill:
            self.migrate_stats.count("prefill_ops")
            fut = src.server.submit_prefill(bucket, prefix)
            fut.add_done_callback(
                lambda f, m=mig: self._mig_prefilled(m, f))
        else:
            self._mig_export(mig)

    def _mig_prefilled(self, mig: _Migration,
                       fut: migrate_mod.OpFuture) -> None:
        if mig.claimed:
            return
        if fut.error is not None:
            self._mig_fallback(mig, f"prefill failed: {fut.error!r}")
            return
        self._mig_export(mig)

    def _mig_export(self, mig: _Migration) -> None:
        cfg, clock = self.migrate_config, self.clock
        fut = mig.src.server.submit_page_op(
            lambda eng, m=mig: migrate_mod.export_prefix(
                eng, m.bucket, m.prefix_ids, from_token=m.dst_tokens,
                config=cfg, clock=clock))
        fut.add_done_callback(
            lambda f, m=mig: self._mig_exported(m, f))

    def _mig_exported(self, mig: _Migration,
                      fut: migrate_mod.OpFuture) -> None:
        if mig.claimed:
            return
        if fut.error is not None:
            self._mig_fallback(mig, f"export failed: {fut.error!r}")
            return
        export = fut.value
        if export is None:
            self._mig_fallback(
                mig, f"nothing cached to export on {mig.src.replica_id}")
            return
        try:
            # The wire hop — the chaos fault seam (migration_stall
            # sleeps here past the chain deadline; migration_corrupt
            # flips chunk bytes under the checksums).
            export = self.migrator.transfer(export)
        except Exception as err:  # noqa: BLE001 — any wire failure
            # has the same answer: local re-prefill.
            self.migrate_stats.count("stalls")
            self._mig_fallback(mig, f"transfer failed: {err!r}")
            return
        cfg, clock = self.migrate_config, self.clock
        fut2 = mig.dst.server.submit_page_op(
            lambda eng, e=export: migrate_mod.import_prefix(
                eng, e, config=cfg, clock=clock))
        fut2.add_done_callback(
            lambda f, m=mig, e=export: self._mig_imported(m, e, f))

    def _mig_imported(self, mig: _Migration,
                      export: migrate_mod.PageExport,
                      fut: migrate_mod.OpFuture) -> None:
        if fut.error is not None:
            if isinstance(fut.error, migrate_mod.MigrationError) \
                    and "checksum" in str(fut.error):
                self.migrate_stats.count("corrupt_chunks")
            self._mig_fallback(mig, f"import failed: {fut.error!r}")
            return
        if not mig.claim():
            return          # timed out meanwhile; the pages (verified)
            # still landed — the pool is simply warmer for the fallback.
        with self._lock:
            self._migrations.pop(id(mig), None)
        imp = fut.value
        if imp.pages > 0:
            self.migrator.account(export, imp)
        else:
            self.migrate_stats.count("cluster_tree_hits")
        tracing.add_span("router/migrate_done", self.clock(),
                         self.clock(),
                         request_id=mig.pending.request.request_id,
                         pages=int(imp.pages))
        self._attempt(mig.pending, mig.dst, "migrated")

    def _mig_fallback(self, mig: _Migration, reason: str) -> None:
        """Abandon a chain: the request scores with a LOCAL re-prefill
        on the decode replica (or any survivor) — the stalled/corrupt
        transfer cost latency, never correctness."""
        if not mig.claim():
            return
        with self._lock:
            self._migrations.pop(id(mig), None)
        self.migrate_stats.count("refetch_fallbacks")
        log.warning("router: migration abandoned for request %s (%s); "
                    "falling back to local re-prefill",
                    mig.pending.request.request_id, reason)
        dst: Optional[_Replica] = mig.dst
        if not (dst.alive and dst.breaker.allow()):
            dst = self._pick(
                mig.pending.model_id,
                exclude={mig.dst.replica_id},
                remaining_s=max(mig.pending.t_deadline - self.clock(),
                                0.0))
        if dst is None:
            if mig.pending.claim_resolution():
                self.stats.count("errors")
                mig.pending.future.resolve(ServeResult(
                    request_id=mig.pending.request.request_id,
                    status=STATUS_ERROR,
                    note=f"migration failed ({reason}) and no replica "
                         f"survives to re-prefill locally"))
                self._forget(mig.pending)
            return
        self._attempt(mig.pending, dst, "refetch")

    # -- failover ------------------------------------------------------------

    def kill_replica(self, replica_id: str) -> int:
        """A replica observed DEAD (process gone, host lost, chaos
        schedule): force its breaker open, stop placing traffic on it,
        and re-admit its unresolved in-flight requests to survivors —
        exactly once each (the zombie's late payloads are dropped by
        resolve-once + content dedup). Returns how many were
        re-admitted."""
        handle = self._handles[replica_id]
        handle.alive = False
        handle.breaker.trip()
        self.stats.count("kills")
        # Migration chains touching the dead replica fail over NOW
        # (kill-mid-migration): their requests re-prefill locally on a
        # survivor instead of waiting out the chain deadline.
        with self._lock:
            migs = [m for m in self._migrations.values()
                    if replica_id in (m.src.replica_id,
                                      m.dst.replica_id)]
        for m in migs:
            self._mig_fallback(
                m, f"replica {replica_id} died mid-migration")
        victims = handle.take_inflight()
        n = 0
        t0 = self.clock()
        for p in victims:
            with p.lock:
                if p.resolved:
                    continue
            nxt = self._pick(p.model_id, exclude={replica_id},
                             remaining_s=max(p.t_deadline - t0, 0.0))
            if nxt is None:
                if p.claim_resolution():
                    self.stats.count("errors")
                    p.future.resolve(ServeResult(
                        request_id=p.request.request_id,
                        status=STATUS_ERROR,
                        note=f"replica {replica_id} died with no "
                             f"survivor to re-admit to"))
                    self._forget(p)
                continue
            n += 1
            self.stats.count("re_admitted")
            self._attempt(p, nxt, "re_admit")
        tracing.add_span("router/replica_kill", t0, self.clock(),
                         replica=replica_id, re_admitted=n)
        log.warning("router: replica %s killed; %d in-flight request(s) "
                    "re-admitted to survivors", replica_id, n)
        return n

    def revive_replica(self, replica_id: str) -> None:
        """The replica rejoined: mark it placeable again. Its breaker
        stays OPEN until the cooldown elapses, so the first request it
        sees is the ordinary half-open probe — success closes the
        breaker, failure re-opens it."""
        handle = self._handles[replica_id]
        handle.alive = True
        self.stats.count("revives")
        log.info("router: replica %s revived (breaker %s; probe after "
                 "cooldown)", replica_id, handle.breaker.state)

    # -- the tick (hedging) --------------------------------------------------

    def _tick(self) -> None:
        now = self.clock()
        # Reading state lazily promotes OPEN -> HALF_OPEN breakers.
        for h in self._handles.values():
            h.breaker.state  # noqa: B018 — promotion side effect
        # Migration chains past their deadline fall back to local
        # re-prefill (a stalled transfer costs one timeout, not the
        # request — the migration_stall chaos contract).
        with self._lock:
            stale = [m for m in self._migrations.values()
                     if now >= m.t_deadline]
        for m in stale:
            if not m.claimed:
                self.migrate_stats.count("stalls")
                self._mig_fallback(
                    m, f"chain exceeded "
                       f"{self.migrate_config.timeout_s:.1f}s deadline")
        if self.config.hedge_s <= 0:
            return
        with self._lock:
            pendings = list(self._pending.values())
        for p in pendings:
            remaining = p.t_deadline - now
            if remaining > self.config.hedge_s:
                continue
            with p.lock:
                if p.resolved or p.hedged:
                    continue
                tried = set(p.tried)
            nxt = self._pick(p.model_id, exclude=tried,
                             remaining_s=max(remaining, 0.0))
            if nxt is None:
                continue
            with p.lock:
                if p.resolved or p.hedged:
                    continue
                p.hedged = True
            self.stats.count("hedged")
            tracing.add_span("router/hedge", now, self.clock(),
                             request_id=p.request.request_id,
                             to=nxt.replica_id)
            self._attempt(p, nxt, "hedge")
