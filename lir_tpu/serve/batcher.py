"""Continuous batcher: online dispatch formation over the bucket ladder.

The offline ragged scheduler (engine/scheduler.py) plans a KNOWN grid up
front; serving has an arrival process instead, so this module keeps the
same bucket/price machinery but runs it incrementally, Orca-style at
iteration granularity — here the "iteration" is one fused decode scan
(the engine's decode programs are fixed-budget XLA scans, so admission
happens between scans, and freed decode slots are refilled from the queue
when the next dispatch forms):

- **Bucket snapping**: every admitted request was tokenized at submit
  time and snapped to the nearest edge of the SAME precompiled ladder the
  offline sweep uses (tokens.assign_bucket over engine.buckets), so a
  request reuses the sweep's executables — with the boot precompile
  (compile_plan.sweep_specs_for_ladder + serve_batches) no request ever
  triggers a trace.
- **Slot refill**: a dispatch takes up to ``batch_size`` rows from one
  bucket queue; rows whose deadline expired while queued resolve as
  partial results and their slots refill from the same queue, so padding
  never rides where real work is waiting. An UNDERFULL ripe bucket is
  additionally promoted into the next bucket's queue whenever that
  bucket has waiting work and scheduler.bucket_cost says the promoted
  rows riding a fuller dispatch beat a padded tail of their own — the
  offline planner's slot-refill rule, run incrementally.
- **Price-model bucket selection**: among buckets that are ripe (full
  batch, or the oldest row outwaited the linger window), dispatch the one
  with the lowest cost per real row under scheduler.bucket_cost — the
  exact price model the offline planner's slot-refill rule uses, so the
  online and offline policies cannot drift apart.

Per-request results are identical to the offline sweep's for the same
cells (pinned by tests/test_serve.py): the dispatch path is the sweep's
own decode_fused_shared call with the same pretokenized ids, bucket,
suffix edges, budgets, and cache-handoff donation chain.
"""

from __future__ import annotations

import json
import time
from collections import deque
from typing import Callable, Deque, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..engine import scheduler as sched_mod
from ..engine import score as score_mod
from ..engine import tokens as tok
from ..engine.runner import _tail_batch
from ..engine.sweep import _decode_complete, _parse_confidence
from ..observe import tracing
from ..utils.profiling import ServeStats
from .queue import (STATUS_EXPIRED, Pending, ServeResult)


class ContinuousBatcher:
    """Per-bucket queues + dispatch formation + the engine call."""

    def __init__(self, engine, stats: ServeStats, linger_s: float,
                 clock: Callable[[], float] = time.monotonic,
                 pad_full: bool = True, prefix_cache: bool = True):
        self.engine = engine
        self.stats = stats
        self.linger_s = float(linger_s)
        self.clock = clock
        self.pad_full = pad_full
        # Cross-request radix prefix cache (ServeConfig.prefix_cache):
        # dispatches resume shared prefixes from the engine's page pool
        # and insert fresh pages after — reuse across requests AND
        # batches is the serving default. False restores the PR-3
        # behavior (exact-match dedup only).
        self.prefix_cache = bool(prefix_cache
                                 and engine.prefix_cache is not None)
        self.batch = engine.rt.batch_size
        rt = engine.rt
        # Decode budgets: exactly the sweep's derivation (engine/sweep.py)
        # so served scores equal swept scores.
        self.new_tokens = (rt.max_new_tokens if rt.sweep_full_completions
                           else min(rt.sweep_decode_tokens,
                                    rt.max_new_tokens))
        self.conf_tokens = (rt.max_new_tokens if rt.sweep_full_completions
                            else min(rt.sweep_confidence_tokens,
                                     rt.max_new_tokens))
        self.early_stop = (rt.sweep_early_stop
                           and not rt.sweep_full_completions)
        self.decode_cost = self.new_tokens + self.conf_tokens
        # Price dispatches with the engine's kernel mode: the decode
        # floor constant differs between the fused flash-decode kernels,
        # the dense fallback, and the speculative verify windows
        # (scheduler.decode_token_cost).
        self.fused_decode = bool(getattr(rt, "fused_decode", True))
        self.spec_decode = bool(
            getattr(engine, "spec_supported", lambda: False)())
        self._queues: Dict[int, Deque[Pending]] = {
            int(b): deque() for b in engine.buckets}

    # -- queue side ---------------------------------------------------------

    def admit(self, pending: Pending) -> None:
        # Admission runs on the supervisor thread AFTER the loop drains
        # page ops, so a tier promote queued at submit time has already
        # landed in the radix tree — refresh the submit-side advisory
        # hint against the live tree so bucket pricing sees promoted
        # pages as the free prefill they now are (serve/tiers.py).
        if (self.prefix_cache
                and getattr(self.engine, "_tier_store", None) is not None):
            pending.cached_hint = self.engine.prefix_cache.match_len(
                pending.bucket, pending.bin_ids[:pending.lcp])
        self._queues[pending.bucket].append(pending)

    @property
    def pending_rows(self) -> int:
        return sum(len(q) for q in self._queues.values())

    def oldest_wait(self, now: float) -> float:
        """Seconds the OLDEST bucketed row has waited — the router's
        SLO signal (serve/router.py): a replica with a stale backlog is
        a bad home for a deadline-tight request even when its row count
        looks shallow. 0 when nothing is queued."""
        oldest = min((q[0].t_submit for q in self._queues.values() if q),
                     default=None)
        return 0.0 if oldest is None else max(now - oldest, 0.0)

    def snapshot(self) -> List[Pending]:
        """Non-destructive copy of every bucketed entry, bucket order
        (the serve state checkpoint reads this after the supervisor
        loop has stopped — the batcher itself is supervisor-private, so
        no lock is needed once that thread is joined)."""
        return [p for _, q in sorted(self._queues.items()) for p in q]

    def _expire(self, p: Pending, now: float) -> None:
        """Deadline passed while queued: a PARTIAL confidence-free result
        (status only; every measurement field None) instead of failing
        the batch or silently dropping the request."""
        self.stats.count("expired")
        p.future.resolve(ServeResult(
            request_id=p.request.request_id, status=STATUS_EXPIRED,
            note=f"deadline passed before dispatch "
                 f"(waited {now - p.t_submit:.3f}s)",
            latency_s=now - p.t_submit))

    def _batch_cap(self) -> int:
        """Dispatch-row cap: the configured batch, halved while the HBM
        governor's batch_down rung is engaged (engine/hbm.py — smaller
        dispatch caches under pressure; per-row results are unchanged,
        batch composition is masked out of every readout). Restores to
        the full batch when the rung re-arms."""
        gov = getattr(self.engine, "governor", None)
        return self.batch if gov is None else gov.batch_cap(self.batch)

    def _dispatch_rows(self, n: int) -> int:
        """Padded batch rows a dispatch of ``n`` real rows pays for:
        the full batch cap under ``pad_full`` (shape stability), else
        the offline sweep's power-of-two tail."""
        cap = self._batch_cap()
        return cap if self.pad_full else _tail_batch(n, cap)

    def _cascade_trunk(self, rows: List["Pending"], bucket: int) -> int:
        """Shared-trunk tokens the engine's cascade-prefill path would
        dedupe for these queued rows (0 when cascade is off or the rows
        are ineligible). Advisory pricing input only — the dispatch
        itself re-derives eligibility from the same rows, so the price
        model and the routing can never disagree on the discount."""
        fn = getattr(self.engine, "cascade_trunk_for", None)
        if fn is None or len(rows) < 2:
            return 0
        return fn([list(p.bin_ids[:p.lcp]) for p in rows],
                  len(rows), bucket)

    def _decode_trunk(self, rows: List["Pending"], bucket: int) -> int:
        """Shared-trunk tokens the engine's cascade-DECODE path would
        dedupe per decode step for these queued rows (0 when cascade
        decode is off or the rows are ineligible). Advisory pricing
        input, like :meth:`_cascade_trunk` — the dispatch re-derives
        the extent from the same rows."""
        fn = getattr(self.engine, "decode_trunk_for", None)
        if fn is None or len(rows) < 2:
            return 0
        return fn([list(p.bin_ids[:p.lcp]) for p in rows],
                  len(rows), bucket)

    def next_dispatch(self, now: float, flush: bool = False
                      ) -> Optional[Tuple[int, List[Pending]]]:
        """Form the next dispatch, or None when no bucket is ripe. A
        bucket is ripe with a full batch, once its oldest request has
        waited out the linger window, or unconditionally under ``flush``
        (shutdown drain). An underfull ripe bucket promotes into a
        NONEMPTY next bucket when the price model favors it (there must
        be work there to ride — unlike the offline planner, the online
        queue can't assume more same-bucket work is coming)."""
        import time as _time

        t_form = _time.monotonic()
        while True:
            ripe = [edge for edge, q in self._queues.items() if q
                    and (flush or len(q) >= self.batch
                         or now - q[0].t_submit >= self.linger_s)]
            if not ripe:
                return None

            def price(edge: int) -> Tuple[float, float]:
                q = self._queues[edge]
                n = min(len(q), self.batch)
                # Prefix-aware pricing: radix-cached prefix tokens of
                # the rows this dispatch would take are free prefill
                # (advisory submit-time hints; scheduler.bucket_cost).
                cached = (sum(q[i].cached_hint for i in range(n))
                          if self.prefix_cache else 0)
                picked = [q[i] for i in range(n)]
                trunk = self._cascade_trunk(picked, edge)
                dtrunk = self._decode_trunk(picked, edge)
                per_row = sched_mod.bucket_cost(
                    self._dispatch_rows(n), edge, self.batch,
                    self.decode_cost, cached_tokens=cached,
                    fused_decode=self.fused_decode,
                    spec_decode=self.spec_decode,
                    cascade=trunk > 0, trunk_tokens=trunk,
                    decode_trunk_frac=(dtrunk / edge if edge else 0.0)
                    ) / n
                return per_row, q[0].t_submit

            edge = min(ripe, key=price)
            q = self._queues[edge]
            n = len(q)
            if n < self.batch:
                bigger = [b for b in sorted(self._queues) if b > edge]
                nxt = bigger[0] if bigger else None
                if (nxt is not None and self._queues[nxt]
                        and n * nxt < sched_mod.bucket_cost(
                            self._dispatch_rows(n), edge, self.batch,
                            self.decode_cost,
                            fused_decode=self.fused_decode,
                            spec_decode=self.spec_decode)):
                    promoted = [q.popleft() for _ in range(n)]
                    for p in reversed(promoted):
                        self._queues[nxt].appendleft(p)
                    self.stats.count("promoted", n)
                    continue    # re-select (promotion may cascade)
            rows: List[Pending] = []
            cap = self._batch_cap()
            while q and len(rows) < cap:
                p = q.popleft()
                if now >= p.t_deadline:
                    self._expire(p, now)  # slot refills from the queue
                    continue
                rows.append(p)
            if rows:
                # Batch-formation span only when a dispatch actually
                # formed (the idle-poll None path must stay silent).
                tracing.add_span("serve/batch_form", t_form,
                                 _time.monotonic(), bucket=int(edge),
                                 rows=len(rows))
                return edge, rows
            # every candidate row expired — re-scan the other buckets

    def prefill(self, bucket: int,
                prefix_rows: List[Tuple[int, ...]]) -> int:
        """PREFILL-ONLY dispatch (disaggregated serving — serve/migrate
        .py): compute the rows' prefix KV at ``bucket`` and insert full
        pages into this engine's pool + radix tree, decoding nothing.
        Rows are padded exactly the way :meth:`score` pads its batch
        (pad_full / power-of-two tail, repeating the last row) so a
        prefill-role replica's prefill programs share the score path's
        shape discipline — and its page VALUES are bitwise the pages a
        full scoring dispatch would have inserted
        (engine.prefill_insert). Returns the page-aligned tokens
        covered for the first row."""
        n = len(prefix_rows)
        bsz = max(self._dispatch_rows(n), _tail_batch(n, self.batch))
        full = [list(r) for r in prefix_rows]
        full += [list(prefix_rows[-1])] * (bsz - n)
        with tracing.span("serve/prefill", bucket=int(bucket), rows=n):
            return self.engine.prefill_insert(bucket, full)

    def flush_all(self, status: str, note: str) -> int:
        """Resolve every bucketed request with ``status`` (health-flag
        drain); returns how many were flushed."""
        n = 0
        now = self.clock()
        for q in self._queues.values():
            while q:
                p = q.popleft()
                self.stats.count("errors")
                p.future.resolve(ServeResult(
                    request_id=p.request.request_id, status=status,
                    note=note, latency_s=now - p.t_submit))
                n += 1
        return n

    # -- engine side --------------------------------------------------------

    def score(self, bucket: int, rows: List[Pending]) -> List[Dict]:
        """One engine dispatch over ``rows`` (all snapped to ``bucket``),
        mirroring the offline sweep's shared-dispatch path exactly:
        power-of-two tail padding by repeating the last row, per-dispatch
        suffix edges from the shared suffix ladder, pretokenized ids,
        donated KV-cache handoff, position-0 readout. Returns one
        measurement payload per REAL row (padding rows are dropped)."""
        engine = self.engine
        n = len(rows)
        bsz = max(self._dispatch_rows(n), _tail_batch(n, self.batch))
        full = list(rows) + [rows[-1]] * (bsz - n)
        gov = getattr(engine, "governor", None)
        if gov is not None:
            gov.tick()      # one ladder tick per serve dispatch
        t1 = np.asarray([p.t1 for p in full], np.int32)
        t2 = np.asarray([p.t2 for p in full], np.int32)
        la = max(max(len(p.bin_ids) - p.lcp for p in full), 1)
        lb = max(max(len(p.conf_ids) - p.lcp for p in full), 1)
        ba = tok.pick_bucket([la], sched_mod.SUFFIX_BUCKETS)
        bb = tok.pick_bucket([lb], sched_mod.SUFFIX_BUCKETS)
        with tracing.span("serve/dispatch", bucket=int(bucket), rows=n):
            fused, cfused = engine.decode_fused_shared(
                [p.request.binary_prompt for p in full],
                [p.request.confidence_prompt for p in full],
                t1, t2, new_tokens=self.new_tokens,
                conf_tokens=self.conf_tokens, early_stop=self.early_stop,
                pretokenized_a=[list(p.bin_ids) for p in full],
                pretokenized_b=[list(p.conf_ids) for p in full],
                bucket=bucket, sfx_buckets_ab=(ba, bb), reuse_cache=True,
                use_prefix_cache=self.prefix_cache, n_real=n)
            res = score_mod.readout_from_fused(
                fused, jnp.asarray(t1), jnp.asarray(t2), scan_positions=1)
        with tracing.span("serve/readout", bucket=int(bucket), rows=n):
            res_h, lp_vals, lp_ids, gen_host = jax.device_get(
                (res, fused.topk_logprobs, fused.topk_ids,
                 fused.generated))
            wconf, cgen_host = jax.device_get(
                (cfused.weighted_confidence, cfused.generated))
        if self.spec_decode:
            # Prompt-lookup drafting warms itself: record the observed
            # continuations into the radix tree's token history and fold
            # the dispatch's SpecOut counters (we just synchronized on
            # the payload device_get, so the flush costs nothing extra).
            engine.spec_record(bucket, [list(p.bin_ids) for p in full],
                               gen_host, n)
            engine.spec_record(bucket, [list(p.conf_ids) for p in full],
                               cgen_host, n)
            engine.spec_flush()
        payloads: List[Dict] = []
        for j in range(n):
            conf_text = engine.decode_completion(cgen_host[j])
            conf_complete = (engine.rt.sweep_full_completions
                             or _decode_complete(cgen_host[j],
                                                 engine.eos_id))
            payloads.append(dict(
                model_response=engine.decode_completion(gen_host[j]),
                model_confidence_response=conf_text,
                token_1_prob=float(res_h.yes_prob[j]),
                token_2_prob=float(res_h.no_prob[j]),
                log_probabilities=json.dumps({
                    int(i): round(float(v), 6)
                    for i, v in zip(lp_ids[j], lp_vals[j])}),
                confidence_value=_parse_confidence(conf_text,
                                                   conf_complete),
                weighted_confidence=float(wconf[j]),
            ))
        self.stats.add_dispatch(n, bsz)
        return payloads


class FleetBatcher:
    """Per-model dispatch queues over co-resident models — the fleet
    layer's serve seam (engine/fleet.ModelFleet underneath).

    One :class:`ContinuousBatcher` per fleet model keeps the bucket/
    linger/price machinery unchanged per model; this class adds the two
    things a multi-model server needs on top:

    - **Resident-first selection**: among models with a ripe bucket, one
      whose weights are already in HBM dispatches before any model that
      would pay a swap (AlpaServe's statistical-multiplexing insight:
      co-resident models absorb each other's bursts for free). The
      resident scan order rotates per call so equally-loaded resident
      models round-robin instead of the first one starving the rest;
      a non-resident model's rows still age toward their deadlines and
      dispatch as soon as no resident work is ripe.
    - **Swap overlap**: the moment a dispatch is chosen, the next
      NON-resident model with waiting work starts streaming its weights
      in the background (fleet.prefetch), so the swap it will
      eventually pay hides behind this dispatch's device time.

    ``score`` wraps the per-model batcher's dispatch in fleet
    acquire/release, so the LRU weight cache can never evict a model
    mid-dispatch (refcount) and swap timing lands in FleetStats.
    """

    def __init__(self, fleet, stats: ServeStats, linger_s: float,
                 clock: Callable[[], float] = time.monotonic,
                 pad_full: bool = True):
        self.fleet = fleet
        self.stats = stats
        self.clock = clock
        self.batchers: Dict[str, ContinuousBatcher] = {
            mid: ContinuousBatcher(fleet.engine(mid), stats, linger_s,
                                   clock, pad_full=pad_full,
                                   prefix_cache=False)
            for mid in fleet.model_ids}
        self._rr = 0

    def admit(self, pending: Pending) -> None:
        self.batchers[pending.model_id].admit(pending)

    @property
    def pending_rows(self) -> int:
        return sum(b.pending_rows for b in self.batchers.values())

    def oldest_wait(self, now: float) -> float:
        """Oldest queued-row wait across every model's batcher (the
        router's SLO signal — see ContinuousBatcher.oldest_wait)."""
        return max((b.oldest_wait(now) for b in self.batchers.values()),
                   default=0.0)

    def snapshot(self) -> List[Pending]:
        return [p for mid in sorted(self.batchers)
                for p in self.batchers[mid].snapshot()]

    def next_dispatch(self, now: float, flush: bool = False
                      ) -> Optional[Tuple[str, int, List[Pending]]]:
        """(model_id, bucket, rows) of the next dispatch, or None when
        no model has a ripe bucket."""
        mids = list(self.batchers)
        resident = [m for m in mids if self.fleet.resident(m)]
        if resident:
            self._rr = (self._rr + 1) % len(resident)
            resident = resident[self._rr:] + resident[:self._rr]
        rest = [m for m in mids if not self.fleet.resident(m)]
        for mid in resident + rest:
            d = self.batchers[mid].next_dispatch(now, flush=flush)
            if d is None:
                continue
            bucket, rows = d
            for nxt in mids:
                if (nxt != mid and not self.fleet.resident(nxt)
                        and self.batchers[nxt].pending_rows):
                    self.fleet.prefetch(nxt)
                    break
            return mid, bucket, rows
        return None

    def flush_all(self, status: str, note: str) -> int:
        return sum(b.flush_all(status, note)
                   for b in self.batchers.values())

    def score(self, model_id: str, bucket: int,
              rows: List[Pending]) -> List[Dict]:
        """One dispatch on ``model_id``'s engine with its weights held
        resident (fleet refcount) for the duration — and, when
        RuntimeConfig.spec_draft_model names a co-resident model, that
        draft model's weights too (engine/spec.py fleet drafting:
        both refcounts held across the dispatch, so neither side can
        evict the other mid-verify)."""
        engine = self.fleet.acquire(model_id)
        draft_id = self.fleet.acquire_spec_draft(engine, model_id)
        try:
            return self.batchers[model_id].score(bucket, rows)
        finally:
            self.fleet.release_spec_draft(engine, draft_id)
            self.fleet.release(model_id)
