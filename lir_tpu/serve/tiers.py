"""Tiered KV + weight store: HBM -> pinned host DRAM -> local disk.

ROADMAP item 3 (Mooncake's KVCache-centric store, PAPERS.md): every
HBM-pressure response in this engine used to be a *deletion* — a
governor rung or LRU eviction threw radix pages or model weights away,
and the production workload (millions of users re-asking variations of
~5 legal trunks) paid the full prefill or weight-stream bill again.
This module makes those responses reversible *demotions* down a tier
ladder, and makes the bottom tier survive process death:

- **Demotion** (:meth:`TieredPageStore.demote`): the radix tree's
  coldest evictable leaves (``RadixPrefixCache.coldest_leaves``) are
  exported to host chunks through ``serve/migrate.export_prefix`` —
  the SAME chunked double-buffered checksummed transfer discipline the
  disaggregated handoff uses, pointed down-ladder — then their tail
  pages leave HBM via ``evict_tail`` (which REFUSES dispatch-pinned
  pages: refcount discipline survives demotion). The host pool is a
  byte-budgeted LRU; overflow spills to :class:`DiskPageStore`.
- **Promotion** (:meth:`TieredPageStore.promote`): the deepest tier
  match re-enters HBM through ``serve/migrate.import_prefix`` — the
  ordinary paged-warm insert path, per-chunk checksums verified first
  — so promoted pages back dispatches bitwise-identically to pages
  computed in place. A corrupt chunk is refused (``tier_corrupt``
  chaos kind -> ``checksum_refusals``) and a disk read past
  ``TierConfig.disk_timeout_s`` is abandoned (``disk_stall`` ->
  ``disk_stalls``); either way the request re-prefills locally —
  never a wrong answer, never a dropped request.
- **Disk tier** (:class:`DiskPageStore` / :class:`TieredWeightStore`):
  one ``.npz`` per spilled prefix or staged weight tree plus an
  append-only JSONL index riding the manifest ``__meta__`` discipline
  (utils/manifest.SweepManifest): a torn trailing line from a
  kill-mid-spill is detected at load and truncated before the next
  append, so a crash during spill can never poison restart-warm.
- **Restart-warm** (:meth:`TieredPageStore.reseed` /
  :meth:`TieredWeightStore.get`): a restarted server replays the disk
  index, promotes spilled prefixes back into its radix tree, and
  re-stages spilled weight trees — serving warm in seconds instead of
  re-prefilling the whole working set.
- **Fault seam** (:meth:`TieredPageStore.transfer`): the identity hop
  every promote passes through, mirroring ``PageMigrator.transfer`` —
  ``faults.wrap_tiers`` injects the ``tier_corrupt`` / ``disk_stall``
  chaos kinds there.

Movement runs on the owning replica's supervisor thread (demotions
inside governor rung engagements, promotions as page ops), honoring the
radix tree's single-threaded contract; ``match_len`` is the only probe
submit threads touch, and it takes the store's own lock.
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
import time
import zlib
from collections import OrderedDict
from pathlib import Path
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from ..config import MigrationConfig, TierConfig
from ..utils.logging import get_logger
from ..utils.profiling import TierStats
from . import migrate

log = get_logger(__name__)

# Tier names, top to bottom. "hbm" lives in the radix tree/page pool;
# this module owns the other two.
TIER_HBM, TIER_HOST, TIER_DISK = "hbm", "host", "disk"

# Tier residency events (the cluster index rides these beside the
# radix tree's PageListener events): fn(event, tier, bucket, ids) with
# event "insert"/"evict" and tier "host"/"disk".
TierListener = Callable[[str, str, int, Tuple[int, ...]], None]

_Key = Tuple[int, Tuple[int, ...]]


def _fsync_dir(path: Path) -> None:
    """Durable directory entry (atomic_write/SweepManifest discipline)."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


class _TierIndex:
    """Append-only JSONL index with the SweepManifest kill-mid-append
    discipline: a ``{"__meta__": ...}`` first line, one JSON record per
    append, fsync per append, and a torn trailing line (the process
    died mid-write) detected at load and truncated before the next
    append — never raised past the constructor, never replayed."""

    def __init__(self, path: Path, meta: Dict[str, Any]):
        self.path = Path(path)
        self.records: List[Dict[str, Any]] = []
        self._truncate_to: Optional[int] = None
        if self.path.exists():
            self._load()
        else:
            self.path.parent.mkdir(parents=True, exist_ok=True)
            with open(self.path, "wb") as f:
                f.write(json.dumps({"__meta__": meta}).encode() + b"\n")
                f.flush()
                os.fsync(f.fileno())
            _fsync_dir(self.path.parent)

    def _load(self) -> None:
        raw = self.path.read_bytes()
        pos = 0
        for chunk in raw.split(b"\n"):
            start = pos
            pos += len(chunk) + 1
            if not chunk.strip():
                continue
            try:
                rec = json.loads(chunk)
            except (ValueError, UnicodeDecodeError):
                # Torn tail from a kill mid-append: everything after it
                # must be whitespace, else the file is really corrupt.
                rest = raw[start:].split(b"\n")
                if all(not c.strip() for c in rest[1:]):
                    self._truncate_to = start
                    log.warning("tier index %s: torn trailing line "
                                "truncated at byte %d", self.path, start)
                    break
                raise
            if "__meta__" in rec:
                continue
            self.records.append(rec)

    def append(self, record: Dict[str, Any]) -> None:
        with open(self.path, "r+b") as f:
            if self._truncate_to is not None:
                f.truncate(self._truncate_to)
                self._truncate_to = None
            f.seek(0, os.SEEK_END)
            f.write(json.dumps(record).encode() + b"\n")
            f.flush()
            os.fsync(f.fileno())
        self.records.append(record)


def _key_of(bucket: int, ids) -> _Key:
    return int(bucket), tuple(int(t) for t in ids)


def _lcp_tokens(entry_ids: Tuple[int, ...], ids, page_size: int) -> int:
    """Page-aligned longest common prefix between a stored prefix and a
    request's token ids — what a promote of this entry could warm."""
    n = min(len(entry_ids), len(ids))
    lcp = 0
    while lcp < n and int(ids[lcp]) == entry_ids[lcp]:
        lcp += 1
    return (lcp // page_size) * page_size


# ---------------------------------------------------------------------------
# Disk tier: one .npz per prefix + the append-only index
# ---------------------------------------------------------------------------


class DiskPageStore:
    """On-disk page store for spilled :class:`~.migrate.PageExport`
    payloads. Each entry is one ``.npz`` (chunk leaves flattened in
    ``jax.tree.leaves`` order — the promote side unflattens against the
    destination pool's own treedef) plus one index record carrying the
    export's metadata and checksums. Oldest entries drop past the byte
    budget (file unlinked, tombstone appended). Single-writer by
    contract (the owning TieredPageStore's lock)."""

    def __init__(self, root: Path, budget_bytes: int, page_size: int):
        self.root = Path(root)
        self.root.mkdir(parents=True, exist_ok=True)
        self.budget_bytes = int(budget_bytes)
        self._index = _TierIndex(self.root / "index.jsonl",
                                 meta={"version": 1, "kind": "pages",
                                       "page_size": int(page_size)})
        self._seq = 0
        # Replay: last put per key wins; tombstones remove.
        self.entries: "OrderedDict[_Key, Dict[str, Any]]" = OrderedDict()
        for rec in self._index.records:
            if "put" in rec:
                meta = rec["put"]
                key = _key_of(meta["bucket"], meta["ids"])
                self.entries.pop(key, None)
                if (self.root / meta["file"]).exists():
                    self.entries[key] = meta
                self._seq = max(self._seq, meta.get("seq", 0))
            elif "del" in rec:
                key = _key_of(rec["del"]["bucket"], rec["del"]["ids"])
                self.entries.pop(key, None)

    @property
    def index_path(self) -> Path:
        return self.root / "index.jsonl"

    def total_bytes(self) -> int:
        return sum(m["nbytes"] for m in self.entries.values())

    def has(self, key: _Key) -> bool:
        return key in self.entries

    def keys(self) -> List[_Key]:
        return list(self.entries)

    def put(self, key: _Key, export: migrate.PageExport) -> int:
        """Spill one export; returns bytes written. The data file lands
        fsynced BEFORE its index record (a crash between the two leaves
        an orphan file, never a record naming a missing file)."""
        import jax

        self._seq += 1
        fname = f"pages-{self._seq:06d}.npz"
        arrays: Dict[str, np.ndarray] = {}
        real: List[int] = []
        n_leaves = 0
        for ci, (host, n) in enumerate(export.chunks):
            leaves = jax.tree.leaves(host)
            n_leaves = len(leaves)
            real.append(int(n))
            for li, leaf in enumerate(leaves):
                arrays[f"c{ci}_l{li}"] = np.asarray(leaf)
        tmp = self.root / (fname + ".tmp")
        with open(tmp, "wb") as f:
            np.savez(f, **arrays)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.root / fname)
        _fsync_dir(self.root)
        meta = {
            "seq": self._seq, "file": fname,
            "bucket": int(export.bucket), "ids": list(export.ids),
            "start_tokens": int(export.start_tokens),
            "page_size": int(export.page_size),
            "n_pages": int(export.n_pages),
            "chunk_pages": int(export.chunk_pages),
            "real": real, "n_leaves": n_leaves,
            "checksums": [int(c) for c in export.checksums],
            "nbytes": int(export.nbytes),
        }
        old = self.entries.pop(key, None)
        if old is not None:
            self._unlink(old)
        self._index.append({"put": meta})
        self.entries[key] = meta
        nbytes = (self.root / fname).stat().st_size
        self._enforce_budget()
        return int(nbytes)

    def get(self, key: _Key, treedef) -> Optional[migrate.PageExport]:
        """Rebuild one spilled export (chunks unflattened against the
        promoting pool's ``treedef``); None when the entry or its file
        is gone — the caller just re-prefills."""
        import jax

        meta = self.entries.get(key)
        if meta is None:
            return None
        path = self.root / meta["file"]
        try:
            with np.load(path) as z:
                chunks: List[Tuple[Any, int]] = []
                for ci, n in enumerate(meta["real"]):
                    leaves = [z[f"c{ci}_l{li}"]
                              for li in range(meta["n_leaves"])]
                    chunks.append(
                        (jax.tree.unflatten(treedef, leaves), int(n)))
        except Exception as err:  # noqa: BLE001 — np.load's lazy zip
            # reads surface container-level corruption (BadZipFile,
            # zip CRC) here, alongside vanished/truncated files; any
            # unreadable entry drops and the caller re-prefills.
            log.warning("disk tier: unreadable entry %s (%r) — "
                        "dropping", meta["file"], err)
            self.delete(key)
            return None
        return migrate.PageExport(
            bucket=int(meta["bucket"]), ids=tuple(meta["ids"]),
            start_tokens=int(meta["start_tokens"]),
            page_size=int(meta["page_size"]),
            n_pages=int(meta["n_pages"]),
            chunk_pages=int(meta["chunk_pages"]), chunks=chunks,
            checksums=list(meta["checksums"]),
            nbytes=int(meta["nbytes"]))

    def delete(self, key: _Key) -> None:
        meta = self.entries.pop(key, None)
        if meta is None:
            return
        self._unlink(meta)
        self._index.append({"del": {"bucket": key[0],
                                    "ids": list(key[1])}})

    def _unlink(self, meta: Dict[str, Any]) -> None:
        try:
            (self.root / meta["file"]).unlink()
        except OSError:
            pass

    def _enforce_budget(self) -> None:
        while len(self.entries) > 1 and self.total_bytes() > self.budget_bytes:
            key = next(iter(self.entries))    # oldest spill first
            self.delete(key)


# ---------------------------------------------------------------------------
# The tiered page store (per replica)
# ---------------------------------------------------------------------------


class TieredPageStore:
    """The HBM -> host -> disk ladder for one replica's KV radix pages
    (module docstring). Owns the host LRU pool and the disk store;
    attach with ``ScoringEngine.attach_tiers`` so the governor's
    ``evict_pages`` rung demotes instead of deleting."""

    def __init__(self, config: Optional[TierConfig] = None,
                 stats: Optional[TierStats] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.cfg = config or TierConfig(enabled=True)
        self.stats = stats if stats is not None else TierStats()
        self.clock = clock
        self._lock = threading.RLock()
        self._host: "OrderedDict[_Key, migrate.PageExport]" = OrderedDict()
        self._host_bytes = 0
        self._listeners: List[TierListener] = []
        # Export/import run with the migration discipline's defaults;
        # only the verify switch is the tier store's own.
        self._mig_cfg = MigrationConfig(verify=self.cfg.verify)
        self.disk: Optional[DiskPageStore] = None
        if self.cfg.disk_dir:
            self.disk = DiskPageStore(
                Path(self.cfg.disk_dir) / "pages",
                self.cfg.disk_budget_bytes,
                page_size=0)
            self.stats.gauge("disk_bytes", self.disk.total_bytes())

    # -- events --------------------------------------------------------------

    def add_listener(self, fn: TierListener) -> None:
        """Subscribe to tier insert/evict events (``TierListener``
        contract) — the router feeds them into the cluster prefix
        index's tier dimension."""
        self._listeners.append(fn)

    def _notify(self, event: str, tier: str, bucket: int,
                ids: Tuple[int, ...]) -> None:
        for fn in list(self._listeners):
            try:
                fn(event, tier, int(bucket), ids)
            except Exception:  # noqa: BLE001 — an index listener must
                # never take the tier store down with it.
                log.exception("tier listener failed (%s/%s)", event, tier)

    def emit_residency(self) -> None:
        """Re-fire "insert" for every current entry — a restarted
        replica rejoining a router announces its disk-tier residency."""
        with self._lock:
            host = list(self._host)
            disk = self.disk.keys() if self.disk is not None else []
        for bucket, ids in host:
            self._notify("insert", TIER_HOST, bucket, ids)
        for bucket, ids in disk:
            self._notify("insert", TIER_DISK, bucket, ids)

    # -- the fault seam ------------------------------------------------------

    def transfer(self, export: migrate.PageExport) -> migrate.PageExport:
        """The hop every promote passes through on its way back toward
        HBM (PageMigrator.transfer's sibling, pointed up-ladder). In
        process: a no-op. ``faults.wrap_tiers`` wraps it —
        ``tier_corrupt`` flips chunk bytes under the checksums,
        ``disk_stall`` sleeps past ``disk_timeout_s``."""
        return export

    # -- probes --------------------------------------------------------------

    def _best_entry(self, bucket: int, ids
                    ) -> Tuple[Optional[_Key], str, int]:
        """(key, tier, lcp tokens) of the deepest stored match — host
        beats disk at equal depth (cheaper promote)."""
        best: Tuple[Optional[_Key], str, int] = (None, TIER_HOST, 0)
        with self._lock:
            for (b, eids), export in self._host.items():
                if b != int(bucket):
                    continue
                lcp = _lcp_tokens(eids, ids, export.page_size)
                if lcp > best[2]:
                    best = ((b, eids), TIER_HOST, lcp)
            if self.disk is not None:
                for key, meta in self.disk.entries.items():
                    if key[0] != int(bucket):
                        continue
                    lcp = _lcp_tokens(key[1], ids, meta["page_size"])
                    if lcp > best[2]:
                        best = (key, TIER_DISK, lcp)
        return best

    def match_len(self, bucket: int, ids) -> int:
        """Tokens of ``ids``' leading prefix a promote could warm from
        the host/disk tiers right now — the submit-side probe deciding
        whether to queue a promote op. Advisory (entries can move or
        drop between probe and promote; the promote re-checks)."""
        return self._best_entry(bucket, ids)[2]

    def host_bytes(self) -> int:
        with self._lock:
            return self._host_bytes

    # -- demotion (supervisor thread: governor rung engagements) -------------

    def demote(self, engine, n_pages: Optional[int] = None) -> bool:
        """Demote up to ``n_pages`` of the radix tree's coldest leaves
        to the host tier (the ``evict_pages`` rung's engage when tiers
        are attached). Returns True when any HBM page was actually
        freed — the governor's engage contract."""
        tree = getattr(engine, "prefix_cache", None)
        if tree is None:
            return False
        want = int(n_pages or self.cfg.demote_pages_per_step)
        freed = 0
        for bucket, ids in tree.coldest_leaves(limit=max(8, want)):
            if freed >= want:
                break
            freed += self.demote_prefix(engine, bucket, ids,
                                        max_pages=want - freed)
        return freed > 0

    def demote_prefix(self, engine, bucket: int, ids,
                      max_pages: int = 0) -> int:
        """Demote one cached prefix: export the full path to host
        chunks, then evict its tail pages from HBM (``evict_tail``
        refuses pinned pages — a refused demotion books
        ``pin_refusals`` and stores nothing). Returns HBM pages
        freed."""
        tree = engine.prefix_cache
        key = _key_of(bucket, ids)
        with self._lock:
            stored = (key in self._host
                      or (self.disk is not None and self.disk.has(key)))
        export = None
        if not stored:
            export = migrate.export_prefix(engine, bucket, ids,
                                           config=self._mig_cfg,
                                           clock=self.clock)
            if export is None:
                return 0
        n_pages = max_pages or len(tuple(ids)) // tree.page_size
        removed = tree.evict_tail(bucket, ids, n_pages)
        if removed == 0:
            if tree.match_len(bucket, ids) > 0:
                self.stats.count("pin_refusals")
            return 0
        if export is not None:
            self._put_host(key, export)
        return removed

    def _put_host(self, key: _Key, export: migrate.PageExport) -> None:
        with self._lock:
            old = self._host.pop(key, None)
            if old is not None:
                self._host_bytes -= old.nbytes
            self._host[key] = export
            self._host_bytes += export.nbytes
        self.stats.site("demotions", TIER_HOST)
        self.stats.count("pages_demoted", export.n_pages)
        self._notify("insert", TIER_HOST, key[0], key[1])
        self._enforce_host_budget()
        self.stats.gauge("host_bytes", self.host_bytes())

    def _enforce_host_budget(self) -> None:
        """LRU host overflow spills to disk (or drops without one)."""
        while True:
            with self._lock:
                if (self._host_bytes <= self.cfg.host_budget_bytes
                        or not self._host):
                    break
                key, export = self._host.popitem(last=False)
                self._host_bytes -= export.nbytes
            self._notify("evict", TIER_HOST, key[0], key[1])
            if self.disk is not None:
                with self._lock:
                    nbytes = self.disk.put(key, export)
                self.stats.site("demotions", TIER_DISK)
                self.stats.count("bytes_spilled", nbytes)
                self._notify("insert", TIER_DISK, key[0], key[1])
                self.stats.gauge("disk_bytes", self.disk.total_bytes())

    # -- promotion (supervisor thread: page ops) -----------------------------

    def promote(self, engine, bucket: int, ids) -> int:
        """Promote the deepest stored match of ``ids`` back into HBM
        through the ordinary paged-warm import path. Returns pages
        landed (0: nothing stored, HBM already deeper, checksum
        refused, or disk stalled — the request just prefills)."""
        key, tier, lcp = self._best_entry(bucket, ids)
        if key is None:
            return 0
        tree = getattr(engine, "prefix_cache", None)
        if tree is None or lcp <= tree.match_len(bucket, ids):
            return 0
        return self._promote_entry(engine, key, tier)

    def _promote_entry(self, engine, key: _Key, tier: str) -> int:
        tree = engine.prefix_cache
        t0 = self.clock()
        if tier == TIER_HOST:
            with self._lock:
                export = self._host.get(key)
                if export is not None:
                    self._host.move_to_end(key)     # promote = touch
        else:
            import jax

            treedef = jax.tree.structure(tree.pool.leaves)
            with self._lock:
                export = (self.disk.get(key, treedef)
                          if self.disk is not None else None)
        if export is None:
            return 0
        export = self.transfer(export)
        if tier == TIER_DISK and self.clock() - t0 > self.cfg.disk_timeout_s:
            # The watchdog semantics: a disk leg past its deadline is
            # abandoned (the caller re-prefills); the entry stays — a
            # transient stall is not corruption.
            self.stats.count("disk_stalls")
            log.warning("disk tier: read of bucket=%d exceeded %.1fs — "
                        "abandoning promote, re-prefilling",
                        key[0], self.cfg.disk_timeout_s)
            return 0
        try:
            imp = migrate.import_prefix(engine, export,
                                        config=self._mig_cfg,
                                        clock=self.clock)
        except migrate.MigrationError as err:
            if "checksum" in str(err):
                # Poisoned entry: drop it everywhere so it can never be
                # offered again; the request re-prefills.
                self.stats.count("checksum_refusals")
                self.drop(key)
                log.warning("tier promote refused (checksum): %s", err)
            else:
                log.warning("tier promote failed: %s", err)
            return 0
        if imp.pages:
            self.stats.site("promotions", tier)
            self.stats.count("pages_promoted", imp.pages)
            self.stats.count("bytes_promoted", imp.nbytes)
        return imp.pages

    def drop(self, key: _Key) -> None:
        """Remove one entry from every tier (poisoned or obsolete)."""
        with self._lock:
            export = self._host.pop(key, None)
            if export is not None:
                self._host_bytes -= export.nbytes
            had_disk = self.disk is not None and self.disk.has(key)
            if had_disk:
                self.disk.delete(key)
        if export is not None:
            self._notify("evict", TIER_HOST, key[0], key[1])
            self.stats.gauge("host_bytes", self.host_bytes())
        if had_disk:
            self._notify("evict", TIER_DISK, key[0], key[1])
            self.stats.gauge("disk_bytes",
                             self.disk.total_bytes() if self.disk else 0)

    # -- restart-warm --------------------------------------------------------

    def reseed(self, engine, max_pages: Optional[int] = None) -> int:
        """Replay the disk index into the engine's radix tree (restart-
        warm boot): every spilled prefix promotes through the ordinary
        verified import path, newest spills first, until the pool or
        ``max_pages`` says stop. Returns pages re-seeded."""
        if self.disk is None or not self.cfg.restart_warm:
            return 0
        total = 0
        for key in reversed(self.disk.keys()):     # newest spill first
            if max_pages is not None and total >= max_pages:
                break
            pages = self._promote_entry(engine, key, TIER_DISK)
            total += pages
            if pages:
                self._notify("insert", TIER_DISK, key[0], key[1])
        if total:
            self.stats.count("restart_pages_reseeded", total)
            log.info("restart-warm: re-seeded %d KV pages from %s",
                     total, self.disk.root)
        return total

    def summary(self) -> Dict[str, object]:
        out = dict(self.stats.summary())
        out["host_entries"] = len(self._host)
        out["disk_entries"] = (len(self.disk.entries)
                               if self.disk is not None else 0)
        return out


# ---------------------------------------------------------------------------
# The tiered weight store (fleet-wide)
# ---------------------------------------------------------------------------


class TieredWeightStore:
    """Disk tier for staged model weight trees (models/weights.py
    ``host_stage`` output: numpy leaves, QuantTensor payload+scale
    preserved). The host tier for weights already exists — the fleet
    keeps each slot's staged tree when ``stage_reloads`` is on — so
    this store adds the legs the fleet lacked: a record that survives
    eviction with staging off, and a restart-warm re-stage that skips
    the original checkpoint read entirely. Entries are one ``.npz``
    per model (path-keyed leaves) plus the same torn-tail-tolerant
    JSONL index the page store rides; every leaf carries a CRC32
    verified at :meth:`get` — a corrupt record is refused and dropped
    (``checksum_refusals``), and the fleet falls back to its ordinary
    cold load."""

    def __init__(self, root: Path,
                 stats: Optional[TierStats] = None,
                 budget_bytes: Optional[int] = None):
        self.root = Path(root)
        self.root.mkdir(parents=True, exist_ok=True)
        self.stats = stats if stats is not None else TierStats()
        self.budget_bytes = budget_bytes
        self._lock = threading.Lock()
        self._index = _TierIndex(self.root / "index.jsonl",
                                 meta={"version": 1, "kind": "weights"})
        self._seq = 0
        self.entries: "OrderedDict[str, Dict[str, Any]]" = OrderedDict()
        for rec in self._index.records:
            if "put" in rec:
                meta = rec["put"]
                self.entries.pop(meta["model"], None)
                if (self.root / meta["file"]).exists():
                    self.entries[meta["model"]] = meta
                self._seq = max(self._seq, meta.get("seq", 0))
            elif "del" in rec:
                self.entries.pop(rec["del"]["model"], None)

    @staticmethod
    def _flatten(staged) -> List[Tuple[str, str, np.ndarray, bool]]:
        """(path, kind, array, dynamic) per leaf — QuantTensor leaves
        contribute a payload and a scale entry each."""
        import jax

        from ..models.quant import QuantTensor

        flat, _ = jax.tree_util.tree_flatten_with_path(
            staged, is_leaf=lambda x: isinstance(x, QuantTensor))
        out: List[Tuple[str, str, np.ndarray, bool]] = []
        for path, leaf in flat:
            name = "/".join(str(getattr(p, "key", getattr(p, "idx", p)))
                            for p in path)
            if isinstance(leaf, QuantTensor):
                out.append((name, "quant_q", np.asarray(leaf.q),
                            bool(leaf.dynamic)))
                out.append((name, "quant_scale", np.asarray(leaf.scale),
                            bool(leaf.dynamic)))
            else:
                out.append((name, "array", np.asarray(leaf), False))
        return out

    def has(self, model_id: str) -> bool:
        with self._lock:
            return str(model_id) in self.entries

    def models(self) -> List[str]:
        with self._lock:
            return list(self.entries)

    def put(self, model_id: str, staged) -> int:
        """Record one staged tree; returns bytes written (0 when the
        model is already recorded — staged trees never change after
        staging, so one record is enough)."""
        model_id = str(model_id)
        with self._lock:
            if model_id in self.entries:
                return 0
            self._seq += 1
            fname = f"weights-{self._seq:06d}.npz"
            leaves = self._flatten(staged)
            arrays = {f"l{i}": arr for i, (_, _, arr, _) in
                      enumerate(leaves)}
            tmp = self.root / (fname + ".tmp")
            with open(tmp, "wb") as f:
                np.savez(f, **arrays)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self.root / fname)
            _fsync_dir(self.root)
            meta = {
                "seq": self._seq, "model": model_id, "file": fname,
                "leaves": [
                    {"path": name, "kind": kind, "dynamic": dyn,
                     "crc": int(zlib.crc32(
                         np.ascontiguousarray(arr).tobytes()))}
                    for name, kind, arr, dyn in leaves],
            }
            self._index.append({"put": meta})
            self.entries[model_id] = meta
            nbytes = (self.root / fname).stat().st_size
        self.stats.site("demotions", "weights")
        self.stats.count("bytes_spilled", int(nbytes))
        return int(nbytes)

    def get(self, model_id: str):
        """Rebuild one staged tree (nested dicts, QuantTensor leaves
        re-assembled), every leaf CRC-verified. None when absent,
        unreadable, or corrupt (corrupt entries are dropped and booked
        as ``checksum_refusals`` — the fleet cold-loads instead)."""
        from ..models.quant import QuantTensor

        model_id = str(model_id)
        with self._lock:
            meta = self.entries.get(model_id)
        if meta is None:
            return None
        try:
            with np.load(self.root / meta["file"]) as z:
                arrays = [z[f"l{i}"] for i in range(len(meta["leaves"]))]
        except FileNotFoundError:
            log.warning("weight tier: entry file vanished for %s",
                        model_id)
            self.delete(model_id)
            return None
        except Exception as err:  # noqa: BLE001 — np.load's lazy zip
            # reads surface container-level corruption (BadZipFile,
            # zip CRC) here, before the per-leaf CRCs get a look — the
            # same refusal: drop the entry, the model cold-loads.
            self.stats.count("checksum_refusals")
            log.warning("weight tier: unreadable/corrupt entry for %s "
                        "(%r) — dropping, cold load", model_id, err)
            self.delete(model_id)
            return None
        for arr, leaf_meta in zip(arrays, meta["leaves"]):
            if zlib.crc32(np.ascontiguousarray(arr).tobytes()) \
                    != leaf_meta["crc"] % 2**32:
                self.stats.count("checksum_refusals")
                log.warning("weight tier: checksum refused for %s "
                            "(leaf %s) — dropping entry, cold load",
                            model_id, leaf_meta["path"])
                self.delete(model_id)
                return None
        tree: Dict[str, Any] = {}
        quants: Dict[str, Dict[str, Any]] = {}
        for arr, leaf_meta in zip(arrays, meta["leaves"]):
            path, kind = leaf_meta["path"], leaf_meta["kind"]
            if kind == "array":
                self._set_path(tree, path, arr)
            else:
                q = quants.setdefault(path,
                                      {"dynamic": leaf_meta["dynamic"]})
                q["q" if kind == "quant_q" else "scale"] = arr
        for path, parts in quants.items():
            self._set_path(tree, path,
                           QuantTensor(q=parts["q"],
                                       scale=parts["scale"],
                                       dynamic=parts["dynamic"]))
        self.stats.site("promotions", "weights")
        return tree

    @staticmethod
    def _set_path(tree: Dict[str, Any], path: str, value) -> None:
        parts = path.split("/")
        node = tree
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = value

    def delete(self, model_id: str) -> None:
        with self._lock:
            meta = self.entries.pop(str(model_id), None)
            if meta is None:
                return
            try:
                (self.root / meta["file"]).unlink()
            except OSError:
                pass
            self._index.append({"del": {"model": str(model_id)}})
