"""Request queue with admission control, per-class deadlines, and
shed-on-overload.

The queue is the serving layer's backpressure boundary: depth is bounded
(``config.ServeConfig.queue_depth``), so memory and worst-case queueing
delay are bounded too. When a submit arrives at a full queue the policy is
deadline-aware: the newcomer is shed UNLESS it is more urgent than the
least-urgent queued request (latest absolute deadline), in which case that
request is shed instead — under overload the queue keeps the work most
likely to still meet its deadline, rather than strict tail-drop.

Everything here is host-side and engine-agnostic; the continuous batcher
(serve/batcher.py) drains admitted entries into per-bucket queues.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from collections import deque
from typing import Callable, Deque, List, Optional, Tuple

from ..utils.profiling import ServeStats

# Result statuses, in order of decreasing happiness.
STATUS_OK = "ok"
STATUS_EXPIRED = "deadline_exceeded"
STATUS_SHED = "shed"
STATUS_ERROR = "error"


@dataclasses.dataclass(frozen=True)
class ServeRequest:
    """One interpretation probe: the two sweep-format prompts of a grid
    cell (grid.GridCell semantics) plus serving metadata. ``deadline_s``
    overrides the request class's default deadline; ``klass`` names a
    deadline class from config.ServeConfig.classes."""

    binary_prompt: str
    confidence_prompt: str
    targets: Tuple[str, str] = ("Yes", "No")
    klass: str = "batch"
    deadline_s: Optional[float] = None
    request_id: str = ""

    def to_record(self) -> dict:
        """JSON-safe dict for the serve state checkpoint."""
        rec = dataclasses.asdict(self)
        rec["targets"] = list(self.targets)
        return rec

    @classmethod
    def from_record(cls, rec: dict) -> "ServeRequest":
        known = {f.name for f in dataclasses.fields(cls)}
        kwargs = {k: v for k, v in rec.items() if k in known}
        kwargs["targets"] = tuple(kwargs.get("targets", ("Yes", "No")))
        return cls(**kwargs)


@dataclasses.dataclass
class ServeResult:
    """What a request resolves to. ``status`` is "ok", or one of the
    graceful degradations: "deadline_exceeded" rows return PARTIAL
    confidence-free results (prompt acknowledged, every measurement field
    None) rather than failing their batch; "shed" rows were refused
    admission; "error" rows hit a device fault that outlived the retry
    policy. ``cached=True`` marks a dedup hit served from the result
    cache without touching the device."""

    request_id: str
    status: str
    model_response: str = ""
    model_confidence_response: str = ""
    token_1_prob: Optional[float] = None
    token_2_prob: Optional[float] = None
    log_probabilities: str = ""
    confidence_value: Optional[int] = None
    weighted_confidence: Optional[float] = None
    cached: bool = False
    latency_s: float = 0.0
    note: str = ""


class ServeFuture:
    """Minimal completion handle (threading.Event + slot): the submitting
    thread blocks in :meth:`result`, the supervisor resolves exactly
    once. No cancellation — the server resolves every admitted request
    with SOME status (that's the graceful-degradation contract).

    ``add_done_callback`` is the router seam (serve/router.py): the
    elastic router reacts to a replica's resolution (forward the
    payload, fail over, drop a zombie/hedge loser) without a waiter
    thread per attempt. First resolution wins remains the contract —
    callbacks registered after resolution fire immediately with the
    winning result; late ``resolve`` calls are dropped and fire
    nothing."""

    def __init__(self) -> None:
        self._done = threading.Event()
        self._result: Optional[ServeResult] = None
        self._lock = threading.Lock()
        self._callbacks: List[Callable[[ServeResult], None]] = []  # guarded-by: _lock

    def resolve(self, result: ServeResult) -> None:
        with self._lock:
            if self._done.is_set():    # first resolution wins
                return
            self._result = result
            self._done.set()
            callbacks, self._callbacks = self._callbacks, []
        for fn in callbacks:           # outside the lock: callbacks may
            fn(result)                 # resolve OTHER futures

    def add_done_callback(
            self, fn: Callable[[ServeResult], None]) -> None:
        """Run ``fn(result)`` when this future resolves (immediately if
        it already has). Callbacks run on the resolving thread — keep
        them short and never block on another future inside one."""
        with self._lock:
            if not self._done.is_set():
                self._callbacks.append(fn)
                return
            result = self._result
        assert result is not None
        fn(result)

    def done(self) -> bool:
        return self._done.is_set()

    def result(self, timeout: Optional[float] = None) -> ServeResult:
        if not self._done.wait(timeout):
            raise TimeoutError("serve request not resolved in time")
        assert self._result is not None
        return self._result


@dataclasses.dataclass
class Pending:
    """An admitted request plus everything the batcher needs, computed
    ONCE at submit time on the caller's thread (tokenization off the
    supervisor's critical path): token ids for both formats, the shared
    prefix split, the snapped ladder bucket, per-request target token
    ids, and the content-address of the result-cache entry."""

    request: ServeRequest
    future: ServeFuture
    t_submit: float
    t_deadline: float
    bin_ids: Tuple[int, ...] = ()
    conf_ids: Tuple[int, ...] = ()
    lcp: int = 0
    bucket: int = 0
    t1: int = 0
    t2: int = 0
    cache_key: str = ""
    # Radix-cached prefix tokens at submit time (engine/prefix_tree.
    # match_len) — ADVISORY: feeds the batcher's prefix-aware
    # bucket_cost pricing; the dispatch re-looks up with a pin.
    cached_hint: int = 0
    # Fleet routing: which model's dispatch queue this row belongs to
    # (serve/batcher.FleetBatcher); "" on single-model servers.
    model_id: str = ""

    @property
    def prefix_len(self) -> int:
        return max(self.lcp, 1)


class RequestQueue:
    """Bounded FIFO with deadline-aware shedding (module docstring)."""

    def __init__(self, maxlen: int, stats: Optional[ServeStats] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.maxlen = int(maxlen)
        self.stats = stats if stats is not None else ServeStats()
        self.clock = clock
        # _lock and _nonempty share one underlying lock; holding either
        # guards the deque (lint/locks.py enforces the annotation).
        self._dq: Deque[Pending] = deque()  # guarded-by: _lock | _nonempty
        self._lock = threading.Lock()
        self._nonempty = threading.Condition(self._lock)

    def __len__(self) -> int:
        with self._lock:
            return len(self._dq)

    def _shed(self, pending: Pending, note: str) -> None:
        self.stats.count("shed")
        pending.future.resolve(ServeResult(
            request_id=pending.request.request_id, status=STATUS_SHED,
            note=note, latency_s=self.clock() - pending.t_submit))

    def offer(self, pending: Pending) -> bool:
        """Admit or shed. Returns True when ``pending`` joined the queue
        (its future will be resolved by the supervisor); False when it
        was shed (its future is already resolved)."""
        with self._nonempty:
            if len(self._dq) < self.maxlen:
                self._dq.append(pending)
                self.stats.count("admitted")
                self.stats.note_queue_depth(len(self._dq))
                self._nonempty.notify()
                return True
            # Full: keep the most-urgent set. Evict the queued request
            # with the LATEST deadline if the newcomer beats it.
            victim = max(self._dq, key=lambda p: p.t_deadline)
            if pending.t_deadline < victim.t_deadline:
                self._dq.remove(victim)
                self._dq.append(pending)
                self.stats.count("admitted")
                self._nonempty.notify()
            else:
                victim = pending
        # resolve outside the lock (victim futures may have waiters)
        self._shed(victim, note="queue full "
                   f"(depth {self.maxlen}) — least-urgent request shed")
        return victim is not pending

    def drain(self) -> List[Pending]:
        """Pop every queued request, FIFO (the supervisor moves them into
        the batcher's bucket queues)."""
        with self._lock:
            out = list(self._dq)
            self._dq.clear()
        return out

    def snapshot(self) -> List[Pending]:
        """Non-destructive copy of the queued entries (the serve state
        checkpoint reads this under SIGTERM)."""
        with self._lock:
            return list(self._dq)

    def wait_nonempty(self, timeout: float) -> bool:
        with self._nonempty:
            if self._dq:
                return True
            return self._nonempty.wait(timeout)

    def kick(self) -> None:
        """Wake a supervisor parked in :meth:`wait_nonempty` without
        enqueueing anything — out-of-band work arrived (a migration
        page op, serve/migrate.py) that the loop should notice now,
        not a poll interval from now."""
        with self._nonempty:
            self._nonempty.notify()

    def flush(self, status: str, note: str) -> int:
        """Resolve every queued request with ``status`` (the drain path
        of the health-flag trip); returns how many were flushed."""
        drained = self.drain()
        now = self.clock()
        for p in drained:
            if status == STATUS_SHED:
                self.stats.count("shed")
            else:
                self.stats.count("errors")
            p.future.resolve(ServeResult(
                request_id=p.request.request_id, status=status, note=note,
                latency_s=now - p.t_submit))
        return len(drained)
