"""Scoring server: the supervisor loop tying queue, cache, and batcher
together into a long-running service.

Lifecycle semantics (the graceful-degradation contract):

- Every admitted request resolves with SOME status. Deadline-exceeded
  rows return partial confidence-free results rather than failing their
  batch; shed rows resolve immediately at submit.
- Device dispatches run under the serve retry policy
  (config.ServeConfig.retry: short, full-jitter, elapsed-capped —
  utils/retry.py) so one transient XLA/runtime hiccup never surfaces to
  clients.
- A dispatch that exhausts its retries enters the DEGRADATION LADDER
  (faults/ladder.py): drop the AOT registry (lazy jit re-trace excludes
  a corrupt precompiled executable), retry the batch once, then bisect
  to isolate poison rows — only the culprit rows resolve as errors, the
  rest are scored, and one pathological request can no longer take its
  neighbors (or, re-queued with new neighbors, the whole service) down.
- After ``max_consecutive_failures`` full dispatch failures in a row the
  CIRCUIT BREAKER opens (faults/breaker.py): the queue drains with error
  results and submits shed — but after ``breaker_cooldown_s`` the
  breaker goes half-open, admits traffic, and probes the device with the
  next dispatch; success closes it (healthy again, no restart needed),
  failure re-opens it for another cooldown. :attr:`healthy` reads the
  breaker, so external supervisors keep their liveness signal.
- On SIGTERM (preemption warning), :meth:`shutdown_checkpoint` stops the
  supervisor WITHOUT finishing the backlog and writes every unresolved
  request to an atomic JSON checkpoint; a restarted server re-submits
  them via :meth:`resume_from_checkpoint` — zero lost requests across a
  preemption, dedup-deduplicated against anything already served.

Dedup rides in front of admission: a submit whose content address is
already cached resolves without touching the queue or the device —
perturbation-style traffic re-asks near-identical questions constantly,
so this is the cheapest capacity the serving layer has.
"""

from __future__ import annotations

import threading
import time
from pathlib import Path
from typing import Callable, Dict, List, Optional, Tuple

from ..config import ServeConfig, TierConfig
from ..engine import compile_plan
from ..engine import hbm
from ..engine import scheduler as sched_mod
from ..engine import stream_stats
from ..engine import tokens as tok
from ..faults import CLOSED, HALF_OPEN, CircuitBreaker, degrade_dispatch
from ..guard import numerics
from ..observe import registry as metrics_mod
from ..observe import tracing
from ..utils.logging import get_logger
from ..utils.manifest import atomic_write_json
from ..utils.profiling import FaultStats, ServeStats
from ..utils.retry import retry_with_exponential_backoff
from . import migrate as migrate_mod
from . import tiers as tiers_mod
from .batcher import ContinuousBatcher, FleetBatcher
from .cache import ResultCache, content_key
from .queue import (STATUS_ERROR, STATUS_EXPIRED, STATUS_OK, STATUS_SHED,
                    Pending, RequestQueue, ServeFuture, ServeRequest,
                    ServeResult)

log = get_logger(__name__)

CHECKPOINT_VERSION = 1


class ScoringServer:
    """Continuous-batching scoring service over one ScoringEngine.

    ``precompile=True`` AOT-compiles every (ladder edge x suffix edge x
    padded batch) shared executable at boot (compile_plan.sweep_specs_
    for_ladder with serve_batches — background threads, lazy-jit
    fallback on any miss), so no request ever pays a trace.
    """

    def __init__(self, engine, model_name: str,
                 config: Optional[ServeConfig] = None,
                 stats: Optional[ServeStats] = None,
                 clock: Callable[[], float] = time.monotonic,
                 precompile: bool = False,
                 tiers: Optional[TierConfig] = None):
        self.engine = engine
        self.model_name = model_name
        self.config = config or ServeConfig()
        self.stats = stats if stats is not None else ServeStats()
        self.clock = clock
        self.queue = RequestQueue(self.config.queue_depth, self.stats,
                                  clock)
        self.cache = ResultCache(self.config.cache_entries, self.stats)
        # Cross-request radix prefix cache (ServeConfig.prefix_cache, ON
        # by default): build the engine's page pool + radix index before
        # the batcher snapshots it; every dispatch then pays prefill
        # only for its rows' unshared suffixes, across requests and
        # batches, with results bitwise-identical to the unpaged path.
        if self.config.prefix_cache:
            engine.enable_prefix_cache()
        self.batcher = ContinuousBatcher(engine, self.stats,
                                         self.config.linger_s, clock,
                                         pad_full=self.config.pad_full,
                                         prefix_cache=self.config.prefix_cache)
        self.faults = FaultStats()
        # Live streaming statistics (engine/stream_stats.ServeStreamSink):
        # every OK-resolved payload folds once (keyed by content
        # address — idempotent across checkpoint/resume and dedup) into
        # a bounded ring, so the `stats` endpoint answers in-progress
        # percentile/kappa estimates mid-run without touching the
        # device. Gated on RuntimeConfig.streaming_stats.
        self.stream = None
        if (getattr(engine.rt, "streaming_stats", False)
                and self.config.stream_window > 0):
            self.stream = stream_stats.ServeStreamSink(
                window=self.config.stream_window)
        self.breaker = CircuitBreaker(
            failure_threshold=self.config.max_consecutive_failures,
            cooldown_s=self.config.breaker_cooldown_s,
            clock=clock, stats=self.faults)
        # Unified telemetry spine (lir_tpu/observe): every stats object
        # this server touches registers into ONE MetricsRegistry, read
        # live by the {"op": "metrics"} JSONL endpoint and logged at
        # exit. The snapshot carries the per-device HBM gauges too, so
        # memory pressure is observable before anything OOMs.
        self.metrics = metrics_mod.MetricsRegistry()
        self.metrics.register("serve", self.stats)
        self.metrics.register("serve_faults", self.faults)
        metrics_mod.engine_registry(engine, sink=self.stream,
                                    registry=self.metrics)
        # Tiered KV residency (serve/tiers.py; config.TierConfig): the
        # governor's reclaim rungs DEMOTE radix pages down the
        # HBM -> pinned-host -> disk ladder instead of deleting them,
        # and a fresh process reseeds its radix tree from the disk tier
        # before taking traffic (restart-warm). Requires the prefix
        # cache — the tiers store PageExports of its radix paths.
        self.tiers: Optional[tiers_mod.TieredPageStore] = None
        if (tiers is not None and tiers.enabled
                and self.config.prefix_cache):
            self.tiers = tiers_mod.TieredPageStore(tiers, clock=clock)
            engine.attach_tiers(self.tiers)
            self.metrics.register("tiers", self.tiers.stats)
            if tiers.restart_warm and self.tiers.disk is not None:
                # Constructor runs before start(): the supervisor
                # thread does not exist yet, so importing into the
                # radix tree here honors its single-thread contract.
                n = self.tiers.reseed(engine)
                if n:
                    log.info("serve: restart-warm — reseeded %d KV "
                             "pages from the disk tier", n)
        rec = tracing.get_recorder()
        if rec is not None:
            self.metrics.register("trace", rec)
        self._engine_key = engine.cache_manifest_key
        # Target-token memo: written from EVERY submitter thread (submit
        # runs client-side), so its mutations take a dedicated lock —
        # racing dict writes are benign under today's GIL but the
        # guarded-by convention is enforced statically (lint/locks.py),
        # not by interpreter implementation details.
        self._memo_lock = threading.Lock()
        self._target_memo: Dict[
            Tuple[str, str], Tuple[int, int]] = {}  # guarded-by: _memo_lock
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._abort = False          # stop WITHOUT draining (checkpoint)
        self._inflight: List[Pending] = []
        # Page ops (serve/migrate.py): tree/pool work queued by the
        # disaggregation router — prefill-only dispatches, page
        # exports, page imports — drained on the supervisor thread
        # ahead of dispatch formation, so every radix-tree touch stays
        # on the one thread the tree's contract allows.
        self._page_lock = threading.Lock()
        self._page_ops: List[migrate_mod.PageOp] = []  # guarded-by: _page_lock
        engine.fresh_handoff()     # fresh donation chain per session
        if precompile and engine.rt.aot_precompile:
            # pad_full pins every dispatch to the full batch shape, so
            # only that shape needs warming; tail mode warms the whole
            # power-of-two grid.
            batches = ((engine.rt.batch_size,) if self.config.pad_full
                       else compile_plan.serve_batches(
                           engine.rt.batch_size))
            specs = compile_plan.sweep_specs_for_ladder(
                engine, sfx_buckets=(8, 16), batches=batches)
            engine.exec_registry = compile_plan.precompile_async(
                engine, specs, max_workers=engine.rt.precompile_workers)
            log.info("serve: precompiling %d executable shapes in the "
                     "background", len(specs))

    @property
    def healthy(self) -> bool:
        """True while the circuit breaker is CLOSED. Half-open (probing
        after a cooldown) reads unhealthy to external supervisors but
        already admits traffic — a probe success flips this back True
        without a restart."""
        return self.breaker.state == CLOSED

    @property
    def queue_depth(self) -> int:
        """Admitted-but-undispatched rows (queue + bucketed) — the
        router's load signal (serve/router.py). Best-effort while the
        supervisor runs; placement only needs relative ordering."""
        return len(self.queue) + self.batcher.pending_rows

    def oldest_wait(self, now: Optional[float] = None) -> float:
        """Oldest bucketed-row wait in seconds (router SLO signal)."""
        return self.batcher.oldest_wait(self.clock() if now is None
                                        else now)

    @property
    def hbm_pressure(self) -> float:
        """HBM-governor ledger pressure (router placement signal —
        serve/router.py; 0.0 when ungoverned/unbounded)."""
        gov = getattr(self.engine, "governor", None)
        return 0.0 if gov is None else float(gov.pressure())

    # -- client side ---------------------------------------------------------

    def _target_ids(self, targets: Tuple[str, str]) -> Tuple[int, int]:
        with self._memo_lock:
            ids = self._target_memo.get(targets)
        if ids is None:
            with self.engine._tok_lock:
                t1, t2 = tok.target_token_ids(
                    self.engine.tokenizer, targets,
                    encoder_decoder=self.engine.encoder_decoder)
            ids = (int(t1), int(t2))
            with self._memo_lock:
                self._target_memo[targets] = ids
        return ids

    def submit(self, request: ServeRequest) -> ServeFuture:
        """Admit one request; returns a future that resolves with a
        ServeResult (possibly immediately: dedup hit, shed, breaker
        open). Tokenization runs here on the caller's thread, keeping
        the supervisor loop on the device's critical path only."""
        with tracing.span("serve/admit", request_id=request.request_id):
            return self._submit(request)

    def _submit(self, request: ServeRequest) -> ServeFuture:
        self.stats.count("submitted")
        fut = ServeFuture()
        now = self.clock()
        key = content_key(self._engine_key, request)
        if self.cache.max_entries > 0:
            hit = self.cache.get(key)
            if hit is not None:
                self.stats.count("completed")
                self.stats.record_latency(self.clock() - now)
                fut.resolve(ServeResult(
                    request_id=request.request_id, status=STATUS_OK,
                    cached=True, latency_s=self.clock() - now, **hit))
                return fut
        if not self.breaker.allow():
            self.stats.count("shed")
            fut.resolve(ServeResult(
                request_id=request.request_id, status=STATUS_SHED,
                note="server unhealthy — circuit breaker open "
                     f"(cooldown {self.config.breaker_cooldown_s:.1f}s)"))
            return fut
        gov = getattr(self.engine, "governor", None)
        if gov is not None and gov.should_shed():
            # Terminal backpressure rung of the HBM degradation ladder
            # (engine/hbm.py): memory is not coming back this tick, so
            # refuse loudly instead of queueing behind it. Re-arms
            # (stops shedding) the moment pressure clears.
            self.stats.count("shed")
            fut.resolve(ServeResult(
                request_id=request.request_id, status=STATUS_SHED,
                note=f"memory pressure — HBM governor shedding "
                     f"(pressure {gov.pressure():.2f}, engaged rungs: "
                     f"{','.join(gov.engaged_rungs())})"))
            return fut
        with self.engine._tok_lock:
            bin_ids = tuple(int(i) for i in self.engine.tokenizer(
                request.binary_prompt).input_ids)
            conf_ids = tuple(int(i) for i in self.engine.tokenizer(
                request.confidence_prompt).input_ids)
        lcp = tok.shared_prefix_len(bin_ids, conf_ids)
        t1, t2 = self._target_ids(tuple(request.targets))
        deadline = (request.deadline_s if request.deadline_s is not None
                    else self.config.deadline_for(request.klass))
        bucket = tok.assign_bucket(max(lcp, 1), self.engine.buckets)
        # Admission-time radix probe (read-only, no pins): how much of
        # this request's shared prefix is already resident — feeds the
        # batcher's prefix-aware bucket pricing; the dispatch re-looks
        # up with a pin.
        cached_hint = 0
        if self.batcher.prefix_cache:
            cached_hint = self.engine.prefix_cache.match_len(
                bucket, bin_ids[:lcp])
            # Tier promote probe: when the host/disk ladder holds a
            # DEEPER prefix than HBM, queue a promote op ahead of this
            # request's dispatch — the ordinary paged-warm import fills
            # exactly the missing tail (bitwise), and the dispatch's
            # pinned re-lookup sees the promoted pages. Advisory like
            # cached_hint: a promote that loses the race (entry
            # dropped, checksum refusal, disk stall) just means plain
            # prefill.
            if self.tiers is not None:
                prefix = bin_ids[:lcp]
                if self.tiers.match_len(bucket, prefix) > cached_hint:
                    store = self.tiers
                    self.submit_page_op(
                        lambda eng: store.promote(eng, bucket, prefix))
        pending = Pending(
            request=request, future=fut, t_submit=now,
            t_deadline=now + deadline, bin_ids=bin_ids, conf_ids=conf_ids,
            lcp=lcp, bucket=bucket,
            t1=t1, t2=t2, cache_key=key, cached_hint=cached_hint)
        self.queue.offer(pending)
        return fut

    # -- supervisor side -----------------------------------------------------

    def start(self) -> "ScoringServer":
        assert self._thread is None, "server already started"
        self._thread = threading.Thread(target=self._loop,
                                        name="serve-supervisor",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self, timeout: Optional[float] = None) -> None:
        """Drain: finish everything queued (flushing partial buckets),
        then stop the supervisor."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout)
            self._thread = None

    def _loop(self) -> None:
        while True:
            stopping = self._stop.is_set()
            if stopping and self._abort:
                return           # checkpoint path: leave the backlog be
            self._drain_page_ops()
            for p in self.queue.drain():
                self.batcher.admit(p)
            d = self.batcher.next_dispatch(self.clock(), flush=stopping)
            if d is None:
                if (stopping and len(self.queue) == 0
                        and self.batcher.pending_rows == 0):
                    return
                # Lingering rows need sub-window wakeups; an idle server
                # can sleep longer (still bounded so stop() is prompt).
                self.queue.wait_nonempty(
                    0.005 if self.batcher.pending_rows else 0.05)
                continue
            self._dispatch(*d)

    def stream_summary(self) -> Dict:
        """Live streaming-statistics estimates (the `stats` endpoint):
        percentile/kappa over the last stream_window served rows. Safe
        from any thread; empty dict when the sink is disabled."""
        if self.stream is None:
            return {}
        return self.stream.summary()

    # -- page ops (disaggregated serving — serve/migrate.py) -----------------

    def submit_page_op(self, fn) -> migrate_mod.OpFuture:
        """Queue ``fn(engine)`` for the supervisor thread (drained
        ahead of dispatch formation each loop turn) — the seam the
        disaggregation router's handoff chain runs page exports/imports
        through, so every tree/pool mutation happens on this server's
        one dispatch thread. Returns the op's completion future
        (callbacks fire on the supervisor thread)."""
        op = migrate_mod.PageOp(fn)
        with self._page_lock:
            self._page_ops.append(op)
        self.queue.kick()            # wake an idle supervisor now
        return op.future

    def submit_prefill(self, bucket: int,
                       prefix_ids) -> migrate_mod.OpFuture:
        """Queue a PREFILL-ONLY dispatch over one token prefix (the
        prefill-role replica's unit of work): compute the prefix KV at
        ``bucket`` and insert full pages into this replica's pool +
        radix tree, decoding nothing (serve/batcher.prefill). The
        future resolves with the page-aligned tokens covered."""
        ids = tuple(int(t) for t in prefix_ids)
        return self.submit_page_op(
            lambda eng: self.batcher.prefill(int(bucket), [ids]))

    def _drain_page_ops(self) -> None:
        while True:
            with self._page_lock:
                if not self._page_ops:
                    return
                op = self._page_ops.pop(0)
            op.run(self.engine)

    def _resolve_ok(self, p: Pending, payload: Dict, now: float) -> None:
        self.cache.put(p.cache_key, payload)
        if self.stream is not None:
            # Fold AFTER the row survived the numerics guard, BEFORE the
            # future resolves — keyed by content address, so a
            # checkpoint-resumed or deadline-cancelled-then-resubmitted
            # row can never fold twice.
            self.stream.fold_payload(p.cache_key,
                                     tuple(p.request.targets), payload)
        latency = now - p.t_submit
        self.stats.count("completed")
        if now > p.t_deadline:
            self.stats.count("late")
        self.stats.record_latency(latency)
        p.future.resolve(ServeResult(
            request_id=p.request.request_id, status=STATUS_OK,
            latency_s=latency, **payload))

    def _resolve_payload(self, p: Pending, payload: Dict,
                         now: float) -> None:
        """One scored row crosses the guard boundary: numerics-invalid
        payloads are QUARANTINED as error:numerics (the ladder's poison-
        row semantics — neighbors untouched, only the corrupt row is
        withheld); rows whose future already resolved (deadline passed
        mid-dispatch — see :meth:`_cancel_expired_inflight`) drop their
        payload; everything else resolves ok."""
        reason = None
        if self.engine.rt.numerics_guard:
            self.engine.guard_stats.site("checked", "serve")
            reason = numerics.check_payload(payload)
        if reason is not None:
            self.engine.guard_stats.quarantine("serve", reason)
            self.stats.count("errors")
            log.warning("numerics guard: quarantined request %s (%s)",
                        p.request.request_id, reason)
            p.future.resolve(ServeResult(
                request_id=p.request.request_id, status=STATUS_ERROR,
                note=f"{numerics.NUMERICS_ERROR} — {reason} "
                     f"(row quarantined by the numerics guard)",
                latency_s=now - p.t_submit))
            return
        if p.future.done():
            return          # expired mid-dispatch; partial already sent
        self._resolve_ok(p, payload, now)

    def _cancel_expired_inflight(self) -> None:
        """Watchdog tick callback, run on the supervisor thread while a
        WATCHED dispatch is on the device: a request whose deadline
        passes mid-dispatch resolves its partial (confidence-free)
        result IMMEDIATELY instead of waiting out the device call — the
        deadline is now enforced against wall time, not against
        whenever the dispatch happens to return."""
        now = self.clock()
        for p in self._inflight:
            if not p.future.done() and now >= p.t_deadline:
                self.stats.count("expired")
                self.engine.guard_stats.count("inflight_cancelled")
                p.future.resolve(ServeResult(
                    request_id=p.request.request_id,
                    status=STATUS_EXPIRED,
                    note=f"deadline passed mid-dispatch (waited "
                         f"{now - p.t_submit:.3f}s; dispatch watched, "
                         f"partial resolved without waiting it out)",
                    latency_s=now - p.t_submit))

    def _dispatch(self, bucket: int, rows) -> None:
        probing = self.breaker.state == HALF_OPEN
        attempts = {"n": 0}
        gov = getattr(self.engine, "governor", None)

        def call():
            attempts["n"] += 1
            try:
                return self.batcher.score(bucket, rows)
            except Exception as err:  # noqa: BLE001 — classified below
                from ..utils.profiling import is_oom_error

                if gov is not None and is_oom_error(err):
                    # Capacity, not transience: lift the OOM out of the
                    # generic retry loop (BaseException marker) so it
                    # reaches the governor's reclaim-and-retry without
                    # burning retries or feeding the breaker.
                    raise hbm.OomSignal(err) from err
                raise

        # Watched executor (guard/watchdog): the dispatch runs on a
        # watched thread priced by the SAME bucket_cost model the
        # batcher formed it with. A hang surfaces DispatchStalled into
        # the retry -> ladder -> breaker path below, and the tick
        # callback resolves deadline-expired rows partial mid-dispatch.
        wd = getattr(self.engine, "watchdog", None)
        if wd is not None and wd.enabled:
            cost = sched_mod.bucket_cost(
                len(rows), bucket, self.engine.rt.batch_size,
                self.batcher.decode_cost,
                fused_decode=self.batcher.fused_decode)
            dispatch_call = lambda: wd.watch(  # noqa: E731
                call, cost=cost, site="serve",
                on_tick=self._cancel_expired_inflight)
        else:
            dispatch_call = call

        # Per-request queue-wait spans: the slice of each row's life
        # between admission and this dispatch forming (t_submit is in
        # the recorder's time.monotonic domain — the serve clock).
        now0 = self.clock()
        for p in rows:
            tracing.add_span("serve/queue_wait", p.t_submit, now0,
                             request_id=p.request.request_id,
                             bucket=int(bucket))
        self._inflight = list(rows)
        try:
            try:
                payloads = retry_with_exponential_backoff(
                    dispatch_call, retry_on=(Exception,),
                    config=self.config.retry,
                    log=lambda m: log.warning("serve dispatch retry: %s",
                                              m),
                    clock=self.clock)
            except (KeyboardInterrupt, SystemExit):
                raise
            except hbm.OomSignal as sig:
                # Device OOM: governor reclaim + ONE retry; the breaker
                # never hears about it either way (capacity is not
                # device death — the same bypass guard/numerics errors
                # get). A second OOM quarantines only this dispatch.
                payloads = self._dispatch_oom(bucket, rows, sig.err,
                                              gov)
                if payloads is None:
                    return
            except Exception as err:  # noqa: BLE001 — degrade, never crash
                self._dispatch_failed(bucket, rows, err, probing)
                return
            if attempts["n"] > 1:
                # Transient fault outlived by the retry policy alone.
                self.faults.count("recovered_dispatches")
            self.breaker.record_success()
            now = self.clock()
            with tracing.span("serve/resolve", rows=len(rows)):
                for p, payload in zip(rows, payloads):
                    self._resolve_payload(p, payload, now)
        finally:
            self._inflight = []

    def _dispatch_oom(self, bucket: int, rows, err: BaseException,
                      gov) -> Optional[List[Dict]]:
        """Serve-path OOM routing (engine/hbm.py): force-engage the
        governor's reclaim rungs and retry the dispatch ONCE against
        the freed headroom. Success returns the payloads (the caller
        resolves them normally — the breaker sees a success). Failure
        quarantines ONLY this dispatch: its rows resolve as errors
        carrying the full ledger arithmetic, and the breaker's
        consecutive-failure count is NOT advanced — an undersized
        budget must not walk the server into an outage drain the way
        three unlucky big dispatches otherwise would."""
        log.warning("serve: dispatch OOMed (%r); routing through the "
                    "HBM governor", err)
        if gov.handle_oom("serve"):
            try:
                payloads = self.batcher.score(bucket, rows)
            except (KeyboardInterrupt, SystemExit):
                raise
            except Exception as err2:  # noqa: BLE001 — quarantined below
                err = err2
                gov.stats.count("oom_exhausted")
            else:
                self.faults.count("recovered_dispatches")
                self.breaker.record_success()
                return payloads
        note = gov.oom_message("serve", err)
        now = self.clock()
        self.stats.count("errors", len(rows))
        log.error("serve: %s", note)
        for p in rows:
            p.future.resolve(ServeResult(
                request_id=p.request.request_id, status=STATUS_ERROR,
                note=note, latency_s=now - p.t_submit))
        return None

    def _dispatch_failed(self, bucket: int, rows, err: BaseException,
                         probing: bool) -> None:
        """Retries exhausted on the full batch: run the degradation
        ladder (unless this was a half-open probe — a probe exists to
        test the device cheaply, not to bisect during an outage), and
        only on TOTAL failure fall through to the breaker."""
        if self.config.degrade_ladder and not probing:
            self.faults.count("degraded_dispatches")
            self.engine.degrade_to_lazy()
            log.warning("serve: dispatch failed after retries (%r); "
                        "degrading AOT registry -> lazy jit and bisecting "
                        "%d rows", err, len(rows))
            results = degrade_dispatch(
                lambda rs: self.batcher.score(bucket, rs), rows,
                log=lambda m: log.warning("serve degrade: %s", m))
            n_ok = sum(r is not None for r in results)
            if n_ok:
                # The device works; the failure was transient or row-
                # local. Culprit rows resolve as errors, neighbors are
                # scored, the breaker sees a success.
                self.faults.count("recovered_dispatches")
                self.breaker.record_success()
                now = self.clock()
                n_poison = 0
                for p, payload in zip(rows, results):
                    if payload is None:
                        n_poison += 1
                        self.stats.count("errors")
                        p.future.resolve(ServeResult(
                            request_id=p.request.request_id,
                            status=STATUS_ERROR,
                            note=f"poison row isolated by the degradation "
                                 f"ladder: {err!r}",
                            latency_s=now - p.t_submit))
                    else:
                        self._resolve_payload(p, payload, now)
                if n_poison:
                    self.faults.count("degraded_rows", n_poison)
                    log.warning("serve: degradation ladder isolated %d "
                                "poison row(s) out of %d; dispatch "
                                "recovered", n_poison, len(rows))
                return
        # Total failure: every row errors, the breaker counts it.
        now = self.clock()
        self.stats.count("errors", len(rows))
        for p in rows:
            p.future.resolve(ServeResult(
                request_id=p.request.request_id, status=STATUS_ERROR,
                note=f"device error after retries: {err!r}",
                latency_s=now - p.t_submit))
        opened = self.breaker.record_failure()
        log.warning("serve: dispatch failed (%d consecutive, breaker %s)"
                    ": %r", self.breaker.consecutive_failures,
                    self.breaker.state, err)
        if opened:
            self._drain_open(err)

    def _drain_open(self, err: BaseException) -> None:
        """The breaker just opened: resolve every waiting request with an
        error result — fail fast and visibly instead of queueing behind
        a device that is not answering. Submits shed until the half-open
        probe succeeds."""
        note = (f"server unhealthy — circuit breaker open after "
                f"{self.breaker.consecutive_failures} consecutive "
                f"dispatch failures: {err!r}")
        n = self.queue.flush(STATUS_ERROR, note)
        n += self.batcher.flush_all(STATUS_ERROR, note)
        log.error("serve: circuit breaker OPEN; drained %d queued "
                  "requests; half-open probe in %.1fs (%s)", n,
                  self.config.breaker_cooldown_s, note)

    # -- crash-consistent shutdown/resume ------------------------------------

    def pending_requests(self) -> List[ServeRequest]:
        """Every admitted-but-unresolved request: queued, bucketed, and
        in-flight rows whose futures have not resolved. Exact once the
        supervisor thread is stopped; best-effort while it runs."""
        pendings = (self.queue.snapshot() + self.batcher.snapshot()
                    + list(self._inflight))
        return [p.request for p in pendings if not p.future.done()]

    def save_checkpoint(self, path) -> int:
        """Atomically write the unresolved-request state (manifest.
        atomic_write_json: tmp + fsync + rename — a kill mid-checkpoint
        leaves the previous checkpoint, never a torn one). Returns the
        number of requests checkpointed."""
        reqs = [r.to_record() for r in self.pending_requests()]
        # Flush the partial streaming accumulator with the checkpoint:
        # the resumed server restores the ring AND the folded-key set,
        # so rows this incarnation already counted (including rows whose
        # deadline passed mid-dispatch and will be re-submitted) are
        # never double-counted on resume.
        atomic_write_json(Path(path), {
            "version": CHECKPOINT_VERSION,
            "model": self.model_name,
            "requests": reqs,
            "stream": (self.stream.state()
                       if self.stream is not None else None),
        })
        return len(reqs)

    def shutdown_checkpoint(self, path, timeout: float = 10.0) -> int:
        """SIGTERM path (preemption warning): stop the supervisor WITHOUT
        working off the backlog — the host has seconds, not minutes —
        then checkpoint every unresolved request. In-flight dispatch
        rows are included iff their futures have not resolved, so a
        request is never both served and checkpointed."""
        self._abort = True
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout)
            self._thread = None
        n = self.save_checkpoint(path)
        log.info("serve: shutdown checkpoint wrote %d pending requests "
                 "-> %s", n, path)
        return n

    def resume_from_checkpoint(self, path) -> List[ServeFuture]:
        """Re-submit every request from a shutdown checkpoint. Requests
        the previous incarnation already served may ride the dedup cache
        (same content address); unserved ones score fresh. Returns the
        futures in checkpoint order."""
        import json

        data = json.loads(Path(path).read_text())
        if self.stream is not None:
            self.stream.restore(data.get("stream"))
        reqs = [ServeRequest.from_record(r)
                for r in data.get("requests", ())]
        log.info("serve: resuming %d checkpointed requests from %s",
                 len(reqs), path)
        return [self.submit(r) for r in reqs]


# ---------------------------------------------------------------------------
# Fleet serving: one question across all resident models (the agreement
# axis as a request class)
# ---------------------------------------------------------------------------


def fleet_decision(token_1_prob, token_2_prob):
    """Binary decision for the agreement statistic — EXACTLY the rule
    the streaming-statistics lattice folds (engine/stream_stats.py:
    yes > no on device == float64 Relative_Prob > 0.5): 1/0, or None
    when the row is invalid (missing/non-finite/zero-total probs), so
    fleet kappa is bitwise-comparable with every other kappa this
    framework reports."""
    import math

    if token_1_prob is None or token_2_prob is None:
        return None
    t1, t2 = float(token_1_prob), float(token_2_prob)
    total = t1 + t2
    if not math.isfinite(total) or total <= 0:
        return None
    return 1 if t1 / total > 0.5 else 0


def aggregate_fleet(request_id: str, results: Dict[str, "ServeResult"],
                    latency_s: float) -> Dict:
    """Fold one fleet_score fan-out's per-model results into the
    agreement payload: per-model P(yes)/P(no)/decision, the within-
    question kappa over the valid decisions — routed through stats/
    streaming.kappa_from_counts, the SAME contingency path the
    streaming sink and the csv pipeline use, so serve-reported kappa is
    bitwise what an offline analysis of the same rows computes — and
    the pairwise disagreement fraction (1 - observed agreement over all
    model pairs)."""
    import numpy as np

    from ..stats import streaming

    per_model: Dict[str, Dict] = {}
    decisions = []
    for mid in sorted(results):
        r = results[mid]
        dec = (fleet_decision(r.token_1_prob, r.token_2_prob)
               if r.status == STATUS_OK else None)
        per_model[mid] = {
            "status": r.status,
            "token_1_prob": r.token_1_prob,
            "token_2_prob": r.token_2_prob,
            "weighted_confidence": r.weighted_confidence,
            "confidence_value": r.confidence_value,
            "decision": dec,
            "cached": r.cached,
        }
        if r.note:
            per_model[mid]["note"] = r.note
        if dec is not None:
            decisions.append(dec)
    n_ok = sum(1 for m in per_model.values()
               if m["status"] == STATUS_OK)
    if decisions:
        n_g, s_g = streaming.group_counts(
            np.zeros(len(decisions), dtype=np.int64),
            np.asarray(decisions, dtype=np.int64))
        kap = streaming.kappa_from_counts(n_g, s_g)
    else:
        kap = {"kappa": float("nan"),
               "observed_agreement": float("nan"),
               "expected_agreement": float("nan")}
    n = len(decisions)
    n_pairs = n * (n - 1) // 2
    disagreement = (1.0 - float(kap["observed_agreement"])
                    if n_pairs > 0 else float("nan"))
    status = (STATUS_OK if n_ok == len(per_model) and per_model
              else "partial" if n_ok else STATUS_ERROR)
    return {
        "request_id": request_id,
        "status": status,
        "n_models": len(per_model),
        "n_valid": n,
        "per_model": per_model,
        "kappa": {k: float(v) for k, v in kap.items()},
        "disagreement": disagreement,
        "latency_s": latency_s,
    }


class FleetScoreFuture:
    """Completion handle for one fleet fan-out: resolves when every
    per-model sub-future has (each with SOME status — the serving
    contract), then aggregates probabilities + agreement."""

    def __init__(self, request_id: str, futures: Dict[str, ServeFuture],
                 t_submit: float,
                 clock: Callable[[], float] = time.monotonic):
        self.request_id = request_id
        self._futures = futures
        self._t_submit = t_submit
        self._clock = clock

    def done(self) -> bool:
        return all(f.done() for f in self._futures.values())

    def result(self, timeout: Optional[float] = None) -> Dict:
        deadline = (None if timeout is None
                    else self._clock() + timeout)
        results: Dict[str, ServeResult] = {}
        for mid, fut in self._futures.items():
            left = (None if deadline is None
                    else max(deadline - self._clock(), 0.0))
            results[mid] = fut.result(left)
        return aggregate_fleet(self.request_id, results,
                               self._clock() - self._t_submit)


class FleetScoringServer:
    """Multiplexed scoring service over a ModelFleet: per-model dispatch
    queues (serve/batcher.FleetBatcher), resident-first selection with
    background weight prefetch, and the ``fleet_score`` request class —
    one question fanned across every fleet model, answered with
    per-model P(yes)/P(no) plus pairwise kappa/disagreement through the
    stats/streaming contingency path.

    Deliberately leaner than :class:`ScoringServer` (which remains the
    single-model production server with breaker/ladder/checkpoint):
    the fleet supervisor keeps the retry policy, deadline expiry, and
    the numerics-guard quarantine boundary — the pieces that shape
    per-row results — and trades the failure-domain machinery for
    model-multiplexing. Per-model results are BITWISE what the same
    request on a single-model ScoringServer over the same engine
    returns (pinned by tests/test_fleet.py): the dispatch path is the
    same ContinuousBatcher.score call on the same engine.
    """

    def __init__(self, fleet, config: Optional[ServeConfig] = None,
                 fleet_deadline_s: float = 60.0,
                 stats: Optional[ServeStats] = None,
                 clock: Callable[[], float] = time.monotonic,
                 tiers: Optional[TierConfig] = None):
        self.fleet = fleet
        self.config = config or ServeConfig()
        self.fleet_deadline_s = float(fleet_deadline_s)
        self.stats = stats if stats is not None else ServeStats()
        self.clock = clock
        self.queue = RequestQueue(self.config.queue_depth, self.stats,
                                  clock)
        self.batcher = FleetBatcher(fleet, self.stats,
                                    self.config.linger_s, clock,
                                    pad_full=self.config.pad_full)
        for mid in fleet.model_ids:
            fleet.engine(mid).fresh_handoff()
        # One ledger for the whole replica (engine/hbm.py): the fleet
        # adopts the first engine's governor so weight residency, page
        # pools, pins and dispatch caches all press on ONE budget — and
        # every member engine reports into it.
        if fleet.governor is None:
            for mid in fleet.model_ids:
                eng = fleet.engine(mid)
                gov = getattr(eng, "governor", None)
                if gov is not None:
                    fleet.attach_governor(gov)
                    break
        if fleet.governor is not None:
            for mid in fleet.model_ids:
                eng = fleet.engine(mid)
                if eng is not None:
                    eng.governor = fleet.governor
        # Unified telemetry spine: the serve counters, the fleet's swap
        # accounting, and every member engine's guard/compile/fault
        # stats in ONE registry ({"op": "metrics"} reads it live).
        self.metrics = metrics_mod.MetricsRegistry()
        self.metrics.register("serve", self.stats)
        self.metrics.register("fleet", fleet.stats)
        if fleet.governor is not None:
            # The shared HBM ledger's gauges ride the metrics endpoint
            # next to device_memory_stats().
            self.metrics.register("mem", fleet.governor.stats)
        for mid in fleet.model_ids:
            eng = fleet.engine(mid)
            if eng is not None:
                self.metrics.register(f"model:{mid}:guard",
                                      eng.guard_stats)
                self.metrics.register(f"model:{mid}:compile",
                                      eng.compile_stats)
        # Tiered weight residency (serve/tiers.TieredWeightStore): the
        # governor's evict_weights rung records each evicted staged
        # tree to disk first (ModelFleet.evict_idle), and a fresh
        # process re-stages every recorded model from disk before
        # taking traffic — restart-warm weights, CRC-checked per leaf.
        self.weight_tiers: Optional[tiers_mod.TieredWeightStore] = None
        if tiers is not None and tiers.enabled and tiers.disk_dir:
            self.weight_tiers = tiers_mod.TieredWeightStore(
                Path(tiers.disk_dir) / "weights")
            fleet.attach_tiers(self.weight_tiers)
            self.metrics.register("tiers", self.weight_tiers.stats)
            if tiers.restart_warm:
                n = fleet.reseed_weights(self.weight_tiers)
                if n:
                    log.info("serve: restart-warm — re-staged %d fleet "
                             "weight trees from the disk tier", n)
        rec = tracing.get_recorder()
        if rec is not None:
            self.metrics.register("trace", rec)
        # Reliability observatory (observe/sentinel.SentinelScheduler):
        # attached by the CLI/bench when a sentinel grid is configured;
        # the stats endpoint then serves its window history + alerts.
        self.observatory = None
        # Optional health gate: the elastic router (serve/router.py)
        # assigns this replica's router-side CircuitBreaker here, and
        # the sentinel scheduler pauses sweeps while it is OPEN (a
        # failover window must not alert as model drift). None = no
        # breaker fronting this server.
        self.breaker = None
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    @property
    def model_ids(self):
        return self.fleet.model_ids

    @property
    def queue_depth(self) -> int:
        """Router load signal — see ScoringServer.queue_depth."""
        return len(self.queue) + self.batcher.pending_rows

    def oldest_wait(self, now: Optional[float] = None) -> float:
        return self.batcher.oldest_wait(self.clock() if now is None
                                        else now)

    @property
    def hbm_pressure(self) -> float:
        """Shared-ledger pressure of this fleet replica (router
        placement signal; 0.0 when ungoverned/unbounded)."""
        gov = self.fleet.governor
        return 0.0 if gov is None else float(gov.pressure())

    def resident_models(self) -> List[str]:
        """Model ids whose weights are currently in this replica's
        WeightCache — the router's residency seed (listener events keep
        it current afterwards)."""
        return [m for m in self.fleet.model_ids if self.fleet.resident(m)]

    # -- client side ---------------------------------------------------------

    def submit(self, request: ServeRequest, model_id: str) -> ServeFuture:
        """Admit one request routed to ONE fleet model. Tokenization
        runs here with THAT model's tokenizer (per-model vocabularies —
        the reason the fleet layer is model-id-aware all the way down)."""
        with tracing.span("serve/admit", request_id=request.request_id,
                          model=model_id):
            return self._submit(request, model_id)

    def _submit(self, request: ServeRequest, model_id: str
                ) -> ServeFuture:
        self.stats.count("submitted")
        engine = self.fleet.engine(model_id)
        assert engine is not None, f"unknown fleet model {model_id}"
        fut = ServeFuture()
        now = self.clock()
        with engine._tok_lock:
            bin_ids = tuple(int(i) for i in engine.tokenizer(
                request.binary_prompt).input_ids)
            conf_ids = tuple(int(i) for i in engine.tokenizer(
                request.confidence_prompt).input_ids)
        lcp = tok.shared_prefix_len(bin_ids, conf_ids)
        with engine._tok_lock:
            t1, t2 = tok.target_token_ids(
                engine.tokenizer, tuple(request.targets),
                encoder_decoder=engine.encoder_decoder)
        deadline = (request.deadline_s if request.deadline_s is not None
                    else self.fleet_deadline_s)
        bucket = tok.assign_bucket(max(lcp, 1), engine.buckets)
        self.queue.offer(Pending(
            request=request, future=fut, t_submit=now,
            t_deadline=now + deadline, bin_ids=bin_ids,
            conf_ids=conf_ids, lcp=lcp, bucket=bucket,
            t1=int(t1), t2=int(t2), model_id=model_id))
        return fut

    def submit_fleet(self, request: ServeRequest,
                     models: Optional[List[str]] = None
                     ) -> FleetScoreFuture:
        """The fleet request class: fan ``request`` across every fleet
        model (or the ``models`` subset) and aggregate agreement."""
        mids = list(models) if models is not None else self.fleet.model_ids
        self.fleet.stats.count("fleet_requests")
        self.fleet.stats.count("fleet_rows", len(mids))
        t0 = self.clock()
        futures = {
            mid: self.submit(dataclasses_replace_id(request, mid), mid)
            for mid in mids}
        return FleetScoreFuture(request.request_id, futures, t0,
                                self.clock)

    # -- supervisor side -----------------------------------------------------

    def start(self) -> "FleetScoringServer":
        assert self._thread is None, "server already started"
        self._thread = threading.Thread(target=self._loop,
                                        name="fleet-supervisor",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self, timeout: Optional[float] = None) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout)
            self._thread = None

    def _loop(self) -> None:
        while True:
            stopping = self._stop.is_set()
            for p in self.queue.drain():
                self.batcher.admit(p)
            d = self.batcher.next_dispatch(self.clock(), flush=stopping)
            if d is None:
                if (stopping and len(self.queue) == 0
                        and self.batcher.pending_rows == 0):
                    return
                self.queue.wait_nonempty(
                    0.005 if self.batcher.pending_rows else 0.05)
                continue
            self._dispatch(*d)

    def attach_observatory(self, scheduler) -> None:
        """Install a SentinelScheduler (observe/sentinel.py): its window
        history and drift alerts ride the ``stats`` endpoint, and its
        sweep/alert counters land in this server's metrics registry."""
        self.observatory = scheduler
        if scheduler.registry is None:
            scheduler.registry = self.metrics

    def stats_summary(self) -> Dict:
        """The fleet ``stats`` endpoint payload: serve counters, fleet
        swap accounting, and — when the observatory is attached — the
        windowed drift history and alerts."""
        out = {"serve": self.stats.summary(),
               "fleet": self.fleet.stats.summary()}
        if self.observatory is not None:
            out["observatory"] = self.observatory.summary()
        return out

    def _dispatch(self, model_id: str, bucket: int, rows) -> None:
        engine = self.fleet.engine(model_id)
        now0 = self.clock()
        for p in rows:
            tracing.add_span("serve/queue_wait", p.t_submit, now0,
                             request_id=p.request.request_id,
                             model=model_id, bucket=int(bucket))
        try:
            payloads = retry_with_exponential_backoff(
                lambda: self.batcher.score(model_id, bucket, rows),
                retry_on=(Exception,), config=self.config.retry,
                log=lambda m: log.warning(
                    "fleet dispatch retry (%s): %s", model_id, m),
                clock=self.clock)
        except (KeyboardInterrupt, SystemExit):
            raise
        except Exception as err:  # noqa: BLE001 — resolve, never crash
            now = self.clock()
            self.stats.count("errors", len(rows))
            for p in rows:
                p.future.resolve(ServeResult(
                    request_id=p.request.request_id, status=STATUS_ERROR,
                    note=f"device error after retries on {model_id}: "
                         f"{err!r}",
                    latency_s=now - p.t_submit))
            return
        now = self.clock()
        with tracing.span("serve/resolve", model=model_id,
                          rows=len(rows)):
            self._resolve_rows(engine, model_id, rows, payloads, now)

    def _resolve_rows(self, engine, model_id: str, rows, payloads,
                      now: float) -> None:
        for p, payload in zip(rows, payloads):
            reason = None
            if engine.rt.numerics_guard:
                engine.guard_stats.site("checked", "fleet")
                reason = numerics.check_payload(payload)
            if reason is not None:
                engine.guard_stats.quarantine("fleet", reason)
                self.stats.count("errors")
                p.future.resolve(ServeResult(
                    request_id=p.request.request_id, status=STATUS_ERROR,
                    note=f"{numerics.NUMERICS_ERROR} — {reason} "
                         f"(row quarantined by the numerics guard)",
                    latency_s=now - p.t_submit))
                continue
            self.stats.count("completed")
            self.stats.record_latency(now - p.t_submit)
            p.future.resolve(ServeResult(
                request_id=p.request.request_id, status=STATUS_OK,
                latency_s=now - p.t_submit, **payload))

    def fleet_summary(self) -> Dict:
        return self.fleet.stats.summary()


def dataclasses_replace_id(request: ServeRequest,
                           model_id: str) -> ServeRequest:
    """Per-model sub-request of a fleet fan-out: same prompts/targets,
    request id suffixed with the model so every sub-result is
    attributable in logs and checkpoints."""
    import dataclasses as _dc

    return _dc.replace(
        request, request_id=f"{request.request_id}#{model_id}")
