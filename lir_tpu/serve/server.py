"""Scoring server: the supervisor loop tying queue, cache, and batcher
together into a long-running service.

Lifecycle semantics (the graceful-degradation contract):

- Every admitted request resolves with SOME status. Deadline-exceeded
  rows return partial confidence-free results rather than failing their
  batch; shed rows resolve immediately at submit.
- Device dispatches run under the serve retry policy
  (config.ServeConfig.retry: short, full-jitter, elapsed-capped —
  utils/retry.py) so one transient XLA/runtime hiccup never surfaces to
  clients.
- After ``max_consecutive_failures`` dispatch failures in a row the
  server drains the queue with error results and flips :attr:`healthy`
  — the signal for an external supervisor (k8s liveness, systemd) to
  restart the process; subsequent submits shed immediately instead of
  queueing behind a dead device.

Dedup rides in front of admission: a submit whose content address is
already cached resolves without touching the queue or the device —
perturbation-style traffic re-asks near-identical questions constantly,
so this is the cheapest capacity the serving layer has.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, Optional, Tuple

from ..config import ServeConfig
from ..engine import compile_plan
from ..engine import tokens as tok
from ..utils.logging import get_logger
from ..utils.profiling import ServeStats
from ..utils.retry import retry_with_exponential_backoff
from .batcher import ContinuousBatcher
from .cache import ResultCache, content_key
from .queue import (STATUS_ERROR, STATUS_OK, STATUS_SHED, Pending,
                    RequestQueue, ServeFuture, ServeRequest, ServeResult)

log = get_logger(__name__)


class ScoringServer:
    """Continuous-batching scoring service over one ScoringEngine.

    ``precompile=True`` AOT-compiles every (ladder edge x suffix edge x
    padded batch) shared executable at boot (compile_plan.sweep_specs_
    for_ladder with serve_batches — background threads, lazy-jit
    fallback on any miss), so no request ever pays a trace.
    """

    def __init__(self, engine, model_name: str,
                 config: Optional[ServeConfig] = None,
                 stats: Optional[ServeStats] = None,
                 clock: Callable[[], float] = time.monotonic,
                 precompile: bool = False):
        self.engine = engine
        self.model_name = model_name
        self.config = config or ServeConfig()
        self.stats = stats if stats is not None else ServeStats()
        self.clock = clock
        self.queue = RequestQueue(self.config.queue_depth, self.stats,
                                  clock)
        self.cache = ResultCache(self.config.cache_entries, self.stats)
        self.batcher = ContinuousBatcher(engine, self.stats,
                                         self.config.linger_s, clock,
                                         pad_full=self.config.pad_full)
        self._engine_key = engine.cache_manifest_key
        self._target_memo: Dict[Tuple[str, str], Tuple[int, int]] = {}
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._consecutive = 0
        self._healthy = True
        engine.fresh_handoff()     # fresh donation chain per session
        if precompile and engine.rt.aot_precompile:
            # pad_full pins every dispatch to the full batch shape, so
            # only that shape needs warming; tail mode warms the whole
            # power-of-two grid.
            batches = ((engine.rt.batch_size,) if self.config.pad_full
                       else compile_plan.serve_batches(
                           engine.rt.batch_size))
            specs = compile_plan.sweep_specs_for_ladder(
                engine, sfx_buckets=(8, 16), batches=batches)
            engine.exec_registry = compile_plan.precompile_async(
                engine, specs, max_workers=engine.rt.precompile_workers)
            log.info("serve: precompiling %d executable shapes in the "
                     "background", len(specs))

    @property
    def healthy(self) -> bool:
        return self._healthy

    # -- client side ---------------------------------------------------------

    def _target_ids(self, targets: Tuple[str, str]) -> Tuple[int, int]:
        ids = self._target_memo.get(targets)
        if ids is None:
            with self.engine._tok_lock:
                t1, t2 = tok.target_token_ids(
                    self.engine.tokenizer, targets,
                    encoder_decoder=self.engine.encoder_decoder)
            ids = (int(t1), int(t2))
            self._target_memo[targets] = ids
        return ids

    def submit(self, request: ServeRequest) -> ServeFuture:
        """Admit one request; returns a future that resolves with a
        ServeResult (possibly immediately: dedup hit, shed, unhealthy).
        Tokenization runs here on the caller's thread, keeping the
        supervisor loop on the device's critical path only."""
        self.stats.count("submitted")
        fut = ServeFuture()
        now = self.clock()
        key = content_key(self._engine_key, request)
        if self.cache.max_entries > 0:
            hit = self.cache.get(key)
            if hit is not None:
                self.stats.count("completed")
                self.stats.record_latency(self.clock() - now)
                fut.resolve(ServeResult(
                    request_id=request.request_id, status=STATUS_OK,
                    cached=True, latency_s=self.clock() - now, **hit))
                return fut
        if not self._healthy:
            self.stats.count("shed")
            fut.resolve(ServeResult(
                request_id=request.request_id, status=STATUS_SHED,
                note="server unhealthy — repeated device errors"))
            return fut
        with self.engine._tok_lock:
            bin_ids = tuple(int(i) for i in self.engine.tokenizer(
                request.binary_prompt).input_ids)
            conf_ids = tuple(int(i) for i in self.engine.tokenizer(
                request.confidence_prompt).input_ids)
        lcp = tok.shared_prefix_len(bin_ids, conf_ids)
        t1, t2 = self._target_ids(tuple(request.targets))
        deadline = (request.deadline_s if request.deadline_s is not None
                    else self.config.deadline_for(request.klass))
        pending = Pending(
            request=request, future=fut, t_submit=now,
            t_deadline=now + deadline, bin_ids=bin_ids, conf_ids=conf_ids,
            lcp=lcp,
            bucket=tok.assign_bucket(max(lcp, 1), self.engine.buckets),
            t1=t1, t2=t2, cache_key=key)
        self.queue.offer(pending)
        return fut

    # -- supervisor side -----------------------------------------------------

    def start(self) -> "ScoringServer":
        assert self._thread is None, "server already started"
        self._thread = threading.Thread(target=self._loop,
                                        name="serve-supervisor",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self, timeout: Optional[float] = None) -> None:
        """Drain: finish everything queued (flushing partial buckets),
        then stop the supervisor."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout)
            self._thread = None

    def _loop(self) -> None:
        while True:
            stopping = self._stop.is_set()
            for p in self.queue.drain():
                self.batcher.admit(p)
            d = self.batcher.next_dispatch(self.clock(), flush=stopping)
            if d is None:
                if (stopping and len(self.queue) == 0
                        and self.batcher.pending_rows == 0):
                    return
                # Lingering rows need sub-window wakeups; an idle server
                # can sleep longer (still bounded so stop() is prompt).
                self.queue.wait_nonempty(
                    0.005 if self.batcher.pending_rows else 0.05)
                continue
            self._dispatch(*d)

    def _dispatch(self, bucket: int, rows) -> None:
        try:
            payloads = retry_with_exponential_backoff(
                lambda: self.batcher.score(bucket, rows),
                retry_on=(Exception,), config=self.config.retry,
                log=lambda m: log.warning("serve dispatch retry: %s", m),
                clock=self.clock)
        except Exception as err:  # noqa: BLE001 — degraded, never crash
            self._consecutive += 1
            now = self.clock()
            self.stats.count("errors", len(rows))
            for p in rows:
                p.future.resolve(ServeResult(
                    request_id=p.request.request_id, status=STATUS_ERROR,
                    note=f"device error after retries: {err!r}",
                    latency_s=now - p.t_submit))
            log.warning("serve: dispatch failed (%d consecutive): %r",
                        self._consecutive, err)
            if self._consecutive >= self.config.max_consecutive_failures:
                self._trip_health(err)
            return
        self._consecutive = 0
        now = self.clock()
        for p, payload in zip(rows, payloads):
            self.cache.put(p.cache_key, payload)
            latency = now - p.t_submit
            self.stats.count("completed")
            if now > p.t_deadline:
                self.stats.count("late")
            self.stats.record_latency(latency)
            p.future.resolve(ServeResult(
                request_id=p.request.request_id, status=STATUS_OK,
                latency_s=latency, **payload))

    def _trip_health(self, err: BaseException) -> None:
        """Repeated device errors: flip the health flag and drain every
        waiting request with an error result — fail fast and visibly
        instead of queueing behind a dead device."""
        self._healthy = False
        note = (f"server unhealthy after "
                f"{self._consecutive} consecutive dispatch failures: "
                f"{err!r}")
        n = self.queue.flush(STATUS_ERROR, note)
        n += self.batcher.flush_all(STATUS_ERROR, note)
        log.error("serve: health flag tripped; drained %d queued "
                  "requests (%s)", n, note)
