"""Online serving layer: continuous-batching request queue over the
ragged scheduler's bucket/price model.

Everything else in lir_tpu is an offline batch sweep launched from the
CLI; this package turns the same engine into a long-running scoring
service — the paper's workload (thousands of yes/no interpretation
probes per model) is exactly the shape iteration-level continuous
batching (Orca) and shared-prefix reuse (vLLM) were built for, and the
bucket ladder + AOT executable registry already fix every dispatch shape
ahead of time, which is the precondition for admitting streaming
requests without new compiles.

Components:

- queue.RequestQueue — bounded admission control with per-class
  deadlines and deadline-aware shed-on-overload.
- cache.ResultCache — content-addressed dedup of identical
  (model, prompt, target) probes.
- batcher.ContinuousBatcher — snaps requests to the precompiled bucket
  ladder, refills decode slots from the queue, prices dispatches with
  the offline planner's own scheduler.bucket_cost model.
- server.ScoringServer — the supervisor loop: retry with full jitter and
  an elapsed cap (utils/retry.py), partial results on deadline expiry,
  a circuit breaker (open on repeated device errors, half-open probe
  after a cooldown, closed on probe success — lir_tpu/faults), a
  degradation ladder that bisects failing batches to isolate poison
  rows, and a SIGTERM state checkpoint for preemption-safe restarts.
- router.ReplicaRouter — elastic multi-replica serving: one request
  stream spread over N replica servers with queue-depth / breaker /
  weight-residency placement, exactly-once failover of a dead
  replica's in-flight requests, and deadline-whisker hedging with
  first-payload-wins resolution (RouterConfig knobs; DEPLOY.md §1m).
- migrate (+ router roles) — disaggregated prefill/decode serving:
  prefill-role replicas absorb long-prompt prefills, their KV pages
  stream to decode-role replicas as chunked double-buffered checksummed
  transfers, and the cluster-wide prefix index (engine/prefix_tree.
  ClusterPrefixIndex) makes a prefix prefilled anywhere warm
  everywhere; a stalled/corrupt transfer falls back to local
  re-prefill (MigrationConfig knobs; DEPLOY.md §1p).
- tiers.TieredPageStore / tiers.TieredWeightStore — tiered memory: the
  HBM governor's reclaim rungs DEMOTE radix KV pages and fleet weight
  trees down an HBM -> pinned-host-DRAM -> local-disk ladder instead of
  deleting them (same bytes freed, nothing lost), promotes ride the
  checksummed paged-warm import path (bitwise), and a restarted replica
  reseeds its radix tree and weight cache from the disk tier before
  taking traffic (TierConfig knobs; DEPLOY.md §1s).
- batcher.FleetBatcher + server.FleetScoringServer — the multi-model
  fleet layer (engine/fleet.py underneath): per-model dispatch queues
  with resident-first selection and background weight prefetch, and the
  ``fleet_score`` request class fanning one question across every fleet
  model, answered with per-model P(yes)/P(no) plus pairwise
  kappa/disagreement through the stats/streaming contingency path.

Surface: the ``lir_tpu serve`` CLI subcommand (JSONL over stdin/stdout),
profiling.ServeStats observability, and bench.py's Poisson open-loop
load driver ("serve" headline key).
"""

from .batcher import ContinuousBatcher, FleetBatcher
from .cache import ResultCache, content_key
from .migrate import (MigrationError, PageExport, PageMigrator,
                      export_prefix, import_prefix)
from .queue import (STATUS_ERROR, STATUS_EXPIRED, STATUS_OK, STATUS_SHED,
                    RequestQueue, ServeFuture, ServeRequest, ServeResult)
from .router import ReplicaRouter
from .tiers import (TIER_DISK, TIER_HBM, TIER_HOST, DiskPageStore,
                    TieredPageStore, TieredWeightStore)
from .server import (FleetScoreFuture, FleetScoringServer, ScoringServer,
                     aggregate_fleet, fleet_decision)

__all__ = [
    "ContinuousBatcher", "FleetBatcher", "ResultCache", "content_key",
    "RequestQueue", "ServeFuture", "ServeRequest", "ServeResult",
    "ScoringServer", "FleetScoringServer", "FleetScoreFuture",
    "ReplicaRouter",
    "MigrationError", "PageExport", "PageMigrator",
    "export_prefix", "import_prefix",
    "TieredPageStore", "TieredWeightStore", "DiskPageStore",
    "TIER_HBM", "TIER_HOST", "TIER_DISK",
    "aggregate_fleet", "fleet_decision",
    "STATUS_OK", "STATUS_EXPIRED", "STATUS_SHED", "STATUS_ERROR",
]
