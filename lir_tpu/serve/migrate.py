"""KV-page migration between replica pools (disaggregated serving).

ROADMAP item 2 made the page the unit of KV *ownership* (models/paged
.KVPagePool + engine/prefix_tree.RadixPrefixCache); this module makes it
the unit of *placement*: the KV pages of a prefix prefilled on one
replica stream to another replica's pool, land in its radix tree, and
back that replica's decode dispatches bitwise-identically to pages it
would have computed itself. That is the DistServe/Mooncake handoff —
a long prompt prefills on a PREFILL-role replica, decode resumes on a
DECODE-role replica — expressed in this engine's own primitives:

- **Export** (:func:`export_prefix`): the source tree pins the deepest
  cached match (ordinary lookup reference discipline — eviction cannot
  free a page mid-export), then the pool pages stream device->host in
  fixed-size chunks with a bounded in-flight window — the SAME chunked
  double-buffered transfer discipline ``models/weights.stream_params``
  uses for weight streaming, pointed at KV pages. Each chunk carries a
  CRC so corruption on the wire is detectable at import.
- **Import** (:func:`import_prefix`): the destination tree allocates
  pages + nodes through its ordinary ``plan_insert`` (so the cluster
  index hears about them exactly like locally-produced pages), then the
  chunks land host->device double-buffered, each ``jax.device_put``
  taking the destination pool leaf's own sharding — pages arrive
  already partitioned for the destination mesh, no post-hoc reshard.
  Any failure (checksum mismatch, device error) ROLLS BACK: the fresh
  nodes leave the tree (:meth:`RadixPrefixCache.forget_tail`) and their
  pages return to the free list, so a dispatch can never gather a
  half-filled page — the never-a-wrong-answer contract.
- **Page ops** (:class:`PageOp`/:class:`OpFuture`): every tree/pool
  touch runs on the OWNING replica's supervisor thread (the tree's
  single-threaded contract), queued through
  ``ScoringServer.submit_page_op`` and chained by the router with
  completion callbacks — the handoff protocol is a pipeline of ops,
  never a cross-thread mutation.
- **Fault seam** (:meth:`PageMigrator.transfer`): the host-side hop
  between export and import, where the seeded chaos kinds inject —
  ``migration_stall`` sleeps past the chain deadline and
  ``migration_corrupt`` flips transferred bytes (faults/plan.py). Both
  end in the router's fallback: the decode replica re-prefills locally.

Everything here is advisory-index tolerant: the export re-looks pages
up with a pin, the import re-plans against the destination tree's
actual state, and a migration that cannot complete costs a local
re-prefill (``MigrationStats.refetch_fallbacks``), never a wrong or
dropped request.

The export/import legs are also the MOVEMENT ENGINE of the tiered
memory ladder (serve/tiers.py): a demotion is an ``export_prefix`` kept
in host DRAM or spilled to disk instead of shipped to a peer, and a
promotion is the same ``import_prefix`` — checksum verify, plan_insert,
skip-what's-resident, rollback — pointed back at the exporting
replica's own tree. One transfer discipline, three directions.
"""

from __future__ import annotations

import dataclasses
import threading
import time
import zlib
from collections import deque
from typing import Any, Callable, List, Optional, Tuple

import jax
import numpy as np

from ..config import MigrationConfig
from ..utils.logging import get_logger

log = get_logger(__name__)


class MigrationError(RuntimeError):
    """A page transfer that must not land (checksum mismatch, layout
    disagreement, vanished source pages). The router's reaction is
    always the same: abandon the chain, re-prefill locally."""


# ---------------------------------------------------------------------------
# Page ops: engine work queued onto the owning supervisor thread
# ---------------------------------------------------------------------------


class OpFuture:
    """Generic completion handle for one page op: resolves exactly once
    with a value OR an exception; callbacks run on the resolving
    (supervisor) thread. The migration chain's links are these
    callbacks — no waiter thread per hop."""

    def __init__(self) -> None:
        self._done = threading.Event()
        self._lock = threading.Lock()
        self.value: Any = None
        self.error: Optional[BaseException] = None
        self._callbacks: List[Callable[["OpFuture"], None]] = []  # guarded-by: _lock

    def _resolve(self, value: Any, error: Optional[BaseException]) -> None:
        with self._lock:
            if self._done.is_set():
                return
            self.value, self.error = value, error
            self._done.set()
            callbacks, self._callbacks = self._callbacks, []
        for fn in callbacks:
            fn(self)

    def set_result(self, value: Any) -> None:
        self._resolve(value, None)

    def set_exception(self, error: BaseException) -> None:
        self._resolve(None, error)

    def done(self) -> bool:
        return self._done.is_set()

    def add_done_callback(self, fn: Callable[["OpFuture"], None]) -> None:
        with self._lock:
            if not self._done.is_set():
                self._callbacks.append(fn)
                return
        fn(self)

    def result(self, timeout: Optional[float] = None) -> Any:
        if not self._done.wait(timeout):
            raise TimeoutError("page op not resolved in time")
        if self.error is not None:
            raise self.error
        return self.value


class PageOp:
    """One unit of tree/pool work bound for a replica's supervisor
    thread (``ScoringServer.submit_page_op``)."""

    def __init__(self, fn: Callable[[Any], Any]):
        self.fn = fn
        self.future = OpFuture()

    def run(self, engine) -> None:
        try:
            self.future.set_result(self.fn(engine))
        except (KeyboardInterrupt, SystemExit):
            raise
        except Exception as err:  # noqa: BLE001 — the chain's fallback
            # decides what a failed op means; the supervisor must live.
            self.future.set_exception(err)


# ---------------------------------------------------------------------------
# The transfer payload
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class PageExport:
    """One prefix's pages, staged on the host for the wire hop.

    ``ids`` is the page-aligned token prefix the pages cover (the
    import side re-plans against it); ``start_tokens`` is where the
    export begins (the destination already held ``[0, start_tokens)``
    at probe time). ``chunks`` holds ``(host block tree, real pages)``
    pairs at a stable ``chunk_pages`` width (trailing pad entries are
    trash-page blocks); ``checksums`` carries one CRC32 per chunk,
    computed at export — the import side's corruption detector."""

    bucket: int
    ids: Tuple[int, ...]
    start_tokens: int
    page_size: int
    n_pages: int
    chunk_pages: int
    chunks: List[Tuple[Any, int]]
    checksums: List[int]
    nbytes: int
    wall_s: float = 0.0
    serial_s: float = 0.0


@dataclasses.dataclass
class ImportResult:
    """What one import landed: pages written, device bytes, and the
    wall/serial split the overlap accounting reads."""

    pages: int
    nbytes: int
    wall_s: float = 0.0
    serial_s: float = 0.0


def chunk_checksum(block_tree: Any) -> int:
    """CRC32 over every leaf's raw bytes, leaf order — cheap enough to
    run per chunk, strong enough that a flipped transfer byte cannot
    land silently."""
    crc = 0
    for leaf in jax.tree.leaves(block_tree):
        crc = zlib.crc32(np.ascontiguousarray(leaf).tobytes(), crc)
    return crc


# ---------------------------------------------------------------------------
# Export / import legs
# ---------------------------------------------------------------------------


def export_prefix(engine, bucket: int, ids, from_token: int = 0,
                  config: Optional[MigrationConfig] = None,
                  clock: Callable[[], float] = time.monotonic
                  ) -> Optional[PageExport]:
    """Stage the cached pages of ``ids``' deepest match (from
    ``from_token`` on) to host chunks. Runs on the SOURCE replica's
    supervisor thread (a page op); the match is pinned for the
    duration, so eviction cannot free a page mid-copy. Returns None
    when nothing beyond ``from_token`` is cached — the chain falls back
    to a local prefill."""
    cfg = config or MigrationConfig()
    tree = getattr(engine, "prefix_cache", None)
    if tree is None:
        return None
    match = tree.lookup(bucket, ids, record=False)
    gov_key = None
    try:
        ps = tree.page_size
        from_page = max(int(from_token), 0) // ps
        pages = list(match.pages[from_page:])
        if not pages:
            return None
        # Transfer staging is real memory: ledger it for the duration
        # (the PR-14 HBM governor sees migration buffers next to the
        # pool reservation, so a squeeze accounts for in-flight
        # exports too).
        gov = getattr(engine, "governor", None)
        if gov is not None:
            gov_key = ("migrate_buf:"
                       f"{getattr(engine.cfg, 'name', 'model')}")
            gov.register(gov_key,
                         tree.pool.page_nbytes() * len(pages))
        chunk_n = max(int(cfg.chunk_pages), 1)
        window = max(int(cfg.inflight_chunks), 1)
        t0 = clock()
        serial = 0.0
        chunks: List[Tuple[Any, int]] = []
        sums: List[int] = []
        pending: deque = deque()

        def consume() -> None:
            nonlocal serial
            blocks, n, t_disp = pending.popleft()
            # Owned, writable host copies: the chunk may cross a
            # process/wire boundary (and the corruption chaos kind
            # mutates it in place).
            host = jax.tree.map(lambda a: np.array(a),
                                jax.device_get(blocks))
            serial += clock() - t_disp
            chunks.append((host, n))
            sums.append(chunk_checksum(host))

        for k in range(0, len(pages), chunk_n):
            pc = pages[k:k + chunk_n]
            # Dispatch the next chunk's device gather BEFORE consuming
            # the previous one — the double-buffered in-flight window
            # (stream_params' discipline, device->host direction).
            pending.append((tree.pool.extract(pc, pad_to=chunk_n),
                            len(pc), clock()))
            while len(pending) >= window + 1:
                consume()
        while pending:
            consume()
        wall = clock() - t0
        return PageExport(
            bucket=int(bucket),
            ids=tuple(int(t) for t in ids[:match.tokens]),
            start_tokens=from_page * ps, page_size=ps,
            n_pages=len(pages), chunk_pages=chunk_n, chunks=chunks,
            checksums=sums,
            nbytes=tree.pool.page_nbytes() * len(pages),
            wall_s=wall, serial_s=serial)
    finally:
        if gov_key is not None:
            engine.governor.unregister(gov_key)
        tree.release(match)


def import_prefix(engine, export: PageExport,
                  config: Optional[MigrationConfig] = None,
                  clock: Callable[[], float] = time.monotonic
                  ) -> ImportResult:
    """Land an export in the DESTINATION replica's pool + tree. Runs on
    the destination's supervisor thread (a page op), atomically from
    any dispatch's point of view: the tree nodes appear and their pages
    fill inside one op, or — on any failure — roll back entirely
    (refcounts restored, nodes removed, pages freed). Raises
    :class:`MigrationError` on checksum mismatch / layout disagreement;
    the router's fallback then re-prefills locally."""
    cfg = config or MigrationConfig()
    tree = getattr(engine, "prefix_cache", None)
    if tree is None:
        raise MigrationError("destination replica has no page pool")
    if tree.page_size != export.page_size:
        raise MigrationError(
            f"page-size mismatch: export {export.page_size} vs "
            f"destination {tree.page_size}")
    if cfg.verify:
        for ci, (host, _) in enumerate(export.chunks):
            if chunk_checksum(host) != export.checksums[ci]:
                raise MigrationError(
                    f"transfer chunk {ci} checksum mismatch — pages "
                    f"corrupted in flight, refusing to land them")
    ps = export.page_size
    t0 = clock()
    start_tok, new_pages = tree.plan_insert(export.bucket, export.ids)
    if not new_pages:
        return ImportResult(pages=0, nbytes=0, wall_s=clock() - t0)
    if start_tok < export.start_tokens:
        # The destination lost pages between probe and import; the
        # export cannot fill the gap — a torn prefix must never enter
        # the tree.
        tree.forget_tail(export.bucket, export.ids, len(new_pages))
        raise MigrationError(
            f"export starts at token {export.start_tokens} but the "
            f"destination needs from {start_tok} (pages evicted since "
            f"the probe)")
    # Transfer pin: fresh pages are unevictable until their data lands.
    tree.pool.incref(new_pages)
    skip = (start_tok - export.start_tokens) // ps
    window = max(int(cfg.inflight_chunks), 1)
    serial = 0.0
    # In-flight device_put blocks are real memory on the destination:
    # ledger them for the import's duration (PR-14 HBM governor).
    gov = getattr(engine, "governor", None)
    gov_key = None
    if gov is not None:
        gov_key = f"migrate_buf:{getattr(engine.cfg, 'name', 'model')}"
        gov.register(gov_key,
                     tree.pool.page_nbytes() * len(new_pages))
    try:
        shardings = jax.tree.map(lambda l: l.sharding, tree.pool.leaves)
        pending: deque = deque()

        def land() -> None:
            nonlocal serial
            dev, dst_ids, t_disp = pending.popleft()
            tree.pool.insert(dev, dst_ids)
            serial += clock() - t_disp

        idx = 0
        for host, n in export.chunks:
            lo = max(idx, skip)
            hi = min(idx + n, skip + len(new_pages))
            if hi > lo:
                s0, s1 = lo - idx, hi - idx
                block = jax.tree.map(lambda a: a[:, :, s0:s1], host)
                # Pages land already partitioned for the destination
                # mesh: each leaf's device_put takes the destination
                # pool leaf's own sharding (the pjit-resharding
                # pattern stream_params uses for weights).
                dev = jax.tree.map(
                    lambda b, sh: jax.device_put(b, sh),
                    block, shardings)
                pending.append(
                    (dev, new_pages[lo - skip:hi - skip], clock()))
                while len(pending) >= window + 1:
                    land()
            idx += n
        while pending:
            land()
    except BaseException:
        tree.pool.decref(new_pages)           # the transfer pin
        tree.forget_tail(export.bucket, export.ids, len(new_pages))
        raise
    finally:
        if gov_key is not None:
            gov.unregister(gov_key)
    tree.pool.decref(new_pages)
    return ImportResult(
        pages=len(new_pages),
        nbytes=tree.pool.page_nbytes() * len(new_pages),
        wall_s=clock() - t0, serial_s=serial)


# ---------------------------------------------------------------------------
# The migrator (router-held; the chaos fault seam)
# ---------------------------------------------------------------------------


class PageMigrator:
    """The router's migration policy object: config + stats + the
    ``transfer`` wire hop between export and import.

    ``transfer`` is deliberately an identity function on one object —
    it exists so the transport (today an in-process handoff; a DCN hop
    in a multi-process deployment) and the chaos kinds
    (``faults.wrap_migrator``: ``migration_stall`` sleeps past the
    chain deadline, ``migration_corrupt`` flips chunk bytes under the
    checksums) have one seam to wrap."""

    def __init__(self, config: Optional[MigrationConfig] = None,
                 stats=None,
                 clock: Callable[[], float] = time.monotonic):
        from ..utils.profiling import MigrationStats

        self.config = config or MigrationConfig()
        self.stats = stats if stats is not None else MigrationStats()
        self.clock = clock

    def transfer(self, export: PageExport) -> PageExport:
        """The wire hop (module docstring). In-process: a no-op."""
        return export

    def account(self, export: PageExport, imp: ImportResult) -> None:
        """Fold one completed chain into MigrationStats: exposed =
        critical-path wall seconds, hidden = in-flight seconds the
        double-buffered window overlapped away (serial sum minus
        wall, per leg)."""
        self.stats.add_transfer(
            pages=imp.pages, nbytes=imp.nbytes,
            chunks=len(export.chunks),
            exposed_s=export.wall_s + imp.wall_s,
            hidden_s=(max(export.serial_s - export.wall_s, 0.0)
                      + max(imp.serial_s - imp.wall_s, 0.0)))
