"""Perturbation-analysis figures (C23).

Parity targets: analyze_perturbation_results.py —
  create_probability_histogram :622-667   -> prompt_N_distribution.png
  create_confidence_histogram  :670-720   -> prompt_N_confidence_distribution.png
  create_qq_plot               :498-620   -> prompt_N[_confidence]_qq_plot.png
  create_truncated_model_plot  :339-496   -> prompt_N[_confidence]_truncated_model.png
  create_combined_visualization:911-997   -> combined_prompts_visualization.png
  create_combined_confidence_visualization :1000-1092
                                          -> combined_confidence_visualization.png

The QQ bootstrap bands (1000 resamples of the order statistics, reference
:547-573 as a Python loop) are computed here as one vmapped sort on device.

Matplotlib runs headless (Agg); same filenames, same chart content.
"""

from __future__ import annotations

from pathlib import Path
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import matplotlib

matplotlib.use("Agg")
import matplotlib.pyplot as plt  # noqa: E402
import numpy as np  # noqa: E402
import pandas as pd  # noqa: E402
from scipy import stats as scipy_stats  # noqa: E402

from ..stats.core import resample_indices  # noqa: E402

_sorted_resamples = jax.jit(
    jax.vmap(lambda v, i: jnp.sort(v[i]), in_axes=(None, 0))
)


def _ensure_dir(path: Path) -> Path:
    path = Path(path)
    path.mkdir(parents=True, exist_ok=True)
    return path


def probability_histogram(
    data: pd.DataFrame,
    prompt_idx: int,
    token_options: Sequence[str],
    output_dir: Path,
) -> Optional[Path]:
    """Histogram of Relative_Prob with the central 95% interval shaded."""
    vals = data["Relative_Prob"].to_numpy(dtype=float)
    vals = vals[np.isfinite(vals)]
    if vals.size == 0:
        return None
    lo, hi = np.percentile(vals, [2.5, 97.5])
    fig, ax = plt.subplots(figsize=(10, 6))
    ax.hist(vals, bins=50, range=(0, 1), edgecolor="black", alpha=0.75)
    ax.axvspan(lo, hi, alpha=0.15, color="green", label="95% interval")
    ax.axvline(vals.mean(), color="red", linestyle="--",
               label=f"Mean = {vals.mean():.3f}")
    ax.set_xlabel(
        f'Relative probability of "{token_options[0]}" vs "{token_options[1]}"'
    )
    ax.set_ylabel("Count")
    ax.set_title(f"Prompt {prompt_idx + 1}: Relative Probability Distribution")
    ax.legend()
    out = _ensure_dir(output_dir) / f"prompt_{prompt_idx + 1}_distribution.png"
    fig.savefig(out, dpi=150, bbox_inches="tight")
    plt.close(fig)
    return out


def confidence_histogram(
    data: pd.DataFrame,
    prompt_idx: int,
    token_options: Sequence[str],
    output_dir: Path,
) -> Optional[Path]:
    if "Weighted Confidence" not in data.columns:
        return None
    vals = data["Weighted Confidence"].to_numpy(dtype=float)
    vals = vals[np.isfinite(vals)]
    if vals.size == 0:
        return None
    lo, hi = np.percentile(vals, [2.5, 97.5])
    fig, ax = plt.subplots(figsize=(10, 6))
    ax.hist(vals, bins=50, range=(0, 100), edgecolor="black", alpha=0.75)
    ax.axvspan(lo, hi, alpha=0.15, color="green", label="95% interval")
    ax.axvline(vals.mean(), color="red", linestyle="--",
               label=f"Mean = {vals.mean():.1f}")
    ax.set_xlabel(f'Weighted confidence for "{token_options[0]}"')
    ax.set_ylabel("Count")
    ax.set_title(f"Prompt {prompt_idx + 1}: Weighted Confidence Distribution")
    ax.legend()
    out = _ensure_dir(output_dir) / (
        f"prompt_{prompt_idx + 1}_confidence_distribution.png"
    )
    fig.savefig(out, dpi=150, bbox_inches="tight")
    plt.close(fig)
    return out


def qq_plot(
    data: pd.DataFrame,
    column_name: str,
    prompt_idx: int,
    token_options: Sequence[str],
    output_dir: Path,
    key: Optional[jax.Array] = None,
    n_bootstrap: int = 1000,
) -> Optional[Path]:
    """Normal QQ plot with bootstrap confidence bands on the order
    statistics — the reference's 1000-resample loop (:547-573) as one
    vmapped device sort."""
    vals = data[column_name].to_numpy(dtype=float)
    vals = vals[np.isfinite(vals)]
    if vals.size < 3:
        return None
    key = key if key is not None else jax.random.PRNGKey(42)

    sorted_vals = np.sort(vals)
    n = vals.size
    theoretical = scipy_stats.norm.ppf((np.arange(1, n + 1) - 0.5) / n)
    theoretical = vals.mean() + vals.std() * theoretical

    idx = resample_indices(key, n_bootstrap, n)
    boot_sorted = np.asarray(_sorted_resamples(jnp.asarray(vals), idx))
    band_lo = np.percentile(boot_sorted, 2.5, axis=0)
    band_hi = np.percentile(boot_sorted, 97.5, axis=0)

    fig, ax = plt.subplots(figsize=(8, 8))
    ax.fill_between(theoretical, band_lo, band_hi, alpha=0.2, color="gray",
                    label="95% bootstrap band")
    ax.plot(theoretical, sorted_vals, "o", markersize=3, alpha=0.6,
            label="Sample quantiles")
    lims = [min(theoretical.min(), sorted_vals.min()),
            max(theoretical.max(), sorted_vals.max())]
    ax.plot(lims, lims, "r--", label="y = x")
    ax.set_xlabel("Theoretical quantiles (fitted normal)")
    ax.set_ylabel("Sample quantiles")
    ax.set_title(
        f"Prompt {prompt_idx + 1}: QQ Plot ({column_name}, "
        f'"{token_options[0]}")'
    )
    ax.legend()
    suffix = "_confidence" if "Confidence" in column_name else ""
    out = _ensure_dir(output_dir) / (
        f"prompt_{prompt_idx + 1}{suffix}_qq_plot.png"
    )
    fig.savefig(out, dpi=150, bbox_inches="tight")
    plt.close(fig)
    return out


def truncated_model_plot(
    data: pd.DataFrame,
    column_name: str,
    prompt_idx: int,
    token_options: Sequence[str],
    simulated: np.ndarray,
    output_dir: Path,
    ks_statistic: float,
) -> Optional[Path]:
    """Observed vs truncated-normal-simulated distribution overlay."""
    vals = data[column_name].to_numpy(dtype=float)
    vals = vals[np.isfinite(vals)]
    if vals.size == 0 or np.asarray(simulated).size == 0:
        return None
    fig, ax = plt.subplots(figsize=(10, 6))
    rng = (min(vals.min(), simulated.min()), max(vals.max(), simulated.max()))
    ax.hist(vals, bins=50, range=rng, density=True, alpha=0.55,
            label="Observed", edgecolor="black")
    ax.hist(np.asarray(simulated), bins=50, range=rng, density=True,
            alpha=0.45, label="Truncated-normal model")
    ax.set_xlabel(column_name)
    ax.set_ylabel("Density")
    ax.set_title(
        f"Prompt {prompt_idx + 1}: Truncated Normal Fit "
        f"(KS = {ks_statistic:.4f})"
    )
    ax.legend()
    suffix = "_confidence" if "Confidence" in column_name else ""
    out = _ensure_dir(output_dir) / (
        f"prompt_{prompt_idx + 1}{suffix}_truncated_model.png"
    )
    fig.savefig(out, dpi=150, bbox_inches="tight")
    plt.close(fig)
    return out


def _combined_violin(
    df: pd.DataFrame,
    column: str,
    prompts,
    output_path: Path,
    ylabel: str,
    ylim,
    rng: np.random.Generator,
) -> Optional[Path]:
    groups, labels = [], []
    for idx, prompt in enumerate(prompts):
        pdata = df[df["Original Main Part"] == prompt.main]
        vals = pdata[column].to_numpy(dtype=float)
        vals = vals[np.isfinite(vals)]
        if vals.size:
            groups.append(vals)
            labels.append(
                f"Prompt {idx + 1}\n"
                f'"{prompt.target_tokens[0]}" vs "{prompt.target_tokens[1]}"'
            )
    if not groups:
        return None
    fig, ax = plt.subplots(figsize=(14, 7))
    parts = ax.violinplot(groups, showmeans=True, showextrema=False)
    for pc in parts["bodies"]:
        pc.set_alpha(0.5)
    for i, vals in enumerate(groups):
        jitter = rng.normal(0, 0.06, size=vals.size)
        ax.plot(
            np.full(vals.size, i + 1) + jitter, vals, ".", markersize=2,
            alpha=0.25, color="black",
        )
    ax.set_xticks(range(1, len(labels) + 1))
    ax.set_xticklabels(labels, fontsize=8)
    ax.set_ylabel(ylabel)
    ax.set_ylim(*ylim)
    ax.set_title("All Prompts: Perturbation Response Distributions")
    out = Path(output_path)
    out.parent.mkdir(parents=True, exist_ok=True)
    fig.savefig(out, dpi=150, bbox_inches="tight")
    plt.close(fig)
    return out


def combined_visualization(
    df: pd.DataFrame, prompts, output_dir: Path,
    rng: Optional[np.random.Generator] = None,
) -> Optional[Path]:
    """Violin + jitter across all prompts (Relative_Prob; :911-997)."""
    return _combined_violin(
        df, "Relative_Prob", prompts,
        Path(output_dir) / "combined_prompts_visualization.png",
        "Relative probability of first token", (-0.02, 1.02),
        rng or np.random.default_rng(42),
    )


def combined_confidence_visualization(
    df: pd.DataFrame, prompts, output_dir: Path,
    rng: Optional[np.random.Generator] = None,
) -> Optional[Path]:
    """Violin + jitter across all prompts (Weighted Confidence; :1000-1092)."""
    if "Weighted Confidence" not in df.columns:
        return None
    return _combined_violin(
        df, "Weighted Confidence", prompts,
        Path(output_dir) / "combined_confidence_visualization.png",
        "Weighted confidence", (-2, 102),
        rng or np.random.default_rng(42),
    )
