"""LaTeX artifact generators for the perturbation analysis (C27, C25/C26).

Parity targets in the reference:
  - create_latex_table                analysis/analyze_perturbation_results.py:722-864
  - create_standalone_latex_document  :866-909
  - create_compliance_latex_table     :1453-1499
  - create_confidence_compliance_latex_table :1677-1716

The representative-rephrasing tables use percentile-stratified sampling (20
chunks, one random row each); randomness is an explicit numpy Generator so
tables are reproducible (reference uses pandas' global-state .sample()).
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np
import pandas as pd

PROMPT_DESCRIPTIONS = (
    "Insurance Policy Water Damage Exclusion",
    "Prenuptial Agreement Petition Filing Date",
    "Contract Term Affiliate Interpretation",
    "Construction Payment Terms Interpretation",
    "Insurance Policy Burglary Coverage",
)


def _escape(text: str) -> str:
    return (
        str(text).replace("_", "\\_").replace("%", "\\%").replace("&", "\\&")
    )


def _stratified_rows(
    sorted_df: pd.DataFrame, rng: np.random.Generator, num_chunks: int = 20
) -> pd.DataFrame:
    """One random row from each of `num_chunks` percentile chunks
    (:777-795)."""
    n = len(sorted_df)
    chunk_size = n // num_chunks
    if chunk_size == 0:
        return sorted_df
    picks = []
    for i in range(num_chunks):
        start = i * chunk_size
        end = (i + 1) * chunk_size if i < num_chunks - 1 else n
        if start < end:
            picks.append(sorted_df.iloc[int(rng.integers(start, end))])
    return pd.DataFrame(picks)


def perturbation_latex_table(
    data: pd.DataFrame,
    prompt_idx: int,
    prompt_main: str,
    token_options: Sequence[str],
    rng: Optional[np.random.Generator] = None,
) -> str:
    """Longtable of 20 representative rephrasings with relative probability
    and percentile; confidence table appended when data exists (:722-864)."""
    rng = rng or np.random.default_rng(42)
    first_token, second_token = token_options[0], token_options[1]
    description = (
        PROMPT_DESCRIPTIONS[prompt_idx]
        if prompt_idx < len(PROMPT_DESCRIPTIONS)
        else f"Prompt {prompt_idx + 1}"
    )
    has_confidence = (
        "Weighted Confidence" in data.columns
        and not data["Weighted Confidence"].isna().all()
    )

    out: List[str] = [
        f"\\subsection*{{Prompt {prompt_idx + 1}: {description}}}",
        "",
        f"\\textbf{{Original Prompt:}} {prompt_main}",
        "",
        "\\subsubsection*{Next-Token Distribution Table}",
        "",
        "\\begin{longtable}{p{0.65\\textwidth}cc}",
        f"\\caption{{Representative Relative Probabilities for {description}: "
        f'"{first_token}" vs "{second_token}" (Prompt {prompt_idx + 1})}} \\\\',
        "\\hline",
        "Prompt Variation & \\makecell{Relative\\\\Probability} & Percentile \\\\",
        "\\hline",
        "\\endhead",
        "\\hline",
        "\\endfoot",
    ]

    finite = data[np.isfinite(data["Relative_Prob"])]
    if len(finite) == 0:
        out += [
            "No valid data available for this prompt. & - & - \\\\",
            "\\end{longtable}",
            "",
        ]
        return "\n".join(out)

    sorted_df = finite.sort_values("Relative_Prob")
    for _, row in _stratified_rows(sorted_df, rng).iterrows():
        prob = float(row["Relative_Prob"])
        percentile = 100 * float((sorted_df["Relative_Prob"] <= prob).mean())
        out.append(
            f"{_escape(row['Full Rephrased Prompt'])} & {prob:.3f} & "
            f"{percentile:.1f}\\% \\\\"
        )
    out += ["\\end{longtable}", ""]

    if has_confidence:
        out += [
            "\\subsubsection*{Confidence Estimates Table}",
            "",
            "\\begin{longtable}{p{0.65\\textwidth}cc}",
            f"\\caption{{Representative Weighted Confidence for {description}: "
            f'"{first_token}" (Prompt {prompt_idx + 1})}} \\\\',
            "\\hline",
            "Prompt Variation & \\makecell{Weighted\\\\Confidence} & Percentile \\\\",
            "\\hline",
            "\\endhead",
            "\\hline",
            "\\endfoot",
        ]
        filtered = data.dropna(subset=["Weighted Confidence"])
        if len(filtered) > 0:
            sorted_conf = filtered.sort_values("Weighted Confidence")
            for _, row in _stratified_rows(sorted_conf, rng).iterrows():
                conf = float(row["Weighted Confidence"])
                percentile = 100 * float(
                    (sorted_conf["Weighted Confidence"] <= conf).mean()
                )
                out.append(
                    f"{_escape(row['Full Confidence Prompt'])} & {conf:.1f} & "
                    f"{percentile:.1f}\\% \\\\"
                )
        else:
            out.append("No confidence data available for this prompt. & - & - \\\\")
        out += ["\\end{longtable}", ""]
    return "\n".join(out)


STANDALONE_PREAMBLE = r"""\documentclass[12pt]{article}
\usepackage{amsfonts}
\usepackage[utf8]{inputenc}
\usepackage{hyperref}
\usepackage[margin=1.25in]{geometry}
\usepackage{natbib}
\usepackage{longtable}
\usepackage{subcaption}
\usepackage{graphicx}
\usepackage{makecell}
\usepackage{float}
\usepackage{amsmath}
\usepackage{setspace}
\usepackage{comment}
\usepackage[font=normal,labelfont=bf,skip=6pt]{caption}

\setlength{\parskip}{0.5em}

\title{Prompt Perturbation Analysis Appendix}
\author{}
\date{\today}

\begin{document}
\maketitle

\section*{Prompt Perturbation Analysis}

This appendix presents the detailed results of the prompt perturbation
analysis. For each legal interpretation prompt, the original prompt is shown
in plain text followed by a table of 20 representative prompt variations
selected from different percentile ranges of the distribution, with each
rephrasing's relative probability and its percentile rank.

"""


def standalone_latex_document(tables: Sequence[str]) -> str:
    """Complete compilable document wrapping the per-prompt tables
    (:866-909)."""
    return STANDALONE_PREAMBLE + "\n".join(tables) + "\n\\end{document}"


def compliance_latex_table(compliance_df: pd.DataFrame) -> str:
    """Output-instruction compliance summary table (:1453-1499)."""
    lines = [
        "\\begin{table}[h]",
        "\\centering",
        "\\caption{Output Instruction Compliance Analysis}",
        "\\begin{tabular}{lccc}",
        "\\hline",
        "Prompt & \\makecell{First Token\\\\Non-Compliance (\\%)} & "
        "\\makecell{Conditional Subsequent\\\\Non-Compliance (\\%)} & "
        "\\makecell{Total\\\\Samples} \\\\",
        "\\hline",
    ]
    for _, row in compliance_df.iterrows():
        sub = row.get("Conditional_Subsequent_Non_Compliance_Rate")
        sub_str = f"{sub:.3f}" if pd.notna(sub) else "N/A"
        lines.append(
            f"{row['Prompt']} & {row['First_Token_Non_Compliance_Rate']:.3f} & "
            f"{sub_str} & {row['Total_Samples']} \\\\"
        )
    lines.append("\\hline")

    overall_first = (
        compliance_df["First_Token_Non_Compliant"].sum()
        / compliance_df["Total_Samples"].sum()
        * 100
    )
    total_all = compliance_df["Total_Samples"].sum()
    sub_col = "Conditional_Subsequent_Non_Compliance_Rate"
    overall_sub_str = "N/A"
    if sub_col in compliance_df.columns:
        valid = compliance_df[compliance_df[sub_col].notna()]
        if len(valid) > 0 and valid["First_Token_Compliant"].sum() > 0:
            w = valid["First_Token_Compliant"]
            overall_sub = (w * valid[sub_col]).sum() / w.sum()
            overall_sub_str = f"\\textbf{{{overall_sub:.3f}}}"
    lines += [
        f"\\textbf{{Overall}} & \\textbf{{{overall_first:.3f}}} & "
        f"{overall_sub_str} & \\textbf{{{total_all}}} \\\\",
        "\\hline",
        "\\end{tabular}",
        "\\end{table}",
    ]
    return "\n".join(lines)


def confidence_compliance_latex_table(confidence_df: pd.DataFrame) -> str:
    """Integer-confidence compliance summary table (:1677-1716)."""
    lines = [
        "\\begin{table}[h]",
        "\\centering",
        "\\caption{Confidence Output Compliance Analysis (Integer Requirement)}",
        "\\begin{tabular}{lcccccc}",
        "\\hline",
        "Prompt & \\makecell{Non-Compliance\\\\Rate (\\%)} & "
        "\\makecell{Total\\\\Samples} & \\makecell{Float\\\\Errors} & "
        "\\makecell{Text\\\\Errors} & \\makecell{Out of\\\\Range} & "
        "\\makecell{Other\\\\Errors} \\\\",
        "\\hline",
    ]
    for _, row in confidence_df.iterrows():
        lines.append(
            f"{row['Prompt']} & {row['Confidence_Non_Compliance_Rate']:.3f} & "
            f"{row['Total_Confidence_Samples']} & {row['Float_Errors']} & "
            f"{row['Text_Errors']} & {row['Out_Of_Range_Errors']} & "
            f"{row['Other_Errors']} \\\\"
        )
    lines.append("\\hline")
    overall = (
        confidence_df["Confidence_Non_Compliant"].sum()
        / confidence_df["Total_Confidence_Samples"].sum()
        * 100
    )
    lines += [
        f"\\textbf{{Overall}} & \\textbf{{{overall:.3f}}} & "
        f"\\textbf{{{confidence_df['Total_Confidence_Samples'].sum()}}} & "
        f"\\textbf{{{confidence_df['Float_Errors'].sum()}}} & "
        f"\\textbf{{{confidence_df['Text_Errors'].sum()}}} & "
        f"\\textbf{{{confidence_df['Out_Of_Range_Errors'].sum()}}} & "
        f"\\textbf{{{confidence_df['Other_Errors'].sum()}}} \\\\",
        "\\hline",
        "\\end{tabular}",
        "\\end{table}",
    ]
    return "\n".join(lines)
