"""Survey-analysis figures (C39/C43 visual outputs).

Parity targets:
  - analyze_llm_human_agreement.py:210-259 -> best_worst_model_agreement.png
    (scatter of best/worst model vs human averages) and
    model_mae_comparison.png (horizontal MAE bar chart, instruct vs base)
  - calculate_correlation_pvalues.py:326-371 ->
    correlation_pvalue_distributions.png (2x2 histogram panel of LLM/human
    correlations and their p-values)
"""

from __future__ import annotations

from pathlib import Path
from typing import Dict, List, Optional

import matplotlib

matplotlib.use("Agg")
import matplotlib.pyplot as plt  # noqa: E402
import numpy as np  # noqa: E402


def best_worst_agreement_plot(
    all_results: List[Dict[str, object]], path: Path
) -> Optional[Path]:
    """Scatter of the best and worst models (by MAE) against human averages
    (:214-239). `all_results` is analyze_all_models output (sorted by MAE)."""
    if not all_results:
        return None
    best, worst = all_results[0], all_results[-1]
    fig, axes = plt.subplots(1, 2, figsize=(15, 6))
    for ax, result, label in ((axes[0], best, "Best"), (axes[1], worst, "Worst")):
        matched = result["matched"]
        ax.scatter(matched["human_avg"], matched["model_prob"], alpha=0.6)
        ax.plot([0, 1], [0, 1], "r--", alpha=0.5)
        ax.set_xlabel("Human Average Rating")
        ax.set_ylabel("Model Probability")
        ax.set_title(
            f"{label} Model: {result['model']}\n"
            f"MAE = {result['mae']:.4f}, r = {result['pearson_r']:.4f}"
        )
        ax.set_xlim(-0.05, 1.05)
        ax.set_ylim(-0.05, 1.05)
    fig.tight_layout()
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    fig.savefig(path, dpi=150)
    plt.close(fig)
    return path


def mae_comparison_plot(
    all_results: List[Dict[str, object]], path: Path
) -> Optional[Path]:
    """Horizontal MAE bar chart, instruct blue / base green (:241-258)."""
    if not all_results:
        return None
    names = [
        r["model"].split("/")[-1][:20] + "..."
        if len(r["model"]) > 20 else r["model"]
        for r in all_results
    ]
    maes = [r["mae"] for r in all_results]
    colors = [
        "blue" if r["model_type"] == "instruct" else "green"
        for r in all_results
    ]
    fig, ax = plt.subplots(figsize=(12, 8))
    ax.barh(names, maes, color=colors)
    ax.set_xlabel("Mean Absolute Error (lower is better)")
    ax.set_title("Model Agreement with Human Average Ratings")
    from matplotlib.patches import Patch

    ax.legend(
        handles=[
            Patch(facecolor="blue", label="Instruct Models"),
            Patch(facecolor="green", label="Base Models"),
        ],
        loc="lower right",
    )
    fig.tight_layout()
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    fig.savefig(path, dpi=150)
    plt.close(fig)
    return path


def correlation_pvalue_panel(
    llm_correlations: List[Dict[str, object]],
    human_correlations: List[Dict[str, object]],
    path: Path,
) -> Optional[Path]:
    """2x2 histogram panel: LLM/human correlation and p-value distributions
    (calculate_correlation_pvalues.py:329-368)."""
    if not llm_correlations or not human_correlations:
        return None
    llm_r = np.asarray([c["correlation"] for c in llm_correlations])
    human_r = np.asarray([c["correlation"] for c in human_correlations])
    llm_p = np.asarray([c["p_value"] for c in llm_correlations])
    human_p = np.asarray([c["p_value"] for c in human_correlations])

    fig, axes = plt.subplots(2, 2, figsize=(14, 10))
    panels = (
        (axes[0, 0], llm_r, "LLM Pairwise Correlations", None, "C0"),
        (axes[0, 1], human_r, "Human Pairwise Correlations", None, "green"),
        (axes[1, 0], llm_p, "LLM Correlation P-values", 0.05, "C0"),
        (axes[1, 1], human_p, "Human Correlation P-values", 0.05, "green"),
    )
    for ax, vals, title, vline, color in panels:
        ax.hist(vals[np.isfinite(vals)], bins=30, edgecolor="black",
                alpha=0.7, color=color)
        if vline is None:
            ax.axvline(np.nanmean(vals), color="red", linestyle="--",
                       label=f"Mean: {np.nanmean(vals):.3f}")
        else:
            ax.axvline(vline, color="red", linestyle="--", label=f"p = {vline}")
        ax.set_xlabel("Correlation Coefficient" if vline is None else "P-value")
        ax.set_ylabel("Frequency")
        ax.set_title(title)
        ax.legend()
    fig.suptitle("Correlation Analysis: LLMs vs Humans", fontsize=14,
                 fontweight="bold")
    fig.tight_layout()
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    fig.savefig(path, dpi=150, bbox_inches="tight")
    plt.close(fig)
    return path
