"""Reporting layer: figures and LaTeX artifact generators (L5)."""

from . import figures
from .latex import (
    compliance_latex_table,
    confidence_compliance_latex_table,
    perturbation_latex_table,
    standalone_latex_document,
)
