"""Fault-injection harness + self-healing primitives.

The failure path engineered like the hot path (ROADMAP north star): a
deterministic, seeded fault-injection layer (plan.FaultPlan) that wraps
the engine and serve boundaries, and the three recovery mechanisms it
proves out —

- breaker.CircuitBreaker: the serve health flag as a real closed/open/
  half-open breaker, so a transient device outage no longer kills the
  server forever (serve/server.py);
- ladder.degrade_dispatch: bisect a failing batch to isolate poison
  rows, resolve only the culprits as errors (serve/server.py, after the
  AOT->lazy fallback runner.ScoringEngine.degrade_to_lazy);
- crash-consistent resume: torn-tail-tolerant fsync'd manifest appends
  (utils/manifest.py), results-seeded done-sets (engine/sweep.py), and
  the serve SIGTERM state checkpoint (server.shutdown_checkpoint).

Silent failure kinds (``SiteSchedule.hang_at`` / ``nan_at``) exercise
the third reliability layer, lir_tpu/guard: the dispatch watchdog must
stall-out an injected hang into THESE recovery mechanisms, and the
numerics guard must quarantine injected-NaN rows as error:numerics.

Chaos drivers: ``make chaos-smoke`` (tools/chaos_smoke.py) and
``python bench.py --chaos`` run sweeps and serve sessions under seeded
kill/fault schedules and assert zero lost / zero duplicated / zero
corrupted rows vs a fault-free run; counters land in
profiling.FaultStats and profiling.GuardStats.
"""

from .breaker import CLOSED, HALF_OPEN, OPEN, CircuitBreaker
from .ladder import degrade_dispatch
from .plan import (KINDS, SITES, FaultPlan, InjectedFault,
                   InjectedPreemption, InjectedReplicaKill, SiteSchedule,
                   corrupt_export_chunks, corrupt_result_nan,
                   tear_jsonl_tail, wrap_engine, wrap_governor,
                   wrap_migrator, wrap_replica, wrap_server, wrap_tiers)

__all__ = [
    "FaultPlan", "SiteSchedule", "InjectedFault", "InjectedPreemption",
    "InjectedReplicaKill",
    "SITES", "KINDS", "wrap_engine", "wrap_server", "wrap_replica",
    "wrap_governor", "wrap_migrator", "wrap_tiers", "tear_jsonl_tail",
    "corrupt_result_nan", "corrupt_export_chunks",
    "CircuitBreaker", "CLOSED", "OPEN", "HALF_OPEN",
    "degrade_dispatch",
]
