"""Deterministic, seeded fault injection at the engine/serve boundaries.

The reliability claims of this repo (resume-idempotent sweeps, a serving
layer that degrades instead of crashing) are only claims until something
actually kills the process mid-checkpoint and unplugs the device
mid-dispatch. A :class:`FaultPlan` is that something, made reproducible:
per-SITE failure schedules (explicit call indices + an optional seeded
Bernoulli rate) that raise :class:`InjectedFault` — standing in for a
transient XLA/runtime device error — or :class:`InjectedPreemption` — a
simulated preemption/kill signal — at exactly the same calls on every
run with the same seed.

Sites are plain strings; the canonical ones (``SITES``) cover the
boundaries the recovery machinery wraps:

- ``dispatch``   — the engine's fused-decode calls / the batcher's score
- ``compile``    — AOT registry compiles (compile_plan)
- ``tokenize``   — tokenizer encode at submit/plan time
- ``manifest_write``   — SweepManifest appends
- ``checkpoint_write`` — serve state-checkpoint writes
- ``preempt``    — an explicit preemption check (sweep/serve loops)

``InjectedPreemption`` subclasses BaseException on purpose: a real
SIGKILL does not flow through ``except Exception`` recovery paths, so
neither may its simulation — it must unwind all the way out, exactly
like the writer-thread re-raise contract in engine/sweep.py expects.

Beyond raise-style faults, two SILENT failure kinds exercise the guard
layer (lir_tpu/guard):

- ``kind="hang"`` — the wrapped call sleeps ``hang_s`` seconds (a stall
  the dispatch watchdog must detect and abandon within its deadline),
  then raises InjectedFault on release. The sleep happens BEFORE the
  real call runs, and release raises instead of proceeding, so an
  abandoned worker thread never mutates engine state (KV-cache
  donation chain) behind a live retry — which is also how a real stuck
  collective ends: aborted, not completed.
- ``kind="nan"`` — the real call runs, then its RESULT is corrupted:
  NaN written into the probability/logprob/confidence fields of the
  rows named by ``nan_rows`` (FusedDecodeOut tuples from the engine's
  fused decodes, or serve payload dicts from batcher.score). The
  numerics guard must quarantine exactly those rows while their
  neighbors score bitwise identical to a clean run.
"""

from __future__ import annotations

import dataclasses
import random
import threading
import time
from typing import Callable, Dict, Optional, Tuple

from ..utils.profiling import FaultStats

SITES = ("dispatch", "compile", "tokenize", "manifest_write",
         "checkpoint_write", "preempt", "replica", "hbm", "migrate",
         "tiers")

KINDS = ("fault", "preempt", "hang", "nan", "replica_kill",
         "replica_lag", "hbm_squeeze", "migration_stall",
         "migration_corrupt", "tier_corrupt", "disk_stall")


class InjectedFault(RuntimeError):
    """A scheduled transient failure (device error stand-in)."""


class InjectedReplicaKill(InjectedFault):
    """A scheduled replica death (serve/router.py chaos): the replica's
    in-flight dispatch dies AND the router marks the replica dead —
    what an abrupt process/host loss looks like from the front. An
    ordinary Exception (unlike InjectedPreemption): the ROUTER is the
    recovery layer under test, and it must survive the death, not die
    with it."""

    def __init__(self, msg: str, replica_id: str = ""):
        super().__init__(msg)
        self.replica_id = replica_id


class InjectedPreemption(BaseException):
    """A scheduled kill. BaseException so recovery code catching
    Exception cannot accidentally 'survive' it — a real preemption
    wouldn't ask first."""


@dataclasses.dataclass(frozen=True)
class SiteSchedule:
    """When one site fails.

    - ``fail_calls``: explicit 0-based call indices that fail — the
      precise tool (an outage is a contiguous range).
    - ``rate``: additionally, a seeded Bernoulli failure probability per
      call — the statistical tool (soak tests).
    - ``max_failures``: hard bound on total injections at this site (a
      rate-based schedule then models a TRANSIENT outage the recovery
      machinery must outlast, not a permanently broken device).
    - ``kind``: "fault" raises InjectedFault, "preempt" raises
      InjectedPreemption, "hang" sleeps ``hang_s`` then raises
      InjectedFault (a stall for the watchdog), "nan" corrupts the
      wrapped call's RESULT rows ``nan_rows`` (for the numerics guard;
      only meaningful through :meth:`FaultPlan.wrap`), "replica_kill"
      raises InjectedReplicaKill carrying ``replica_id`` (through
      :func:`wrap_replica` it also marks the replica dead in its
      router first — the chaos proof for elastic failover), and
      "replica_lag" sleeps ``lag_s`` BEFORE the call and then lets it
      COMPLETE (a straggler, not a death: the late payload exercises
      the router's hedge/zombie paths), and "draft_corrupt" overwrites
      the speculative-decode draft tokens of rows ``nan_rows`` with
      seeded in-vocab garbage BEFORE the verify dispatch (through
      :meth:`FaultPlan.corrupt_draft` — the chaos proof that a bad
      draft only costs re-verification: results stay bitwise and
      SpecStats.rejected_tokens counts the injections).
    """

    fail_calls: Tuple[int, ...] = ()
    rate: float = 0.0
    max_failures: Optional[int] = None
    kind: str = "fault"
    hang_s: float = 30.0
    nan_rows: Tuple[int, ...] = (0,)
    replica_id: str = ""
    lag_s: float = 1.0
    squeeze_frac: float = 0.5
    squeeze_calls: int = 8

    @classmethod
    def outage(cls, start: int, length: int) -> "SiteSchedule":
        """Every call in [start, start+length) fails — a device outage."""
        return cls(fail_calls=tuple(range(start, start + length)))

    @classmethod
    def kill_at(cls, call: int) -> "SiteSchedule":
        """Simulated preemption at one call index."""
        return cls(fail_calls=(call,), kind="preempt")

    @classmethod
    def hang_at(cls, call: int, seconds: float = 30.0) -> "SiteSchedule":
        """Simulated stall at one call index: sleep ``seconds`` before
        the real call would run, then raise on release. Pick ``seconds``
        well past the watchdog deadline under test — the watchdog should
        abandon the call long before the sleep ends."""
        return cls(fail_calls=(call,), kind="hang", hang_s=seconds)

    @classmethod
    def nan_at(cls, call: int,
               rows: Tuple[int, ...] = (0,)) -> "SiteSchedule":
        """Simulated numerics corruption (SDC stand-in) at one call
        index: NaN into the named result rows' measurement fields."""
        return cls(fail_calls=(call,), kind="nan", nan_rows=rows)

    @classmethod
    def draft_corrupt_at(cls, call: int,
                         rows: Tuple[int, ...] = (0,)) -> "SiteSchedule":
        """Corrupt the named rows' speculative draft tokens at one
        ``corrupt_draft`` call index (site "draft" by convention).
        Row indices ride ``nan_rows`` — the same per-row selector the
        nan kind uses."""
        return cls(fail_calls=(call,), kind="draft_corrupt", nan_rows=rows)

    @classmethod
    def hbm_squeeze_at(cls, call: int, frac: float = 0.5,
                       calls: int = 8) -> "SiteSchedule":
        """Shrink the HBM governor's ledger budget to ``frac`` of its
        base at governor tick ``call`` for the next ``calls`` ticks,
        then auto-restore (site "hbm" by convention; wire through
        :func:`wrap_governor`) — the OOM-squeeze chaos proof: the
        degradation ladder must walk down under the squeeze and back
        up after it, with zero crashed dispatches and every consumed
        row bitwise-identical to an unpressured run."""
        return cls(fail_calls=(call,), kind="hbm_squeeze",
                   squeeze_frac=frac, squeeze_calls=calls)

    @classmethod
    def migration_stall_at(cls, call: int,
                           seconds: float = 30.0) -> "SiteSchedule":
        """Stall one page-migration transfer (site "migrate" by
        convention; wire through :func:`wrap_migrator`): the wire hop
        sleeps ``seconds`` — pick it past MigrationConfig.timeout_s —
        then raises on release, exactly a wedged DCN transfer. The
        router must abandon the chain within its deadline and the
        decode replica re-prefill LOCALLY (MigrationStats.
        refetch_fallbacks), with the request's payload bitwise a
        colocated run's — never a wrong answer."""
        return cls(fail_calls=(call,), kind="migration_stall",
                   hang_s=seconds)

    @classmethod
    def migration_corrupt_at(cls, call: int) -> "SiteSchedule":
        """Corrupt one page-migration transfer in flight (site
        "migrate"; :func:`wrap_migrator`): chunk bytes are flipped
        UNDER the export's checksums, so the import must detect the
        mismatch, refuse to land any page (rollback: destination
        refcounts/tree untouched), and fall back to local
        re-prefill."""
        return cls(fail_calls=(call,), kind="migration_corrupt")

    @classmethod
    def tier_corrupt_at(cls, call: int) -> "SiteSchedule":
        """Corrupt one tiered-store promote in flight (site "tiers";
        :func:`wrap_tiers`): the promoted export's chunk bytes are
        flipped UNDER its recorded checksums — a bad host buffer or
        disk sector. The promote's verify must refuse the chunks
        (TierStats.checksum_refusals), drop the poisoned entry, and
        the request re-prefill locally — never a wrong answer."""
        return cls(fail_calls=(call,), kind="tier_corrupt")

    @classmethod
    def disk_stall_at(cls, call: int,
                      seconds: float = 30.0) -> "SiteSchedule":
        """Stall one tiered-store disk read (site "tiers";
        :func:`wrap_tiers`): the promote's transfer hop sleeps
        ``seconds`` — pick it past TierConfig.disk_timeout_s — then
        PROCEEDS, exactly a wedged disk. The store's deadline check
        must abandon the promote (TierStats.disk_stalls), keep the
        entry (a stall is not corruption), and let the request
        re-prefill locally."""
        return cls(fail_calls=(call,), kind="disk_stall", hang_s=seconds)

    @classmethod
    def replica_kill_at(cls, call: int,
                        replica_id: str = "") -> "SiteSchedule":
        """Simulated replica death at one call index (the elastic
        chaos proof: wire through :func:`wrap_replica` so the router
        observes the death and re-admits the in-flight work)."""
        return cls(fail_calls=(call,), kind="replica_kill",
                   replica_id=replica_id)

    @classmethod
    def replica_lag_at(cls, call: int, seconds: float,
                       replica_id: str = "") -> "SiteSchedule":
        """Simulated straggler replica: its dispatch at ``call`` sleeps
        ``seconds`` then COMPLETES — the router's hedge should win the
        race and the late payload must be dropped, never
        double-resolved."""
        return cls(fail_calls=(call,), kind="replica_lag",
                   lag_s=seconds, replica_id=replica_id)


class FaultPlan:
    """Seeded per-site failure schedules + the counters they feed.

    Thread-safe: call counters and the per-site PRNGs sit behind one
    lock, so concurrent sites (the serve supervisor + submit threads)
    see a single deterministic schedule.
    """

    def __init__(self, seed: int = 0,
                 schedules: Optional[Dict[str, SiteSchedule]] = None,
                 stats: Optional[FaultStats] = None):
        self.seed = int(seed)
        self.schedules: Dict[str, SiteSchedule] = dict(schedules or {})
        self.stats = stats if stats is not None else FaultStats()
        self._calls: Dict[str, int] = {}
        self._injected: Dict[str, int] = {}
        self._rngs: Dict[str, random.Random] = {}
        self._lock = threading.Lock()

    def calls(self, site: str) -> int:
        with self._lock:
            return self._calls.get(site, 0)

    def injected(self, site: str) -> int:
        with self._lock:
            return self._injected.get(site, 0)

    def _decide(self, site: str) -> Optional[SiteSchedule]:
        """Advance the site's call counter; return its schedule when THIS
        call should fail. One lock-held decision so schedules are exact
        under concurrency."""
        with self._lock:
            idx = self._calls.get(site, 0)
            self._calls[site] = idx + 1
            sched = self.schedules.get(site)
            if sched is None:
                return None
            done = self._injected.get(site, 0)
            if sched.max_failures is not None and done >= sched.max_failures:
                return None
            fail = idx in sched.fail_calls
            if not fail and sched.rate > 0.0:
                rng = self._rngs.get(site)
                if rng is None:
                    # Site-keyed stream: adding a site never perturbs
                    # another site's draws.
                    rng = random.Random(f"{self.seed}:{site}")
                    self._rngs[site] = rng
                fail = rng.random() < sched.rate
            if not fail:
                return None
            self._injected[site] = done + 1
        return sched

    def _fire(self, sched: SiteSchedule, site: str) -> None:
        """Raise the scheduled raise-style failure (fault / preempt /
        hang / replica_kill). "nan" is result corruption and
        "replica_lag" is a delay-then-complete — neither can fire here;
        only :meth:`wrap` (which owns the call) handles them."""
        idx = self.calls(site) - 1
        if sched.kind == "preempt":
            self.stats.inject(site, preemption=True)
            raise InjectedPreemption(
                f"injected preemption at {site} call {idx}")
        self.stats.inject(site)
        if sched.kind == "hang":
            time.sleep(sched.hang_s)
            raise InjectedFault(
                f"injected hang at {site} call {idx} released after "
                f"{sched.hang_s:.2f}s")
        if sched.kind == "replica_kill":
            raise InjectedReplicaKill(
                f"injected replica kill at {site} call {idx}"
                + (f" (replica {sched.replica_id})"
                   if sched.replica_id else ""),
                replica_id=sched.replica_id)
        raise InjectedFault(f"injected fault at {site} call {idx}")

    def check(self, site: str) -> None:
        """The injection point: raise when the schedule says this call
        fails, else return. Every wrapped boundary calls this first.
        A scheduled "nan" corruption is a no-op here (no result to
        corrupt); "replica_lag" sleeps in place then proceeds — use
        :meth:`wrap` when the lagged call's RESULT matters."""
        sched = self._decide(site)
        if sched is None or sched.kind in ("nan", "draft_corrupt",
                                           "hbm_squeeze",
                                           "migration_stall",
                                           "migration_corrupt",
                                           "tier_corrupt",
                                           "disk_stall"):
            return
        if sched.kind == "replica_lag":
            self.stats.inject(site)
            time.sleep(sched.lag_s)
            return
        self._fire(sched, site)

    def corrupt_draft(self, drafts, vocab_size: int,
                      site: str = "draft") -> int:
        """The speculative-decode injection point (engine/spec.
        build_plan): when the ``site`` schedule fires with kind
        "draft_corrupt", overwrite the scheduled rows' draft tokens —
        ``drafts`` is a list of (tokens (B, T) int32, lens (B,) int32)
        host arrays, mutated in place — with seeded IN-VOCAB garbage
        (corrupted tokens are teacher-forced into the verify pass, so
        they must embed; wrongness, not invalidity, is the fault).
        Rows without a draft gain a short forced one so the injection
        always reaches the verifier. Returns tokens corrupted."""
        sched = self._decide(site)
        if sched is None or sched.kind != "draft_corrupt":
            return 0
        self.stats.inject(site)
        idx = self.calls(site) - 1
        rng = random.Random(f"{self.seed}:{site}:{idx}")
        corrupted = 0
        for toks, lens in drafts:
            budget = toks.shape[1]
            for r in sched.nan_rows:
                if r >= toks.shape[0]:
                    continue
                if lens[r] == 0:
                    lens[r] = min(2, budget)
                for t in range(int(lens[r])):
                    toks[r, t] = (int(toks[r, t]) + 1
                                  + rng.randrange(max(vocab_size - 1, 1))
                                  ) % vocab_size
                    corrupted += 1
        return corrupted

    def wrap(self, site: str, fn: Callable) -> Callable:
        """``fn`` under the site's schedule (indexed by call count at
        ``site``, not by wrapper): raise-style kinds fire BEFORE the
        call; "nan" runs the call and corrupts its result rows;
        "replica_lag" sleeps then runs the call to completion (the
        straggler whose late payload the router must drop)."""

        def wrapped(*args, **kwargs):
            sched = self._decide(site)
            if sched is not None:
                if sched.kind == "nan":
                    self.stats.inject(site)
                    return corrupt_result_nan(fn(*args, **kwargs),
                                              sched.nan_rows)
                if sched.kind == "replica_lag":
                    self.stats.inject(site)
                    time.sleep(sched.lag_s)
                    return fn(*args, **kwargs)
                self._fire(sched, site)
            return fn(*args, **kwargs)

        wrapped.__wrapped__ = fn  # type: ignore[attr-defined]
        return wrapped


def wrap_engine(engine, plan: FaultPlan):
    """Inject the plan's ``dispatch`` site in front of the engine's fused
    decode entry points (the sweep's device boundary), and hand the plan
    to the speculative drafter (site ``draft`` — engine/spec.build_plan
    calls :meth:`FaultPlan.corrupt_draft` per dispatch). Instance-level
    shadowing only — the class stays clean and other engines untouched."""
    engine.decode_fused_shared = plan.wrap("dispatch",
                                           engine.decode_fused_shared)
    engine.decode_fused_grouped = plan.wrap("dispatch",
                                            engine.decode_fused_grouped)
    engine.spec_fault_plan = plan
    return engine


def wrap_server(server, plan: FaultPlan):
    """Inject the plan's ``dispatch`` site in front of the batcher's
    score call (the serve device boundary — under the supervisor's retry
    policy, so recovery is exercised, not bypassed)."""
    server.batcher.score = plan.wrap("dispatch", server.batcher.score)
    return server


def wrap_replica(router, replica_id: str, plan: FaultPlan,
                 site: str = "replica"):
    """Inject the plan's ``site`` schedule in front of ONE router
    replica's dispatch boundary (serve/router.ReplicaRouter). The
    replica-specific kinds get their router semantics here:

    - ``replica_kill``: the ROUTER observes the death first
      (``kill_replica`` — breaker tripped, in-flight re-admitted to
      survivors), then the dispatch dies with InjectedReplicaKill,
      exactly the order an abrupt host loss presents: the work is gone
      before any error surfaces.
    - ``replica_lag``: the dispatch sleeps ``lag_s`` then COMPLETES —
      the straggler whose late payload must lose the hedge race and
      never double-resolve.

    Other kinds (fault/hang/nan/preempt) behave as in :meth:`wrap`, so
    outage and corruption schedules compose onto replicas too."""
    handle = router.handle(replica_id)
    inner = handle.server.batcher.score

    def wrapped(*args, **kwargs):
        sched = plan._decide(site)
        if sched is not None:
            if sched.kind == "replica_kill":
                plan.stats.inject(site)
                router.kill_replica(replica_id)
                raise InjectedReplicaKill(
                    f"injected replica kill on {replica_id}",
                    replica_id=replica_id)
            if sched.kind == "replica_lag":
                plan.stats.inject(site)
                time.sleep(sched.lag_s)
                return inner(*args, **kwargs)
            if sched.kind == "nan":
                plan.stats.inject(site)
                return corrupt_result_nan(inner(*args, **kwargs),
                                          sched.nan_rows)
            plan._fire(sched, site)
        return inner(*args, **kwargs)

    wrapped.__wrapped__ = inner  # type: ignore[attr-defined]
    handle.server.batcher.score = wrapped
    return router


def wrap_migrator(migrator, plan: FaultPlan, site: str = "migrate"):
    """Inject the plan's ``site`` schedule at a router migrator's wire
    hop (serve/migrate.PageMigrator.transfer — the seam between page
    export and page import):

    - ``migration_stall``: the transfer sleeps ``hang_s`` (pick it past
      MigrationConfig.timeout_s so the router's chain deadline fires
      first) then raises on release — a wedged DCN hop. Either way the
      request must fall back to LOCAL re-prefill on the decode replica
      and resolve bitwise-identical to a colocated run.
    - ``migration_corrupt``: the export's chunk bytes are flipped IN
      PLACE under its recorded checksums (seeded, counter-indexed) —
      silent wire corruption. The import's verify must refuse the
      chunk, roll the destination tree/refcounts back untouched, and
      fall back the same way.

    Other kinds behave as in :meth:`FaultPlan.wrap` (a "fault" here is
    a transport error), so outage schedules compose onto migrations."""
    inner = migrator.transfer

    def wrapped(export):
        sched = plan._decide(site)
        if sched is not None:
            if sched.kind == "migration_stall":
                plan.stats.inject(site)
                idx = plan.calls(site) - 1
                time.sleep(sched.hang_s)
                raise InjectedFault(
                    f"injected migration stall at {site} call {idx} "
                    f"released after {sched.hang_s:.2f}s")
            if sched.kind == "migration_corrupt":
                plan.stats.inject(site)
                idx = plan.calls(site) - 1
                corrupt_export_chunks(
                    export, seed=f"{plan.seed}:{site}:{idx}")
                return inner(export)
            plan._fire(sched, site)
        return inner(export)

    wrapped.__wrapped__ = inner  # type: ignore[attr-defined]
    migrator.transfer = wrapped
    return migrator


def wrap_tiers(store, plan: FaultPlan, site: str = "tiers"):
    """Inject the plan's ``site`` schedule at a tiered store's promote
    hop (serve/tiers.TieredPageStore.transfer — the seam every promote
    passes on its way back toward HBM):

    - ``tier_corrupt``: the promoted export's chunk bytes are flipped
      IN PLACE under its recorded checksums (seeded, counter-indexed)
      — a rotted host buffer or bad disk sector. The promote's verify
      must refuse the chunks, drop the poisoned entry, and the request
      re-prefill locally with a bitwise-identical payload.
    - ``disk_stall``: the hop sleeps ``hang_s`` (pick it past
      TierConfig.disk_timeout_s) then PROCEEDS — a wedged disk read,
      not a death. The store's own deadline check must observe the
      elapsed time, abandon the promote (TierStats.disk_stalls), and
      keep the entry for later.

    Other kinds behave as in :meth:`FaultPlan.wrap` (a "fault" here is
    an I/O error on the tier hop)."""
    inner = store.transfer

    def wrapped(export):
        sched = plan._decide(site)
        if sched is not None:
            if sched.kind == "disk_stall":
                plan.stats.inject(site)
                time.sleep(sched.hang_s)
                return inner(export)
            if sched.kind == "tier_corrupt":
                plan.stats.inject(site)
                idx = plan.calls(site) - 1
                corrupt_export_chunks(
                    export, seed=f"{plan.seed}:{site}:{idx}")
                return inner(export)
            plan._fire(sched, site)
        return inner(export)

    wrapped.__wrapped__ = inner  # type: ignore[attr-defined]
    store.transfer = wrapped
    return store


def corrupt_export_chunks(export, seed: str = "0") -> int:
    """Flip bytes in a PageExport's host chunks WITHOUT touching its
    recorded checksums — the in-flight corruption the import-side
    verify exists to catch. Mutates the (owned, writable) numpy leaves
    in place; returns bytes flipped."""
    import jax as _jax
    import numpy as _np

    rng = random.Random(seed)
    flipped = 0
    for host, _n in export.chunks:
        for leaf in _jax.tree.leaves(host):
            flat = _np.asarray(leaf).view(_np.uint8).reshape(-1)
            if flat.size == 0:
                continue
            for _ in range(min(8, flat.size)):
                j = rng.randrange(flat.size)
                flat[j] ^= 0xFF
                flipped += 1
        break            # one chunk is enough: any mismatch aborts
    return flipped


def wrap_governor(governor, plan: FaultPlan, site: str = "hbm"):
    """Inject the plan's ``site`` schedule in front of an HBM
    governor's tick (engine/hbm.HbmGovernor — one tick per dispatch
    boundary). A firing ``hbm_squeeze`` shrinks the governed budget to
    ``squeeze_frac`` of its base for the next ``squeeze_calls`` ticks,
    then auto-restores — seeded and counter-indexed like every other
    kind, so the squeeze lands at exactly the same dispatch on every
    run. Other kinds behave as in :meth:`FaultPlan.check` (a "fault"
    here stands in for a failing memory-stats probe)."""
    inner = governor.tick

    def wrapped(*args, **kwargs):
        sched = plan._decide(site)
        if sched is not None:
            if sched.kind == "hbm_squeeze":
                plan.stats.inject(site)
                governor.squeeze(sched.squeeze_frac,
                                 calls=sched.squeeze_calls)
            else:
                plan._fire(sched, site)
        return inner(*args, **kwargs)

    wrapped.__wrapped__ = inner  # type: ignore[attr-defined]
    governor.tick = wrapped
    return governor


def corrupt_result_nan(result, rows: Tuple[int, ...]):
    """NaN-corrupt the measurement fields of ``rows`` in a dispatch
    result — the simulated silent-data-corruption the numerics guard
    exists to catch. Handles the engine's fused-decode results (tuples
    of FusedDecodeOut: NaN into p_yes/p_no/topk_logprobs/weighted_
    confidence at the given batch rows) and serve payload lists (NaN
    into the per-row measurement dict). Anything else passes through
    untouched (e.g. the grouped dispatch's member-count int)."""
    if isinstance(result, tuple):
        return tuple(corrupt_result_nan(r, rows) for r in result)
    if isinstance(result, list):
        out = list(result)
        for r in rows:
            if 0 <= r < len(out) and isinstance(out[r], dict):
                p = dict(out[r])
                nan = float("nan")
                p["token_1_prob"] = nan
                p["token_2_prob"] = nan
                p["weighted_confidence"] = nan
                out[r] = p
        return out
    if dataclasses.is_dataclass(result) and hasattr(result, "p_yes"):
        import jax.numpy as jnp

        nan = jnp.float32(float("nan"))
        p_yes, p_no = result.p_yes, result.p_no
        topk, wconf = result.topk_logprobs, result.weighted_confidence
        for r in rows:
            if not 0 <= r < int(p_yes.shape[0]):
                continue
            p_yes = p_yes.at[r].set(nan)
            p_no = p_no.at[r].set(nan)
            topk = topk.at[r].set(nan)
            wconf = wconf.at[r].set(nan)
        return dataclasses.replace(result, p_yes=p_yes, p_no=p_no,
                                   topk_logprobs=topk,
                                   weighted_confidence=wconf)
    return result


def tear_jsonl_tail(path, fragment: str = '{"model": "m", "orig') -> None:
    """Append a torn (non-JSON, newline-free) fragment to a JSONL file —
    the exact on-disk state a kill mid-append leaves behind. Chaos tests
    use it to prove SweepManifest resume survives its own crash mode."""
    with open(path, "a") as f:
        f.write(fragment)
        f.flush()
