"""Degradation ladder: isolate poison rows instead of failing a batch.

A dispatch that keeps failing after retries has two very different
causes with two very different remedies:

1. The DEVICE (or an executable) is broken — retrying subsets fails
   everywhere. The caller should fail the dispatch and let the circuit
   breaker take over.
2. One ROW is poison — a pathological prompt that crashes the kernel, a
   tokenizer edge case, a corrupt cache interaction. Failing the whole
   batch punishes every innocent neighbor, and under continuous
   batching the poison row re-queues with NEW neighbors and takes them
   down too: one bad request can wedge a whole service.

:func:`degrade_dispatch` tells them apart by bisection: retry the full
batch once (the caller has usually just dropped the AOT registry via
``ScoringEngine.degrade_to_lazy`` — a corrupt precompiled executable is
remedy zero), then split-and-recurse; rows that fail ALONE are poison
and come back as None, everything else comes back scored. Cost is
O(poison * log batch) extra dispatches, zero when the full-batch retry
succeeds.
"""

from __future__ import annotations

from typing import Callable, List, Optional, Sequence

from .plan import InjectedPreemption  # noqa: F401  (re-export for callers)


def degrade_dispatch(score_fn: Callable[[list], List[dict]],
                     rows: Sequence,
                     log: Optional[Callable[[str], None]] = None,
                     ) -> List[Optional[dict]]:
    """Score ``rows`` through ``score_fn`` (which takes a row subset and
    returns one payload per row), bisecting on failure to isolate poison
    rows. Returns a list aligned with ``rows``: a payload dict, or None
    for rows that fail even in a batch of one.

    KeyboardInterrupt/SystemExit/InjectedPreemption always propagate —
    the ladder recovers work, it does not resist being killed.
    """
    rows = list(rows)
    out: List[Optional[dict]] = [None] * len(rows)

    def solve(lo: int, hi: int) -> None:
        try:
            payloads = score_fn(rows[lo:hi])
        except (KeyboardInterrupt, SystemExit):
            raise
        except Exception as err:  # noqa: BLE001 — bisect decides
            if hi - lo == 1:
                if log is not None:
                    log(f"poison row isolated at index {lo}: {err!r}")
                return
            mid = (lo + hi) // 2
            solve(lo, mid)
            solve(mid, hi)
            return
        out[lo:hi] = list(payloads)

    if rows:
        solve(0, len(rows))
    return out
