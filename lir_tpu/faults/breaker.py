"""Circuit breaker: the serve health flag upgraded into a state machine
with a way BACK to healthy.

The old contract (serve/server.py pre-PR4) was one-way: after
``max_consecutive_failures`` dispatch failures the server drained its
queue and flipped ``healthy`` False forever — correct for a dead device,
wrong for the common case (a transient runtime wobble, a preempted
neighbor, a driver hiccup) where the device comes back in seconds and
the only thing keeping the server down is its own flag.

Standard three-state breaker semantics instead:

- CLOSED: normal operation. Failures increment a consecutive counter;
  reaching ``failure_threshold`` opens the breaker (the caller drains
  queued work with error results, exactly like the old trip).
- OPEN: every submit sheds immediately — no queue can build up behind a
  device that isn't answering. After ``cooldown_s`` the next state READ
  promotes to HALF_OPEN (promotion is lazy: no timer thread; the first
  submit or supervisor poll after the cooldown sees HALF_OPEN).
- HALF_OPEN: admits traffic again; the first dispatch is the probe.
  Success closes the breaker (healthy, counter reset); failure re-opens
  it for another cooldown — one cheap dispatch is all an outage costs
  per cooldown period.

Every transition is recorded into profiling.FaultStats, so the recovery
story of a chaos run is readable from ``transitions`` alone.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional

from ..utils.profiling import FaultStats

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"


class CircuitBreaker:
    """Thread-safe closed/open/half-open breaker over a MONOTONIC clock.

    The cooldown is an elapsed-time comparison (``clock() - opened_at``),
    so the clock must be ``time.monotonic`` (the default), never
    ``time.time``: an NTP step or operator clock change under a
    wall-clock breaker either holds it open long past its cooldown
    (backward step) or promotes it early (forward step) — on a router
    fronting N replicas that is N breakers mis-timing at once. Injected
    test clocks are fine; they stand in for monotonic time. Pinned by
    tests/test_faults.py (wall-clock steps cannot move the cooldown).
    """

    def __init__(self, failure_threshold: int = 3,
                 cooldown_s: float = 30.0,
                 clock: Callable[[], float] = time.monotonic,
                 stats: Optional[FaultStats] = None):
        self.failure_threshold = max(1, int(failure_threshold))
        self.cooldown_s = float(cooldown_s)
        self.clock = clock
        self.stats = stats if stats is not None else FaultStats()
        self._state = CLOSED                      # guarded-by: _lock
        self._consecutive = 0                     # guarded-by: _lock
        self._opened_at: Optional[float] = None   # guarded-by: _lock
        self._lock = threading.Lock()

    def _transition(self, to: str) -> None:  # guarded-by: _lock
        frm, self._state = self._state, to
        self.stats.transition(frm, to)

    def _promote_locked(self) -> None:  # guarded-by: _lock
        """OPEN -> HALF_OPEN once the cooldown has elapsed."""
        if (self._state == OPEN and self._opened_at is not None
                and self.clock() - self._opened_at >= self.cooldown_s):
            self._transition(HALF_OPEN)

    @property
    def state(self) -> str:
        with self._lock:
            self._promote_locked()
            return self._state

    @property
    def consecutive_failures(self) -> int:
        with self._lock:
            return self._consecutive

    def allow(self) -> bool:
        """May traffic flow? True in CLOSED and HALF_OPEN (the half-open
        admissions become the probe dispatch)."""
        return self.state != OPEN

    def record_success(self) -> None:
        with self._lock:
            self._consecutive = 0
            if self._state == HALF_OPEN:
                self._transition(CLOSED)

    def trip(self) -> None:
        """Force the breaker OPEN now, regardless of the failure count —
        the router's replica-kill path: a replica observed DEAD (not
        merely erroring) must stop receiving traffic immediately, and
        recovery still flows through the ordinary open -> half_open ->
        closed probe once the replica rejoins."""
        with self._lock:
            if self._state != OPEN:
                self._opened_at = self.clock()
                self._transition(OPEN)

    def record_failure(self) -> bool:
        """One dispatch failure (retries already exhausted). Returns True
        when the breaker OPENED on this failure — the caller then drains
        queued work with error results."""
        with self._lock:
            self._promote_locked()
            self._consecutive += 1
            if self._state == HALF_OPEN:
                # The probe failed: back to OPEN for another cooldown.
                self._opened_at = self.clock()
                self._transition(OPEN)
                return True
            if (self._state == CLOSED
                    and self._consecutive >= self.failure_threshold):
                self._opened_at = self.clock()
                self._transition(OPEN)
                return True
        return False
