"""graft-lint: in-tree static analysis proving the engine's JAX/XLA
invariants at review time.

Seven PRs of perf and robustness work (ragged scheduler, donated KV
handoff chains, paged prefix cache, fused Pallas decode, piggyback
chaining, the threaded serve/guard layer) piled up invariants that
nothing checked until a TPU run silently retraced, double-freed a
donated buffer, or deadlocked the batcher. The guard layer (PR 5)
catches those at RUNTIME; this package holds the line STATICALLY — five
AST passes (stdlib ``ast`` only, zero heavy imports, runs in well under
ten seconds) wired into ``lir_tpu lint``, ``make lint``, ``make
verify`` and the pre-push hook:

- **donation-safety** (lint/donation.py): any binding passed through a
  ``donate_argnames``/``donate_argnums`` call site and READ afterwards
  in the same function is a use-after-donate — the XLA buffer behind it
  is dead the moment the donating call dispatches.
- **trace-hazard** (lint/trace.py): inside functions reachable from
  ``jit``/``pjit``/``pallas_call`` tracing, python branching on traced
  values, ``int()``/``bool()``/``float()``/``.item()`` coercions, and
  unordered-collection iteration feeding pytree construction — the
  retrace / ConcretizationError / multihost-desync hazards.
- **host-sync** (lint/hostsync.py): implicit device→host transfers
  (``np.asarray``, ``.tolist()``, ``.item()``, truthiness, scalar
  coercion) in the hot-path modules (``engine/``, ``ops/``,
  ``serve/batcher.py``); legitimate readout boundaries are marked with
  the ``@host_readout`` decorator (utils/annotations.py) or a
  ``# lint: allow(host-sync)`` comment.
- **lock-discipline** (lint/locks.py): an attribute annotated
  ``# guarded-by: <lock>`` may only be mutated inside ``with
  self.<lock>:`` (or from a method annotated as running with the lock
  already held) — the batcher/queue state, breaker state machine, and
  watchdog EWMA are the enforced surfaces.
- **config-drift** (lint/configdrift.py): every ``RuntimeConfig`` /
  ``ServeConfig`` field must have a cli.py flag, a DEPLOY.md mention,
  and (RuntimeConfig) coverage by ``compile_cache.manifest_key`` — a
  new knob can never silently miss the cache key again.

Findings diff against the checked-in baseline (tools/lint_baseline.json)
so the gate is zero-NEW-findings from day one while pre-existing ones
burn down. Conventions, triage, and the allowlist story: DEPLOY.md §1i.
"""

from .core import (ALL_PASSES, Finding, Project, load_baseline,  # noqa: F401
                   load_project, run_passes, save_baseline)
