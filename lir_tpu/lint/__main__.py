"""`python -m lir_tpu.lint` — the dependency-free lint entry point."""

import sys

from .cli import main

sys.exit(main())
