"""Finding/reporting core shared by every lint pass.

Design constraints (ISSUE 8):

- stdlib only (``ast``, ``re``, ``json``) — the linter must run in a
  bare CI container and in the pre-push hook without importing jax or
  any engine module;
- deterministic output — findings sort by (path, line, pass) and their
  MESSAGES carry no line numbers, so the baseline survives unrelated
  edits shifting code around;
- baseline diffing — the gate is "zero findings outside
  tools/lint_baseline.json", counted per fingerprint (pass, path,
  scope, message) so two identical violations in one function need two
  baseline entries;
- suppression — a ``# lint: allow(<pass>[, <pass>...])`` comment on the
  finding's line waives exactly those passes there; ``# lint:
  skip-file`` waives a whole module. Passes may add their own richer
  conventions (``@host_readout``, ``# guarded-by:``) on top.

Each pass is a small class with ``name`` and ``run(project)``; new
fleet-era passes (ROADMAP items 3/5) slot into :data:`ALL_PASSES`.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import re
from collections import Counter
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

ALLOW_RE = re.compile(r"#\s*lint:\s*allow\(([a-z0-9_\-, ]+)\)")
SKIP_FILE_RE = re.compile(r"#\s*lint:\s*skip-file")

Fingerprint = Tuple[str, str, str, str]  # (pass, path, scope, message)


@dataclasses.dataclass(frozen=True)
class Finding:
    """One violation. ``scope`` is the enclosing function/class qualname
    (or "<module>"); ``line`` is for humans and clickable editors only —
    the baseline fingerprint deliberately excludes it so re-indenting a
    file does not churn the baseline."""

    pass_name: str
    path: str            # repo-relative posix path
    line: int
    scope: str
    message: str

    @property
    def fingerprint(self) -> Fingerprint:
        return (self.pass_name, self.path, self.scope, self.message)

    def render(self) -> str:
        return (f"{self.path}:{self.line}: [{self.pass_name}] "
                f"{self.scope}: {self.message}")


class Module:
    """One parsed source file + its suppression comments."""

    def __init__(self, path: Path, rel: str, source: str):
        self.path = path
        self.rel = rel
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=rel)
        self.skip = bool(SKIP_FILE_RE.search(source[:2048]))
        self.allow: Dict[int, Set[str]] = {}
        for i, text in enumerate(self.lines, start=1):
            m = ALLOW_RE.search(text)
            if m:
                names = {t.strip() for t in m.group(1).split(",") if t.strip()}
                self.allow[i] = names

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ""

    def allowed(self, pass_name: str, lineno: int) -> bool:
        if self.skip:
            return True
        names = self.allow.get(lineno, ())
        return pass_name in names or "*" in names


class Project:
    """The analyzed tree: every parsed module under ``lir_tpu/`` (or the
    whole root for fixture mini-projects) plus root-level text files the
    config-drift pass reads (DEPLOY.md)."""

    def __init__(self, root: Path, modules: Sequence[Module]):
        self.root = root
        self.modules = list(modules)
        self._by_rel = {m.rel: m for m in self.modules}

    def module(self, rel: str) -> Optional[Module]:
        return self._by_rel.get(rel)

    def text(self, rel: str) -> Optional[str]:
        p = self.root / rel
        try:
            return p.read_text(encoding="utf-8")
        except OSError:
            return None


def load_project(root: Path) -> Project:
    """Parse the tree. Scans ``root/lir_tpu`` when present (the real
    repo — tests and tools are out of scope: fixtures SEED violations
    and tools are one-off host scripts), else every .py under ``root``
    (fixture mini-projects)."""
    root = Path(root).resolve()
    base = root / "lir_tpu" if (root / "lir_tpu").is_dir() else root
    modules: List[Module] = []
    for path in sorted(base.rglob("*.py")):
        if "__pycache__" in path.parts:
            continue
        rel = path.relative_to(root).as_posix()
        modules.append(Module(path, rel, path.read_text(encoding="utf-8")))
    return Project(root, modules)


# ---------------------------------------------------------------------------
# AST helpers shared by the passes
# ---------------------------------------------------------------------------

def dotted(node: ast.AST) -> Optional[str]:
    """'a', 'a.b.c' for Name/Attribute chains, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def terminal_name(func: ast.AST) -> Optional[str]:
    """The rightmost component of a call target: ``f`` for both ``f(...)``
    and ``mod.sub.f(...)`` — cross-module matching by convention (this
    codebase never reuses an exported callable name for something with
    different donation/trace semantics)."""
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Attribute):
        return func.attr
    return None


def parent_map(tree: ast.AST) -> Dict[ast.AST, ast.AST]:
    parents: Dict[ast.AST, ast.AST] = {}
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            parents[child] = node
    return parents


def iter_functions(module: Module):
    """Yield (qualname, FunctionDef) for every def in the module, with
    Class.method / outer.inner qualnames."""

    def walk(node: ast.AST, prefix: str):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                q = f"{prefix}{child.name}"
                yield q, child
                yield from walk(child, f"{q}.")
            elif isinstance(child, ast.ClassDef):
                yield from walk(child, f"{prefix}{child.name}.")
            else:
                yield from walk(child, prefix)

    yield from walk(module.tree, "")


def const_str_tuple(node: ast.AST) -> Tuple[str, ...]:
    """String constants out of a 'x' / ('x', 'y') / ['x'] node."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        return tuple(e.value for e in node.elts
                     if isinstance(e, ast.Constant)
                     and isinstance(e.value, str))
    return ()


def const_int_tuple(node: ast.AST) -> Tuple[int, ...]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        return tuple(e.value for e in node.elts
                     if isinstance(e, ast.Constant)
                     and isinstance(e.value, int))
    return ()


def arg_names(fn: ast.FunctionDef) -> List[str]:
    a = fn.args
    return ([p.arg for p in a.posonlyargs] + [p.arg for p in a.args]
            + [p.arg for p in a.kwonlyargs])


# ---------------------------------------------------------------------------
# Pass registry + runner
# ---------------------------------------------------------------------------

class LintPass:
    """Base class: subclasses set ``name`` and implement ``run``."""

    name = "abstract"

    def run(self, project: Project) -> List[Finding]:  # pragma: no cover
        raise NotImplementedError


def all_passes() -> List[LintPass]:
    # Imported lazily so ``from lir_tpu.lint import core`` never cycles.
    from . import (configdrift, donation, hostsync, locks, metricsdrift,
                   trace)

    return [donation.DonationPass(), trace.TraceHazardPass(),
            hostsync.HostSyncPass(), locks.LockDisciplinePass(),
            configdrift.ConfigDriftPass(),
            metricsdrift.MetricsDriftPass()]


ALL_PASSES = tuple(p.name for p in all_passes())


def run_passes(project: Project,
               only: Optional[Iterable[str]] = None) -> List[Finding]:
    """Run every (selected) pass, drop suppressed findings, sort."""
    selected = set(only) if only else None
    findings: List[Finding] = []
    for p in all_passes():
        if selected is not None and p.name not in selected:
            continue
        for f in p.run(project):
            mod = project.module(f.path)
            if mod is not None and mod.allowed(p.name, f.line):
                continue
            findings.append(f)
    findings.sort(key=lambda f: (f.path, f.line, f.pass_name, f.message))
    return findings


# ---------------------------------------------------------------------------
# Baseline
# ---------------------------------------------------------------------------

BASELINE_VERSION = 1


def load_baseline(path: Path) -> Counter:
    """Fingerprint -> allowed count. Missing file = empty baseline."""
    try:
        data = json.loads(Path(path).read_text(encoding="utf-8"))
    except OSError:
        return Counter()
    allowed: Counter = Counter()
    for rec in data.get("findings", ()):
        fp: Fingerprint = (rec["pass"], rec["path"], rec["scope"],
                           rec["message"])
        allowed[fp] += int(rec.get("count", 1))
    return allowed


def save_baseline(path: Path, findings: Sequence[Finding]) -> None:
    counts: Counter = Counter(f.fingerprint for f in findings)
    recs = [{"pass": fp[0], "path": fp[1], "scope": fp[2], "message": fp[3],
             "count": n}
            for fp, n in sorted(counts.items())]
    Path(path).write_text(json.dumps(
        {"version": BASELINE_VERSION,
         "comment": "graft-lint baseline: pre-existing findings being "
                    "burned down. Never ADD entries to ship a new "
                    "violation — fix it or justify a # lint: allow "
                    "(DEPLOY.md §1i).",
         "findings": recs}, indent=2) + "\n", encoding="utf-8")


def diff_baseline(findings: Sequence[Finding], allowed: Counter
                  ) -> Tuple[List[Finding], int]:
    """(new findings, stale baseline entries). A fingerprint's findings
    beyond its baselined count are new; baseline entries with no live
    finding left are stale (burned down — prune with --write-baseline)."""
    remaining = Counter(allowed)
    new: List[Finding] = []
    for f in findings:
        if remaining[f.fingerprint] > 0:
            remaining[f.fingerprint] -= 1
        else:
            new.append(f)
    stale = sum(n for n in remaining.values() if n > 0)
    return new, stale
