"""trace-hazard: python control flow / coercion on traced values.

Inside a function being traced by ``jit``/``pjit``/``pallas_call``,
array arguments are tracers. Three things silently cost a cold compile,
raise ``ConcretizationTypeError``, or — worst — hang a multihost pod:

- **python branching on a traced value** (``if``/``while``/ternary/
  ``assert``): forces concretization. Branching on ``x.shape``/
  ``x.ndim``/``x.dtype`` or identity (``x is None``) is static and
  exempt;
- **scalar coercion** — ``int()``/``bool()``/``float()``/``.item()``/
  ``.tolist()`` on a traced value: same concretization, usually smuggled
  in via an innocent-looking ``max()`` or format string;
- **unordered-collection iteration feeding pytree construction**: a
  ``for``/comprehension over a ``set`` inside traced code bakes
  iteration order into the jaxpr. Set order varies across processes
  (PYTHONHASHSEED), so two pod hosts can trace DIFFERENT programs from
  identical source — the desync the PR-5 heartbeat barrier only catches
  after it hangs. (Python dicts are insertion-ordered and exempt; a
  dict BUILT from a set inherits the hazard at the set.)

Reachability: roots are functions decorated with ``jit``/``pjit``
(directly or via ``functools.partial``) — minus their
``static_argnames``/``static_argnums`` parameters — and kernels passed
to ``pallas_call`` (every parameter is a Ref). Taint then propagates
through same-module calls: an argument expression containing a traced
name marks the callee's parameter traced, to a fixpoint. Assignments
propagate taint locally (``y = x * 2`` taints ``y``).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from .core import (Finding, LintPass, Module, Project, arg_names,
                   const_int_tuple, const_str_tuple, dotted, iter_functions,
                   parent_map, terminal_name)

JIT_NAMES = {"jit", "pjit"}
# Calls whose result is a tracer when any input is (taint conduits).
DEVICE_PREFIXES = ("jnp.", "jax.lax.", "jax.nn.", "jax.random.", "lax.",
                   "jax.vmap", "vmap")
# Attribute reads that are static under tracing (shape metadata).
STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "sharding", "aval",
                "weak_type"}
COERCIONS = {"int", "bool", "float", "complex"}
CONCRETIZING_METHODS = {"item", "tolist"}
MAX_ROUNDS = 8


def _jit_statics(deco: ast.Call) -> Tuple[Set[str], Set[int]]:
    names: Set[str] = set()
    nums: Set[int] = set()
    for kw in deco.keywords:
        if kw.arg == "static_argnames":
            names |= set(const_str_tuple(kw.value))
        elif kw.arg == "static_argnums":
            nums |= set(const_int_tuple(kw.value))
    return names, nums


def _traced_root_params(fn: ast.FunctionDef) -> Optional[Set[str]]:
    """Param names traced when ``fn`` is a jit/pjit root, else None."""
    for deco in fn.decorator_list:
        call = deco if isinstance(deco, ast.Call) else None
        target = terminal_name(call.func if call else deco)
        if target == "partial" and call and call.args:
            inner = terminal_name(call.args[0])
            if inner not in JIT_NAMES:
                continue
        elif target not in JIT_NAMES:
            continue
        params = arg_names(fn)
        if call is not None:
            static_names, static_nums = _jit_statics(call)
        else:
            static_names, static_nums = set(), set()
        return {p for i, p in enumerate(params)
                if p not in static_names and i not in static_nums}
    return None


def _pallas_kernels(mod: Module) -> Set[str]:
    """Names of functions passed (by name) to pallas_call in this
    module — every parameter of a Pallas kernel is a traced Ref."""
    kernels: Set[str] = set()
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Call) \
                and terminal_name(node.func) == "pallas_call" and node.args:
            name = terminal_name(node.args[0])
            if name:
                kernels.add(name)
    return kernels


class _FunctionScan:
    """Per-function taint scan. ``traced`` seeds from the root/propagated
    parameter set; assignments extend it in source-line order."""

    def __init__(self, pass_name: str, mod: Module, qual: str,
                 fn: ast.FunctionDef, traced: Set[str]):
        self.pass_name = pass_name
        self.mod = mod
        self.qual = qual
        self.fn = fn
        self.traced = set(traced)
        self.parents = parent_map(fn)
        self.findings: List[Finding] = []
        # calls into same-module defs with traced args: (callee, {pos})
        self.propagations: List[Tuple[str, Dict[int, bool],
                                      Dict[str, bool]]] = []

    # -- taint ----------------------------------------------------------------

    def _is_static_use(self, name_node: ast.AST) -> bool:
        """True when this traced-name occurrence only feeds static
        metadata (x.shape, len-free), or an identity test."""
        node = name_node
        parent = self.parents.get(node)
        while parent is not None and not isinstance(parent, ast.stmt):
            if isinstance(parent, ast.Attribute) and parent.value is node \
                    and parent.attr in STATIC_ATTRS:
                return True
            if isinstance(parent, ast.Compare) \
                    and all(isinstance(op, (ast.Is, ast.IsNot))
                            for op in parent.ops):
                return True
            node, parent = parent, self.parents.get(parent)
        return False

    def _tainted_names(self, expr: ast.AST) -> List[ast.Name]:
        """Traced names feeding ``expr`` DIRECTLY. Names nested inside
        other calls are shielded: ``is_per_row_keys(key)`` inspects
        ``key.ndim`` and returns a static bool — only jnp/lax/random
        calls are known to return tracers for tracer inputs."""
        shielded: Set[int] = set()
        for n in ast.walk(expr):
            if not isinstance(n, ast.Call):
                continue
            path = dotted(n.func) or ""
            if not path.startswith(DEVICE_PREFIXES):
                shielded.update(id(x) for x in ast.walk(n))
                shielded.discard(id(n))
        out = []
        for n in ast.walk(expr):
            if isinstance(n, ast.Name) and n.id in self.traced \
                    and isinstance(n.ctx, ast.Load) \
                    and id(n) not in shielded \
                    and not self._is_static_use(n):
                out.append(n)
        return out

    def _expr_tainted(self, expr: ast.AST) -> bool:
        if isinstance(expr, ast.Call):
            # Result taint only through tracer-producing calls.
            path = dotted(expr.func) or ""
            if not path.startswith(DEVICE_PREFIXES):
                return False
            return any(self._expr_tainted(a) for a in expr.args) \
                or any(self._expr_tainted(kw.value)
                       for kw in expr.keywords)
        return bool(self._tainted_names(expr))

    # -- scan -----------------------------------------------------------------

    def scan(self, module_defs: Dict[str, ast.FunctionDef]) -> None:
        nested: Set[int] = set()
        for child in ast.walk(self.fn):
            if child is not self.fn and isinstance(
                    child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                nested.update(id(n) for n in ast.walk(child))
        nodes = [n for n in ast.walk(self.fn) if id(n) not in nested]
        nodes.sort(key=lambda n: (getattr(n, "lineno", 0),
                                  getattr(n, "col_offset", 0)))
        for node in nodes:
            if isinstance(node, ast.Assign) and self._expr_tainted(node.value):
                for t in node.targets:
                    for n in ast.walk(t):
                        if isinstance(n, ast.Name):
                            self.traced.add(n.id)
            elif isinstance(node, (ast.If, ast.While)):
                self._flag_branch(node.test, "python branch")
            elif isinstance(node, ast.IfExp):
                self._flag_branch(node.test, "conditional expression")
            elif isinstance(node, ast.Assert):
                self._flag_branch(node.test, "assert")
            elif isinstance(node, ast.Call):
                self._check_call(node, module_defs)
            elif isinstance(node, (ast.For, ast.comprehension)):
                it = node.iter
                if self._is_unordered(it):
                    self.findings.append(Finding(
                        self.pass_name, self.mod.rel,
                        getattr(node, "lineno", it.lineno), self.qual,
                        "iteration over an unordered set inside traced "
                        "code — pytree/program order can differ across "
                        "hosts (retrace or multihost desync); sort it or "
                        "use an ordered collection"))

    def _is_unordered(self, it: ast.AST) -> bool:
        if isinstance(it, ast.Set):
            return True
        if isinstance(it, ast.Call) \
                and terminal_name(it.func) in {"set", "frozenset"}:
            return True
        return False

    def _flag_branch(self, test: ast.AST, what: str) -> None:
        hits = self._tainted_names(test)
        if hits:
            self.findings.append(Finding(
                self.pass_name, self.mod.rel, hits[0].lineno, self.qual,
                f"{what} on traced value '{hits[0].id}' — concretizes "
                f"under jit (trace error or silent host sync + retrace); "
                f"use lax.cond/lax.select or hoist the branch out of the "
                f"traced region"))

    def _check_call(self, call: ast.Call,
                    module_defs: Dict[str, ast.FunctionDef]) -> None:
        name = terminal_name(call.func)
        if isinstance(call.func, ast.Name) and name in COERCIONS:
            for arg in call.args:
                hits = self._tainted_names(arg)
                if hits:
                    self.findings.append(Finding(
                        self.pass_name, self.mod.rel, call.lineno,
                        self.qual,
                        f"{name}() coerces traced value '{hits[0].id}' "
                        f"to a python scalar inside traced code — use "
                        f"jnp/lax equivalents or mark the argument "
                        f"static"))
                    return
        if isinstance(call.func, ast.Attribute) \
                and call.func.attr in CONCRETIZING_METHODS \
                and self._expr_tainted(call.func.value):
            base = dotted(call.func.value) or "<expr>"
            self.findings.append(Finding(
                self.pass_name, self.mod.rel, call.lineno, self.qual,
                f".{call.func.attr}() on traced value '{base}' inside "
                f"traced code — concretization hazard"))
            return
        # Same-module taint propagation: record which callee params
        # receive traced expressions.
        if name in module_defs and isinstance(call.func, ast.Name):
            by_pos = {i: True for i, a in enumerate(call.args)
                      if self._expr_tainted(a)}
            by_kw = {kw.arg: True for kw in call.keywords
                     if kw.arg and self._expr_tainted(kw.value)}
            if by_pos or by_kw:
                self.propagations.append((name, by_pos, by_kw))


class TraceHazardPass(LintPass):
    name = "trace-hazard"

    def run(self, project: Project) -> List[Finding]:
        findings: List[Finding] = []
        for mod in project.modules:
            findings.extend(self._run_module(mod))
        return findings

    def _run_module(self, mod: Module) -> List[Finding]:
        defs: Dict[str, ast.FunctionDef] = {}
        quals: Dict[str, str] = {}
        for q, fn in iter_functions(mod):
            defs.setdefault(fn.name, fn)
            quals.setdefault(fn.name, q)
        kernels = _pallas_kernels(mod)
        # Seed traced-param sets per function name.
        traced: Dict[str, Set[str]] = {}
        for q, fn in iter_functions(mod):
            root = _traced_root_params(fn)
            if fn.name in kernels:
                root = set(arg_names(fn))
            if root is not None:
                traced[fn.name] = set(traced.get(fn.name, set())) | root
        findings: List[Finding] = []
        seen: Dict[str, frozenset] = {}
        for _ in range(MAX_ROUNDS):
            frontier = {n: p for n, p in traced.items()
                        if seen.get(n) != frozenset(p)}
            if not frontier:
                break
            round_findings: List[Finding] = []
            new_traced: Dict[str, Set[str]] = {}
            for name, params in sorted(frontier.items()):
                seen[name] = frozenset(params)
                fn = defs.get(name)
                if fn is None:
                    continue
                scan = _FunctionScan(self.name, mod, quals[name], fn,
                                     params)
                scan.scan(defs)
                round_findings.extend(scan.findings)
                for callee, by_pos, by_kw in scan.propagations:
                    target = defs.get(callee)
                    if target is None or callee in kernels:
                        continue
                    names = arg_names(target)
                    marked = new_traced.setdefault(
                        callee, set(traced.get(callee, set())))
                    for i in by_pos:
                        if i < len(names):
                            marked.add(names[i])
                    for kw in by_kw:
                        if kw in names:
                            marked.add(kw)
            # Findings are recomputed per round as taint widens; keep
            # only the final round's scan per function by replacing.
            findings = [f for f in findings
                        if f.scope not in {quals.get(n) for n in frontier}]
            findings.extend(round_findings)
            for name, params in new_traced.items():
                traced[name] = set(traced.get(name, set())) | params
        return findings
