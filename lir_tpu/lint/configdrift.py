"""config-drift: every config knob is flagged, documented, and cache-keyed.

PR 7's ``fused_decode`` had to be HAND-re-keyed into the compile-cache
manifest after review noticed a new knob changed compiled code without
changing ``compile_cache.manifest_key`` — the exact silent-staleness
class the persistent cache was built to make impossible. This pass
closes the loop structurally. For every field of ``RuntimeConfig`` and
``ServeConfig`` (``lir_tpu/config.py``):

1. **CLI flag** — ``lir_tpu/cli.py`` must mention the field: the
   snake_case identifier (``rt_kw["field"]`` / ``args.field``), its
   kebab-case flag, or the spelling declared by a ``# cli: --flag``
   trailing comment on the field (for renamed flags like
   ``linger_s`` → ``--linger-ms``).
2. **DEPLOY.md mention** — the operator manual must contain the field
   name or its declared flag. A knob nobody can find is a knob set
   wrong at 3am.
3. **manifest-key coverage** (RuntimeConfig only) — the engine's
   ``cache_manifest_key`` must pass the WHOLE RuntimeConfig to
   ``compile_cache.manifest_key`` (the ``self.rt`` argument — then
   every present and future field is canonicalized into the key by
   construction). If that call site ever degrades into a hand-picked
   projection (a Dict literal / constructor call), every field absent
   from the projection and not marked ``# host-only`` is flagged —
   ``fused_decode`` can never happen again.

A field that deliberately has no flag (composite policy objects,
derived values) carries ``# lint: allow(config-drift)`` with the
justification in the surrounding comment. ``# host-only`` marks fields
that cannot change compiled executables (watchdog deadlines, barrier
timeouts) and therefore owe nothing to the manifest key.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set, Tuple

from .core import Finding, LintPass, Module, Project, dotted, terminal_name

CONFIG_REL = "lir_tpu/config.py"
CLI_REL = "lir_tpu/cli.py"
RUNNER_REL = "lir_tpu/engine/runner.py"
DEPLOY_REL = "DEPLOY.md"
CLASSES = ("RuntimeConfig", "ServeConfig", "ObserveConfig", "SpecConfig",
           "RouterConfig", "GovernorConfig", "MigrationConfig",
           "CascadeConfig", "TierConfig")

CLI_COMMENT_RE = re.compile(r"#\s*cli:\s*(--[A-Za-z0-9-]+)")
HOST_ONLY_RE = re.compile(r"#\s*host-only\b")


def _fields(mod: Module, cls: ast.ClassDef
            ) -> List[Tuple[str, int, Optional[str], bool]]:
    """(name, line, declared cli flag, host_only) per dataclass field."""
    out = []
    for node in cls.body:
        if isinstance(node, ast.AnnAssign) \
                and isinstance(node.target, ast.Name):
            end = getattr(node, "end_lineno", node.lineno)
            flag = None
            host_only = False
            for line in range(node.lineno, end + 1):
                text = mod.line_text(line)
                m = CLI_COMMENT_RE.search(text)
                if m and flag is None:
                    flag = m.group(1)
                if HOST_ONLY_RE.search(text):
                    host_only = True
            out.append((node.target.id, node.lineno, flag, host_only))
    return out


def _manifest_runtime_arg(runner: Module) -> Optional[ast.AST]:
    """The ``runtime`` argument of the manifest_key(...) call site."""
    for node in ast.walk(runner.tree):
        if isinstance(node, ast.Call) \
                and terminal_name(node.func) == "manifest_key":
            if len(node.args) >= 2:
                return node.args[1]
            for kw in node.keywords:
                if kw.arg == "runtime":
                    return kw.value
    return None


def _projection_keys(node: ast.AST) -> Optional[Set[str]]:
    """Keys of a hand-built projection (Dict literal / dict(...) call),
    or None when the argument is a whole config object."""
    if isinstance(node, ast.Dict):
        return {k.value for k in node.keys
                if isinstance(k, ast.Constant) and isinstance(k.value, str)}
    if isinstance(node, ast.Call) and terminal_name(node.func) == "dict":
        return {kw.arg for kw in node.keywords if kw.arg}
    return None


class ConfigDriftPass(LintPass):
    name = "config-drift"

    def run(self, project: Project) -> List[Finding]:
        cfg = project.module(CONFIG_REL)
        if cfg is None:
            return []
        cli = project.module(CLI_REL)
        cli_src = cli.source if cli is not None else ""
        deploy = project.text(DEPLOY_REL) or ""
        runner = project.module(RUNNER_REL)
        findings: List[Finding] = []

        projection: Optional[Set[str]] = None
        have_manifest_call = False
        if runner is not None:
            arg = _manifest_runtime_arg(runner)
            if arg is not None:
                have_manifest_call = True
                projection = _projection_keys(arg)

        for node in ast.walk(cfg.tree):
            if not isinstance(node, ast.ClassDef) or node.name not in CLASSES:
                continue
            for name, line, flag, host_only in _fields(cfg, node):
                scope = f"{node.name}.{name}"
                kebab = name.replace("_", "-")
                spellings = [name, f"--{kebab}"]
                if flag:
                    spellings.append(flag)
                if not any(s in cli_src for s in spellings):
                    findings.append(Finding(
                        self.name, cfg.rel, line, scope,
                        f"config field '{name}' has no cli.py flag "
                        f"(looked for --{kebab}, the identifier, or a "
                        f"`# cli: --flag` declaration) — every knob must "
                        f"be reachable without editing source"))
                if not any(s in deploy for s in spellings):
                    findings.append(Finding(
                        self.name, cfg.rel, line, scope,
                        f"config field '{name}' is not mentioned in "
                        f"DEPLOY.md — document what it does and when to "
                        f"change it"))
                if node.name == "RuntimeConfig" and not host_only:
                    if not have_manifest_call:
                        findings.append(Finding(
                            self.name, cfg.rel, line, scope,
                            f"no compile_cache.manifest_key call site "
                            f"found covering RuntimeConfig field "
                            f"'{name}' — compiled-shape knobs must "
                            f"participate in the cache key"))
                    elif projection is not None and name not in projection:
                        findings.append(Finding(
                            self.name, cfg.rel, line, scope,
                            f"RuntimeConfig field '{name}' is missing "
                            f"from the hand-built manifest_key "
                            f"projection — a stale compile cache can "
                            f"serve this knob's old executables; add it "
                            f"or pass the whole RuntimeConfig"))
        return findings
