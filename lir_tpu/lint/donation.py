"""donation-safety: no read of a binding after it was donated.

``jax.jit(..., donate_argnums=...)`` / ``donate_argnames=...`` hands the
argument's device buffer to XLA for reuse as an output buffer: the
moment the donating call dispatches, the caller's binding points at a
DELETED buffer, and touching it raises (best case) or — under the
engine's async dispatch chains — silently reads freed memory on a
runtime that doesn't check. The motivating surfaces are the
``CacheHandoff`` donation chain threaded through ``engine/runner.py``
and the page pool's donated ``scatter_pages`` (``models/paged.py``): a
refactor that innocently logs or re-dispatches a cache after handing it
off is exactly the class of bug the PR-5 guard layer only sees as a
runtime crash on device.

Mechanics (two phases, whole-project):

1. **Registry**: every ``FunctionDef`` whose decorators include
   ``jit``/``pjit`` (directly or via ``functools.partial``) with
   ``donate_argnames``/``donate_argnums`` is recorded with its donated
   parameter names/positions; ``name = jax.jit(fn, donate_argnums=...)``
   module-level assignments register under the ASSIGNED name too.
2. **Call-site scan**: in every function body, a call to a registered
   donor with a plain name (or dotted attribute) in a donated slot marks
   that binding dead from the call's line on; any later load of the same
   binding in the same function — without an intervening rebind — is a
   finding. ``x = f(x)`` rebinding on the donating statement itself is
   the sanctioned chain idiom and clears the binding.

The line-order approximation (source order stands in for control flow)
is deliberate: it is exact for the straight-line dispatch code this
engine writes, and a branch-heavy false positive is a ``# lint:
allow(donation-safety)`` with a justification — cheap next to a
use-after-donate on a pod.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional, Set, Tuple

from .core import (Finding, LintPass, Module, Project, arg_names,
                   const_int_tuple, const_str_tuple, dotted, iter_functions,
                   parent_map, terminal_name)

JIT_NAMES = {"jit", "pjit"}

# Donors the registry scan can't see syntactically: compile_plan.
# registry_call feeds its ``scratch`` argument to an AOT-compiled
# executable whose donation signature mirrors the lazy-jit fallback's —
# the caller's scratch binding is just as dead afterwards.
EXTRA_DONORS = {
    "registry_call": ("exe", "dyn_args", "stop_kwargs", "scratch"),
}
EXTRA_DONATED = {"registry_call": {"scratch"}}


@dataclasses.dataclass
class DonorSig:
    """A callable that donates some of its arguments."""

    name: str
    params: List[str]              # positional order, '' when unknown
    donated_names: Set[str]
    donated_positions: Set[int]

    def donated_param(self, index: int, keyword: Optional[str]
                      ) -> Optional[str]:
        """The donated parameter a call-site argument lands in, else
        None. ``index`` for positional args, ``keyword`` for keywords
        (``**kwargs`` splats pass keyword=None and never match — the
        dict binding itself is not the donated buffer)."""
        if keyword is not None:
            if keyword in self.donated_names:
                return keyword
            if self.params and keyword in self.params:
                if self.params.index(keyword) in self.donated_positions:
                    return keyword
            return None
        if index < 0:
            return None
        if index in self.donated_positions:
            return (self.params[index] if index < len(self.params)
                    else f"arg{index}")
        if self.params and index < len(self.params) \
                and self.params[index] in self.donated_names:
            return self.params[index]
        return None


def _donation_kwargs(call: ast.Call) -> Tuple[Set[str], Set[int]]:
    names: Set[str] = set()
    nums: Set[int] = set()
    for kw in call.keywords:
        if kw.arg == "donate_argnames":
            names |= set(const_str_tuple(kw.value))
        elif kw.arg == "donate_argnums":
            nums |= set(const_int_tuple(kw.value))
    return names, nums


def _jit_call_with_donation(node: ast.AST) -> Optional[Tuple[Set[str],
                                                             Set[int]]]:
    """``node`` is a Call of jit/pjit or partial(jit/pjit, ...) carrying
    donation kwargs -> (donated names, donated positions)."""
    if not isinstance(node, ast.Call):
        return None
    t = terminal_name(node.func)
    if t == "partial" and node.args:
        inner = terminal_name(node.args[0])
        if inner not in JIT_NAMES:
            return None
    elif t not in JIT_NAMES:
        return None
    names, nums = _donation_kwargs(node)
    if not names and not nums:
        return None
    return names, nums


def build_registry(project: Project) -> Dict[str, DonorSig]:
    """Donating callables by terminal name, across every module."""
    registry: Dict[str, DonorSig] = {}
    for mod in project.modules:
        defs = {q.rsplit(".", 1)[-1]: fn for q, fn in iter_functions(mod)}
        for q, fn in iter_functions(mod):
            for deco in fn.decorator_list:
                don = _jit_call_with_donation(deco)
                if don is not None:
                    names, nums = don
                    registry[fn.name] = DonorSig(
                        fn.name, arg_names(fn), set(names), set(nums))
        # name = jax.jit(fn, donate_argnums=...) assignments
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Assign) or len(node.targets) != 1:
                continue
            target = node.targets[0]
            if not isinstance(target, ast.Name):
                continue
            don = _jit_call_with_donation(node.value)
            if don is None:
                continue
            names, nums = don
            wrapped = node.value
            params: List[str] = []
            if isinstance(wrapped, ast.Call) and wrapped.args:
                base = wrapped.args[0]
                if terminal_name(wrapped.func) == "partial" \
                        and len(wrapped.args) > 1:
                    base = wrapped.args[1]
                base_name = terminal_name(base)
                if base_name in defs:
                    params = arg_names(defs[base_name])
            registry[target.id] = DonorSig(target.id, params, set(names),
                                           set(nums))
    for name, params in EXTRA_DONORS.items():
        registry.setdefault(name, DonorSig(
            name, list(params), set(EXTRA_DONATED[name]), set()))
    return registry


class DonationPass(LintPass):
    name = "donation-safety"

    def run(self, project: Project) -> List[Finding]:
        registry = build_registry(project)
        if not registry:
            return []
        findings: List[Finding] = []
        for mod in project.modules:
            for qual, fn in iter_functions(mod):
                findings.extend(self._check_function(mod, qual, fn,
                                                     registry))
        return findings

    def _check_function(self, mod: Module, qual: str, fn: ast.FunctionDef,
                        registry: Dict[str, DonorSig]) -> List[Finding]:
        # Gather loads/stores of dotted bindings and donation events, all
        # keyed by line (source order approximates control flow; see
        # module docstring). Nested defs are checked separately — skip
        # their bodies here.
        findings: List[Finding] = []
        nested: Set[int] = set()
        for child in ast.walk(fn):
            if child is fn:
                continue
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                nested.update(id(n) for n in ast.walk(child))
        parents = parent_map(fn)
        loads: List[Tuple[str, int, ast.AST]] = []
        stores: List[Tuple[str, int, ast.AST]] = []
        events: List[Tuple[str, str, str, int, int, ast.AST]] = []
        for node in ast.walk(fn):
            if id(node) in nested and node is not fn:
                continue
            if isinstance(node, (ast.Name, ast.Attribute)):
                path = dotted(node)
                if path is None:
                    continue
                ctx = getattr(node, "ctx", None)
                if isinstance(ctx, ast.Store):
                    stores.append((path, node.lineno, node))
                elif isinstance(ctx, ast.Load):
                    loads.append((path, node.lineno, node))
                continue
            if isinstance(node, ast.Call):
                callee = terminal_name(node.func)
                sig = registry.get(callee or "")
                if sig is None:
                    continue
                end = getattr(node, "end_lineno", node.lineno)
                for i, arg in enumerate(node.args):
                    param = sig.donated_param(i, None)
                    path = dotted(arg)
                    if param and path:
                        events.append((path, param, callee, node.lineno,
                                       end, node))
                for kw in node.keywords:
                    if kw.arg is None:       # **splat: not a donated slot
                        continue
                    param = sig.donated_param(-1, kw.arg)
                    path = dotted(kw.value)
                    if param and path:
                        events.append((path, param, callee, node.lineno,
                                       end, node))
        for path, param, callee, line, end, call_node in events:
            # A rebind on/after the donating statement revives the name
            # (the x = f(x) chain idiom assigns AFTER the call returns).
            rebinds = sorted(
                l for p, l, n in stores
                if p == path and l >= line
                and not _exclusive_branches(call_node, n, parents))
            for lpath, lline, lnode in sorted(loads, key=lambda t: t[1]):
                if lpath != path or lline <= end:
                    continue
                if rebinds and rebinds[0] <= lline:
                    break
                if _exclusive_branches(call_node, lnode, parents):
                    continue      # read sits in the sibling if/else arm
                if _identity_use(lnode, parents):
                    continue      # `x is None` touches the ref, not the
                    #               dead buffer
                findings.append(Finding(
                    self.name, mod.rel, lline, qual,
                    f"'{path}' is read after being donated to "
                    f"{callee}() (parameter '{param}') — the buffer is "
                    f"dead once the donating call dispatches; rebind the "
                    f"name from the call's result or drop the read"))
                break          # one finding per donation event
        return findings


def _branch_chain(node: ast.AST, parents: Dict[ast.AST, ast.AST]
                  ) -> Dict[int, str]:
    """{id(if_stmt): arm} for every enclosing If — 'body' or 'orelse'."""
    chain: Dict[int, str] = {}
    cur = node
    parent = parents.get(cur)
    while parent is not None:
        if isinstance(parent, ast.If):
            in_body = any(cur is s or any(cur is w for w in ast.walk(s))
                          for s in parent.body)
            chain[id(parent)] = "body" if in_body else "orelse"
        cur, parent = parent, parents.get(parent)
    return chain


def _exclusive_branches(a: ast.AST, b: ast.AST,
                        parents: Dict[ast.AST, ast.AST]) -> bool:
    """True when ``a`` and ``b`` sit in different arms of a shared If —
    line order lies about reachability there."""
    ca, cb = _branch_chain(a, parents), _branch_chain(b, parents)
    return any(ca[k] != cb[k] for k in ca.keys() & cb.keys())


def _identity_use(node: ast.AST, parents: Dict[ast.AST, ast.AST]) -> bool:
    """The load only feeds an ``is``/``is not`` test: identity checks
    touch the python reference, never the (dead) device buffer."""
    parent = parents.get(node)
    cur = node
    while parent is not None and not isinstance(parent, ast.stmt):
        if isinstance(parent, ast.Compare) \
                and all(isinstance(op, (ast.Is, ast.IsNot))
                        for op in parent.ops):
            return True
        cur, parent = parent, parents.get(parent)
    return False
