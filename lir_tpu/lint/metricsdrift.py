"""metrics-drift: every counter field reaches the metrics endpoint.

Sibling of config-drift, closing the same class of silent hole one
layer up: config-drift proves every knob is REACHABLE; this pass proves
every counter is OBSERVABLE. The unified telemetry spine
(lir_tpu/observe/registry.py) snapshots each registered ``*Stats``
object through :data:`~lir_tpu.observe.registry.STATS_SCHEMA` — a pure
dict literal mapping class name → tuple of public field names. A PR
that adds a counter field to a ``*Stats`` dataclass in
utils/profiling.py without adding it to that schema ships a counter the
``{"op": "metrics"}`` endpoint silently never reports. This pass makes
that a lint failure:

1. every ``*Stats`` class in utils/profiling.py must have a
   STATS_SCHEMA entry;
2. every PUBLIC dataclass field (AnnAssign, no leading underscore) of
   such a class must appear in its entry's tuple;
3. schema entries naming fields that no longer exist are stale —
   flagged too, so the schema cannot rot in the other direction.

Underscore-prefixed fields are implementation detail (locks, ring
buffers) and owe nothing to the endpoint. A field that deliberately
stays out of the snapshot carries ``# lint: allow(metrics-drift)``
with its justification.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Tuple

from .core import Finding, LintPass, Module, Project

PROFILING_REL = "lir_tpu/utils/profiling.py"
REGISTRY_REL = "lir_tpu/observe/registry.py"
SCHEMA_NAME = "STATS_SCHEMA"


def _stats_classes(mod: Module) -> List[ast.ClassDef]:
    return [node for node in ast.walk(mod.tree)
            if isinstance(node, ast.ClassDef)
            and node.name.endswith("Stats")]


def _public_fields(cls: ast.ClassDef) -> List[Tuple[str, int]]:
    out = []
    for node in cls.body:
        if isinstance(node, ast.AnnAssign) \
                and isinstance(node.target, ast.Name) \
                and not node.target.id.startswith("_"):
            out.append((node.target.id, node.lineno))
    return out


def _parse_schema(mod: Module) -> Optional[Dict[str, Tuple[str, ...]]]:
    """The STATS_SCHEMA literal: {str: (str, ...)}; None when absent."""
    for node in ast.walk(mod.tree):
        target = None
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            target = node.targets[0]
        elif isinstance(node, ast.AnnAssign):
            target = node.target
        else:
            continue
        if not (isinstance(target, ast.Name)
                and target.id == SCHEMA_NAME):
            continue
        value = node.value
        if not isinstance(value, ast.Dict):
            return None
        schema: Dict[str, Tuple[str, ...]] = {}
        for k, v in zip(value.keys, value.values):
            if not (isinstance(k, ast.Constant)
                    and isinstance(k.value, str)):
                continue
            if isinstance(v, (ast.Tuple, ast.List)):
                schema[k.value] = tuple(
                    e.value for e in v.elts
                    if isinstance(e, ast.Constant)
                    and isinstance(e.value, str))
        return schema
    return None


class MetricsDriftPass(LintPass):
    name = "metrics-drift"

    def run(self, project: Project) -> List[Finding]:
        prof = project.module(PROFILING_REL)
        if prof is None:
            return []
        classes = _stats_classes(prof)
        if not classes:
            return []
        reg = project.module(REGISTRY_REL)
        schema = _parse_schema(reg) if reg is not None else None
        findings: List[Finding] = []
        if schema is None:
            findings.append(Finding(
                self.name, prof.rel, 1, "<module>",
                f"no parseable {SCHEMA_NAME} dict literal in "
                f"{REGISTRY_REL} — the metrics endpoint has no snapshot "
                f"schema to hold these *Stats counters"))
            return findings
        seen_fields: Dict[str, set] = {}
        for cls in classes:
            fields = _public_fields(cls)
            seen_fields[cls.name] = {n for n, _ in fields}
            declared = schema.get(cls.name)
            if declared is None:
                findings.append(Finding(
                    self.name, prof.rel, cls.lineno, cls.name,
                    f"stats class '{cls.name}' has no {SCHEMA_NAME} "
                    f"entry in {REGISTRY_REL} — its counters never "
                    f"reach the metrics endpoint"))
                continue
            for fname, line in fields:
                if fname not in declared:
                    findings.append(Finding(
                        self.name, prof.rel, line,
                        f"{cls.name}.{fname}",
                        f"counter field '{fname}' is missing from "
                        f"{SCHEMA_NAME}['{cls.name}'] — it silently "
                        f"drops out of the metrics snapshot; add it "
                        f"(or justify a lint allow)"))
        for cls_name, declared in schema.items():
            have = seen_fields.get(cls_name)
            if have is None:
                continue        # schema may describe classes elsewhere
            for fname in declared:
                if fname not in have:
                    findings.append(Finding(
                        self.name, prof.rel, 1, f"{cls_name}.{fname}",
                        f"{SCHEMA_NAME}['{cls_name}'] declares "
                        f"'{fname}' but the dataclass has no such "
                        f"public field — stale schema entry"))
        return findings
