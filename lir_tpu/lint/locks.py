"""lock-discipline: annotated shared state mutates only under its lock.

The serve/guard layer is the engine's only genuinely threaded surface:
submitter threads, the supervisor loop, the watchdog's watched workers
and the sweep writer all touch batcher queues, breaker state and
calibration EWMAs. The convention this pass enforces (DEPLOY.md §1i):

- **Attribute annotation** — a trailing comment on the attribute's
  ``__init__`` assignment::

      self._dq = deque()        # guarded-by: _lock | _nonempty

  declares that ``self._dq`` may only be MUTATED (assignment,
  aug-assignment, ``del``, or a mutating method call such as
  ``.append()``/``.pop()``/``.update()``) inside a ``with self._lock:``
  (or ``with self._nonempty:``) block. ``|``/``,`` list alternatives —
  a ``Condition`` wraps the same underlying lock as the ``Lock`` it was
  built from. Reads are NOT enforced (racy reads of monotonic counters
  are this codebase's accepted idiom); single-thread-confined state
  simply stays unannotated.
- **Held-by-caller annotation** — the same comment on (or directly
  above) a ``def`` line::

      def _transition(self, to):   # guarded-by: _lock

  declares the method runs with the lock already held (the
  ``_promote_locked`` idiom); its mutations of attributes guarded by
  that lock are exempt.
- ``__init__`` itself is exempt (construction happens-before
  publication), as is any line carrying ``# lint:
  allow(lock-discipline)``.

The pass also cross-checks that every named lock is actually created in
``__init__`` (``threading.Lock/RLock/Condition``) — an annotation
naming a lock that does not exist is a typo worth failing on.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set, Tuple

from .core import (Finding, LintPass, Module, Project, dotted,
                   parent_map, terminal_name)

GUARDED_RE = re.compile(
    r"#\s*guarded-by:\s*([A-Za-z_]\w*(?:\s*[|,]\s*[A-Za-z_]\w*)*)")
LOCK_CTORS = {"Lock", "RLock", "Condition", "Semaphore", "BoundedSemaphore"}
MUTATORS = {"append", "appendleft", "extend", "extendleft", "insert",
            "pop", "popleft", "popitem", "remove", "discard", "clear",
            "update", "add", "setdefault", "sort", "reverse",
            "rotate", "put", "put_nowait", "move_to_end"}


def _parse_locks(text: str) -> Set[str]:
    m = GUARDED_RE.search(text)
    if not m:
        return set()
    return {t.strip() for t in re.split(r"[|,]", m.group(1)) if t.strip()}


def _stmt_annotation(mod: Module, node: ast.stmt) -> Set[str]:
    """Locks named by a guarded-by comment on any source line the
    statement spans (trailing comments usually sit on the first line)."""
    end = getattr(node, "end_lineno", node.lineno)
    locks: Set[str] = set()
    for line in range(node.lineno, end + 1):
        locks |= _parse_locks(mod.line_text(line))
    return locks


def _def_annotation(mod: Module, fn: ast.FunctionDef) -> Set[str]:
    """Held-by-caller locks: comment on the def line or the line above."""
    locks = _parse_locks(mod.line_text(fn.lineno))
    locks |= _parse_locks(mod.line_text(fn.lineno - 1))
    return locks


def _self_attr(node: ast.AST) -> Optional[str]:
    """'x' for self.x; also unwraps self.x[...] subscripts."""
    while isinstance(node, ast.Subscript):
        node = node.value
    if isinstance(node, ast.Attribute) \
            and isinstance(node.value, ast.Name) \
            and node.value.id == "self":
        return node.attr
    return None


class LockDisciplinePass(LintPass):
    name = "lock-discipline"

    def run(self, project: Project) -> List[Finding]:
        findings: List[Finding] = []
        for mod in project.modules:
            if "guarded-by:" not in mod.source:
                continue
            for node in ast.walk(mod.tree):
                if isinstance(node, ast.ClassDef):
                    findings.extend(self._check_class(mod, node))
        return findings

    # -- per class -----------------------------------------------------------

    def _collect(self, mod: Module, cls: ast.ClassDef
                 ) -> Tuple[Dict[str, Set[str]], Set[str]]:
        """(guarded attr -> lock alternatives, locks created in class)."""
        guarded: Dict[str, Set[str]] = {}
        created: Set[str] = set()
        for fn in (n for n in cls.body
                   if isinstance(n, ast.FunctionDef)):
            for node in ast.walk(fn):
                if isinstance(node, (ast.Assign, ast.AnnAssign)):
                    targets = (node.targets
                               if isinstance(node, ast.Assign)
                               else [node.target])
                    value = node.value
                    for t in targets:
                        attr = _self_attr(t)
                        if attr is None:
                            continue
                        locks = _stmt_annotation(mod, node)
                        if locks:
                            guarded.setdefault(attr, set()).update(locks)
                        if isinstance(value, ast.Call) \
                                and terminal_name(value.func) in LOCK_CTORS:
                            created.add(attr)
        return guarded, created

    def _check_class(self, mod: Module, cls: ast.ClassDef) -> List[Finding]:
        guarded, created = self._collect(mod, cls)
        findings: List[Finding] = []
        if not guarded:
            return findings
        all_locks = set().union(*guarded.values())
        for lock in sorted(all_locks - created):
            findings.append(Finding(
                self.name, mod.rel, cls.lineno, cls.name,
                f"guarded-by names lock '{lock}' which is never created "
                f"in {cls.name}.__init__ (threading.Lock/RLock/"
                f"Condition) — typo or missing lock"))
        for fn in (n for n in cls.body if isinstance(n, ast.FunctionDef)):
            if fn.name == "__init__":
                continue
            held = _def_annotation(mod, fn)
            parents = parent_map(fn)
            for node in ast.walk(fn):
                for attr, mutation in self._mutations(node):
                    locks = guarded.get(attr)
                    if not locks or locks & held:
                        continue
                    if self._under_lock(node, parents, locks):
                        continue
                    findings.append(Finding(
                        self.name, mod.rel, node.lineno,
                        f"{cls.name}.{fn.name}",
                        f"{mutation} of 'self.{attr}' (guarded-by "
                        f"{'/'.join(sorted(locks))}) outside a `with "
                        f"self.<lock>:` block — annotate the method "
                        f"`# guarded-by: <lock>` if the caller holds it"))
        return findings

    def _mutations(self, node: ast.AST):
        """Yield (attr, kind) for mutations of self.<attr> at ``node``."""
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            for t in targets:
                attr = _self_attr(t)
                if attr is not None:
                    yield attr, ("augmented assignment"
                                 if isinstance(node, ast.AugAssign)
                                 else "assignment")
        elif isinstance(node, ast.Delete):
            for t in node.targets:
                attr = _self_attr(t)
                if attr is not None:
                    yield attr, "deletion"
        elif isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr in MUTATORS:
            attr = _self_attr(node.func.value)
            if attr is not None:
                yield attr, f".{node.func.attr}() call"

    def _under_lock(self, node: ast.AST, parents: Dict[ast.AST, ast.AST],
                    locks: Set[str]) -> bool:
        cur = parents.get(node)
        while cur is not None:
            if isinstance(cur, ast.With):
                for item in cur.items:
                    ctx = item.context_expr
                    if isinstance(ctx, ast.Call):
                        ctx = ctx.func   # with self._lock: vs acquire()
                    attr = _self_attr(ctx)
                    if attr in locks:
                        return True
            cur = parents.get(cur)
        return False
