"""Command-line entry for graft-lint (`lir_tpu lint` / `make lint`).

Kept free of jax and of every engine import on purpose: the pre-push
hook and bare CI containers run this; budget is seconds (the whole
suite parses ~90 files with stdlib ``ast`` in well under one).
"""

from __future__ import annotations

import argparse
import sys
import time
from pathlib import Path
from typing import List, Optional

from .core import (ALL_PASSES, diff_baseline, load_baseline, load_project,
                   run_passes, save_baseline)

DEFAULT_BASELINE = "tools/lint_baseline.json"


def build_parser(parser: Optional[argparse.ArgumentParser] = None
                 ) -> argparse.ArgumentParser:
    p = parser or argparse.ArgumentParser(
        prog="lir_tpu lint",
        description="AST static analysis proving the engine's JAX/XLA "
                    "invariants (DEPLOY.md §1i)")
    p.add_argument("--root", type=Path, default=None,
                   help="project root (default: the repo this package "
                        "lives in)")
    p.add_argument("--baseline", type=Path, default=None,
                   help=f"baseline file (default {DEFAULT_BASELINE} under "
                        "the root; 'none' disables)")
    p.add_argument("--write-baseline", action="store_true",
                   help="rewrite the baseline to the current findings "
                        "(burn-down bookkeeping; review the diff!)")
    p.add_argument("--select", action="append", default=None,
                   metavar="PASS", choices=sorted(ALL_PASSES),
                   help="run only this pass (repeatable); default all: "
                        f"{', '.join(sorted(ALL_PASSES))}")
    p.add_argument("--all", action="store_true",
                   help="print every finding including baselined ones")
    return p


def run(args: argparse.Namespace) -> int:
    t0 = time.perf_counter()
    root = args.root
    if root is None:
        # lir_tpu/lint/cli.py -> repo root two levels above the package.
        root = Path(__file__).resolve().parent.parent.parent
    project = load_project(root)
    findings = run_passes(project, only=args.select)
    baseline_path = args.baseline
    if baseline_path is None:
        baseline_path = root / DEFAULT_BASELINE
    if args.write_baseline:
        save_baseline(baseline_path, findings)
        print(f"lint: wrote {len(findings)} finding(s) -> {baseline_path}")
        return 0
    use_baseline = str(baseline_path) != "none"
    allowed = load_baseline(baseline_path) if use_baseline else None
    if allowed:
        new, stale = diff_baseline(findings, allowed)
    else:
        new, stale = list(findings), 0
    shown = findings if args.all else new
    for f in shown:
        print(f.render())
    dt = time.perf_counter() - t0
    n_base = len(findings) - len(new)
    print(f"lint: {len(project.modules)} files, {len(findings)} finding(s) "
          f"({n_base} baselined, {len(new)} new) in {dt:.2f}s")
    if stale:
        print(f"lint: {stale} baseline entr{'y' if stale == 1 else 'ies'} "
              f"no longer fire — burn-down! prune with --write-baseline")
    if new:
        print("lint: FAIL — new findings above are not in "
              f"{baseline_path}; fix them or justify a "
              "`# lint: allow(<pass>)` (DEPLOY.md §1i)")
        return 1
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    return run(build_parser().parse_args(argv))


if __name__ == "__main__":
    sys.exit(main())
