"""host-sync: no implicit device→host transfer on the hot path.

JAX dispatch is asynchronous: the sweep/serve loops stay ahead of the
device only while nothing host-side touches a live device value. An
innocent ``np.asarray(x)``, ``float(x)``, ``x.tolist()``, ``if x:`` or
per-element iteration BLOCKS the dispatching thread until the device
catches up — the exact stall class VERDICT r2 measured as the sweep
running at 49% of the isolated scoring rate before the writer thread
split. The sanctioned pattern is one EXPLICIT ``jax.device_get`` at a
readout boundary (off the dispatch thread where possible), then pure
host work on the result.

Scope: the hot-path modules only — ``lir_tpu/engine/``, ``lir_tpu/ops/``
and ``lir_tpu/serve/batcher.py``. Statistics, report, survey and CLI
code sync freely.

Taint: a value is "device" when it flows from a ``jnp.``/``jax.lax.``/
``jax.nn.``/``jax.random.`` call, from a function this project jits
(shared registry with the donation pass), or from one of the engine's
dispatch entry points (:data:`DEVICE_FNS`). ``jax.device_get(...)``
(and ``np.asarray`` itself — flagged once) launder the result back to
host. Taint follows assignments, tuple unpacking, attribute/subscript
access, and same-module calls (a helper called with a device row is
analyzed with that parameter tainted — that is how the reference's
"decode one row at a time straight off the device" bugs get caught at
the helper's ``np.asarray``).

Allowlist for legitimate boundaries: decorate the function with
``@host_readout`` (``lir_tpu/utils/annotations.py``) or put ``# lint:
allow(host-sync)`` on the line; both carry an implicit "this is a
deliberate sync point" claim reviewers can see.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from .core import (Finding, LintPass, Module, Project, arg_names, dotted,
                   iter_functions, parent_map, terminal_name)

HOT_DIRS = ("lir_tpu/engine/", "lir_tpu/ops/")
HOT_FILES = ("lir_tpu/serve/batcher.py",)

DEVICE_PREFIXES = ("jnp.", "jax.lax.", "jax.nn.", "jax.random.", "lax.")
# Engine entry points that return live device values (codebase-specific
# table — the passes are allowed to know this repo).
DEVICE_FNS = {
    "decode_fused", "decode_fused_shared", "decode_fused_grouped",
    "decode_fused_shared_piggy", "piggy_drain", "prefill",
    "readout_from_fused", "readout_from_step_logits", "sample_decode",
    "greedy_decode_fused_shared", "greedy_decode_fused_grouped",
    "greedy_decode_fused_shared_paged", "greedy_decode_fused_grouped_paged",
    "gather_slots", "scatter_pages", "flash_attention", "flash_decode",
    # Streaming-statistics sink (engine/stream_stats.py): the fold
    # update returns the live device accumulator; touching it host-side
    # anywhere but an explicit snapshot() readout is the per-row sync
    # the sink exists to eliminate. (Redundant with the jitted-def
    # registry while fold_update keeps its jax.jit decorator — pinned
    # here so renaming the decorator can't silently drop coverage.)
    "fold_update",
}
LAUNDER_FNS = {"device_get", "block_until_ready"}
NP_TRANSFER = {"asarray", "array", "ascontiguousarray"}
STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "sharding", "aval",
                "weak_type", "nbytes"}
CONCRETIZING_METHODS = {"item", "tolist"}
COERCIONS = {"int", "bool", "float"}
READOUT_DECORATOR = "host_readout"
MAX_ROUNDS = 6


def _jitted_def_names(project: Project) -> Set[str]:
    from .donation import JIT_NAMES  # same decorator grammar

    names: Set[str] = set()
    for mod in project.modules:
        for q, fn in iter_functions(mod):
            for deco in fn.decorator_list:
                call = deco if isinstance(deco, ast.Call) else None
                t = terminal_name(call.func if call else deco)
                if t == "partial" and call and call.args:
                    if terminal_name(call.args[0]) in JIT_NAMES:
                        names.add(fn.name)
                elif t in JIT_NAMES:
                    names.add(fn.name)
    return names


def _is_hot(rel: str) -> bool:
    return rel.startswith(HOT_DIRS) or rel in HOT_FILES


def _has_readout_decorator(fn: ast.FunctionDef) -> bool:
    for deco in fn.decorator_list:
        if terminal_name(deco if not isinstance(deco, ast.Call)
                         else deco.func) == READOUT_DECORATOR:
            return True
    return False


class _Scan:
    def __init__(self, pass_name: str, mod: Module, qual: str,
                 fn: ast.FunctionDef, tainted: Set[str],
                 device_calls: Set[str]):
        self.pass_name = pass_name
        self.mod = mod
        self.qual = qual
        self.fn = fn
        self.tainted = set(tainted)
        self.device_calls = device_calls
        self.parents = parent_map(fn)
        self.findings: List[Finding] = []
        self.flagged_lines: Set[int] = set()
        self.propagations: List[Tuple[str, Dict[int, bool],
                                      Dict[str, bool]]] = []

    def _is_device_call(self, call: ast.Call) -> bool:
        path = dotted(call.func)
        if path and path.startswith(DEVICE_PREFIXES):
            return True
        name = terminal_name(call.func)
        return name in self.device_calls

    def _is_launder_call(self, call: ast.Call) -> bool:
        return terminal_name(call.func) in LAUNDER_FNS

    def _is_static_use(self, node: ast.AST) -> bool:
        parent = self.parents.get(node)
        cur = node
        while parent is not None and not isinstance(parent, ast.stmt):
            if isinstance(parent, ast.Attribute) and parent.value is cur \
                    and parent.attr in STATIC_ATTRS:
                return True
            if isinstance(parent, ast.Compare) \
                    and all(isinstance(op, (ast.Is, ast.IsNot))
                            for op in parent.ops):
                return True
            if isinstance(parent, ast.Call) \
                    and terminal_name(parent.func) in {"len", "isinstance",
                                                       "id", "type", "repr"}:
                return True
            cur, parent = parent, self.parents.get(parent)
        return False

    def _tainted_names(self, expr: ast.AST) -> List[ast.Name]:
        # Names nested inside OTHER calls don't count: ``f(x)`` on a
        # device value usually returns host data (metadata probes,
        # decode helpers) — if ``f`` itself produces device values it is
        # in the device-call table and ``_expr_device`` covers it. A
        # laundering call likewise cleans its own subtree.
        shielded: Set[int] = set()
        for n in ast.walk(expr):
            if not isinstance(n, ast.Call):
                continue
            if self._is_launder_call(n) or not self._is_device_call(n):
                shielded.update(id(x) for x in ast.walk(n))
                shielded.discard(id(n))    # the call node itself may
                #                            still be judged by
                #                            _expr_device
        return [n for n in ast.walk(expr)
                if isinstance(n, ast.Name) and n.id in self.tainted
                and isinstance(n.ctx, ast.Load)
                and id(n) not in shielded
                and not self._is_static_use(n)]

    def _expr_device(self, expr: ast.AST) -> bool:
        """Expression yields a device value: tainted name, or a direct
        device-producing call."""
        if isinstance(expr, ast.Call):
            # A call either produces device values (table/prefix match)
            # or it doesn't — device args to an unknown host function do
            # NOT make its RESULT device (decode helpers, metadata
            # probes return host data; the sync, if any, is inside the
            # callee, which the cross-function propagation analyzes with
            # the tainted parameter).
            return (self._is_device_call(expr)
                    and not self._is_launder_call(expr))
        if isinstance(expr, (ast.Attribute, ast.Subscript)):
            return self._expr_device(expr.value)
        if isinstance(expr, (ast.Tuple, ast.List)):
            return any(self._expr_device(e) for e in expr.elts)
        if isinstance(expr, ast.BinOp):
            return (self._expr_device(expr.left)
                    or self._expr_device(expr.right))
        if isinstance(expr, ast.Name):
            return (expr.id in self.tainted
                    and not self._is_static_use(expr))
        return bool(self._tainted_names(expr))

    def _flag(self, line: int, message: str) -> None:
        if line in self.flagged_lines:
            return
        self.flagged_lines.add(line)
        self.findings.append(Finding(self.pass_name, self.mod.rel, line,
                                     self.qual, message))

    def scan(self, module_defs: Dict[str, ast.FunctionDef]) -> None:
        nested: Set[int] = set()
        for child in ast.walk(self.fn):
            if child is not self.fn and isinstance(
                    child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                nested.update(id(n) for n in ast.walk(child))
        nodes = [n for n in ast.walk(self.fn) if id(n) not in nested]
        nodes.sort(key=lambda n: (getattr(n, "lineno", 0),
                                  getattr(n, "col_offset", 0)))
        for node in nodes:
            if isinstance(node, ast.Assign):
                value_device = self._expr_device(node.value)
                laundered = (isinstance(node.value, ast.Call)
                             and self._is_launder_call(node.value))
                for t in node.targets:
                    for n in ast.walk(t):
                        if isinstance(n, ast.Name):
                            if value_device and not laundered:
                                self.tainted.add(n.id)
                            else:
                                self.tainted.discard(n.id)
            elif isinstance(node, (ast.If, ast.While)):
                hits = self._tainted_names(node.test)
                if hits:
                    self._flag(hits[0].lineno,
                               f"truthiness of device value "
                               f"'{hits[0].id}' blocks on the device — "
                               f"jax.device_get at an explicit readout "
                               f"boundary first")
            elif isinstance(node, ast.For):
                hits = self._tainted_names(node.iter)
                if hits:
                    self._flag(node.lineno,
                               f"python iteration over device value "
                               f"'{hits[0].id}' synchronizes per element "
                               f"— device_get the whole array once")
            elif isinstance(node, ast.Call):
                self._check_call(node, module_defs)

    def _check_call(self, call: ast.Call,
                    module_defs: Dict[str, ast.FunctionDef]) -> None:
        func = call.func
        name = terminal_name(func)
        path = dotted(func) or ""
        if path.startswith(("np.", "numpy.")) and name in NP_TRANSFER:
            for arg in call.args[:1]:
                hits = self._tainted_names(arg)
                if hits or self._expr_device(arg):
                    label = hits[0].id if hits else (dotted(arg) or "<expr>")
                    self._flag(call.lineno,
                               f"np.{name}() on device value '{label}' is "
                               f"an implicit device→host transfer — use "
                               f"jax.device_get at an explicit readout "
                               f"boundary")
                    return
        if isinstance(func, ast.Name) and name in COERCIONS:
            for arg in call.args:
                hits = self._tainted_names(arg)
                if hits or self._expr_device(arg):
                    label = hits[0].id if hits else (dotted(arg) or "<expr>")
                    self._flag(call.lineno,
                               f"{name}() on device value '{label}' "
                               f"synchronizes the dispatch thread — "
                               f"device_get first")
                    return
        if isinstance(func, ast.Attribute) \
                and func.attr in CONCRETIZING_METHODS:
            base_hits = self._tainted_names(func.value)
            if base_hits or self._expr_device(func.value):
                label = (base_hits[0].id if base_hits
                         else (dotted(func.value) or "<expr>"))
                self._flag(call.lineno,
                           f".{func.attr}() on device value '{label}' is "
                           f"an implicit device→host transfer — "
                           f"device_get first")
                return
        if isinstance(func, ast.Name) and name in module_defs:
            by_pos = {i: True for i, a in enumerate(call.args)
                      if self._tainted_names(a) or self._expr_device(a)}
            by_kw = {kw.arg: True for kw in call.keywords
                     if kw.arg and (self._tainted_names(kw.value)
                                    or self._expr_device(kw.value))}
            if by_pos or by_kw:
                self.propagations.append((name, by_pos, by_kw))


class HostSyncPass(LintPass):
    name = "host-sync"

    def run(self, project: Project) -> List[Finding]:
        device_calls = set(DEVICE_FNS) | _jitted_def_names(project)
        findings: List[Finding] = []
        for mod in project.modules:
            if not _is_hot(mod.rel):
                continue
            findings.extend(self._run_module(mod, device_calls))
        return findings

    def _run_module(self, mod: Module, device_calls: Set[str]
                    ) -> List[Finding]:
        defs: Dict[str, ast.FunctionDef] = {}
        quals: Dict[str, str] = {}
        skip: Set[str] = set()
        for q, fn in iter_functions(mod):
            defs.setdefault(fn.name, fn)
            quals.setdefault(fn.name, q)
            if _has_readout_decorator(fn):
                skip.add(fn.name)
        tainted: Dict[str, Set[str]] = {name: set() for name in defs}
        findings: List[Finding] = []
        seen: Dict[str, frozenset] = {}
        for _ in range(MAX_ROUNDS):
            frontier = {n: p for n, p in tainted.items()
                        if seen.get(n) != frozenset(p)}
            if not frontier:
                break
            round_findings: List[Finding] = []
            grew: Dict[str, Set[str]] = {}
            for name, params in sorted(frontier.items()):
                seen[name] = frozenset(params)
                if name in skip:
                    continue
                scan = _Scan(self.name, mod, quals[name], defs[name],
                             params, device_calls)
                scan.scan(defs)
                round_findings.extend(scan.findings)
                for callee, by_pos, by_kw in scan.propagations:
                    target = defs.get(callee)
                    if target is None:
                        continue
                    names = arg_names(target)
                    marked = grew.setdefault(
                        callee, set(tainted.get(callee, set())))
                    for i in by_pos:
                        if i < len(names):
                            marked.add(names[i])
                    for kw in by_kw:
                        if kw in names:
                            marked.add(kw)
            findings = [f for f in findings
                        if f.scope not in {quals[n] for n in frontier}]
            findings.extend(round_findings)
            for name, params in grew.items():
                tainted[name] = set(tainted.get(name, set())) | params
        return findings
