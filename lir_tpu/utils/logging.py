"""Session logging: structured logger + captured-session-transcript parity.

The reference tees stdout into a list and dumps it to a txt file at the end
(``log_print``/``save_captured_output``, compare_base_vs_instruct.py:9-31,
548-550). Here the same capability is standard logging with an attachable
capture handler, so sweep transcripts are still written as artifacts without
monkey-patching print.
"""

from __future__ import annotations

import logging
from pathlib import Path
from typing import List, Optional

_LOGGER_NAME = "lir_tpu"


class CaptureHandler(logging.Handler):
    def __init__(self) -> None:
        super().__init__()
        self.lines: List[str] = []

    def emit(self, record: logging.LogRecord) -> None:
        self.lines.append(self.format(record))


def get_logger(name: Optional[str] = None) -> logging.Logger:
    if name is None:
        qualified = _LOGGER_NAME
    elif name.startswith(_LOGGER_NAME + ".") or name == _LOGGER_NAME:
        qualified = name  # already package-qualified (callers pass __name__)
    else:
        qualified = f"{_LOGGER_NAME}.{name}"
    logger = logging.getLogger(qualified)
    root = logging.getLogger(_LOGGER_NAME)
    # Install the console handler exactly once. CaptureHandler derives from
    # logging.Handler (not StreamHandler), so capture handlers attached first
    # never satisfy this check.
    if not any(isinstance(h, logging.StreamHandler) for h in root.handlers):
        handler = logging.StreamHandler()
        handler.setFormatter(logging.Formatter("%(asctime)s %(name)s %(levelname)s %(message)s"))
        root.addHandler(handler)
        root.setLevel(logging.INFO)
    return logger


def start_capture() -> CaptureHandler:
    handler = CaptureHandler()
    handler.setFormatter(logging.Formatter("%(asctime)s %(message)s"))
    logging.getLogger(_LOGGER_NAME).addHandler(handler)
    return handler


def save_captured_output(handler: CaptureHandler, path: Path) -> None:
    """Write the captured session transcript
    (parity: compare_base_vs_instruct.py:27-31)."""
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text("\n".join(handler.lines) + "\n")
    logging.getLogger(_LOGGER_NAME).removeHandler(handler)
