"""Persistent XLA compilation cache wiring + the cache-key manifest.

Cold start is the single largest wall-clock line item after PR 1: a
restarted worker, a model swap in the comparison matrix, or an autoscale
event re-pays ~17 s of XLA compilation for executables that are
byte-identical to the previous process's. JAX ships a persistent
compilation cache (keyed by the HLO fingerprint, so stale reuse is
structurally impossible at the XLA layer); this module is the one place
that turns it on, resolves the cache directory, and records a
human-readable MANIFEST next to the opaque cache entries so operators can
see *what* a cache dir was warmed for (model config, quant mode, mesh,
bucket ladder) — the same key the engine's in-process executable registry
uses (engine/compile_plan.py).

Hit/miss observability: JAX emits monitoring events per backend compile
(`/jax/compilation_cache/compile_requests_use_cache` on every request
that consults the cache, `/jax/compilation_cache/cache_hits` on a disk
hit). ``install_cache_listener`` funnels those into the process-wide
counters that ``profiling.CompileStats`` snapshots per sweep.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import threading
from pathlib import Path
from typing import Any, Dict, Optional, Sequence

from .logging import get_logger

log = get_logger(__name__)

ENV_CACHE_DIR = "LIR_TPU_COMPILE_CACHE"
DEFAULT_CACHE_DIR = "~/.cache/lir_tpu/xla"

_state_lock = threading.Lock()
_enabled_dir: Optional[Path] = None
_listener_installed = False

# Process-wide persistent-cache counters (fed by the jax.monitoring
# listener). CompileStats.snapshot_persistent() diffs these per sweep.
_requests = 0
_hits = 0


def resolve_cache_dir(cache_dir: Optional[os.PathLike | str] = None
                      ) -> Path:
    """Explicit argument > $LIR_TPU_COMPILE_CACHE > the per-user default."""
    raw = cache_dir or os.environ.get(ENV_CACHE_DIR) or DEFAULT_CACHE_DIR
    return Path(raw).expanduser()


def _on_event(event: str, **kwargs) -> None:
    global _requests, _hits
    if event == "/jax/compilation_cache/compile_requests_use_cache":
        _requests += 1
    elif event == "/jax/compilation_cache/cache_hits":
        _hits += 1


def install_cache_listener() -> None:
    """Register the jax.monitoring listener feeding the hit/miss counters
    (idempotent — jax keeps every registered listener forever)."""
    global _listener_installed
    with _state_lock:
        if _listener_installed:
            return
        _listener_installed = True
    import jax

    jax.monitoring.register_event_listener(
        lambda event, **kw: _on_event(event))


def persistent_cache_counters() -> Dict[str, int]:
    """(requests, hits, misses) since process start — the raw counters
    behind CompileStats' per-sweep deltas."""
    return {"requests": _requests, "hits": _hits,
            "misses": _requests - _hits}


def enable_persistent_cache(cache_dir: Optional[os.PathLike | str] = None,
                            *, min_compile_time_secs: float = 0.0
                            ) -> Optional[Path]:
    """Turn on JAX's persistent compilation cache (idempotent).

    Executables then survive process restarts: a warm worker deserializes
    ~instead of recompiling~ every bucket executable it already built in
    any previous life. ``min_compile_time_secs=0`` caches everything —
    the sweep's per-bucket programs are exactly the many-small-programs
    workload the default 1 s threshold would skip. Returns the cache dir,
    or None when the runtime refused it (old jax, unwritable dir) — the
    engine then just compiles lazily, nothing breaks.
    """
    global _enabled_dir
    path = resolve_cache_dir(cache_dir)
    with _state_lock:
        if _enabled_dir == path:
            return path
    try:
        path.mkdir(parents=True, exist_ok=True)
        import jax

        jax.config.update("jax_compilation_cache_dir", str(path))
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          float(min_compile_time_secs))
        # jax initializes its cache object at most once per process and
        # has no config hook on the dir — reset so a changed dir (tests,
        # --compile-cache-dir after an earlier enable) actually takes.
        from jax._src import compilation_cache as _cc

        _cc.reset_cache()
    except Exception as err:  # noqa: BLE001 — cache is an optimization
        log.warning("persistent compile cache unavailable (%s); "
                    "compiles will not survive restarts", err)
        return None
    install_cache_listener()
    with _state_lock:
        _enabled_dir = path
    log.info("persistent compile cache: %s", path)
    return path


def enabled_cache_dir() -> Optional[Path]:
    return _enabled_dir


def disable_persistent_cache() -> None:
    """Turn the persistent cache back off (tests; --no-compile-cache is
    handled by simply never enabling)."""
    global _enabled_dir
    try:
        import jax
        from jax._src import compilation_cache as _cc

        jax.config.update("jax_compilation_cache_dir", None)
        _cc.reset_cache()
    except Exception:  # noqa: BLE001
        pass
    with _state_lock:
        _enabled_dir = None


# ---------------------------------------------------------------------------
# Cache-key manifest
# ---------------------------------------------------------------------------

def _canonical(obj: Any) -> Any:
    """Stable JSON-able projection: dataclasses -> sorted dicts, paths ->
    str, tuples -> lists. Unknown objects hash by repr (stable within a
    release — good enough for a cache KEY whose collisions only cost a
    recompile check, never a wrong result: the XLA layer re-keys by HLO)."""
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        return {f.name: _canonical(getattr(obj, f.name))
                for f in dataclasses.fields(obj)}
    if isinstance(obj, dict):
        return {str(k): _canonical(v) for k, v in sorted(obj.items())}
    if isinstance(obj, (list, tuple)):
        return [_canonical(v) for v in obj]
    if isinstance(obj, (str, int, float, bool)) or obj is None:
        return obj
    if isinstance(obj, os.PathLike):
        return str(obj)
    return repr(obj)


def quant_mode(params: Any) -> str:
    """Quantization fingerprint of a param tree: which leaf flavors it
    holds (QuantTensor static fields change the compiled program — a
    cache warmed for int8 weights must not look reusable for bf16)."""
    import jax

    from ..models import quant as quant_mod

    kinds = set()
    for leaf in jax.tree.leaves(
            params, is_leaf=lambda x: isinstance(x, quant_mod.QuantTensor)):
        if isinstance(leaf, quant_mod.QuantTensor):
            kinds.add("int8-dyn" if getattr(leaf, "dynamic", False)
                      else "int8")
        else:
            kinds.add(str(getattr(leaf, "dtype", type(leaf).__name__)))
    return "+".join(sorted(kinds)) or "empty"


def manifest_key(cfg: Any, runtime: Any, *, buckets: Sequence[int],
                 quant: str = "fp", mesh: Any = None) -> str:
    """16-hex cache key over everything that determines executable shapes:
    model config, runtime decode knobs, quant mode, mesh shape, and the
    bucket ladder. Any change produces a different key, so a registry (or
    a manifest entry) built for one configuration can never serve
    another — stale reuse is impossible by construction."""
    payload = {
        "model": _canonical(cfg),
        "runtime": _canonical(runtime),
        "buckets": [int(b) for b in buckets],
        "quant": quant,
        "mesh": _canonical(mesh),
    }
    blob = json.dumps(payload, sort_keys=True).encode()
    return hashlib.sha256(blob).hexdigest()[:16]


def write_manifest(key: str, payload: Dict[str, Any],
                   cache_dir: Optional[Path] = None) -> Optional[Path]:
    """Record what a cache was warmed for: ``manifest-<key>.json`` in the
    cache dir (first writer wins; the content is a function of the key).
    No-op when no persistent cache is enabled."""
    root = cache_dir or _enabled_dir
    if root is None:
        return None
    path = Path(root) / f"manifest-{key}.json"
    if path.exists():
        return path
    try:
        tmp = path.with_suffix(".tmp")
        tmp.write_text(json.dumps(
            {"key": key, **{k: _canonical(v) for k, v in payload.items()}},
            indent=2, sort_keys=True))
        tmp.replace(path)
    except OSError as err:
        log.warning("could not write cache manifest %s (%s)", path, err)
        return None
    return path
