"""Profiling and throughput accounting.

The reference's only "profiler" is dollar-cost accounting against the
MODEL_PRICING table plus RAM/GPU telemetry strings (SURVEY.md §5;
perturb_prompts.py:51-65,1021-1066, compare_base_vs_instruct.py:53-66).
The TPU-native replacements:

  - ThroughputMeter: prompts/sec/chip — the BASELINE.json headline metric —
    computed from the same counters the cost table consumed.
  - device_memory_stats(): per-device HBM usage, replacing the reference's
    psutil/cuda telemetry prints (surfaced as gauges in the observe
    metrics snapshot).

Every ``*Stats`` dataclass here registers into ONE MetricsRegistry
(lir_tpu/observe/registry.py) whose STATS_SCHEMA must list every public
field — enforced statically by the ``metrics-drift`` lint pass, so a
new counter that never reaches the metrics endpoint fails review.
Trace annotations moved to lir_tpu/observe/tracing.py (structured spans
+ Chrome export, same TraceAnnotation correlation).
"""

from __future__ import annotations

import contextlib
import dataclasses
import time
from typing import Dict, Iterator, Optional

import jax

from .logging import get_logger

log = get_logger(__name__)


@dataclasses.dataclass
class ThroughputMeter:
    """Counts scored prompts and wall time; reports prompts/sec/chip.

    Pass per-batch matmul FLOPs to ``add(..., flops=...)`` (via
    ``scoring_step_flops``) to get implied TFLOPS and MFU against the
    chip's published peak in the summary — the sanity figure that would
    have caught round 1's physically impossible benchmark number at sweep
    time. FLOPs accumulate per call, so mixed-size model sweeps weight
    each model correctly. Set ``int8_dots=True`` for dynamic-int8 sweeps
    so the MFU denominator is the chip's s8 peak, not bf16's.
    """

    n_devices: int = 0
    prompts: int = 0
    tokens_in: int = 0
    tokens_out: int = 0
    elapsed: float = 0.0
    flops: float = 0.0
    int8_dots: bool = False
    _start: Optional[float] = None

    def __post_init__(self) -> None:
        if self.n_devices <= 0:
            self.n_devices = jax.device_count()

    @contextlib.contextmanager
    def measure(self) -> Iterator[None]:
        start = time.perf_counter()
        try:
            yield
        finally:
            self.elapsed += time.perf_counter() - start

    def add(self, prompts: int, tokens_in: int = 0, tokens_out: int = 0,
            flops: float = 0.0) -> None:
        self.prompts += prompts
        self.tokens_in += tokens_in
        self.tokens_out += tokens_out
        self.flops += flops

    @property
    def prompts_per_sec(self) -> float:
        return self.prompts / self.elapsed if self.elapsed > 0 else 0.0

    @property
    def prompts_per_sec_per_chip(self) -> float:
        return self.prompts_per_sec / max(self.n_devices, 1)

    def summary(self) -> Dict[str, float]:
        out = {
            "prompts": self.prompts,
            "tokens_in": self.tokens_in,
            "tokens_out": self.tokens_out,
            "elapsed_s": round(self.elapsed, 3),
            "n_devices": self.n_devices,
            "prompts_per_sec": round(self.prompts_per_sec, 4),
            "prompts_per_sec_per_chip": round(self.prompts_per_sec_per_chip, 4),
        }
        if self.flops > 0 and self.elapsed > 0:
            implied = self.flops / self.elapsed / max(self.n_devices, 1)
            out["implied_tflops_per_chip"] = round(implied / 1e12, 2)
            peak = chip_peak_flops(int8=self.int8_dots)
            if peak is not None:
                out["mfu"] = round(implied / peak, 4)
                if implied > peak:
                    log.warning(
                        "implied %.1f TFLOPS exceeds the %s peak (%.0f) — "
                        "timing is not syncing with the device",
                        implied / 1e12, jax.devices()[0].device_kind,
                        peak / 1e12)
        return out


@dataclasses.dataclass
class BucketCounters:
    """Per-bucket dispatch accounting for the ragged sweep scheduler."""

    dispatches: int = 0
    cells: int = 0            # real grid cells dispatched in this bucket
    slots: int = 0            # batch rows paid for (incl. padding rows)
    used_slots: int = 0       # batch rows carrying real work
    prompt_tokens: int = 0    # real (unpadded) prefix tokens prefilled
    slot_tokens: int = 0      # prefill rows * bucket_len — token slots paid
    refilled: int = 0         # cells promoted here from a smaller bucket's
                              # ragged tail (slot refill)


@dataclasses.dataclass
class OccupancyStats:
    """Ragged-sweep scheduler counters: per-bucket batch occupancy and
    prompt-padding waste, plus decode-step occupancy from the early-stop
    retire positions.

    Definitions (reported by ``summary()`` and printed by bench.py's
    variable-length mode):

    - batch occupancy % = real cells / batch slots paid for — slots lost
      to ragged-tail padding rows. The scheduler's slot refill (promoting
      a bucket's ragged tail into the next bucket's queue) exists to keep
      this high when the grid spreads over many buckets.
    - padding waste %  = padded prefix-token slots / total prefix-token
      slots — the FLOPs fraction the prefill burns on left-padding. The
      bucket ladder exists to keep this low on variable-length grids
      (one global bucket pads every short prompt to the max).
    - decode occupancy % = decode steps that produced a live (pre-retire)
      token / decode steps paid for. Rows retired mid-scan by the early
      stop (EOS / complete-integer) idle until the batch's slowest row.
    """

    buckets: Dict[int, BucketCounters] = dataclasses.field(
        default_factory=dict)
    grouped_cells: int = 0          # cells scored via a cross-cell prefix group
    grouped_prefill_rows: int = 0   # prefix rows actually prefilled for them
    decode_steps_live: int = 0
    decode_steps_paid: int = 0

    def bucket(self, edge: int) -> BucketCounters:
        return self.buckets.setdefault(int(edge), BucketCounters())

    def add_dispatch(self, edge: int, cells: int, slots: int,
                     prompt_tokens: int, refilled: int = 0,
                     used_slots: Optional[int] = None,
                     prefill_slots: Optional[int] = None) -> None:
        """``slots``/``used_slots`` count batch rows (occupancy);
        ``prefill_slots`` counts rows actually prefilled at this bucket's
        width (padding waste) — they differ in grouped dispatches, where
        member rows outnumber the shared prefix rows."""
        b = self.bucket(edge)
        b.dispatches += 1
        b.cells += cells
        b.slots += slots
        b.used_slots += cells if used_slots is None else used_slots
        b.prompt_tokens += prompt_tokens
        b.slot_tokens += (slots if prefill_slots is None
                          else prefill_slots) * int(edge)
        b.refilled += refilled

    def add_decode(self, steps_live: int, steps_paid: int) -> None:
        self.decode_steps_live += steps_live
        self.decode_steps_paid += steps_paid

    @property
    def occupancy_pct(self) -> float:
        slots = sum(b.slots for b in self.buckets.values())
        used = sum(b.used_slots for b in self.buckets.values())
        return 100.0 * used / slots if slots else 0.0

    @property
    def padding_waste_pct(self) -> float:
        tok = sum(b.prompt_tokens for b in self.buckets.values())
        slot_tok = sum(b.slot_tokens for b in self.buckets.values())
        return 100.0 * (slot_tok - tok) / slot_tok if slot_tok else 0.0

    @property
    def decode_occupancy_pct(self) -> float:
        if not self.decode_steps_paid:
            return 0.0
        return 100.0 * self.decode_steps_live / self.decode_steps_paid

    def summary(self) -> Dict[str, object]:
        out: Dict[str, object] = {
            "occupancy_pct": round(self.occupancy_pct, 2),
            "padding_waste_pct": round(self.padding_waste_pct, 2),
            "per_bucket": {
                str(edge): {
                    "dispatches": b.dispatches, "cells": b.cells,
                    "slots": b.slots, "refilled": b.refilled,
                    "padding_waste_pct": round(
                        100.0 * (b.slot_tokens - b.prompt_tokens)
                        / b.slot_tokens, 2) if b.slot_tokens else 0.0,
                }
                for edge, b in sorted(self.buckets.items())
            },
        }
        if self.decode_steps_paid:
            out["decode_occupancy_pct"] = round(self.decode_occupancy_pct, 2)
        if self.grouped_cells:
            out["grouped_cells"] = self.grouped_cells
            out["grouped_prefill_rows"] = self.grouped_prefill_rows
        return out


@dataclasses.dataclass
class CompileStats:
    """Compile-plan accounting (engine/compile_plan.py): where cold-start
    time goes, and whether dispatches ran precompiled or traced lazily.

    - ``shapes``: per-shape AOT compile seconds, keyed by the spec label
      (kind/bucket/batch/suffixes/variant) — the itemized cold-start bill.
    - ``aot_hits``: dispatches served by a registry executable;
      ``lazy_misses``: dispatches that fell back to trace-on-first-call
      (registry miss, failed compile, or precompile disabled).
    - ``persistent_requests/hits``: XLA persistent-cache counters for the
      window between ``snapshot_persistent()`` and ``finish_persistent()``
      (the jax.monitoring events are process-global; the snapshot diff
      scopes them to one sweep).
    - ``cold_start_s`` / ``warm_start_s``: end-to-end warmup wall time with
      a cold vs warm persistent cache — set by the bench, reported in its
      headline JSON.
    """

    shapes: Dict[str, float] = dataclasses.field(default_factory=dict)
    aot_hits: int = 0
    lazy_misses: int = 0
    persistent_requests: int = 0
    persistent_hits: int = 0
    cold_start_s: Optional[float] = None
    warm_start_s: Optional[float] = None
    _persistent_base: Optional[Dict[str, int]] = None

    def record_shape(self, label: str, seconds: float) -> None:
        self.shapes[label] = round(
            self.shapes.get(label, 0.0) + seconds, 4)

    @property
    def compile_s(self) -> float:
        """Total AOT compile seconds (sum over shapes; parallel compiles
        overlap on the wall clock, so this bounds — not equals — the
        cold-start contribution)."""
        return round(sum(self.shapes.values()), 4)

    def snapshot_persistent(self) -> None:
        from . import compile_cache

        self._persistent_base = compile_cache.persistent_cache_counters()

    def finish_persistent(self) -> None:
        from . import compile_cache

        now = compile_cache.persistent_cache_counters()
        base = self._persistent_base or {"requests": 0, "hits": 0}
        self.persistent_requests += now["requests"] - base["requests"]
        self.persistent_hits += now["hits"] - base["hits"]
        self._persistent_base = now

    def summary(self) -> Dict[str, object]:
        out: Dict[str, object] = {
            "aot_shapes": len(self.shapes),
            "aot_compile_s": self.compile_s,
            "aot_hits": self.aot_hits,
            "lazy_misses": self.lazy_misses,
            "persistent_cache_requests": self.persistent_requests,
            "persistent_cache_hits": self.persistent_hits,
            "persistent_cache_misses": (self.persistent_requests
                                        - self.persistent_hits),
        }
        if self.shapes:
            out["per_shape_compile_s"] = {
                k: round(v, 3) for k, v in sorted(self.shapes.items())}
        if self.cold_start_s is not None:
            out["cold_start_s"] = round(self.cold_start_s, 3)
        if self.warm_start_s is not None:
            out["warm_start_s"] = round(self.warm_start_s, 3)
        return out


@dataclasses.dataclass
class KernelStats:
    """Per-phase kernel accounting for the isolated scoring step, plus
    the piggyback-chain counters (ROADMAP item 2: make the MFU plateau
    measurable per COMPONENT, not just in aggregate).

    ``phases`` — filled by bench.py's kernel mode: for each of
    "prefill" (quadratic prompt pass), "decode" (KV-cached greedy scan),
    and "readout" (lm_head + position-0 extras), the measured seconds,
    the analytic matmul TFLOPs executed (scoring_step_flops_split), the
    implied TFLOPS, and — when the chip's peak is known — the phase MFU
    and its complement, the MXU-idle fraction. The decode row is where
    the 36% plateau lived; the fused flash-decode kernel and int8
    matmul fusion attack exactly that row.

    ``counters`` — engine-side chunked-prefill/decode piggybacking:
    chains opened, piggybacked steps (dispatches whose decode scans rode
    the next prefill call), drains, and plain-path fallbacks.
    """

    phases: Dict[str, Dict[str, float]] = dataclasses.field(
        default_factory=dict)
    counters: Dict[str, int] = dataclasses.field(default_factory=dict)

    def record_phase(self, name: str, seconds: float, flops: float,
                     peak: Optional[float] = None) -> None:
        entry: Dict[str, float] = {
            "seconds": round(seconds, 6),
            "tflops_executed": round(flops / 1e12, 4),
            "implied_tflops": (round(flops / seconds / 1e12, 3)
                               if seconds > 0 else 0.0),
        }
        if peak and seconds > 0:
            mfu = flops / seconds / peak
            entry["mfu"] = round(mfu, 4)
            entry["mxu_idle_frac"] = round(1.0 - mfu, 4)
        self.phases[name] = entry

    def count(self, name: str, n: int = 1) -> None:
        self.counters[name] = self.counters.get(name, 0) + n

    def summary(self) -> Dict[str, object]:
        out: Dict[str, object] = {k: dict(v) for k, v in
                                  sorted(self.phases.items())}
        if self.counters:
            out["piggyback"] = dict(sorted(self.counters.items()))
        return out


@dataclasses.dataclass
class ServeStats:
    """Online serving counters (lir_tpu/serve): the operator's one-look
    view of queue health, admission control, dedup effectiveness, and
    latency. Thread-safe — the supervisor loop and every submitting
    thread mutate it concurrently.

    Definitions (reported by ``summary()`` and bench.py's "serve" key):

    - submitted / admitted / shed: admission-control accounting. ``shed``
      counts both rejected newcomers and deadline-aware evictions
      (serve/queue.py) — nonzero shed under steady load means the queue
      depth or the fleet is undersized.
    - dedup hit rate = cache hits / lookups — how often a probe was
      answered from the content-addressed result cache without touching
      the device (perturbation-style traffic re-asks near-identical
      questions constantly).
    - expired: rows whose deadline passed while queued; they return
      partial confidence-free results. ``late``: rows that completed but
      past their deadline (excluded from goodput).
    - slot occupancy % = real request rows / padded batch slots across
      every dispatch — the online analogue of OccupancyStats' batch
      occupancy; low values mean the linger window is too short for the
      arrival rate. ``promoted`` counts rows the batcher's online slot
      refill moved into a bigger bucket's queue (scheduler.bucket_cost
      said riding a fuller dispatch beats a padded tail of their own).
    - latency percentiles (p50/p95/p99) over submit -> result seconds.
    """

    submitted: int = 0
    admitted: int = 0
    shed: int = 0
    completed: int = 0
    expired: int = 0
    errors: int = 0
    late: int = 0
    dedup_hits: int = 0
    dedup_misses: int = 0
    dispatches: int = 0
    slots_used: int = 0
    slots_paid: int = 0
    promoted: int = 0
    queue_depth_peak: int = 0
    _latencies: list = dataclasses.field(default_factory=list)
    _max_latencies: int = 100_000

    def __post_init__(self) -> None:
        import threading

        self._lock = threading.Lock()

    def count(self, field: str, n: int = 1) -> None:
        with self._lock:
            setattr(self, field, getattr(self, field) + n)

    def note_queue_depth(self, depth: int) -> None:
        with self._lock:
            self.queue_depth_peak = max(self.queue_depth_peak, depth)

    def add_dispatch(self, used: int, paid: int) -> None:
        with self._lock:
            self.dispatches += 1
            self.slots_used += used
            self.slots_paid += paid

    def record_latency(self, seconds: float) -> None:
        with self._lock:
            if len(self._latencies) < self._max_latencies:
                self._latencies.append(float(seconds))

    @property
    def dedup_hit_rate(self) -> float:
        n = self.dedup_hits + self.dedup_misses
        return self.dedup_hits / n if n else 0.0

    @property
    def slot_occupancy_pct(self) -> float:
        return (100.0 * self.slots_used / self.slots_paid
                if self.slots_paid else 0.0)

    def latency_percentiles(self) -> Dict[str, float]:
        with self._lock:
            lat = sorted(self._latencies)
        if not lat:
            return {"p50_s": 0.0, "p95_s": 0.0, "p99_s": 0.0}

        def pct(p: float) -> float:
            i = min(len(lat) - 1, max(0, int(round(p * (len(lat) - 1)))))
            return lat[i]

        return {"p50_s": round(pct(0.50), 4), "p95_s": round(pct(0.95), 4),
                "p99_s": round(pct(0.99), 4)}

    def goodput(self, elapsed_s: float) -> float:
        """Requests completed WITHIN deadline per second of wall time —
        the serving layer's headline rate (late completions and partial
        results don't count; cache hits do: a served answer is a served
        answer)."""
        if elapsed_s <= 0:
            return 0.0
        return max(0, self.completed - self.late) / elapsed_s

    def summary(self) -> Dict[str, object]:
        out: Dict[str, object] = {
            "submitted": self.submitted,
            "admitted": self.admitted,
            "shed": self.shed,
            "completed": self.completed,
            "expired": self.expired,
            "errors": self.errors,
            "late": self.late,
            "dedup_hits": self.dedup_hits,
            "dedup_misses": self.dedup_misses,
            "dedup_hit_rate": round(self.dedup_hit_rate, 4),
            "dispatches": self.dispatches,
            "slot_occupancy_pct": round(self.slot_occupancy_pct, 2),
            "promoted": self.promoted,
            "queue_depth_peak": self.queue_depth_peak,
        }
        out.update(self.latency_percentiles())
        return out


@dataclasses.dataclass
class FaultStats:
    """Fault-injection / self-healing counters (lir_tpu/faults): what the
    failure path did, with the same one-look intent as ServeStats for the
    hot path. Thread-safe — injection sites, the supervisor loop, and the
    sweep's dispatch recovery all mutate it concurrently.

    Definitions (reported by ``summary()``, bench.py's "chaos" key, and
    ``make chaos-smoke``):

    - ``injected``: per-site injected-fault counts (FaultPlan.check) —
      the chaos schedule's ground truth, so "recovered" can be read
      against "thrown at".
    - ``recovered_dispatches``: dispatches that failed at least once
      (device error, injected fault) and still resolved rows — via the
      retry policy, the AOT->lazy fallback, or the bisection ladder.
    - ``degraded_dispatches``: dispatches that entered the degradation
      ladder (retries exhausted on the full batch).
    - ``degraded_rows``: rows the ladder resolved as error results after
      isolating them as poison — the price of not failing their batch.
    - breaker counters + ``transitions``: every circuit-breaker state
      change in order ((from, to) pairs) — the serve recovery story is
      readable from this list alone (closed->open->half_open->closed).
    """

    injected: Dict[str, int] = dataclasses.field(default_factory=dict)
    recovered_dispatches: int = 0
    degraded_dispatches: int = 0
    degraded_rows: int = 0
    preemptions: int = 0
    breaker_opens: int = 0
    breaker_probes: int = 0
    breaker_closes: int = 0
    transitions: list = dataclasses.field(default_factory=list)

    def __post_init__(self) -> None:
        import threading

        self._lock = threading.Lock()

    def count(self, field: str, n: int = 1) -> None:
        with self._lock:
            setattr(self, field, getattr(self, field) + n)

    def inject(self, site: str, preemption: bool = False) -> None:
        with self._lock:
            self.injected[site] = self.injected.get(site, 0) + 1
            if preemption:
                self.preemptions += 1

    @property
    def injected_total(self) -> int:
        return sum(self.injected.values())

    def transition(self, frm: str, to: str) -> None:
        with self._lock:
            self.transitions.append((frm, to))
            if to == "open":
                self.breaker_opens += 1
            elif to == "half_open":
                self.breaker_probes += 1
            elif to == "closed":
                self.breaker_closes += 1

    def summary(self) -> Dict[str, object]:
        with self._lock:
            return {
                "injected": dict(self.injected),
                "injected_total": sum(self.injected.values()),
                "recovered_dispatches": self.recovered_dispatches,
                "degraded_dispatches": self.degraded_dispatches,
                "degraded_rows": self.degraded_rows,
                "preemptions": self.preemptions,
                "breaker_opens": self.breaker_opens,
                "breaker_probes": self.breaker_probes,
                "breaker_closes": self.breaker_closes,
                "breaker_transitions": [f"{a}->{b}"
                                        for a, b in self.transitions],
            }


@dataclasses.dataclass
class GuardStats:
    """Guard-layer counters (lir_tpu/guard): what the silent-failure
    path saw and did, per SITE ("sweep" / "serve" / "compile" /
    "barrier"). Thread-safe — the sweep writer thread, the serve
    supervisor, and compile-pool threads all mutate it concurrently.

    Definitions (reported by ``summary()``, bench.py's "chaos" key, and
    ``make chaos-smoke``):

    - ``watched``: dispatches run under an enforced watchdog deadline
      (uncalibrated observe-only runs are not counted — they cannot
      fire).
    - ``stalls``: watchdog expiries per site — each one is a dispatch
      that would have hung the run and instead cost one deadline.
      ``stall_dumps`` counts the all-thread stack dumps emitted.
    - ``checked`` / ``quarantined``: numerics-guard rows validated and
      rows withheld as ``error:numerics``; ``reasons`` histograms the
      quarantine causes (NaN probs, out-of-range confidence, ...).
    - ``inflight_cancelled``: serve rows resolved partial because their
      deadline passed while the dispatch was still on the device (the
      watched executor's tick callback).
    - ``barrier_timeouts`` / ``heartbeats``: multihost liveness —
      bounded collectives that expired (a peer presumed dead) and
      heartbeat allgathers completed.
    """

    watched: Dict[str, int] = dataclasses.field(default_factory=dict)
    stalls: Dict[str, int] = dataclasses.field(default_factory=dict)
    checked: Dict[str, int] = dataclasses.field(default_factory=dict)
    quarantined: Dict[str, int] = dataclasses.field(default_factory=dict)
    reasons: Dict[str, int] = dataclasses.field(default_factory=dict)
    stall_dumps: int = 0
    inflight_cancelled: int = 0
    barrier_timeouts: int = 0
    heartbeats: int = 0

    def __post_init__(self) -> None:
        import threading

        self._lock = threading.Lock()

    def count(self, field: str, n: int = 1) -> None:
        with self._lock:
            setattr(self, field, getattr(self, field) + n)

    def site(self, field: str, site: str, n: int = 1) -> None:
        with self._lock:
            d = getattr(self, field)
            d[site] = d.get(site, 0) + n

    def quarantine(self, site: str, reason: str) -> None:
        with self._lock:
            self.quarantined[site] = self.quarantined.get(site, 0) + 1
            self.reasons[reason] = self.reasons.get(reason, 0) + 1

    @property
    def stalls_total(self) -> int:
        with self._lock:
            return sum(self.stalls.values())

    @property
    def quarantined_total(self) -> int:
        with self._lock:
            return sum(self.quarantined.values())

    def summary(self) -> Dict[str, object]:
        with self._lock:
            return {
                "watched": dict(self.watched),
                "stalls": dict(self.stalls),
                "stalls_total": sum(self.stalls.values()),
                "stall_dumps": self.stall_dumps,
                "checked": dict(self.checked),
                "quarantined": dict(self.quarantined),
                "quarantined_total": sum(self.quarantined.values()),
                "quarantine_reasons": dict(self.reasons),
                "inflight_cancelled": self.inflight_cancelled,
                "barrier_timeouts": self.barrier_timeouts,
                "heartbeats": self.heartbeats,
            }


@dataclasses.dataclass
class PrefixCacheStats:
    """Cross-request prefix cache counters (engine/prefix_tree.py over
    the models/paged.py page pool): the operator's one-look view of how
    much prefill the radix tree is saving and how hard the pool is
    churning. Thread-safe — serve admission probes and the dispatch
    thread mutate it concurrently.

    Definitions (reported by ``summary()``, logged per sweep, surfaced
    in serve stats alongside ServeStats, and in bench.py's
    "prefix_serve" key):

    - ``lookups`` / ``hits``: dispatch-time radix probes and probes that
      matched >= 1 cached page. radix hit rate = hits / lookups.
    - ``hit_tokens``: prefix tokens resumed from the pool instead of
      prefilled — THE perf number (prefill_tokens_avoided).
      ``prefill_tokens_total`` counts every prefix token a dispatch
      needed (cached + computed), so avoided_frac = hit / total.
    - ``inserted_pages`` / ``evicted_pages``: pool churn. Sustained
      eviction at low hit rates means the pool is undersized for the
      working set (DEPLOY.md §1g sizing arithmetic).
    - ``pages_in_use`` / ``pages_total``: pool occupancy gauge, updated
      at every insert/evict.
    """

    lookups: int = 0
    hits: int = 0
    hit_tokens: int = 0
    prefill_tokens_total: int = 0
    inserted_pages: int = 0
    evicted_pages: int = 0
    pages_in_use: int = 0
    pages_total: int = 0

    def __post_init__(self) -> None:
        import threading

        self._lock = threading.Lock()

    def count(self, field: str, n: int = 1) -> None:
        with self._lock:
            setattr(self, field, getattr(self, field) + n)

    def gauge_pages(self, in_use: int, total: int) -> None:
        with self._lock:
            self.pages_in_use = in_use
            self.pages_total = total

    @property
    def hit_rate(self) -> float:
        return self.hits / self.lookups if self.lookups else 0.0

    @property
    def avoided_frac(self) -> float:
        return (self.hit_tokens / self.prefill_tokens_total
                if self.prefill_tokens_total else 0.0)

    def summary(self) -> Dict[str, object]:
        with self._lock:
            return {
                "lookups": self.lookups,
                "hits": self.hits,
                "radix_hit_rate": round(self.hits / self.lookups, 4)
                                  if self.lookups else 0.0,
                "prefill_tokens_avoided": self.hit_tokens,
                "prefill_tokens_total": self.prefill_tokens_total,
                "avoided_frac": round(self.hit_tokens
                                      / self.prefill_tokens_total, 4)
                                if self.prefill_tokens_total else 0.0,
                "inserted_pages": self.inserted_pages,
                "evicted_pages": self.evicted_pages,
                "pages_in_use": self.pages_in_use,
                "pages_total": self.pages_total,
            }


@dataclasses.dataclass
class CascadeStats:
    """Shared-prefix cascade-prefill counters (ops/cascade_prefill +
    engine/runner routing; DEPLOY.md §1q). Thread-safe — the sweep loop
    and serve batcher threads mutate it concurrently.

    - ``cascade_dispatches`` / ``dense_fallbacks``: shared dispatches
      that took the cascade split vs ones that ran the dense path while
      cascade was ENABLED (trunk below min_trunk, too few rows, int8 KV
      cache, ...). A high fallback fraction on a shared-trunk workload
      means the eligibility knobs (CascadeConfig) are mistuned.
    - ``trunk_rows_deduped``: rows whose quadratic trunk prefill was NOT
      recomputed (rows - 1 per cascade dispatch; the dense path pays all
      of them) — the dedup the cascade exists for.
    - ``prefix_flops_saved``: analytic matmul FLOPs those deduped trunk
      rows would have cost (the dense prefill's attention + projection
      terms over trunk tokens) — THE perf number; bench.py's ``cascade``
      key divides it into the dense prefill total for the implied
      prefill-MFU uplift.
    - ``cascade_decode_dispatches``: shared dispatches whose DECODE
      scans ran the trunk-aware flash-decode split dedup
      (ops/flash_decode trunk variants; DEPLOY.md §1r) — cascade-prefill
      AND dense-prefill dispatches alike, whenever the trunk extent and
      the decode-side gates line up.
    - ``trunk_bytes_deduped``: analytic HBM bytes those dispatches' trunk
      K/V tiles did NOT stream (once per decode step instead of once per
      row — profiling.cascade_decode_bytes_saved); bench.py's
      ``cascade_decode`` key divides the flat kernel's decode bytes by
      the deduped total for the headline bytes/row reduction.
    """

    cascade_dispatches: int = 0
    dense_fallbacks: int = 0
    trunk_rows_deduped: int = 0
    prefix_flops_saved: int = 0
    cascade_decode_dispatches: int = 0
    trunk_bytes_deduped: int = 0

    def __post_init__(self) -> None:
        import threading

        self._lock = threading.Lock()

    def count(self, field: str, n: int = 1) -> None:
        with self._lock:
            setattr(self, field, getattr(self, field) + n)

    def summary(self) -> Dict[str, object]:
        with self._lock:
            total = self.cascade_dispatches + self.dense_fallbacks
            return {
                "cascade_dispatches": self.cascade_dispatches,
                "dense_fallbacks": self.dense_fallbacks,
                "cascade_frac": (round(self.cascade_dispatches / total, 4)
                                 if total else 0.0),
                "trunk_rows_deduped": self.trunk_rows_deduped,
                "prefix_flops_saved": self.prefix_flops_saved,
                "cascade_decode_dispatches": self.cascade_decode_dispatches,
                "trunk_bytes_deduped": self.trunk_bytes_deduped,
            }


@dataclasses.dataclass
class FleetStats:
    """Multi-model fleet counters (engine/fleet.py over
    models/weights.py): how much model-swap latency the async weight
    streamer hid behind compute, and how hard the LRU weight cache is
    working. Thread-safe — the prefetch worker, the fleet supervisor,
    and serve submitters all mutate it concurrently.

    Definitions (reported by ``summary()``, logged per fleet sweep,
    surfaced in serve fleet stats, and in bench.py's "fleet" key):

    - ``swap_s_hidden`` / ``swap_s_exposed``: per-load wall seconds
      overlapped with the previous model's compute vs actually waited on
      by the scoring loop. hidden > exposed is the tentpole claim — the
      prefetch pipeline genuinely hides swap cost (the sequential
      drop-and-reload baseline is 100% exposed by construction).
    - ``loads`` / ``load_s`` / ``weight_bytes_streamed``: host->device
      weight loads performed, their total wall time, and bytes shipped
      through the chunked streamer.
    - ``prefetch_hits``: acquires satisfied by a prefetched (background)
      load; ``prefetch_misses``: acquires that had to load inline
      (fully exposed); ``cache_hits``: acquires finding the model
      already resident (zero swap cost — the co-residency win).
    - ``evictions``: models dropped by the LRU weight cache under HBM
      pressure; ``resident_models`` / ``resident_bytes``: occupancy
      gauges. Sustained eviction with low cache_hits means the budget
      is undersized for the fleet (DEPLOY.md §1k arithmetic).
    - ``model_swaps``: acquires that changed the active model;
      ``fleet_requests`` / ``fleet_rows``: serve fleet_score fan-outs
      and the per-model rows they produced.
    """

    swap_s_hidden: float = 0.0
    swap_s_exposed: float = 0.0
    loads: int = 0
    load_s: float = 0.0
    weight_bytes_streamed: int = 0
    prefetch_hits: int = 0
    prefetch_misses: int = 0
    cache_hits: int = 0
    evictions: int = 0
    resident_models: int = 0
    resident_bytes: int = 0
    model_swaps: int = 0
    fleet_requests: int = 0
    fleet_rows: int = 0

    def __post_init__(self) -> None:
        import threading

        self._lock = threading.Lock()

    def count(self, field: str, n=1) -> None:
        with self._lock:
            setattr(self, field, getattr(self, field) + n)

    def gauge(self, field: str, value) -> None:
        with self._lock:
            setattr(self, field, value)

    @property
    def hidden_frac(self) -> float:
        total = self.swap_s_hidden + self.swap_s_exposed
        return self.swap_s_hidden / total if total > 0 else 0.0

    def summary(self) -> Dict[str, object]:
        with self._lock:
            total = self.swap_s_hidden + self.swap_s_exposed
            return {
                "swap_s_hidden": round(self.swap_s_hidden, 4),
                "swap_s_exposed": round(self.swap_s_exposed, 4),
                "swap_hidden_frac": round(self.swap_s_hidden / total, 4)
                                    if total > 0 else 0.0,
                "loads": self.loads,
                "load_s": round(self.load_s, 4),
                "weight_bytes_streamed": self.weight_bytes_streamed,
                "prefetch_hits": self.prefetch_hits,
                "prefetch_misses": self.prefetch_misses,
                "cache_hits": self.cache_hits,
                "evictions": self.evictions,
                "resident_models": self.resident_models,
                "resident_bytes": self.resident_bytes,
                "model_swaps": self.model_swaps,
                "fleet_requests": self.fleet_requests,
                "fleet_rows": self.fleet_rows,
            }


@dataclasses.dataclass
class MemStats:
    """HBM-governor counters and gauges (engine/hbm.py): the one-look
    view of who holds HBM, how close the ledger is to its budget, and
    what the pressure-driven degradation ladder did about it.
    Thread-safe — the sweep dispatch loop, the serve supervisor, and
    fleet weight-cache listeners all mutate it concurrently.

    Definitions (reported by ``summary()``, the ``{"op": "metrics"}``
    endpoint's ``mem`` source, bench.py's "memory" key, and
    ``make mem-smoke``):

    - ``ledger_bytes`` / ``budget_bytes`` / ``pressure``: the ledger
      total across registered consumers, the governed budget (0 =
      unbounded), and their ratio — the gauge the degradation ladder
      and the router's placement signal both read.
    - ``rung``: currently-engaged ladder depth (0 = fully armed).
    - ``rung_downs`` / ``rung_ups``: per-rung engage/release
      transitions — a reversible squeeze shows BOTH nonzero.
    - ``admits`` / ``denials``: admission checks passed/refused
      (projected bytes vs budget at consumer registration time).
    - ``oom_events``: real device OOMs routed through the governor,
      per site ("sweep"/"serve"); ``oom_reclaims``: OOMs where the
      ladder freed something and the dispatch retried;
      ``oom_exhausted``: OOMs nothing could be reclaimed for — the
      irreducible dispatch the caller quarantines.
    - ``squeezes``: injected ``hbm_squeeze`` budget shrinks observed
      (the chaos proof's ground truth); ``sheds``: submits refused by
      the terminal backpressure rung.
    """

    ledger_bytes: int = 0
    budget_bytes: int = 0
    pressure: float = 0.0
    rung: int = 0
    rung_downs: Dict[str, int] = dataclasses.field(default_factory=dict)
    rung_ups: Dict[str, int] = dataclasses.field(default_factory=dict)
    admits: int = 0
    denials: int = 0
    oom_events: Dict[str, int] = dataclasses.field(default_factory=dict)
    oom_reclaims: int = 0
    oom_exhausted: int = 0
    squeezes: int = 0
    sheds: int = 0

    def __post_init__(self) -> None:
        import threading

        self._lock = threading.Lock()

    def count(self, field: str, n: int = 1) -> None:
        with self._lock:
            setattr(self, field, getattr(self, field) + n)

    def gauge(self, field: str, value) -> None:
        with self._lock:
            setattr(self, field, value)

    def site(self, field: str, site: str, n: int = 1) -> None:
        with self._lock:
            d = getattr(self, field)
            d[site] = d.get(site, 0) + n

    def summary(self) -> Dict[str, object]:
        with self._lock:
            return {
                "ledger_bytes": self.ledger_bytes,
                "budget_bytes": self.budget_bytes,
                "pressure": round(float(self.pressure), 4),
                "rung": self.rung,
                "rung_downs": dict(self.rung_downs),
                "rung_ups": dict(self.rung_ups),
                "admits": self.admits,
                "denials": self.denials,
                "oom_events": dict(self.oom_events),
                "oom_reclaims": self.oom_reclaims,
                "oom_exhausted": self.oom_exhausted,
                "squeezes": self.squeezes,
                "sheds": self.sheds,
            }


@dataclasses.dataclass
class RouterStats:
    """Elastic-router counters (serve/router.py): how requests spread
    over the replica set and what the failure path did. Thread-safe —
    submitter threads, replica supervisor threads (future callbacks),
    and the router tick thread all mutate it concurrently.

    Definitions (reported by ``summary()``, bench.py's "elastic" key,
    and ``make elastic-smoke``):

    - ``routed``: requests admitted through the router (dedup hits
      excluded); ``routed_resident``: requests whose placement followed
      the weight-residency signal (the model was already in the chosen
      replica's WeightCache); ``per_replica`` histograms placements.
    - ``dedup_hits``: requests answered from the router's own
      content-addressed cache without touching any replica.
    - ``failovers``: attempts re-admitted to a DIFFERENT replica after
      an error/shed result; ``re_admitted``: in-flight requests
      re-admitted because their replica was killed or its breaker
      opened mid-dispatch. Exactly-once: a re-admitted request resolves
      from whichever replica answers first (ServeFuture first-
      resolution-wins + content-address dedup).
    - ``hedged`` / ``hedge_wins`` / ``hedge_losses``: requests
      duplicated onto a second replica inside the deadline whisker, and
      which copy won the first-payload race.
    - ``zombie_payloads``: payloads that arrived from a DEAD replica
      after the request already resolved elsewhere — dropped by the
      resolve-once/dedup discipline, never double-resolved.
    - ``replica_errors`` / ``replica_sheds``: per-attempt outcomes that
      triggered the failover path; ``no_replica_sheds``: requests shed
      because no live replica would admit them.
    - ``kills`` / ``revives``: replica death/rejoin events observed.
    """

    routed: int = 0
    routed_resident: int = 0
    dedup_hits: int = 0
    completed: int = 0
    errors: int = 0
    failovers: int = 0
    re_admitted: int = 0
    hedged: int = 0
    hedge_wins: int = 0
    hedge_losses: int = 0
    zombie_payloads: int = 0
    replica_errors: int = 0
    replica_sheds: int = 0
    no_replica_sheds: int = 0
    kills: int = 0
    revives: int = 0
    per_replica: Dict[str, int] = dataclasses.field(default_factory=dict)

    def __post_init__(self) -> None:
        import threading

        self._lock = threading.Lock()

    def count(self, field: str, n: int = 1) -> None:
        with self._lock:
            setattr(self, field, getattr(self, field) + n)

    def placed(self, replica_id: str) -> None:
        with self._lock:
            self.per_replica[replica_id] = (
                self.per_replica.get(replica_id, 0) + 1)

    def summary(self) -> Dict[str, object]:
        with self._lock:
            return {
                "routed": self.routed,
                "routed_resident": self.routed_resident,
                "dedup_hits": self.dedup_hits,
                "completed": self.completed,
                "errors": self.errors,
                "failovers": self.failovers,
                "re_admitted": self.re_admitted,
                "hedged": self.hedged,
                "hedge_wins": self.hedge_wins,
                "hedge_losses": self.hedge_losses,
                "zombie_payloads": self.zombie_payloads,
                "replica_errors": self.replica_errors,
                "replica_sheds": self.replica_sheds,
                "no_replica_sheds": self.no_replica_sheds,
                "kills": self.kills,
                "revives": self.revives,
                "per_replica": dict(self.per_replica),
            }


@dataclasses.dataclass
class MigrationStats:
    """Disaggregated-serving counters (serve/migrate.py + the router's
    prefill/decode role machinery, serve/router.py). Thread-safe —
    replica supervisor threads (page ops + chain callbacks), the router
    tick (timeout fallbacks), and submit threads all mutate it.

    Definitions (reported by ``summary()``, bench.py's "disagg" key,
    and ``make disagg-smoke``; DEPLOY.md §1p):

    - ``migrations``: completed page-migration chains (pages exported
      from one replica's pool and imported, checksum-verified, into
      another's); ``prefill_ops``: prefill-only dispatches run on
      prefill-role replicas.
    - ``pages_migrated`` / ``bytes_streamed`` / ``chunks_streamed``:
      transfer volume (bytes are device-leaf bytes, both directions
      counted once).
    - ``migration_s_exposed``: transfer wall seconds on the critical
      path before the decode dispatch could be admitted;
      ``migration_s_hidden``: per-chunk in-flight seconds overlapped
      away by the double-buffered window (serial sum minus wall).
    - ``refetch_fallbacks``: chains abandoned (stall past
      ``MigrationConfig.timeout_s``, corrupt chunk, source replica
      died) whose request re-prefilled LOCALLY on the decode replica —
      the never-a-wrong-answer path; ``stalls`` / ``corrupt_chunks``
      classify why.
    - ``cluster_tree_hits``: requests whose prefix the cluster index
      found already page-resident on the chosen decode replica — routed
      straight there, no migration and no prefill needed.
    """

    migrations: int = 0
    prefill_ops: int = 0
    pages_migrated: int = 0
    bytes_streamed: int = 0
    chunks_streamed: int = 0
    migration_s_exposed: float = 0.0
    migration_s_hidden: float = 0.0
    refetch_fallbacks: int = 0
    stalls: int = 0
    corrupt_chunks: int = 0
    cluster_tree_hits: int = 0

    def __post_init__(self) -> None:
        import threading

        self._lock = threading.Lock()

    def count(self, field: str, n: int = 1) -> None:
        with self._lock:
            setattr(self, field, getattr(self, field) + n)

    def add_transfer(self, pages: int, nbytes: int, chunks: int,
                     exposed_s: float, hidden_s: float) -> None:
        with self._lock:
            self.migrations += 1
            self.pages_migrated += pages
            self.bytes_streamed += nbytes
            self.chunks_streamed += chunks
            self.migration_s_exposed += exposed_s
            self.migration_s_hidden += hidden_s

    def summary(self) -> Dict[str, object]:
        with self._lock:
            return {
                "migrations": self.migrations,
                "prefill_ops": self.prefill_ops,
                "pages_migrated": self.pages_migrated,
                "bytes_streamed": self.bytes_streamed,
                "chunks_streamed": self.chunks_streamed,
                "migration_s_exposed": round(self.migration_s_exposed, 4),
                "migration_s_hidden": round(self.migration_s_hidden, 4),
                "refetch_fallbacks": self.refetch_fallbacks,
                "stalls": self.stalls,
                "corrupt_chunks": self.corrupt_chunks,
                "cluster_tree_hits": self.cluster_tree_hits,
            }


@dataclasses.dataclass
class TierStats:
    """Tiered-store counters (serve/tiers.py): how cached state moved
    down and back up the HBM -> host DRAM -> disk ladder. Thread-safe —
    demotions/promotions run on each replica's supervisor thread while
    submit threads probe ``match_len`` and the metrics endpoint reads.

    Definitions (reported by ``summary()``, bench.py's "tiered" key,
    and ``make tiered-smoke``; DEPLOY.md §1s):

    - ``demotions`` / ``promotions``: per-tier movement counts (keys
      ``host``, ``disk``, ``weights``) — a demotion books the tier the
      state LANDED in, a promotion the tier it was READ from.
    - ``pages_demoted`` / ``pages_promoted``: KV page volume either
      direction; ``bytes_spilled``: bytes written to the DISK tier
      (host-pool LRU overflow + weight records); ``bytes_promoted``:
      bytes read back toward HBM.
    - ``restart_pages_reseeded`` / ``restart_weights_reseeded``: state
      recovered from the disk tier by a restart-warm boot.
    - ``checksum_refusals``: promotes refused because a host/disk chunk
      failed its checksum (chaos kind ``tier_corrupt``) — the entry is
      dropped and the request re-prefills, never a wrong answer;
      ``disk_stalls``: disk reads abandoned past
      ``TierConfig.disk_timeout_s`` (chaos kind ``disk_stall``);
      ``pin_refusals``: demotion requests refused because a dispatch
      still pinned the pages (refcount discipline — a pinned page
      never leaves HBM).
    - ``host_bytes`` / ``disk_bytes``: current tier occupancy gauges.
    """

    demotions: Dict[str, int] = dataclasses.field(default_factory=dict)
    promotions: Dict[str, int] = dataclasses.field(default_factory=dict)
    pages_demoted: int = 0
    pages_promoted: int = 0
    bytes_spilled: int = 0
    bytes_promoted: int = 0
    restart_pages_reseeded: int = 0
    restart_weights_reseeded: int = 0
    checksum_refusals: int = 0
    disk_stalls: int = 0
    pin_refusals: int = 0
    host_bytes: int = 0
    disk_bytes: int = 0

    def __post_init__(self) -> None:
        import threading

        self._lock = threading.Lock()

    def count(self, field: str, n: int = 1) -> None:
        with self._lock:
            setattr(self, field, getattr(self, field) + n)

    def gauge(self, field: str, value) -> None:
        with self._lock:
            setattr(self, field, value)

    def site(self, field: str, site: str, n: int = 1) -> None:
        with self._lock:
            d = getattr(self, field)
            d[site] = d.get(site, 0) + n

    def summary(self) -> Dict[str, object]:
        with self._lock:
            return {
                "demotions": dict(self.demotions),
                "promotions": dict(self.promotions),
                "pages_demoted": self.pages_demoted,
                "pages_promoted": self.pages_promoted,
                "bytes_spilled": self.bytes_spilled,
                "bytes_promoted": self.bytes_promoted,
                "restart_pages_reseeded": self.restart_pages_reseeded,
                "restart_weights_reseeded": self.restart_weights_reseeded,
                "checksum_refusals": self.checksum_refusals,
                "disk_stalls": self.disk_stalls,
                "pin_refusals": self.pin_refusals,
                "host_bytes": self.host_bytes,
                "disk_bytes": self.disk_bytes,
            }


@dataclasses.dataclass
class LeaseStats:
    """Shard-lease counters (engine/lease.py): how leased offline-sweep
    shards moved between holders. Thread-safe for symmetry with the
    other stats objects (the lease manager itself runs on one sweep
    thread per host).

    Definitions (reported by ``summary()``, logged per leased sweep,
    and in bench.py's "elastic" key):

    - ``claims``: shards claimed fresh (unclaimed, or re-claimed by
      their own holder on resume); ``renews``: expiry extensions (one
      per manifest flush — renew-on-flush); ``releases``: leases marked
      done.
    - ``steals``: expired leases taken over from a DEAD or slow holder
      — the work-stealing event; re-scored rows fold into the streaming
      lattice as bitwise no-ops (slot idempotence), so a steal can
      never corrupt the merged accumulator.
    - ``refused``: claim attempts refused because another holder's
      lease was still live (double-claim refusal); ``lost``: renews
      refused because the lease had expired and been stolen out from
      under the holder.
    - ``expired_seen``: expired foreign leases observed (steal
      candidates); ``shards_done``: shards this holder completed;
      ``refreshes``: lease-log re-reads.
    """

    claims: int = 0
    renews: int = 0
    releases: int = 0
    steals: int = 0
    refused: int = 0
    lost: int = 0
    expired_seen: int = 0
    shards_done: int = 0
    refreshes: int = 0

    def __post_init__(self) -> None:
        import threading

        self._lock = threading.Lock()

    def count(self, field: str, n: int = 1) -> None:
        with self._lock:
            setattr(self, field, getattr(self, field) + n)

    def summary(self) -> Dict[str, object]:
        with self._lock:
            return {
                "claims": self.claims,
                "renews": self.renews,
                "releases": self.releases,
                "steals": self.steals,
                "refused": self.refused,
                "lost": self.lost,
                "expired_seen": self.expired_seen,
                "shards_done": self.shards_done,
                "refreshes": self.refreshes,
            }


@dataclasses.dataclass
class StreamStats:
    """Streaming-statistics sink counters (engine/stream_stats.py): how
    much of the grid folded on device, how many host bytes the streaming
    path avoided, and what finalize/checkpoint work cost. Thread-safe —
    the sweep writer thread folds while checkpoints and the live serve
    endpoint read concurrently.

    Definitions (reported by ``summary()``, logged per sweep, and in
    bench.py's "streaming_stats" key):

    - ``rows_folded`` / ``dispatch_folds``: grid rows folded into the
      device accumulator and the fused update calls that carried them
      (one per dispatch — the tentpole invariant; rows_folded == grid
      size means no row ever needed the host).
    - ``host_bytes_avoided``: bytes of per-row dispatch payloads
      (generated ids, top-20 maps, confidence scans) that were NEVER
      device_get because the row artifact was skipped — the transfer
      the csv-reload pipeline pays per row. ``accum_bytes`` gauges the
      accumulator's own size: what DOES cross at a checkpoint/finalize.
    - ``checkpoints`` / ``merges``: accumulator snapshots written at
      flush boundaries and multihost fence merges performed.
    - ``finalize_s``: seconds spent in the grid -> CIs finalize;
      ``live_queries`` counts mid-run stats-endpoint reads.
    """

    rows_folded: int = 0
    dispatch_folds: int = 0
    host_bytes_avoided: int = 0
    accum_bytes: int = 0
    checkpoints: int = 0
    merges: int = 0
    live_queries: int = 0
    finalize_s: float = 0.0

    def __post_init__(self) -> None:
        import threading

        self._lock = threading.Lock()

    def count(self, field: str, n: int = 1) -> None:
        with self._lock:
            setattr(self, field, getattr(self, field) + n)

    def gauge(self, field: str, value) -> None:
        with self._lock:
            setattr(self, field, value)

    def summary(self) -> Dict[str, object]:
        with self._lock:
            return {
                "rows_folded": self.rows_folded,
                "dispatch_folds": self.dispatch_folds,
                "host_bytes_avoided": self.host_bytes_avoided,
                "accum_bytes": self.accum_bytes,
                "checkpoints": self.checkpoints,
                "merges": self.merges,
                "live_queries": self.live_queries,
                "finalize_s": round(self.finalize_s, 4),
            }


@dataclasses.dataclass
class SpecStats:
    """Speculative-decode counters (engine/spec.py over generate.
    greedy_decode_fused_shared_spec): how many tokens were drafted,
    where the drafts came from, how many survived greedy verification,
    and how many sequential decode forwards the verify windows
    replaced. Thread-safe — the sweep dispatch thread folds while the
    metrics endpoint reads.

    Definitions (reported by ``summary()``, logged per sweep, and in
    bench.py's "speculative" key):

    - ``drafted_tokens`` / ``accepted_tokens`` / ``rejected_tokens``:
      draft tokens proposed per verify window, the prefix of them the
      verifier's own argmax confirmed, and the remainder (a rejected
      draft costs only its share of the verify forward — results are
      bitwise either way). ``accept_rate`` = accepted / drafted.
    - ``draft_tree`` / ``draft_ngram`` / ``draft_fleet`` (and their
      ``accepted_*`` twins): per-source token counts — radix-tree
      continuation probes, n-gram prompt-lookup, and fleet draft
      models.
    - ``decode_forwards`` / ``seq_forwards``: verify forwards actually
      run vs the forwards the sequential scan would have run on the
      same rows; ``dispatches_saved`` is their difference — the
      headline ≥2x target is seq_forwards / decode_forwards.
    - ``spec_dispatches`` / ``spec_rows``: dispatches and rows that ran
      the speculative path; ``fallbacks`` counts spec-eligible
      dispatches that ran sequentially (layout fallback, k < 2, missing
      draft source).
    """

    drafted_tokens: int = 0
    accepted_tokens: int = 0
    rejected_tokens: int = 0
    draft_tree: int = 0
    draft_ngram: int = 0
    draft_fleet: int = 0
    accepted_tree: int = 0
    accepted_ngram: int = 0
    accepted_fleet: int = 0
    decode_forwards: int = 0
    seq_forwards: int = 0
    dispatches_saved: int = 0
    spec_dispatches: int = 0
    spec_rows: int = 0
    fallbacks: int = 0

    def __post_init__(self) -> None:
        import threading

        self._lock = threading.Lock()

    def count(self, field: str, n: int = 1) -> None:
        with self._lock:
            setattr(self, field, getattr(self, field) + n)

    def add_branch(self, drafted, accepted, chunks: int,
                   seq_steps: int) -> None:
        """Fold one branch's SpecOut readout: ``drafted``/``accepted``
        are (tree, ngram, fleet) token counts."""
        dt, dn, df = (int(x) for x in drafted)
        at, an, af = (int(x) for x in accepted)
        with self._lock:
            self.draft_tree += dt
            self.draft_ngram += dn
            self.draft_fleet += df
            self.accepted_tree += at
            self.accepted_ngram += an
            self.accepted_fleet += af
            self.drafted_tokens += dt + dn + df
            self.accepted_tokens += at + an + af
            self.rejected_tokens += (dt + dn + df) - (at + an + af)
            self.decode_forwards += int(chunks)
            self.seq_forwards += int(seq_steps)
            self.dispatches_saved += max(int(seq_steps) - int(chunks), 0)

    @property
    def accept_rate(self) -> float:
        return (self.accepted_tokens / self.drafted_tokens
                if self.drafted_tokens else 0.0)

    def summary(self) -> Dict[str, object]:
        with self._lock:
            drafted = self.drafted_tokens
            out: Dict[str, object] = {
                "drafted_tokens": drafted,
                "accepted_tokens": self.accepted_tokens,
                "rejected_tokens": self.rejected_tokens,
                "accept_rate": round(
                    self.accepted_tokens / drafted, 4) if drafted else 0.0,
                "decode_forwards": self.decode_forwards,
                "seq_forwards": self.seq_forwards,
                "dispatches_saved": self.dispatches_saved,
                "spec_dispatches": self.spec_dispatches,
                "spec_rows": self.spec_rows,
                "fallbacks": self.fallbacks,
                "draft_source": {
                    "tree": {"drafted": self.draft_tree,
                             "accepted": self.accepted_tree},
                    "ngram": {"drafted": self.draft_ngram,
                              "accepted": self.accepted_ngram},
                    "fleet": {"drafted": self.draft_fleet,
                              "accepted": self.accepted_fleet},
                },
            }
        return out


# Published peak dense-matmul throughput per chip (bf16 FLOPS). Weight-only
# int8 still computes in bf16 on the MXU, so bf16 peak is the MFU denominator
# there; dynamic int8 (s8 x s8 -> s32 dots) gets 2x this on every listed
# chip. Keys are jax Device.device_kind strings.
CHIP_PEAK_BF16_FLOPS = {
    "TPU v5 lite": 197e12,      # v5e
    "TPU v5e": 197e12,
    "TPU v4": 275e12,
    "TPU v5p": 459e12,
    "TPU v6 lite": 918e12,      # v6e / Trillium
}


# s8-dot speedup over bf16 per chip: v5e/v5p/v6e run int8 at 2x bf16 MXU
# rate; TPU v4 has NO accelerated int8 path (s8 dots run at the bf16 rate).
CHIP_INT8_MULTIPLIER = {"TPU v4": 1.0}
_DEFAULT_INT8_MULTIPLIER = 2.0


def chip_peak_flops(device=None, int8: bool = False) -> Optional[float]:
    """Peak matmul FLOPS of the given (default: first) device, or None when
    the chip kind is unknown (e.g. CPU) — callers skip the MFU gate then.
    ``int8=True`` returns the chip's s8-dot peak (2x bf16 on v5e/v5p/v6e,
    1x on v4)."""
    if device is None:
        device = jax.devices()[0]
    kind = getattr(device, "device_kind", "")
    peak = CHIP_PEAK_BF16_FLOPS.get(kind)
    if peak is not None and int8:
        peak *= CHIP_INT8_MULTIPLIER.get(kind, _DEFAULT_INT8_MULTIPLIER)
    return peak


def decoder_matmul_params(cfg) -> int:
    """Matmul-visible parameter count of one ModelConfig decoder: the per-layer
    linear weights plus the lm_head. Embedding lookups do no matmul FLOPs."""
    D, hd = cfg.hidden_size, cfg.head_dim
    H, K, F = cfg.n_heads, cfg.n_kv_heads, cfg.intermediate_size
    per_layer = (D * H * hd          # wq
                 + 2 * D * K * hd    # wk, wv
                 + H * hd * D        # wo
                 + 2 * D * F         # w_up, w_down
                 + (D * F if cfg.gated_mlp else 0))
    return cfg.n_layers * per_layer + D * cfg.vocab_size  # + lm_head


def scoring_step_flops_split(cfg, batch: int, seq: int,
                             new_tokens: int) -> Dict[str, float]:
    """Matmul FLOPs (2 per MAC) of one fused scoring step, itemized by
    PHASE (the KernelStats breakdown): "prefill" — the quadratic prompt
    pass through the layer stack; "decode" — `new_tokens` KV-cached
    greedy steps through the layers (attention over the growing cache
    included); "readout" — the lm_head at the prefill's last position
    and once per decode step (decoder.prefill/_unembed). Sums to
    :func:`scoring_step_flops` exactly."""
    D, hd = cfg.hidden_size, cfg.head_dim
    H, L, V = cfg.n_heads, cfg.n_layers, cfg.vocab_size
    p_layers = decoder_matmul_params(cfg) - D * V
    head = 2 * D * V * batch
    prefill = 2 * p_layers * batch * seq
    prefill += 4 * batch * H * seq * seq * hd * L      # scores + weighted sum
    decode = 0.0
    for t in range(new_tokens):
        decode += 2 * p_layers * batch
        decode += 4 * batch * H * (seq + t + 1) * hd * L
    return {"prefill": float(prefill), "decode": float(decode),
            "readout": float(head * (1 + new_tokens))}


def scoring_step_flops(cfg, batch: int, seq: int, new_tokens: int) -> float:
    """Total matmul FLOPs (2 per MAC) of one fused scoring step: prefill of
    (batch, seq) + `new_tokens` KV-cached greedy decode steps. The lm_head
    runs once at the prefill's last position and once per decode step
    (decoder.prefill/_unembed). Attention score/value matmuls included.
    See :func:`scoring_step_flops_split` for the per-phase breakdown."""
    return float(sum(scoring_step_flops_split(
        cfg, batch, seq, new_tokens).values()))


def cascade_prefill_flops_saved(cfg, rows: int, trunk_len: int) -> float:
    """Analytic matmul FLOPs a cascade dispatch dedups away: the dense
    shared path prefills the ``trunk_len``-token trunk once per row —
    layer-stack linears plus the quadratic attention term, the exact
    per-row prefill arithmetic of :func:`scoring_step_flops_split` —
    while the cascade pays it ONCE, so ``rows - 1`` trunk prefills are
    saved (CascadeStats.prefix_flops_saved; the suffix-leg and merge
    work is common to both paths and cancels)."""
    if rows <= 1 or trunk_len <= 0:
        return 0.0
    D, hd = cfg.hidden_size, cfg.head_dim
    H, L, V = cfg.n_heads, cfg.n_layers, cfg.vocab_size
    p_layers = decoder_matmul_params(cfg) - D * V
    per_row = 2 * p_layers * trunk_len
    per_row += 4 * H * trunk_len * trunk_len * hd * L
    return float((rows - 1) * per_row)


def cascade_decode_bytes_saved(cfg, rows: int, trunk_len: int,
                               cache_len: int, steps: int,
                               itemsize: int = 4) -> float:
    """Analytic HBM bytes the trunk-aware flash-decode split dedup does
    NOT stream: the flat kernel's split-K grid reads every row's trunk
    K/V tiles from HBM each decode step, the trunk variant reads cache
    row 0's ONCE per step and batches every row's query against it
    (ops/flash_decode.flash_decode_trunk) — so each step saves
    ``rows - 1`` copies of the trunk splits' K+V bytes per layer.

    The trunk split count mirrors the kernel's own static ladder
    exactly (``pick_split``'s divisor-of-``cache_len`` pick, then
    ``min(trunk_len, cache_len - 1) // split`` whole splits — partial
    trailing splits stay per-row), so the counter reports the bytes the
    lowered kernel really dedups, not an idealized ``trunk_len`` bound.
    ``itemsize`` is the cache dtype's (float32 = 4; the engine's float
    KV caches — the int8 cache never reaches these kernels)."""
    if rows <= 1 or trunk_len <= 0 or steps <= 0 or cache_len <= 1:
        return 0.0
    from ..ops.flash_attention import DEFAULT_BLOCK_K
    from ..ops.flash_decode import pick_split

    split = pick_split(int(cache_len), DEFAULT_BLOCK_K)
    nt = max(0, min(int(trunk_len), int(cache_len) - 1)) // split
    if nt == 0:
        return 0.0
    n_kv = getattr(cfg, "n_kv_heads", None) or cfg.n_heads
    hd = cfg.head_dim
    per_row_step = 2 * n_kv * (nt * split) * hd * itemsize * cfg.n_layers
    return float(per_row_step * (rows - 1) * steps)


def device_memory_stats() -> Dict[str, Dict[str, float]]:
    """Per-device memory stats in GiB where the backend exposes them."""
    out: Dict[str, Dict[str, float]] = {}
    for dev in jax.devices():
        try:
            stats = dev.memory_stats()
        except Exception:
            continue
        if not stats:
            continue
        out[str(dev)] = {
            k: round(v / 2**30, 3)
            for k, v in stats.items()
            if isinstance(v, (int, float)) and "bytes" in k
        }
    return out


def ensure_cpu_backend() -> bool:
    """Force the CPU backend for statistics-only work.

    The analysis/survey layers are host statistics: tiny kernels where an
    accelerator buys nothing, and under a tunneled-TPU environment (axon)
    every launch round-trips over HTTP — measured 5-75x slower warm and
    minutes of compile cold at the reference's problem sizes
    (tools/stats_device_bench.py; table in SCALE.md). Call before any jax
    computation; returns False when the backend was already initialized to
    something else (work proceeds there).
    """
    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
        return True
    except Exception:
        return jax.default_backend() == "cpu"


def is_oom_error(err: BaseException | str) -> bool:
    """True when an exception (or its text) is a device out-of-memory —
    the ONE place the TPU runtime's OOM message heuristics live
    (RESOURCE_EXHAUSTED / "out of memory", case-insensitive); bench and
    the measurement tools use it to fall down batch ladders instead of
    aborting."""
    msg = str(err)
    return ("RESOURCE_EXHAUSTED" in msg
            or "out of memory" in msg.lower())
