"""Exponential-backoff retry (reference: perturb_prompts.py:72-106).

Generic over exception types so the same policy covers the optional remote-API
backend and any transient local failure (e.g. filesystem hiccups on a
preemptible host). Policy parity: 10 retries, 60 s initial delay capped at
300 s, x1.5 backoff, uniform 0.8-1.2 jitter.
"""

from __future__ import annotations

import random
import time
from typing import Callable, Tuple, Type, TypeVar

from lir_tpu.config import RetryConfig

T = TypeVar("T")


def retry_with_exponential_backoff(
    fn: Callable[[], T],
    retry_on: Tuple[Type[BaseException], ...] = (Exception,),
    config: RetryConfig = RetryConfig(),
    sleep: Callable[[float], None] = time.sleep,
    log: Callable[[str], None] = print,
) -> T:
    delay = config.initial_delay
    for attempt in range(config.max_retries + 1):
        try:
            return fn()
        except retry_on as exc:
            if attempt == config.max_retries:
                raise
            jitter = random.uniform(*config.jitter)
            wait = min(delay * jitter, config.max_delay)
            log(
                f"Attempt {attempt + 1}/{config.max_retries + 1} failed "
                f"({type(exc).__name__}: {exc}); retrying in {wait:.1f}s"
            )
            sleep(wait)
            delay = min(delay * config.backoff_factor, config.max_delay)
    raise AssertionError("unreachable")
