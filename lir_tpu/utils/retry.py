"""Exponential-backoff retry (reference: perturb_prompts.py:72-106).

Generic over exception types so the same policy covers the optional remote-API
backend, the serve supervisor's device dispatches, and any transient local
failure (e.g. filesystem hiccups on a preemptible host). Default policy
parity: 10 retries, 60 s initial delay capped at 300 s, x1.5 backoff, uniform
0.8-1.2 jitter. Two extensions over the reference (config.RetryConfig):

- ``full_jitter``: AWS-style full jitter (wait ~ U[0, delay]) instead of the
  multiplicative band — decorrelates many clients retrying one contended
  resource.
- ``max_elapsed``: a cap on the TOTAL wall time the retry loop may consume
  (attempts + sleeps). The reference's unbounded loop can exceed any caller
  deadline (10 retries at 300 s is 50 minutes); with the cap, once another
  sleep would cross it the last failure re-raises immediately, so a retried
  call composes with the serving layer's per-request deadlines.

KeyboardInterrupt and SystemExit are NEVER retried, even when a caller
passes a broad ``retry_on`` tuple (``(Exception,)`` is common and
``(BaseException,)`` has appeared in chaos wrappers): Ctrl-C during a
300 s backoff sleep must exit promptly, not be logged as "attempt 3
failed (KeyboardInterrupt)" and slept through seven more times.
"""

from __future__ import annotations

import random
import time
from typing import Callable, Tuple, Type, TypeVar

from lir_tpu.config import RetryConfig

T = TypeVar("T")


def retry_with_exponential_backoff(
    fn: Callable[[], T],
    retry_on: Tuple[Type[BaseException], ...] = (Exception,),
    config: RetryConfig = RetryConfig(),
    sleep: Callable[[float], None] = time.sleep,
    log: Callable[[str], None] = print,
    clock: Callable[[], float] = time.monotonic,
) -> T:
    delay = config.initial_delay
    start = clock()
    for attempt in range(config.max_retries + 1):
        try:
            return fn()
        except retry_on as exc:
            if isinstance(exc, (KeyboardInterrupt, SystemExit)):
                raise  # shutdown signals are not transient failures
            if attempt == config.max_retries:
                raise
            if config.full_jitter:
                wait = random.uniform(0.0, min(delay, config.max_delay))
            else:
                wait = min(delay * random.uniform(*config.jitter),
                           config.max_delay)
            if (config.max_elapsed is not None
                    and clock() - start + wait > config.max_elapsed):
                log(
                    f"Attempt {attempt + 1}/{config.max_retries + 1} failed "
                    f"({type(exc).__name__}: {exc}); next retry would exceed "
                    f"the {config.max_elapsed:.1f}s elapsed cap — giving up"
                )
                raise
            log(
                f"Attempt {attempt + 1}/{config.max_retries + 1} failed "
                f"({type(exc).__name__}: {exc}); retrying in {wait:.1f}s"
            )
            sleep(wait)
            delay = min(delay * config.backoff_factor, config.max_delay)
    raise AssertionError("unreachable")
