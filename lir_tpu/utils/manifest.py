"""Resume-idempotent sweep manifest.

The reference achieves preemption safety by (a) a done-set of
(Model, Original Main Part, Rephrased Main Part) keys read from the output
Excel (perturb_prompts.py:161-188), (b) checkpoint files every 100 rows
(:975-984), and (c) a validated perturbation cache (:739-777). This module
keeps those exact semantics but as an append-only JSONL manifest with atomic
line writes, so a killed TPU sweep resumes without duplicate rows (SURVEY.md
§7 hard part 7).
"""

from __future__ import annotations

import json
import os
import tempfile
from pathlib import Path
from typing import Dict, Iterable, Iterator, Optional, Set, Tuple

import pandas as pd

Key = Tuple[str, ...]


class SweepManifest:
    """Append-only record of completed grid cells, keyed by string tuples."""

    def __init__(self, path: Path, key_fields: Tuple[str, ...]):
        self.path = Path(path)
        self.key_fields = key_fields
        self._done: Set[Key] = set()
        if self.path.exists():
            for line in self.path.read_text().splitlines():
                if not line.strip():
                    continue
                rec = json.loads(line)
                self._done.add(tuple(str(rec[f]) for f in key_fields))

    def __len__(self) -> int:
        return len(self._done)

    def key_of(self, record: Dict[str, object]) -> Key:
        return tuple(str(record[f]) for f in self.key_fields)

    def is_done(self, record: Dict[str, object]) -> bool:
        return self.key_of(record) in self._done

    def mark_done(self, record: Dict[str, object]) -> None:
        self.mark_done_many([record])

    def mark_done_many(self, records: Iterable[Dict[str, object]]) -> None:
        """Append all not-yet-done keys in one open + single fsync."""
        lines = []
        for record in records:
            key = self.key_of(record)
            if key in self._done:
                continue
            self._done.add(key)
            lines.append(json.dumps(dict(zip(self.key_fields, key))))
        if not lines:
            return
        self.path.parent.mkdir(parents=True, exist_ok=True)
        with self.path.open("a") as f:
            f.write("\n".join(lines) + "\n")
            f.flush()
            os.fsync(f.fileno())

    def pending(self, records: Iterable[Dict[str, object]]) -> Iterator[Dict[str, object]]:
        for rec in records:
            if not self.is_done(rec):
                yield rec

    @classmethod
    def from_existing_results(
        cls,
        manifest_path: Path,
        results_path: Optional[Path],
        key_fields: Tuple[str, ...],
    ) -> "SweepManifest":
        """Seed the done-set from a prior results file, mirroring
        load_existing_results (perturb_prompts.py:161-188)."""
        m = cls(manifest_path, key_fields)
        if results_path is not None and Path(results_path).exists():
            read = pd.read_excel if str(results_path).endswith(".xlsx") else pd.read_csv
            df = read(results_path)
            if all(f in df.columns for f in key_fields):
                m.mark_done_many(
                    {f: row[f] for f in key_fields} for _, row in df.iterrows()
                )
        return m


def atomic_write_text(path: Path, text: str) -> None:
    """Crash-safe file replacement (orbax-style atomicity for result shards)."""
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=str(path.parent), prefix=path.name + ".tmp")
    try:
        with os.fdopen(fd, "w") as f:
            f.write(text)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)


def atomic_write_json(path: Path, obj) -> None:
    atomic_write_text(path, json.dumps(obj, ensure_ascii=False, indent=2))
