"""Resume-idempotent sweep manifest.

The reference achieves preemption safety by (a) a done-set of
(Model, Original Main Part, Rephrased Main Part) keys read from the output
Excel (perturb_prompts.py:161-188), (b) checkpoint files every 100 rows
(:975-984), and (c) a validated perturbation cache (:739-777). This module
keeps those exact semantics but as an append-only JSONL manifest with atomic
line writes, so a killed TPU sweep resumes without duplicate rows (SURVEY.md
§7 hard part 7).
"""

from __future__ import annotations

import json
import os
import tempfile
from pathlib import Path
from typing import Dict, Iterable, Iterator, Optional, Set, Tuple

import pandas as pd

Key = Tuple[str, ...]


class SweepManifest:
    """Append-only record of completed grid cells, keyed by string tuples.

    Crash-consistent by construction: appends are a single fsync'd write
    (plus a parent-directory fsync so the file itself survives a host
    crash right after creation), and loading TOLERATES a torn trailing
    line — the exact artifact the crash this manifest exists to survive
    leaves behind. A torn (non-JSON or key-incomplete) tail is skipped
    on load and truncated away by the next append; a malformed line
    anywhere ELSE still raises, because that is corruption no crash of
    ours produces."""

    def __init__(self, path: Path, key_fields: Tuple[str, ...]):
        self.path = Path(path)
        self.key_fields = key_fields
        self._done: Set[Key] = set()
        # Sweep-scoped metadata records ({"__meta__": {...}} lines):
        # run parameters that must survive a resume — e.g. the
        # streaming-statistics bootstrap key ("stream_seed"), so a
        # resumed sweep's CIs are drawn from the SAME resample indices
        # as an uninterrupted one (engine/stream_stats.py).
        self.meta: Dict[str, object] = {}
        # Byte offset to truncate to before the next append (a torn
        # trailing line from a mid-append crash); None = file is clean.
        self._truncate_to: Optional[int] = None
        if self.path.exists():
            raw = self.path.read_bytes()
            pos = 0
            chunks = raw.split(b"\n")
            for i, chunk in enumerate(chunks):
                start = pos
                pos += len(chunk) + 1
                if not chunk.strip():
                    continue
                try:
                    rec = json.loads(chunk.decode("utf-8"))
                    if isinstance(rec, dict) and "__meta__" in rec:
                        if isinstance(rec["__meta__"], dict):
                            self.meta.update(rec["__meta__"])
                        continue
                    key = tuple(str(rec[f]) for f in key_fields)
                except (UnicodeDecodeError, json.JSONDecodeError, KeyError,
                        TypeError):
                    if all(not c.strip() for c in chunks[i + 1:]):
                        # Torn tail: the crash happened mid-append. The
                        # rows it named were NOT marked done, so a
                        # resumed sweep re-scores them (write-ahead
                        # order: results first, manifest second).
                        self._truncate_to = start
                        break
                    raise
                self._done.add(key)

    def __len__(self) -> int:
        return len(self._done)

    def key_of(self, record: Dict[str, object]) -> Key:
        return tuple(str(record[f]) for f in self.key_fields)

    def is_done(self, record: Dict[str, object]) -> bool:
        return self.key_of(record) in self._done

    def mark_done(self, record: Dict[str, object]) -> None:
        self.mark_done_many([record])

    def set_meta(self, key: str, value) -> None:
        """Record (or re-record) one metadata value as a durable
        ``{"__meta__": ...}`` line. Idempotent: an unchanged value
        appends nothing, and a resumed manifest returns the recorded
        value via ``self.meta`` before any caller re-derives it."""
        if self.meta.get(key) == value:
            return
        self.meta[key] = value
        self._append_lines([json.dumps({"__meta__": {key: value}})])

    def mark_done_many(self, records: Iterable[Dict[str, object]]) -> None:
        """Append all not-yet-done keys in one open + single fsync."""
        lines = []
        for record in records:
            key = self.key_of(record)
            if key in self._done:
                continue
            self._done.add(key)
            lines.append(json.dumps(dict(zip(self.key_fields, key))))
        self._append_lines(lines)

    def _append_lines(self, lines) -> None:
        if not lines:
            return
        self.path.parent.mkdir(parents=True, exist_ok=True)
        created = not self.path.exists()
        if self._truncate_to is not None and not created:
            # Drop the torn tail found at load time BEFORE appending —
            # otherwise the new first line glues onto the fragment and
            # becomes unparseable itself.
            with self.path.open("r+b") as f:
                f.truncate(self._truncate_to)
        self._truncate_to = None
        with self.path.open("a") as f:
            f.write("\n".join(lines) + "\n")
            f.flush()
            os.fsync(f.fileno())
        if created:
            _fsync_dir(self.path.parent)

    def pending(self, records: Iterable[Dict[str, object]]) -> Iterator[Dict[str, object]]:
        for rec in records:
            if not self.is_done(rec):
                yield rec

    @classmethod
    def from_existing_results(
        cls,
        manifest_path: Path,
        results_path: Optional[Path],
        key_fields: Tuple[str, ...],
        column_map: Optional[Dict[str, str]] = None,
    ) -> "SweepManifest":
        """Seed the done-set from a prior results file, mirroring
        load_existing_results (perturb_prompts.py:161-188).

        This is the crash-consistency half the manifest alone cannot
        give: the flush order is results-append THEN manifest-mark, so a
        kill between the two leaves rows in the results file that the
        manifest does not know about — a manifest-only resume would
        re-score and DUPLICATE them. Seeding the union makes the done
        set exactly "whatever reached the results artifact".

        ``column_map`` maps manifest key fields to results-file column
        names (the D6 workbook uses 'Model'/'Original Main Part'/... for
        the manifest's 'model'/'original_main'/...). An unreadable or
        torn prior file degrades to manifest-only seeding instead of
        failing the resume — losing the seed only re-scores rows, never
        loses or duplicates them (write-ahead order + this union)."""
        m = cls(manifest_path, key_fields)
        if results_path is None or not Path(results_path).exists():
            return m
        cols = {f: (column_map or {}).get(f, f) for f in key_fields}
        try:
            if str(results_path).endswith(".xlsx"):
                df = pd.read_excel(results_path)
            else:
                df = pd.read_csv(results_path, on_bad_lines="skip")
        except Exception:
            return m
        if all(c in df.columns for c in cols.values()):
            df = df.dropna(subset=list(cols.values()))
            m.mark_done_many(
                {f: row[c] for f, c in cols.items()}
                for _, row in df.iterrows()
            )
        return m


def _fsync_dir(path: Path) -> None:
    """fsync a directory so a just-created file's entry is durable (a
    crash after file-fsync but before dir-fsync can lose the whole
    file on some filesystems). Best-effort: not every platform allows
    opening directories."""
    try:
        fd = os.open(str(path), os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def atomic_write_text(path: Path, text: str) -> None:
    """Crash-safe file replacement (orbax-style atomicity for result shards)."""
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=str(path.parent), prefix=path.name + ".tmp")
    try:
        with os.fdopen(fd, "w") as f:
            f.write(text)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)


def atomic_write_json(path: Path, obj) -> None:
    atomic_write_text(path, json.dumps(obj, ensure_ascii=False, indent=2))
