"""Source-level annotations the static-analysis suite (lir_tpu/lint)
understands. Import-free and side-effect-free by design: hot-path
modules may import this without pulling in anything."""

from __future__ import annotations

from typing import Callable, TypeVar

F = TypeVar("F", bound=Callable)


def host_readout(fn: F) -> F:
    """Mark ``fn`` as a LEGITIMATE device→host readout boundary: it
    deliberately synchronizes with the device (``jax.device_get``,
    ``np.asarray`` on device values, scalar coercion) and the host-sync
    lint pass must not flag it. Decorating a function is a reviewable
    claim that the sync is off the dispatch thread's critical path —
    e.g. the sweep's writer thread or a bench's final readout — not a
    license to block dispatch (DEPLOY.md §1i)."""
    return fn
