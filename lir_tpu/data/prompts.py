"""Canonical experiment prompt assets — the single source of truth.

The reference hard-codes these in four+ places with drift (legal prompts:
analysis/perturb_prompts.py:728-734; word-meaning questions:
analysis/compare_base_vs_instruct.py:308-359 and
analysis/compare_instruct_models.py:? (same 50); the question -> Qualtrics-ID
mapping copy-pasted across four survey scripts, e.g.
survey_analysis/analyze_llm_human_agreement.py:31-82). SURVEY.md §1 flags the
duplication; here each asset exists exactly once.

These are *data*, not code: the experimental stimuli whose parity defines
capability parity with the reference study.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Tuple


@dataclasses.dataclass(frozen=True)
class LegalPrompt:
    """One legal-interpretation stimulus (perturb_prompts.py prompt tuple).

    ``target_tokens`` are the two answer-distinguishing first tokens whose
    probabilities are read out of the logits (e.g. "Covered" vs "Not").
    """

    main: str                      # the legal question to be rephrased
    response_format: str           # binary-answer instruction appended verbatim
    target_tokens: Tuple[str, str]  # (token_1, token_2) for the logprob readout
    confidence_format: str         # 0-100 confidence instruction

    @property
    def binary_prompt(self) -> str:
        return f"{self.main} {self.response_format}"

    @property
    def confidence_prompt(self) -> str:
        return f"{self.main} {self.confidence_format}"

    def rephrased_binary(self, rephrased_main: str) -> str:
        return f"{rephrased_main} {self.response_format}"

    def rephrased_confidence(self, rephrased_main: str) -> str:
        return f"{rephrased_main} {self.confidence_format}"


_CONF_COVER = (
    "How confident are you that the insurance policy should cover the loss, on a "
    "scale from 0 (not confident) to 100 (most confident)? Answer only with a "
    "number, without any other explanation."
)

LEGAL_PROMPTS: Tuple[LegalPrompt, ...] = (
    LegalPrompt(
        main=(
            "An insurance policy contains the following exclusion: 'We will not pay "
            "for loss or damage caused directly or indirectly by flood, surface "
            "water, waves, tides, tidal waves, overflow of any body of water, or "
            "their spray, all whether driven by wind or not.' If an insured files a "
            "claim for damage caused by a levee failure rather than natural causes, "
            "does the exclusion apply? Please analyze whether the insurance policy "
            "would cover the damage or not."
        ),
        response_format=(
            "Answer only 'Covered' if insurance covers the loss or 'Not Covered' if "
            "it does not, without any other explanation."
        ),
        target_tokens=("Covered", "Not"),
        confidence_format=_CONF_COVER,
    ),
    LegalPrompt(
        main=(
            "In a prenuptial agreement, if one party files a petition for divorce "
            "after seven years, withdraws it without serving it, and then files "
            "again after ten years, which filing date determines the number of "
            "years of marriage for calculating the payment amount? The agreement "
            "states that payment will be based on 'the number of full years they "
            "have been married at the time a Petition for Dissolution of Marriage "
            "is filed.' Please analyze whether 'a Petition' refers to the first or "
            "ultimate petition filed."
        ),
        response_format=(
            "Answer only 'First Petition' if the first filing date should be used "
            "or 'Ultimate Petition' if the ultimate filing date should be used, "
            "without any other explanation."
        ),
        target_tokens=("Ultimate", "First"),
        confidence_format=(
            "How confident are you that the first filing date should be used, on a "
            "scale from 0 (not confident) to 100 (most confident)? Answer only "
            "with a number, without any other explanation."
        ),
    ),
    LegalPrompt(
        main=(
            "Does the following contract term from 1961 naturally include only "
            "existing affiliates at the time of contract, or does it potentially "
            "encompass affiliates that might be created over time? The term binds "
            "[Company] and its 'other affiliate[s]' to a 50/50 royalty split after "
            "deducting fees charged by third parties that intermediate in foreign "
            "markets. Please analyze whether the term 'other affiliate[s]' "
            "includes only existing affiliates or includes future affiliates as "
            "well."
        ),
        response_format=(
            "Answer only 'Existing Affiliates' or 'Future Affiliates', without any "
            "other explanation."
        ),
        target_tokens=("Existing", "Future"),
        confidence_format=(
            "How confident are you that the royalty split only includes existing "
            "affiliates, on a scale from 0 (not confident) to 100 (most "
            "confident)? Answer only with a number, without any other explanation."
        ),
    ),
    LegalPrompt(
        main=(
            "A contractor and business corresponded about construction of a new "
            "foundry. The contractor offered to do the job either by offering an "
            "itemized list or charging cost + 10%. After a phone call where they "
            "allegedly agreed payment would be made 'in the usual manner', the "
            "foundry accepted in writing. If one party claims it is customary to "
            "pay 85% of payments due at the end of every month, but the other "
            "argues payments are only due upon substantial completion, how should "
            "the term 'usual manner' be interpreted? Does this term refer to the "
            "monthly installment payments or to payment upon completion?"
        ),
        response_format=(
            "Answer only 'Monthly Installment Payments' or 'Payment Upon "
            "Completion', without any other explanation."
        ),
        target_tokens=("Monthly", "Payment"),
        confidence_format=(
            "How confident are you that the payments should be made every month, "
            "on a scale from 0 (not confident) to 100 (most confident)? Answer "
            "only with a number, without any other explanation."
        ),
    ),
    LegalPrompt(
        main=(
            "You are analyzing an insurance policy dispute. The policy states: "
            "'[Insurer will pay for] the felonious abstraction of insured property "
            "(1) from within the premises by a person making felonious entry "
            "therein by actual force and violence, of which force and violence "
            "there are visible marks made by tools, explosives, electricity or "
            "chemicals.' A business has experienced a theft where there is clear "
            "evidence that a third party committed the burglary. No inside job is "
            "suspected. Based on these terms, would this policy provide "
            "compensation for losses resulting from this substantiated third-party "
            "burglary? Please analyze whether coverage would be provided."
        ),
        response_format=(
            "Answer only 'Covered' if insurance covers the loss or 'Not Covered' "
            "if it does not, without any other explanation."
        ),
        target_tokens=("Covered", "Not"),
        confidence_format=_CONF_COVER,
    ),
)


# The 50 "Is an X a Y?" word-meaning questions, in survey order
# (compare_base_vs_instruct.py:308-359). Keys of QUESTION_TO_QUALTRICS map each
# to its Qualtrics slider column; Q*_8 is the attention check and has no
# question.
WORD_MEANING_QUESTIONS: Tuple[str, ...] = (
    'Is a "screenshot" a "photograph"?',
    'Is "advising" someone "instructing" them?',
    'Is an "algorithm" a "procedure"?',
    'Is a "drone" an "aircraft"?',
    'Is "reading aloud" a form of "performance"?',
    'Is "training" an AI model "authoring" content?',
    'Is a "wedding" a "party"?',
    'Is "streaming" a video "broadcasting" that video?',
    'Is "braiding" hair a form of "weaving"?',
    'Is "digging" a form of "construction"?',
    'Is a "smartphone" a "computer"?',
    'Is a "cactus" a "tree"?',
    'Is a "bonus" a form of "wages"?',
    'Is "forwarding" an email "sending" that email?',
    'Is a "chatbot" a "service"?',
    'Is "plagiarism" a form of "theft"?',
    'Is "remote viewing" of an event "attending" it?',
    'Is "whistling" a form of "music"?',
    'Is "caching" data in computer memory "storing" that data?',
    'Is a "waterway" a form of "roadway"?',
    'Is a "deepfake" a "portrait"?',
    'Is "humming" a form of "singing"?',
    'Is "liking" a social media post "endorsing" it?',
    'Is "herding" animals a form of "transporting" them?',
    'Is an "NFT" a "security"?',
    'Is "sleeping" an "activity"?',
    'Is a "driverless car" a "motor vehicle operator"?',
    'Is a "subscription fee" a form of "purchase"?',
    'Is "mentoring" someone a form of "supervising" them?',
    'Is a "biometric scan" a form of "signature"?',
    'Is a "digital wallet" a "bank account"?',
    'Is "dictation" a form of "writing"?',
    'Is a "virtual tour" a form of "inspection"?',
    'Is "bartering" a form of "payment"?',
    'Is "listening" to an audiobook "reading" it?',
    'Is a "nest" a form of "dwelling"?',
    'Is a "QR code" a "document"?',
    'Is a "tent" a "building"?',
    'Is a "whisper" a form of "speech"?',
    'Is "hiking" a form of "travel"?',
    'Is a "recipe" a form of "instruction"?',
    'Is "daydreaming" a form of "thinking"?',
    'Is "gossip" a form of "news"?',
    'Is a "mountain" a form of "hill"?',
    'Is "walking" a form of "exercise"?',
    'Is a "candle" a "lamp"?',
    'Is a "trail" a "road"?',
    'Is "repainting" a house "repairing" it?',
    'Is "kneeling" a form of "sitting"?',
    'Is a "mask" a form of "clothing"?',
)


def _qualtrics_ids():
    # 5 groups x 11 sliders; column 8 is the attention check, skipped.
    ids = []
    for group in range(1, 6):
        for col in list(range(1, 8)) + list(range(9, 12)):
            ids.append(f"Q{group}_{col}")
    return tuple(ids)


QUESTION_TO_QUALTRICS: Dict[str, str] = dict(
    zip(WORD_MEANING_QUESTIONS, _qualtrics_ids())
)
QUALTRICS_TO_QUESTION: Dict[str, str] = {
    v: k for k, v in QUESTION_TO_QUALTRICS.items()
}

ATTENTION_CHECK_COLUMNS: Tuple[str, ...] = tuple(f"Q{g}_8" for g in range(1, 6))

# Few-shot scaffold used for base (non-instruct) models
# (compare_base_vs_instruct.py:458-463).
FEW_SHOT_PREFIX = (
    "Question: Is \"soup\" a \"beverage\"? Answer either 'Yes' or 'No', without "
    "any other text.\nAnswer: No.\n\n"
    "Question: Is a \"tweet\" a \"publication\"? Answer either 'Yes' or 'No', "
    "without any other text.\nAnswer: Yes.\n\n"
)

_ANSWER_SUFFIX = " Answer either 'Yes' or 'No', without any other text."


def format_base_prompt(question: str) -> str:
    """Few-shot 'Question:/Answer:' scaffold for base models."""
    return f"{FEW_SHOT_PREFIX}Question: {question}{_ANSWER_SUFFIX}\nAnswer:"


def format_instruct_prompt(question: str) -> str:
    """Instruct formatting in the base-vs-instruct sweep (D1): the few-shot
    prefix IS included (compare_base_vs_instruct.py:462-463 formats
    ``{few_shot_examples}{prompt} ...`` for instruct models too)."""
    return f"{FEW_SHOT_PREFIX}{question}{_ANSWER_SUFFIX}"


def format_instruct_direct(question: str) -> str:
    """Instruct formatting in the instruct-only sweep (D2): the bare
    question, no few-shot scaffold (compare_instruct_models.py:488)."""
    return f"{question}{_ANSWER_SUFFIX}"


def format_baichuan_prompt(question: str) -> str:
    """Baichuan chat template in the instruct-only sweep
    (compare_instruct_models.py:491-492)."""
    return f"<human>: {question}{_ANSWER_SUFFIX}\n<bot>:"


def rephrase_request(main_prompt: str, n: int = 20) -> str:
    """Rephrasing instruction given to the perturbation-generator model
    (perturb_prompts.py:791-797); served locally by the tpu backend."""
    return (
        f'Here is a question:\n###"{main_prompt}"###\n'
        f"Please rephrase this question in {n} variations that differ from the "
        "original question but preserve the substance of the question. Each "
        "rephrasing should be a complete question, not just a fragment of a "
        f"question. Number each rephrasing from 1 to {n}."
    )
