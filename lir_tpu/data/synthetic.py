"""Deterministic synthetic D6 fixture for differential-parity testing.

The reference's perturbation workbook (D6, `combined_results.xlsx`,
perturb_prompts.py:964-1016) is a *generated* artifact — the upstream repo
commits only D1-D4, so no real D6 exists to test against. For differential
parity (running the reference's own `calculate_cohens_kappa.py` and our
`analysis/` pipeline on IDENTICAL inputs and diffing the outputs) we need a
D6 whose values are fixed forever: this module generates one from a pinned
seed with numpy only, so the tools/ capture script and the tests/ diff both
reconstruct byte-identical values.

The synthetic rows use the five real legal prompts (data/prompts.py — the
keyword matcher in calculate_cohens_kappa.py:230-241 matches on their text)
with per-prompt yes-lean levels spanning the kappa interpretation bands, so
the diff exercises agree_percent/self-kappa over a meaningful range.
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import List

import numpy as np
import pandas as pd

from .prompts import LEGAL_PROMPTS
from .schemas import PERTURBATION_COLUMNS

SYNTH_SEED = 20260730
N_REPHRASINGS = 200          # per prompt (reference scale ~2000; 200 keeps
                             # the fixture fast while self-kappa stays stable)
SYNTH_MODEL = "synthetic-scorer-v1"

# Edge-case model (VERDICT r3 #1): a second model whose rows hit every hairy
# branch of the reference analyzer (analyze_perturbation_results.py) —
# zero/one-inflated Relative_Prob (exact 0/1 mass for the truncated-normal
# MC fit's inflation accounting, :150-156), non-finite rows (Token probs
# both 0), non-compliant first tokens and full responses (:1330-1372),
# unparseable / ast-literal Log Probabilities (:1301-1322), and every
# confidence non-compliance category (float / text / out-of-range / other,
# :1564-1600).
SYNTH_EDGE_MODEL = "synthetic-edge-v1"
N_EDGE_ROWS = 60             # per prompt (>= 100/model so the analyzer's
                             # small-data guard does not trip, :1724)

# Per-prompt P(token_1 wins): spans near-coin-flip to near-unanimous.
_YES_LEAN = (0.55, 0.72, 0.38, 0.9, 0.65)

# Canonical full responses per prompt (the reference's expected_tokens
# table, analyze_perturbation_results.py:1206-1246), pre-split into OpenAI
# content-style token pieces so compliant rows re-join exactly.
_FULL_RESPONSE_TOKENS = (
    {"Covered": ("Covered",), "Not": ("Not", " Covered")},
    {"Ultimate": ("Ultimate", " Petition"), "First": ("First", " Petition")},
    {"Existing": ("Existing", " Affiliates"),
     "Future": ("Future", " Affiliates")},
    {"Monthly": ("Monthly", " Installment", " Payments"),
     "Payment": ("Payment", " Upon", " Completion")},
    {"Covered": ("Covered",), "Not": ("Not", " Covered")},
)


def _content_logprobs(tokens, logprob: float) -> str:
    """OpenAI chat-completions style 'Log Probabilities' payload — the ONLY
    format the reference compliance checker parses (:1313-1326)."""
    return json.dumps(
        {"content": [{"token": t, "logprob": logprob} for t in tokens]})


def synthetic_perturbation_frame() -> pd.DataFrame:
    """The deterministic D6 dataframe (binary-format rows only — the kappa
    path consumes Token_1/2_Prob; confidence columns carry E[v] draws)."""
    rng = np.random.default_rng(SYNTH_SEED)
    records: List[dict] = []
    for pi, (prompt, lean) in enumerate(zip(LEGAL_PROMPTS, _YES_LEAN)):
        for i in range(N_REPHRASINGS):
            # Relative prob drawn around the lean with clipping to (0, 1).
            rel = float(np.clip(rng.normal(lean, 0.18), 1e-3, 1 - 1e-3))
            total = float(rng.uniform(0.7, 0.99))
            t1, t2 = rel * total, (1 - rel) * total
            conf = float(np.clip(rng.normal(70, 15), 0, 100))
            target = (prompt.target_tokens[0] if rel > 0.5
                      else prompt.target_tokens[1])
            pieces = _FULL_RESPONSE_TOKENS[pi][target]
            records.append({
                "Model": SYNTH_MODEL,
                "Original Main Part": prompt.main,
                "Response Format": prompt.response_format,
                "Confidence Format": prompt.confidence_format,
                "Rephrased Main Part": f"[rephrasing {i}] {prompt.main}",
                "Full Rephrased Prompt": prompt.rephrased_binary(
                    f"[rephrasing {i}] {prompt.main}"),
                "Full Confidence Prompt": prompt.rephrased_confidence(
                    f"[rephrasing {i}] {prompt.main}"),
                "Model Response": target,
                "Model Confidence Response": str(int(round(conf))),
                "Log Probabilities": _content_logprobs(
                    pieces, float(np.log(max(t1, t2)))),
                "Token_1_Prob": t1,
                "Token_2_Prob": t2,
                "Odds_Ratio": t1 / t2,
                "Confidence Value": float(int(round(conf))),
                "Weighted Confidence": conf,
            })
    records.extend(_edge_model_records())
    return pd.DataFrame(records, columns=list(PERTURBATION_COLUMNS))


def _edge_model_records() -> List[dict]:
    """synthetic-edge-v1 rows: every analyzer edge branch, deterministic."""
    rng = np.random.default_rng(SYNTH_SEED + 1)
    records: List[dict] = []
    for pi, prompt in enumerate(LEGAL_PROMPTS):
        fulls = _FULL_RESPONSE_TOKENS[pi]
        tok1, tok2 = prompt.target_tokens
        for i in range(N_EDGE_ROWS):
            kind = i % 10
            # Interior draw with HARD clipping to [0, 1]: the clip mass
            # lands exactly on the bounds -> natural zero/one inflation on
            # top of the explicit inflated rows below.
            rel = float(np.clip(rng.normal(0.5, 0.3), 0.0, 1.0))
            total = float(rng.uniform(0.6, 0.95))
            wconf = float(np.clip(rng.normal(55.0, 30.0), 0.0, 100.0))
            target = tok1 if rel > 0.5 else tok2
            compliant_lp = _content_logprobs(fulls[target], -0.3)
            conf: object = str(int(round(wconf)))
            conf_val: float = float(int(round(wconf)))
            lp = compliant_lp
            if kind == 0:          # zero-inflated: P(token_1) exactly 0
                rel, target = 0.0, tok2
                lp = _content_logprobs(fulls[tok2], -0.2)
                conf, conf_val, wconf = "0", 0.0, 0.0
            elif kind == 1:        # one-inflated: P(token_2) exactly 0
                rel, target = 1.0, tok1
                lp = _content_logprobs(fulls[tok1], -0.1)
                conf, conf_val, wconf = "100", 100.0, 100.0
            elif kind == 2:        # non-finite: both token probs zero
                rel, total = float("nan"), 0.0
                conf, conf_val, wconf = None, float("nan"), float("nan")
            elif kind == 3:        # non-compliant FIRST token + float conf
                lp = _content_logprobs(("I", " think", " " + target), -1.0)
                conf, conf_val = "85.5", float("nan")
            elif kind == 4:        # compliant first, non-compliant full +
                lp = _content_logprobs((target, " maybe"), -0.8)
                conf, conf_val = "150", float("nan")   # out-of-range conf
            elif kind == 5:        # unparseable payload (no 'content') +
                lp = json.dumps({tok1: -0.5, tok2: -1.5})
                conf, conf_val = "high", float("nan")  # text conf
            elif kind == 6:        # python-literal payload (ast branch) +
                lp = str({"content": [{"token": t, "logprob": -0.4}
                                      for t in fulls[target]]})
                conf, conf_val = "?", float("nan")     # 'other' conf
            t1 = rel * total if np.isfinite(rel) else 0.0
            t2 = (1.0 - rel) * total if np.isfinite(rel) else 0.0
            odds = (float("inf") if t2 == 0.0 and t1 > 0.0
                    else (t1 / t2 if t2 > 0.0 else float("nan")))
            records.append({
                "Model": SYNTH_EDGE_MODEL,
                "Original Main Part": prompt.main,
                "Response Format": prompt.response_format,
                "Confidence Format": prompt.confidence_format,
                "Rephrased Main Part": f"[edge {i}] {prompt.main}",
                "Full Rephrased Prompt": prompt.rephrased_binary(
                    f"[edge {i}] {prompt.main}"),
                "Full Confidence Prompt": prompt.rephrased_confidence(
                    f"[edge {i}] {prompt.main}"),
                "Model Response": target,
                "Model Confidence Response": conf,
                "Log Probabilities": lp,
                "Token_1_Prob": t1,
                "Token_2_Prob": t2,
                "Odds_Ratio": odds,
                "Confidence Value": conf_val,
                "Weighted Confidence": wconf,
            })
    return records


def write_synthetic_d6(path: Path) -> Path:
    """Write the fixture as .xlsx (falling back to .csv without openpyxl);
    returns the path actually written."""
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    df = synthetic_perturbation_frame()
    if path.suffix == ".xlsx":
        try:
            df.to_excel(path, index=False)
            return path
        except (ImportError, ModuleNotFoundError):
            path = path.with_suffix(".csv")
    df.to_csv(path, index=False)
    return path
