"""Deterministic synthetic D6 fixture for differential-parity testing.

The reference's perturbation workbook (D6, `combined_results.xlsx`,
perturb_prompts.py:964-1016) is a *generated* artifact — the upstream repo
commits only D1-D4, so no real D6 exists to test against. For differential
parity (running the reference's own `calculate_cohens_kappa.py` and our
`analysis/` pipeline on IDENTICAL inputs and diffing the outputs) we need a
D6 whose values are fixed forever: this module generates one from a pinned
seed with numpy only, so the tools/ capture script and the tests/ diff both
reconstruct byte-identical values.

The synthetic rows use the five real legal prompts (data/prompts.py — the
keyword matcher in calculate_cohens_kappa.py:230-241 matches on their text)
with per-prompt yes-lean levels spanning the kappa interpretation bands, so
the diff exercises agree_percent/self-kappa over a meaningful range.
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import List

import numpy as np
import pandas as pd

from .prompts import LEGAL_PROMPTS
from .schemas import PERTURBATION_COLUMNS

SYNTH_SEED = 20260730
N_REPHRASINGS = 200          # per prompt (reference scale ~2000; 200 keeps
                             # the fixture fast while self-kappa stays stable)
SYNTH_MODEL = "synthetic-scorer-v1"

# Per-prompt P(token_1 wins): spans near-coin-flip to near-unanimous.
_YES_LEAN = (0.55, 0.72, 0.38, 0.9, 0.65)


def synthetic_perturbation_frame() -> pd.DataFrame:
    """The deterministic D6 dataframe (binary-format rows only — the kappa
    path consumes Token_1/2_Prob; confidence columns carry E[v] draws)."""
    rng = np.random.default_rng(SYNTH_SEED)
    records: List[dict] = []
    for prompt, lean in zip(LEGAL_PROMPTS, _YES_LEAN):
        for i in range(N_REPHRASINGS):
            # Relative prob drawn around the lean with clipping to (0, 1).
            rel = float(np.clip(rng.normal(lean, 0.18), 1e-3, 1 - 1e-3))
            total = float(rng.uniform(0.7, 0.99))
            t1, t2 = rel * total, (1 - rel) * total
            conf = float(np.clip(rng.normal(70, 15), 0, 100))
            logprobs = {prompt.target_tokens[0]: float(np.log(t1)),
                        prompt.target_tokens[1]: float(np.log(t2))}
            records.append({
                "Model": SYNTH_MODEL,
                "Original Main Part": prompt.main,
                "Response Format": prompt.response_format,
                "Confidence Format": prompt.confidence_format,
                "Rephrased Main Part": f"[rephrasing {i}] {prompt.main}",
                "Full Rephrased Prompt": prompt.rephrased_binary(
                    f"[rephrasing {i}] {prompt.main}"),
                "Full Confidence Prompt": prompt.rephrased_confidence(
                    f"[rephrasing {i}] {prompt.main}"),
                "Model Response": prompt.target_tokens[0] if rel > 0.5
                else prompt.target_tokens[1],
                "Model Confidence Response": str(int(round(conf))),
                "Log Probabilities": json.dumps(logprobs),
                "Token_1_Prob": t1,
                "Token_2_Prob": t2,
                "Odds_Ratio": t1 / t2,
                "Confidence Value": float(int(round(conf))),
                "Weighted Confidence": conf,
            })
    return pd.DataFrame(records, columns=list(PERTURBATION_COLUMNS))


def write_synthetic_d6(path: Path) -> Path:
    """Write the fixture as .xlsx (falling back to .csv without openpyxl);
    returns the path actually written."""
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    df = synthetic_perturbation_frame()
    if path.suffix == ".xlsx":
        try:
            df.to_excel(path, index=False)
            return path
        except (ImportError, ModuleNotFoundError):
            path = path.with_suffix(".csv")
    df.to_csv(path, index=False)
    return path
