from lir_tpu.data.prompts import (
    LEGAL_PROMPTS,
    WORD_MEANING_QUESTIONS,
    QUESTION_TO_QUALTRICS,
    QUALTRICS_TO_QUESTION,
    FEW_SHOT_PREFIX,
    LegalPrompt,
    format_base_prompt,
    format_instruct_prompt,
    rephrase_request,
)

__all__ = [
    "LEGAL_PROMPTS",
    "WORD_MEANING_QUESTIONS",
    "QUESTION_TO_QUALTRICS",
    "QUALTRICS_TO_QUESTION",
    "FEW_SHOT_PREFIX",
    "LegalPrompt",
    "format_base_prompt",
    "format_instruct_prompt",
    "rephrase_request",
]
