"""Typed row schemas for every data artifact, with readers/writers.

The reference's inter-layer API is files with fixed column schemas (SURVEY.md
§1): D1 ``model_comparison_results.csv`` (writer
analysis/compare_base_vs_instruct.py:90-111,508-513), D2
``instruct_model_comparison_results.csv`` (compare_instruct_models.py:103-121),
D6 the 15-column perturbation Excel (perturb_prompts.py:964-1016), D5
``perturbations.json`` (perturb_prompts.py:847-869). Preserving these schemas
bit-for-bit is the parity contract; everything between producer and consumer is
re-designed.
"""

from __future__ import annotations

import dataclasses
import json
import math
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Tuple

import pandas as pd

# ---------------------------------------------------------------------------
# D1: model_comparison_results.csv (base vs instruct sweep)
# ---------------------------------------------------------------------------

MODEL_COMPARISON_COLUMNS = (
    "prompt",
    "model",
    "model_family",
    "base_or_instruct",
    "model_output",
    "yes_prob",
    "no_prob",
    "odds_ratio",
)

# D2: instruct_model_comparison_results.csv
INSTRUCT_COMPARISON_COLUMNS = (
    "prompt",
    "model",
    "model_family",
    "model_output",
    "yes_prob",
    "no_prob",
    "relative_prob",
)

# D6: perturbation results workbook, 15 columns (perturb_prompts.py:965-969)
PERTURBATION_COLUMNS = (
    "Model",
    "Original Main Part",
    "Response Format",
    "Confidence Format",
    "Rephrased Main Part",
    "Full Rephrased Prompt",
    "Full Confidence Prompt",
    "Model Response",
    "Model Confidence Response",
    "Log Probabilities",
    "Token_1_Prob",
    "Token_2_Prob",
    "Odds_Ratio",
    "Confidence Value",
    "Weighted Confidence",
)


def model_family(model_name: str) -> str:
    """Family tag parsed from an HF repo id (compare_base_vs_instruct.py:96)."""
    base = model_name.split("/")[-1]
    return base.split("-")[0].lower()


@dataclasses.dataclass
class ScoreRow:
    """One scored (model, prompt) measurement — the unified D1/D2 record.

    The reference drifts between ``odds_ratio`` (= yes/no,
    compare_base_vs_instruct.py:293) and ``relative_prob`` (= yes/(yes+no),
    compare_instruct_models.py:281); this record carries both readouts from one
    scoring primitive (SURVEY.md §1 seam note).
    """

    prompt: str
    model: str
    base_or_instruct: str          # "base" | "instruct"
    model_output: str
    yes_prob: float
    no_prob: float
    position_found: int = 0
    yes_no_found: bool = True

    @property
    def odds_ratio(self) -> float:
        # Reference semantics (compare_base_vs_instruct.py:293): inf whenever
        # no_prob is zero, even if yes_prob is also zero.
        return self.yes_prob / self.no_prob if self.no_prob > 0 else math.inf

    @property
    def relative_prob(self) -> float:
        denom = self.yes_prob + self.no_prob
        return self.yes_prob / denom if denom > 0 else float("nan")

    @property
    def model_family(self) -> str:
        return model_family(self.model)


def write_model_comparison_csv(rows: Sequence[ScoreRow], path: Path) -> pd.DataFrame:
    """D1 writer — schema parity with compare_base_vs_instruct.py:101-110."""
    df = pd.DataFrame(
        [
            {
                "prompt": r.prompt,
                "model": r.model,
                "model_family": r.model_family,
                "base_or_instruct": r.base_or_instruct,
                "model_output": r.model_output,
                "yes_prob": r.yes_prob,
                "no_prob": r.no_prob,
                "odds_ratio": r.odds_ratio,
            }
            for r in rows
        ],
        columns=list(MODEL_COMPARISON_COLUMNS),
    )
    path.parent.mkdir(parents=True, exist_ok=True)
    df.to_csv(path, index=False)
    return df


def write_instruct_comparison_csv(rows: Sequence[ScoreRow], path: Path) -> pd.DataFrame:
    """D2 writer — schema parity with compare_instruct_models.py:112-120."""
    df = pd.DataFrame(
        [
            {
                "prompt": r.prompt,
                "model": r.model,
                "model_family": r.model_family,
                "model_output": r.model_output,
                "yes_prob": r.yes_prob,
                "no_prob": r.no_prob,
                "relative_prob": r.relative_prob,
            }
            for r in rows
        ],
        columns=list(INSTRUCT_COMPARISON_COLUMNS),
    )
    path.parent.mkdir(parents=True, exist_ok=True)
    df.to_csv(path, index=False)
    return df


@dataclasses.dataclass
class PerturbationRow:
    """One perturbation-grid measurement — the D6 record."""

    model: str
    original_main: str
    response_format: str
    confidence_format: str
    rephrased_main: str
    full_rephrased_prompt: str
    full_confidence_prompt: str
    model_response: str
    model_confidence_response: str
    log_probabilities: str          # stringified token->logprob mapping
    token_1_prob: float
    token_2_prob: float
    confidence_value: Optional[float]
    weighted_confidence: Optional[float]

    @property
    def odds_ratio(self) -> float:
        # Quarantined rows (guard/numerics: error:numerics) carry no
        # token probabilities; their ratio is NaN, not a crash.
        if self.token_1_prob is None or self.token_2_prob is None:
            return math.nan
        if self.token_2_prob > 0:
            return self.token_1_prob / self.token_2_prob
        return math.inf

    def to_record(self) -> Dict[str, object]:
        return {
            "Model": self.model,
            "Original Main Part": self.original_main,
            "Response Format": self.response_format,
            "Confidence Format": self.confidence_format,
            "Rephrased Main Part": self.rephrased_main,
            "Full Rephrased Prompt": self.full_rephrased_prompt,
            "Full Confidence Prompt": self.full_confidence_prompt,
            "Model Response": self.model_response,
            "Model Confidence Response": self.model_confidence_response,
            "Log Probabilities": self.log_probabilities,
            "Token_1_Prob": self.token_1_prob,
            "Token_2_Prob": self.token_2_prob,
            "Odds_Ratio": self.odds_ratio,
            "Confidence Value": self.confidence_value,
            "Weighted Confidence": self.weighted_confidence,
        }


def perturbation_dataframe(rows: Sequence[PerturbationRow]) -> pd.DataFrame:
    return pd.DataFrame(
        [r.to_record() for r in rows], columns=list(PERTURBATION_COLUMNS)
    )


def write_perturbation_results(
    rows: Sequence[PerturbationRow], path: Path, append: bool = True
) -> pd.DataFrame:
    """D6 writer with the reference's append-with-schema-check semantics
    (perturb_prompts.py:987-1016): if an existing file's columns mismatch, the
    old file is backed up and a fresh one written, never silently merged.

    Returns the frame of the rows written by THIS call (read the file via
    read_results_frame for the accumulated artifact — the CSV checkpoint
    path appends without re-reading the whole file, so the combined frame
    is deliberately never materialized here)."""
    df = perturbation_dataframe(rows)
    path.parent.mkdir(parents=True, exist_ok=True)
    if path.suffix == ".xlsx" and not _xlsx_available():
        path = path.with_suffix(".csv")
    if append and path.exists() and path.suffix == ".csv":
        # CSV fast-append: O(new rows) per checkpoint instead of
        # read-whole + concat + rewrite (O(total) per flush, O(total^2)
        # over a sweep — at 20k grid cells the final flushes would cost
        # seconds each and throttle the writer thread). The schema check
        # reads only the HEADER line; a mismatch keeps the reference's
        # backup-and-fresh semantics.
        #
        # Torn-write recovery uses a KNOWN-GOOD-OFFSET sidecar, not a
        # newline heuristic: D6 fields legitimately contain newlines and
        # quotes, so a kill mid-write can leave the file ending in a
        # dangling open-quoted field whose last byte IS a newline —
        # undetectable from the bytes alone, and appending after it would
        # swallow the next rows into the open quote. Instead, every
        # successful write records the file size; on append, anything
        # past the recorded offset is a torn tail and is truncated away.
        # Dropping the fragment loses nothing: the write-ahead flush
        # order marks rows done only AFTER they are written, so torn rows
        # were never marked done and a resumed sweep re-scores them.
        try:
            existing_cols = list(pd.read_csv(path, nrows=0).columns)
        except Exception:
            existing_cols = None
        if existing_cols == list(df.columns):
            if _recover_known_good(path):
                with path.open("a", newline="") as f:
                    df.to_csv(f, index=False, header=False)
                    f.flush()
                _record_known_good(path)
                return df
            # Schema matches but the file cannot be certified for
            # appending (no sidecar and it does not parse — e.g. a
            # pre-sidecar artifact torn inside a quoted field). Fall
            # through to the read-based path: its corrupt-file fallback
            # PRESERVES the damaged main file and writes new rows to the
            # _new sidecar — never backup-and-fresh, which would drop
            # rows the manifest already marks done from the artifact.
        elif existing_cols is not None:
            backup = path.with_name(path.stem + "_backup" + path.suffix)
            path.rename(backup)
            _offset_sidecar(path).unlink(missing_ok=True)
            _write_frame(df, path)
            _record_known_good(path)
            return df
        # Unreadable header (or uncertifiable matching file): fall through
        # to the read-based path below.
    new_df = df
    if append and path.exists():
        read = pd.read_excel if path.suffix == ".xlsx" else pd.read_csv
        try:
            existing = read(path)
        except Exception:
            # Corrupt/truncated prior file (e.g. a kill mid-write): keep it in
            # place and save the fresh rows alongside, as the reference does
            # (perturb_prompts.py:1007-1011) — never lose computed results.
            # Later flushes in the same situation must APPEND to the side
            # file, not overwrite it (rows are already marked done upstream).
            new_path = path.with_name(path.stem + "_new" + path.suffix)
            if new_path.exists():
                try:
                    prev = (pd.read_excel if new_path.suffix == ".xlsx"
                            else pd.read_csv)(new_path)
                    if list(prev.columns) == list(df.columns):
                        df = pd.concat([prev, df], ignore_index=True)
                except Exception:
                    pass
            _write_frame(df, new_path)
            return new_df
        if list(existing.columns) == list(df.columns):
            df = pd.concat([existing, df], ignore_index=True)
        else:
            backup = path.with_name(path.stem + "_backup" + path.suffix)
            path.rename(backup)
    _write_frame(df, path)
    if path.suffix == ".csv":
        _record_known_good(path)
    return new_df


def _offset_sidecar(path: Path) -> Path:
    return path.with_name(path.name + ".offset")


def _record_known_good(path: Path) -> None:
    """Atomically record the artifact's current size as known-good (every
    byte up to it was written by a completed flush)."""
    import os

    side = _offset_sidecar(path)
    tmp = side.with_name(side.name + ".tmp")
    tmp.write_text(str(path.stat().st_size))
    os.replace(tmp, side)


def _recover_known_good(path: Path) -> bool:
    """Prepare ``path`` for a fast append: truncate any torn tail past the
    recorded known-good offset. Returns False when the artifact cannot be
    trusted for appending (no/invalid sidecar and the file does not parse
    cleanly) — the caller then uses the read-based legacy path.

    A legacy file without a sidecar is validated ONCE by a full parse
    (O(total), paid only on the first resume of a pre-sidecar artifact);
    every later flush is O(new rows)."""
    side = _offset_sidecar(path)
    try:
        known = int(side.read_text())
    except (OSError, ValueError):
        known = None
    size = path.stat().st_size
    if known is not None and 0 < known <= size:
        if size > known:
            with path.open("rb+") as f:
                f.truncate(known)
        return True
    # Legacy file: a torn PLAIN tail (no trailing newline) would survive a
    # pandas parse (short rows NaN-pad silently) and then poison the next
    # append — drop it before validating. A tail torn inside a QUOTED
    # field fails the parse below instead, and the caller routes to the
    # corrupt-file sidecar path.
    with path.open("rb") as f:
        end = f.seek(0, 2)
        last = b"\n"
        if end > 0:
            f.seek(end - 1)
            last = f.read(1)
    if last != b"\n":
        _truncate_after_last_newline(path)
    try:
        pd.read_csv(path)          # full one-time validation
    except Exception:
        return False
    _record_known_good(path)
    return True


def _truncate_after_last_newline(path: Path) -> None:
    """Drop a partial last line: scan backward in blocks for the final
    newline and truncate just after it (empty file if none)."""
    with path.open("rb+") as f:
        pos = f.seek(0, 2)
        block = 4096
        while pos > 0:
            start = max(0, pos - block)
            f.seek(start)
            chunk = f.read(pos - start)
            nl = chunk.rfind(b"\n")
            if nl >= 0:
                f.truncate(start + nl + 1)
                return
            pos = start
        f.truncate(0)


def _xlsx_available() -> bool:
    try:
        import openpyxl  # noqa: F401
        return True
    except ImportError:
        return False


def _write_frame(df: pd.DataFrame, path: Path) -> None:
    if path.suffix == ".xlsx" and _xlsx_available():
        df.to_excel(path, index=False)
    else:
        # Environment has no Excel engine: keep the 15-column schema but in
        # CSV next to the requested name (columns, not container, are the
        # D6 contract — SURVEY.md §2.4).
        df.to_csv(path.with_suffix(".csv") if path.suffix == ".xlsx" else path,
                  index=False)


def resolve_results_path(path: Path) -> Path:
    """The path _write_frame will actually use (xlsx -> csv fallback when no
    Excel engine exists). Resolve ONCE at sweep start so manifests, readers,
    and writers agree on the artifact name."""
    path = Path(path)
    if path.suffix == ".xlsx" and not _xlsx_available():
        return path.with_suffix(".csv")
    return path


def concat_host_shards(path: Path,
                       n_hosts: Optional[int] = None) -> Optional[pd.DataFrame]:
    """Merge per-host ``.hostN`` result shards + manifests into the final
    artifact at ``path`` — the TPU-pod replacement for the reference's
    "download each batch output file and append" gather step
    (perturb_prompts.py:161-188,975-984).

    ``n_hosts`` is the EXPECTED shard count (the sweep passes
    ``jax.process_count()``): exactly hosts ``0..n_hosts-1`` are merged,
    so stale ``.hostN`` files from an earlier, larger-pod run at the same
    path are never silently included, and if ANY expected shard is
    missing (a pod without a shared filesystem — each host sees only its
    own shard) the merge returns None instead of writing a
    complete-looking final artifact that holds 1/N of the rows; gather
    rows over the network instead (parallel.multihost.gather_rows).
    ``n_hosts=None`` discovers shards by walking host0, host1, ... until
    the first gap (single-process tooling/cleanup use).

    Shards are concatenated ROW-WISE in host order (the D6 schema has no
    cross-row state) after a column-schema check; the per-host manifests
    are unioned into ``{stem}.manifest.jsonl`` so a later single-process
    resume sees every completed cell. Per-host shard files and manifests
    are left in place — the per-HOST resume story keeps working.
    """
    path = resolve_results_path(Path(path))
    frames = []
    i = 0
    while n_hosts is None or i < n_hosts:
        shard = path.with_name(f"{path.stem}.host{i}{path.suffix}")
        if not shard.exists():
            if n_hosts is not None:
                return None     # expected shard invisible: no shared fs
            break
        df = read_results_frame(shard)
        if frames and list(df.columns) != list(frames[0].columns):
            raise ValueError(
                f"host shard {shard} column schema differs from host0 — "
                f"refusing to merge mismatched artifacts")
        frames.append(df)
        i += 1
    n_hosts = i
    if not frames:
        return None
    merged = pd.concat(frames, ignore_index=True)
    _write_frame(merged, path)
    if path.suffix == ".csv":
        # The merged artifact supersedes any earlier flush history; the
        # known-good offset must follow it or a later append would
        # truncate the merge away.
        _record_known_good(path)
    # Union the per-host manifests (write-ahead order preserved: the merged
    # manifest only ever contains keys whose rows are already in a shard).
    man_path = path.with_suffix(".manifest.jsonl")
    lines = []
    for i in range(n_hosts):
        m = path.with_name(
            f"{path.stem}.host{i}{path.suffix}").with_suffix(
            ".manifest.jsonl")
        if m.exists():
            lines.append(m.read_text().rstrip("\n"))
    if lines:
        man_path.write_text("\n".join(l for l in lines if l) + "\n")
    return merged


def read_results_frame(path: Path) -> pd.DataFrame:
    """Read a results artifact written by _write_frame (xlsx or CSV fallback)."""
    path = Path(path)
    if path.suffix == ".xlsx":
        if path.exists() and _xlsx_available():
            return pd.read_excel(path)
        csv = path.with_suffix(".csv")
        if csv.exists():
            return pd.read_csv(csv)
        if path.exists():
            raise RuntimeError(
                f"{path} exists but no Excel engine (openpyxl) is available "
                f"and no CSV fallback was found at {csv}")
    return pd.read_csv(path)


# ---------------------------------------------------------------------------
# D5: perturbations.json cache
# ---------------------------------------------------------------------------


def save_perturbations(
    path: Path,
    entries: Sequence[Tuple[Tuple[str, str, Tuple[str, str], str], List[str]]],
) -> None:
    """Cache format parity with perturb_prompts.py:851-866."""
    payload = [
        {
            "original_main": parts[0],
            "response_format": parts[1],
            "target_tokens": list(parts[2]),
            "confidence_format": parts[3],
            "rephrasings": rephrasings,
        }
        for parts, rephrasings in entries
    ]
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(json.dumps(payload, ensure_ascii=False, indent=2))


def load_perturbations(
    path: Path,
) -> List[Tuple[Tuple[str, str, Tuple[str, str], str], List[str]]]:
    data = json.loads(path.read_text())
    return [
        (
            (
                item["original_main"],
                item["response_format"],
                tuple(item["target_tokens"]),
                item["confidence_format"],
            ),
            list(item["rephrasings"]),
        )
        for item in data
    ]


def validate_perturbation_cache(
    entries: Sequence[Tuple[Tuple[str, str, Tuple[str, str], str], List[str]]],
    prompts,
) -> bool:
    """Cache-consistency rule (perturb_prompts.py:757-772): entry count and
    every prompt tuple must match the in-code prompt list, else regenerate."""
    if len(entries) != len(prompts):
        return False
    for (loaded_parts, _), p in zip(entries, prompts):
        expected = (p.main, p.response_format, tuple(p.target_tokens), p.confidence_format)
        if tuple(loaded_parts) != expected:
            return False
    return True
