"""Reliability observatory + unified telemetry spine (ROADMAP item 5).

Three pieces, each usable alone, designed to compose:

- :mod:`~lir_tpu.observe.registry` — ONE MetricsRegistry every existing
  ``*Stats`` object (utils/profiling.py) registers into, with one
  canonical JSON snapshot schema. Exposed live through the serve
  ``{"op": "metrics"}`` JSONL endpoint and dumped per sweep; the
  ``metrics-drift`` lint pass (lir_tpu/lint/metricsdrift.py) proves
  statically that no public counter field can silently drop out of it.
- :mod:`~lir_tpu.observe.tracing` — per-request structured trace spans
  over the full serving lifecycle (admit → queue → batch-form →
  dispatch → readout → resolve, plus fleet weight-swap and stream-fold
  spans), correlated with device traces via
  ``jax.profiler.TraceAnnotation`` and exportable as Chrome/Perfetto
  trace JSON (``--trace-out``).
- :mod:`~lir_tpu.observe.drift` + :mod:`~lir_tpu.observe.sentinel` —
  the reliability observatory itself: a :class:`SentinelScheduler` on
  the fleet server re-scores a sentinel grid on interval and on weight-
  cache change, folds results into TIME-WINDOWED accumulator lattices
  (engine/stream_stats.WindowedStreamSink — PR 9's lattice with a time
  axis, idempotent fold + order-free merge preserved per window), and
  computes per-window κ/CI/mean drift on device with σ-threshold
  alerts, queryable through the serve ``stats`` endpoint. "Model X's
  agreement with the fleet dropped 3σ this week" becomes a query
  instead of a postmortem.
"""

from .drift import detect_drift, window_summary
from .registry import STATS_SCHEMA, MetricsRegistry, engine_registry
from .sentinel import SentinelScheduler
from .tracing import (TraceRecorder, add_span, get_recorder, set_recorder,
                      span)

__all__ = [
    "MetricsRegistry", "STATS_SCHEMA", "engine_registry",
    "TraceRecorder", "span", "add_span", "set_recorder", "get_recorder",
    "SentinelScheduler", "window_summary", "detect_drift",
]
