"""Per-window κ/CI/mean drift over the windowed accumulator lattice.

The observatory's question is never "what is κ" — PR 10 already answers
that per request — but "did κ MOVE": is this window's agreement,
per-model mean relative probability, or valid fraction outside what the
previous windows establish as normal. Three pieces:

- :func:`window_reduce` — ONE jitted device reduction over a window's
  live lattice (engine/stream_stats.WindowedStreamSink.device_acc):
  per-row (model) valid counts, means, and 2.5/97.5 percentiles, plus
  per-column (sentinel occurrence) contingency counts — the κ
  sufficient statistic. One ``device_get`` of a few small vectors per
  window finalize; the (R, C) lattice itself never crosses to the host
  on the drift path.
- :func:`window_summary` — the queryable per-window record: fleet κ
  through ``stats/streaming.kappa_from_counts`` (the SAME
  ``within_group_kappa`` code path every other κ in this framework
  runs, so per-window κ is bitwise what offline analysis computes on
  those decisions), per-model mean/CI/valid-fraction, and the raw
  (n_g, s_g) counts for re-derivation.
- :func:`detect_drift` — σ-threshold comparison of the newest window
  against the baseline of prior windows: |x − mean| > σ · max(std,
  floor) on fleet κ, per-model mean relative probability, and
  per-model valid fraction (a NaN-injected model shows up as a
  valid-fraction collapse, not a silent NaN mean). At most ONE alert
  per window, carrying every triggered metric — "model X dropped 3σ in
  window W" is one record, not a page of them.

Tuning: ``sigma`` trades sensitivity for false alarms (3σ default);
the floors put a minimum absolute width on the band so a baseline of
bitwise-identical clean windows (std = 0 — greedy decode is
deterministic) alerts on real movement, never on float dust
(DEPLOY.md §1l).
"""

from __future__ import annotations

import functools
import math
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

# Minimum band half-widths (the std floor inside sigma * max(std,
# floor)): deterministic clean baselines have std == 0.
KAPPA_FLOOR = 0.05
MEAN_FLOOR = 0.02
VALID_FLOOR = 0.05


@functools.partial(jax.jit)
def _reduce(filled, rel, conf, dec):
    """Device-side window reduction (no host values consumed; one
    fused program per lattice shape)."""
    present = filled > 0
    valid = present & (dec >= 0)
    n_folded_row = present.sum(axis=1)
    n_valid_row = valid.sum(axis=1)
    rel_ok = present & jnp.isfinite(rel)
    n_rel_row = rel_ok.sum(axis=1)
    rel0 = jnp.where(rel_ok, rel, 0.0)
    mean_rel_row = rel0.sum(axis=1) / jnp.maximum(n_rel_row, 1)
    conf_ok = present & jnp.isfinite(conf)
    conf0 = jnp.where(conf_ok, conf, 0.0)
    mean_conf_row = conf0.sum(axis=1) / jnp.maximum(conf_ok.sum(axis=1), 1)
    # Percentiles over the row's valid rel values: NaN-masked
    # nanpercentile (invalid cells are NaN in the lattice already;
    # unfilled cells are NaN too by construction).
    masked = jnp.where(rel_ok, rel, jnp.nan)
    pcts = jnp.nanpercentile(masked, jnp.asarray([2.5, 97.5]), axis=1)
    # Per-column contingency counts: each column is one scoring of one
    # sentinel occurrence across every model — the within-group κ
    # grouping ("do the fleet's models agree on this question").
    n_valid_col = valid.sum(axis=0)
    n_yes_col = ((dec == 1) & present).sum(axis=0)
    return {
        "n_folded_row": n_folded_row, "n_valid_row": n_valid_row,
        "n_rel_row": n_rel_row, "mean_rel_row": mean_rel_row,
        "mean_conf_row": mean_conf_row,
        "p2_5_row": pcts[0], "p97_5_row": pcts[1],
        "n_valid_col": n_valid_col, "n_yes_col": n_yes_col,
    }


def window_reduce(acc: Dict[str, jax.Array]) -> Dict[str, np.ndarray]:
    """Reduce one window's LIVE device lattice; returns small host
    vectors (the one sanctioned transfer on the drift path)."""
    out = _reduce(acc["filled"], acc["rel"], acc["conf"], acc["dec"])
    return {k: np.asarray(v) for k, v in jax.device_get(out).items()}


def window_summary(reduced: Dict[str, np.ndarray],
                   model_ids: Sequence[str], window_id: int,
                   window_s: Optional[float] = None,
                   sweeps: int = 0) -> Dict[str, object]:
    """The per-window history record served by the stats endpoint."""
    from ..stats import streaming

    used = reduced["n_valid_col"] > 0
    n_g = reduced["n_valid_col"][used].astype(np.int64)
    s_g = reduced["n_yes_col"][used].astype(np.int64)
    if n_g.size:
        kap = streaming.kappa_from_counts(n_g, s_g)
    else:
        kap = {"kappa": float("nan"),
               "observed_agreement": float("nan"),
               "expected_agreement": float("nan")}
    per_model: Dict[str, object] = {}
    for i, mid in enumerate(model_ids):
        n_folded = int(reduced["n_folded_row"][i])
        n_valid = int(reduced["n_valid_row"][i])
        n_rel = int(reduced["n_rel_row"][i])
        entry: Dict[str, object] = {
            "n_folded": n_folded,
            "n_valid": n_valid,
            "valid_frac": (n_valid / n_folded) if n_folded else
                          float("nan"),
            "mean_relative_prob": (float(reduced["mean_rel_row"][i])
                                   if n_rel else float("nan")),
            "mean_weighted_confidence": (
                float(reduced["mean_conf_row"][i]) if n_folded else
                float("nan")),
            "p2_5": float(reduced["p2_5_row"][i]),
            "p97_5": float(reduced["p97_5_row"][i]),
        }
        entry["ci95_width"] = (entry["p97_5"] - entry["p2_5"]
                               if math.isfinite(entry["p2_5"])
                               and math.isfinite(entry["p97_5"])
                               else float("nan"))
        per_model[mid] = entry
    out: Dict[str, object] = {
        "window": int(window_id),
        "sweeps": int(sweeps),
        "rows_folded": int(reduced["n_folded_row"].sum()),
        "kappa": {k: float(v) for k, v in kap.items()},
        "per_model": per_model,
        "counts": {"n_g": n_g.tolist(), "s_g": s_g.tolist()},
    }
    if window_s is not None:
        out["t_start_s"] = int(window_id) * float(window_s)
    return out


def _metric_drift(name: str, value: float, baseline: List[float],
                  sigma: float, floor: float,
                  model: Optional[str] = None) -> Optional[Dict]:
    base = [b for b in baseline if b is not None and math.isfinite(b)]
    if not base:
        return None
    mean = float(np.mean(base))
    std = float(np.std(base))
    if value is None or not math.isfinite(value):
        # A metric that WAS finite across the baseline going NaN is
        # itself drift (every sentinel row for a model quarantined).
        return {"metric": name, "model": model, "value": None,
                "baseline_mean": mean, "baseline_std": std,
                "z": None, "reason": "metric became undefined"}
    band = sigma * max(std, floor)
    if abs(value - mean) <= band:
        return None
    z = abs(value - mean) / max(std, floor)
    return {"metric": name, "model": model, "value": float(value),
            "baseline_mean": mean, "baseline_std": std,
            "z": round(z, 3),
            "reason": f"|{value:.4f} - {mean:.4f}| > "
                      f"{sigma:g} * max(std={std:.4f}, floor={floor:g})"}


def detect_drift(history: List[Dict], entry: Dict, sigma: float = 3.0,
                 min_baseline: int = 2,
                 kappa_floor: float = KAPPA_FLOOR,
                 mean_floor: float = MEAN_FLOOR,
                 valid_floor: float = VALID_FLOOR) -> Optional[Dict]:
    """Compare one finalized window against the baseline of prior
    windows; returns ONE alert record (or None). ``history`` holds
    prior :func:`window_summary` records in window order — entries
    already flagged drifted are EXCLUDED from the baseline so a real
    regression does not normalize itself into the band over time."""
    baseline = [h for h in history if not h.get("drifted")]
    if len(baseline) < max(int(min_baseline), 1):
        return None
    triggered: List[Dict] = []
    hit = _metric_drift(
        "kappa", entry["kappa"]["kappa"],
        [h["kappa"]["kappa"] for h in baseline], sigma, kappa_floor)
    if hit:
        triggered.append(hit)
    for mid in entry.get("per_model", {}):
        cur = entry["per_model"][mid]
        base = [h["per_model"].get(mid) for h in baseline]
        base = [b for b in base if b is not None]
        for metric, key, floor in (
                ("mean_relative_prob", "mean_relative_prob", mean_floor),
                ("valid_frac", "valid_frac", valid_floor)):
            hit = _metric_drift(metric, cur.get(key),
                                [b.get(key) for b in base], sigma,
                                floor, model=mid)
            if hit:
                triggered.append(hit)
    if not triggered:
        return None
    return {
        "window": entry["window"],
        "sigma": float(sigma),
        "n_baseline_windows": len(baseline),
        "metrics": triggered,
    }
