"""SentinelScheduler: scheduled reliability re-scoring on the fleet
server.

The paper's three axes are one-shot runs; production wants them as a
monitored time series. The scheduler owns that loop: a configured
SENTINEL GRID (a small fixed set of probe questions) is re-scored
across every fleet model on an interval — and immediately whenever the
weight cache's resident set changes, because a re-streamed or newly
loaded model is exactly when silent drift would enter — and each
sweep's per-model decisions fold into the current time window's
accumulator lattice (engine/stream_stats.WindowedStreamSink: rows =
models, cols = sweep-slot x sentinel). When the clock crosses a window
boundary the closed window finalizes: one on-device reduction
(observe/drift.window_reduce), a history record with fleet κ (bitwise
``within_group_kappa``) + per-model mean/CI/valid-fraction, and a
σ-threshold drift check against the clean-window baseline
(observe/drift.detect_drift). History and alerts are queryable through
the serve ``stats`` endpoint while the server keeps serving — the
observatory is a WORKLOAD on the fleet server, not a separate process,
so sentinel traffic rides the same queues, batchers, guard boundary,
and swap accounting as client traffic (sustained mixed load by
construction).

Thread model: one daemon scheduler thread calls :meth:`tick`; tests
and the bench drive :meth:`tick` directly with an injected clock (the
server may keep its real clock — the scheduler only reads its own).
The weight-cache listener just sets an event; it never touches the
cache (it runs under the cache lock).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from ..config import ObserveConfig
from ..utils.logging import get_logger
from . import drift as drift_mod
from . import tracing

log = get_logger(__name__)


class _Slot:
    """Grid coordinates of one fold row (StreamSink.fold's cell
    contract: .prompt_idx / .rephrase_idx)."""

    __slots__ = ("prompt_idx", "rephrase_idx")

    def __init__(self, prompt_idx: int, rephrase_idx: int):
        self.prompt_idx = prompt_idx
        self.rephrase_idx = rephrase_idx


class SentinelScheduler:
    """Scheduled sentinel sweeps + windowed folding + drift alerts over
    one :class:`~lir_tpu.serve.server.FleetScoringServer`."""

    def __init__(self, server, sentinels: Sequence,
                 cfg: Optional[ObserveConfig] = None,
                 clock: Callable[[], float] = time.monotonic,
                 registry=None, result_timeout_s: float = 60.0):
        assert sentinels, "the sentinel grid must not be empty"
        self.server = server
        self.sentinels = list(sentinels)
        self.cfg = cfg or ObserveConfig()
        self.clock = clock
        self.registry = registry
        self.result_timeout_s = float(result_timeout_s)
        self.model_ids: List[str] = list(server.model_ids)
        self._model_idx = {m: i for i, m in enumerate(self.model_ids)}
        from ..engine import stream_stats as stream_mod

        n_cols = len(self.sentinels) * self.cfg.max_sweeps_per_window
        self.windows = stream_mod.WindowedStreamSink(
            n_rows=len(self.model_ids), n_cols=n_cols,
            guard=True, max_windows=self.cfg.history_windows)
        self._lock = threading.Lock()
        self._history: List[Dict] = []   # guarded-by: _lock
        self._alerts: List[Dict] = []    # guarded-by: _lock
        self._sweeps_in_window: Dict[int, int] = {}
        self._finalized: set = set()
        self._last_sweep_t: Optional[float] = None
        self._total_sweeps = 0
        self._skipped_full = 0
        # Breaker gating (the elastic-router satellite): while the
        # server's fronting CircuitBreaker is OPEN — a replica failing
        # over, not a model drifting — sentinel sweeps PAUSE (their
        # rows would be sheds/errors, and a capacity loss must not
        # alert as model drift), and the first tick after recovery
        # forces an immediate re-score so the post-failover window has
        # fresh data. The breaker is read via ``server.breaker``
        # (ScoringServer's own, or the router-side replica breaker the
        # ReplicaRouter assigns onto a fleet server); None = ungated.
        self._paused_breaker = False
        self._skipped_breaker = 0
        self._forced = threading.Event()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # Residency-change trigger: a model streamed in or evicted is
        # exactly when drift would enter — re-score immediately.
        cache = getattr(getattr(server, "fleet", None), "cache", None)
        if cache is not None and hasattr(cache, "add_listener"):
            cache.add_listener(self._on_weight_event)

    # -- triggers ------------------------------------------------------------

    def _on_weight_event(self, event: str, model_id: str) -> None:
        # Runs under the weight cache's lock: set-and-return only.
        self._forced.set()

    def force(self) -> None:
        """Request an immediate sweep at the next tick."""
        self._forced.set()

    def window_id(self, now: Optional[float] = None) -> int:
        t = self.clock() if now is None else now
        return int(t // self.cfg.sentinel_window_s)

    def due(self, now: Optional[float] = None) -> bool:
        t = self.clock() if now is None else now
        if self._forced.is_set() or self._last_sweep_t is None:
            return True
        return t - self._last_sweep_t >= self.cfg.sentinel_interval_s

    # -- the sweep -----------------------------------------------------------

    def _breaker_open(self) -> bool:
        breaker = getattr(self.server, "breaker", None)
        if breaker is None:
            return False
        try:
            return not breaker.allow()
        except Exception:  # noqa: BLE001 — an odd breaker never
            # silences the observatory
            return False

    def tick(self, now: Optional[float] = None) -> Optional[Dict]:
        """One scheduler step: finalize any windows the clock has
        closed, then sweep if due — unless the server's breaker is
        OPEN (failover in progress: pause rather than alert on
        capacity loss as drift; the first tick after recovery
        re-scores immediately). Returns the sweep record (or None when
        nothing was due / sweeps are paused)."""
        t = self.clock() if now is None else now
        self.finalize_closed(t)
        if self._breaker_open():
            if self.due(t):
                self._skipped_breaker += 1
                log.info("sentinel sweep paused: server breaker open "
                         "(failover window, not drift)")
            self._paused_breaker = True
            return None
        if self._paused_breaker:
            # Recovery: re-score NOW — the post-failover window needs
            # fresh sentinel data regardless of the interval.
            self._paused_breaker = False
            self._forced.set()
        if not self.due(t):
            return None
        self._forced.clear()
        return self.sweep(t)

    def sweep(self, now: Optional[float] = None) -> Optional[Dict]:
        """Score the whole sentinel grid across the fleet ONCE and fold
        the per-model results into the current window's lattice."""
        t = self.clock() if now is None else now
        wid = self.window_id(t)
        slot = self._sweeps_in_window.get(wid, 0)
        if slot >= self.cfg.max_sweeps_per_window:
            self._skipped_full += 1
            log.warning("sentinel sweep skipped: window %d already holds"
                        " %d sweeps (max_sweeps_per_window)", wid, slot)
            return None
        self._last_sweep_t = t
        self._sweeps_in_window[wid] = slot + 1
        self._total_sweeps += 1
        with tracing.span("sentinel/sweep", window=wid, slot=slot):
            futures = [
                self.server.submit_fleet(self._request(q, wid, slot, j))
                for j, q in enumerate(self.sentinels)]
            results = [f.result(self.result_timeout_s) for f in futures]
        self._fold(wid, slot, results)
        if self.registry is not None:
            self.registry.counter("sentinel_sweeps")
            self.registry.counter(
                "sentinel_rows", len(self.sentinels) * len(self.model_ids))
            self.registry.gauge("observatory_window", wid)
        return {"window": wid, "slot": slot,
                "results": [r["per_model"] for r in results]}

    def _request(self, sentinel, wid: int, slot: int, j: int):
        from ..serve.queue import ServeRequest

        if isinstance(sentinel, ServeRequest):
            import dataclasses

            return dataclasses.replace(
                sentinel,
                request_id=f"sentinel:{wid}:{slot}:{j}")
        raise TypeError(f"sentinel {j} is not a ServeRequest: "
                        f"{type(sentinel).__name__}")

    def _fold(self, wid: int, slot: int, results: List[Dict]) -> None:
        """One fused fold of the sweep's fleet decisions into the
        window lattice. Invalid per-model rows (quarantined, errored,
        missing probs) fold as NaN and are excluded by the device guard
        — exactly how the single-window sink treats them."""
        import jax.numpy as jnp

        n_m, n_s = len(self.model_ids), len(self.sentinels)
        B = n_m * n_s
        yes = np.full(B, np.nan, np.float32)
        no = np.full(B, np.nan, np.float32)
        wconf = np.full(B, np.nan, np.float32)
        cells: List[_Slot] = []
        k = 0
        for j, res in enumerate(results):
            per_model = res.get("per_model", {})
            for mid in self.model_ids:
                row = per_model.get(mid, {})
                if row.get("status") == "ok":
                    t1, t2 = row.get("token_1_prob"), row.get(
                        "token_2_prob")
                    wc = row.get("weighted_confidence")
                    if t1 is not None and t2 is not None:
                        yes[k], no[k] = t1, t2
                    if wc is not None:
                        wconf[k] = wc
                cells.append(_Slot(self._model_idx[mid],
                                   slot * n_s + j))
                k += 1
        lp = np.zeros((B, 1), np.float32)   # no top-K map for sentinels
        self.windows.fold(wid, jnp.asarray(yes), jnp.asarray(no),
                          jnp.asarray(wconf), jnp.asarray(lp), cells,
                          topk=1)

    # -- window finalize + drift ---------------------------------------------

    def finalize_closed(self, now: Optional[float] = None) -> List[Dict]:
        """Finalize every folded window strictly OLDER than the current
        one: device reduce → history record → drift check. Idempotent —
        already-finalized windows are skipped."""
        t = self.clock() if now is None else now
        current = self.window_id(t)
        out = []
        for wid in self.windows.window_ids():
            if wid >= current or wid in self._finalized:
                continue
            out.append(self._finalize(wid))
        return out

    def finalize_all(self) -> List[Dict]:
        """Finalize everything folded (shutdown / end-of-run path)."""
        return [self._finalize(wid)
                for wid in self.windows.window_ids()
                if wid not in self._finalized]

    def _finalize(self, wid: int) -> Dict:
        reduced = drift_mod.window_reduce(self.windows.device_acc(wid))
        entry = drift_mod.window_summary(
            reduced, self.model_ids, wid,
            window_s=self.cfg.sentinel_window_s,
            sweeps=self._sweeps_in_window.get(wid, 0))
        with self._lock:
            alert = drift_mod.detect_drift(
                self._history, entry, sigma=self.cfg.drift_sigma,
                min_baseline=self.cfg.drift_min_windows)
            if alert is not None:
                entry["drifted"] = True
                self._alerts.append(alert)
            self._history.append(entry)
        self._finalized.add(wid)
        if alert is not None:
            if self.registry is not None:
                self.registry.counter("drift_alerts")
            log.warning("DRIFT ALERT window %d: %s", wid,
                        [f"{m['metric']}"
                         + (f"[{m['model']}]" if m.get("model") else "")
                         for m in alert["metrics"]])
        return entry

    # -- queries (the stats endpoint) ----------------------------------------

    def history(self) -> List[Dict]:
        with self._lock:
            return list(self._history)

    def alerts(self) -> List[Dict]:
        with self._lock:
            return list(self._alerts)

    def summary(self) -> Dict[str, object]:
        """The observatory block of the serve ``stats`` endpoint."""
        with self._lock:
            history = list(self._history)
            alerts = list(self._alerts)
        return {
            "models": list(self.model_ids),
            "n_sentinels": len(self.sentinels),
            "interval_s": self.cfg.sentinel_interval_s,
            "window_s": self.cfg.sentinel_window_s,
            "sigma": self.cfg.drift_sigma,
            "sweeps": self._total_sweeps,
            "sweeps_skipped_window_full": self._skipped_full,
            "sweeps_skipped_breaker_open": self._skipped_breaker,
            "open_windows": [w for w in self.windows.window_ids()
                             if w not in self._finalized],
            "windows": history,
            "alerts": alerts,
        }

    # -- the scheduler thread ------------------------------------------------

    def start(self) -> "SentinelScheduler":
        assert self._thread is None, "scheduler already started"
        self._thread = threading.Thread(target=self._loop,
                                        name="sentinel-scheduler",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self, timeout: Optional[float] = None,
             finalize: bool = True) -> None:
        self._stop.set()
        self._forced.set()       # wake the loop promptly
        if self._thread is not None:
            self._thread.join(timeout)
            self._thread = None
        if finalize:
            self.finalize_all()

    def _loop(self) -> None:
        poll = min(max(self.cfg.sentinel_interval_s / 4, 0.05), 1.0)
        while not self._stop.is_set():
            self._forced.wait(poll)
            if self._stop.is_set():
                return
            try:
                self.tick()
            except Exception:  # noqa: BLE001 — the observatory must
                # never take the serving loop down with it
                log.exception("sentinel sweep failed; continuing")
