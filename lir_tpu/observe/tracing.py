"""Per-request structured trace spans + Chrome/Perfetto export.

Before this module the codebase had exactly one ``jax.profiler``
annotation (a per-model wrapper in engine/multi.py) and no host-side
span record at all: a slow request could not be decomposed into queue
wait vs batch formation vs device time after the fact. This is the
one tracing seam every layer now threads through:

- :func:`span` — context manager recording a completed host span into
  the process recorder AND wrapping ``jax.profiler.TraceAnnotation``,
  so the same names show up inside captured device traces
  (TensorBoard/Perfetto) for correlation. With no recorder installed
  the cost is one TraceAnnotation (nanoseconds when no profiler is
  active) — hot paths keep their spans unconditionally.
- :func:`add_span` — record a span from explicit begin/end timestamps
  (``time.monotonic`` domain — the serve clock), for spans whose start
  predates the code that observes them (queue wait: submit → dispatch).
- :class:`TraceRecorder` — bounded ring of span events (oldest dropped,
  drops counted) with :meth:`~TraceRecorder.export_chrome` producing
  the Chrome trace-event JSON (``{"traceEvents": [...]}``) that
  chrome://tracing and Perfetto load directly; ``--trace-out`` on the
  serve/perturb CLIs writes it at exit.

Span naming convention: ``layer/stage`` (``serve/dispatch``,
``sweep/drain``, ``fleet/weight_swap``, ``weights/stream``,
``stream/fold``) with request/model identity in ``args`` — the
lifecycle of one request is the filter ``args.request_id == X``.
"""

from __future__ import annotations

import contextlib
import json
import threading
import time
from collections import deque
from pathlib import Path
from typing import Dict, Iterator, List, Optional

DEFAULT_CAPACITY = 65536


class TraceRecorder:
    """Bounded in-memory span ring. Thread-safe — every serving and
    sweep thread appends concurrently; export snapshots under the lock.

    Timestamps are ``time.monotonic`` seconds (the serve clock domain);
    export rebases them onto the recorder's construction time so traces
    start near zero.
    """

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        self.capacity = max(int(capacity), 1)
        self._lock = threading.Lock()
        self._events: deque = deque(maxlen=self.capacity)  # guarded-by: _lock
        self._dropped = 0  # guarded-by: _lock
        self._t0 = time.monotonic()

    def add(self, name: str, t0: float, t1: float, cat: str = "host",
            args: Optional[Dict] = None) -> None:
        ev = {"name": str(name), "cat": str(cat), "t0": float(t0),
              "t1": float(t1),
              "thread": threading.current_thread().name}
        if args:
            ev["args"] = args
        with self._lock:
            if len(self._events) == self.capacity:
                self._dropped += 1
            self._events.append(ev)

    def __len__(self) -> int:
        with self._lock:
            return len(self._events)

    @property
    def dropped(self) -> int:
        with self._lock:
            return self._dropped

    def events(self) -> List[Dict]:
        with self._lock:
            return list(self._events)

    def clear(self) -> None:
        with self._lock:
            self._events.clear()
            self._dropped = 0

    def summary(self) -> Dict[str, object]:
        """Registry-facing counters (the recorder is itself a metrics
        source: span volume and ring pressure are operator signals)."""
        with self._lock:
            n = len(self._events)
            names: Dict[str, int] = {}
            for ev in self._events:
                names[ev["name"]] = names.get(ev["name"], 0) + 1
            return {"spans": n, "dropped": self._dropped,
                    "capacity": self.capacity,
                    "per_name": dict(sorted(names.items()))}

    # -- Chrome trace-event export -------------------------------------------

    def export_chrome(self, path: Optional[Path] = None) -> Dict:
        """The Chrome trace-event JSON (``ph: "X"`` complete events, µs
        timestamps, one tid per recording thread with ``thread_name``
        metadata). Loads directly in chrome://tracing and Perfetto;
        device traces captured with ``jax.profiler`` carry the SAME
        span names via TraceAnnotation, so host and device views line
        up by name."""
        events = self.events()
        tids: Dict[str, int] = {}
        trace_events: List[Dict] = []
        for ev in events:
            tid = tids.setdefault(ev["thread"], len(tids) + 1)
            rec = {
                "name": ev["name"], "cat": ev["cat"], "ph": "X",
                "ts": (ev["t0"] - self._t0) * 1e6,
                "dur": max(ev["t1"] - ev["t0"], 0.0) * 1e6,
                "pid": 1, "tid": tid,
            }
            if "args" in ev:
                rec["args"] = ev["args"]
            trace_events.append(rec)
        for name, tid in tids.items():
            trace_events.append({
                "name": "thread_name", "ph": "M", "pid": 1, "tid": tid,
                "args": {"name": name}})
        out = {"traceEvents": trace_events, "displayTimeUnit": "ms",
               "otherData": {"dropped_spans": self.dropped}}
        if path is not None:
            Path(path).write_text(json.dumps(out), encoding="utf-8")
        return out


# Process-wide recorder. None (the default) keeps spans at
# TraceAnnotation-only cost; the CLI installs one under --trace-out,
# the bench's observatory mode and tests install their own.
_RECORDER: Optional[TraceRecorder] = None


def set_recorder(rec: Optional[TraceRecorder]) -> Optional[TraceRecorder]:
    """Install (or clear, with None) the process recorder; returns the
    previous one so tests can restore it."""
    global _RECORDER
    prev, _RECORDER = _RECORDER, rec
    return prev


def get_recorder() -> Optional[TraceRecorder]:
    return _RECORDER


@contextlib.contextmanager
def span(name: str, cat: str = "host", **args) -> Iterator[None]:
    """Named span around a block: recorded host-side when a recorder is
    installed, and ALWAYS annotated into device traces
    (``jax.profiler.TraceAnnotation`` — effectively free when no device
    profiler is capturing)."""
    import jax

    rec = _RECORDER
    with jax.profiler.TraceAnnotation(name):
        if rec is None:
            yield
            return
        t0 = time.monotonic()
        try:
            yield
        finally:
            rec.add(name, t0, time.monotonic(), cat, args or None)


def add_span(name: str, t0: float, t1: float, cat: str = "host",
             **args) -> None:
    """Record a completed span from explicit ``time.monotonic``
    begin/end stamps (queue-wait spans start at submit time, long
    before the dispatch path observes them). No-op without a
    recorder."""
    rec = _RECORDER
    if rec is not None:
        rec.add(name, t0, t1, cat, args or None)
