"""MetricsRegistry: one registry, one canonical snapshot schema.

utils/profiling.py grew eleven disconnected ``*Stats`` objects across
ten PRs — each correct alone, none queryable together: no common
snapshot, no single endpoint, and a new counter was visible only if
someone remembered to log it. This module is the one place runtime
telemetry converges:

- every ``*Stats`` instance registers under a stable source name;
- :meth:`MetricsRegistry.snapshot` produces ONE canonical JSON-safe
  document: per source, the raw public fields declared in
  :data:`STATS_SCHEMA` plus the object's derived ``summary()`` dict,
  plus native registry counters/gauges and the per-device HBM gauges
  (``utils/profiling.device_memory_stats`` — WeightCache budget
  pressure is visible BEFORE ``WeightCacheOOM`` fires);
- the serve ``{"op": "metrics"}`` JSONL endpoint returns it live, the
  sweep dumps it per run, and the CLI logs it at serve exit.

:data:`STATS_SCHEMA` is the snapshot schema contract: a pure literal
mapping every registered ``*Stats`` class to the tuple of public fields
its snapshot carries. The ``metrics-drift`` lint pass
(lir_tpu/lint/metricsdrift.py) parses this literal and the profiling
dataclasses statically, so a PR that adds a counter field without
adding it here fails lint — a counter can never silently drop out of
the endpoint again.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Dict, Optional, Tuple

SNAPSHOT_VERSION = 1

# The snapshot schema contract (parsed by lint/metricsdrift.py — keep
# this a PURE literal: string keys, tuples of string field names).
# Every public field of every *Stats dataclass in utils/profiling.py
# must appear in its class's tuple.
STATS_SCHEMA: Dict[str, Tuple[str, ...]] = {
    "OccupancyStats": (
        "buckets", "grouped_cells", "grouped_prefill_rows",
        "decode_steps_live", "decode_steps_paid",
    ),
    "CompileStats": (
        "shapes", "aot_hits", "lazy_misses", "persistent_requests",
        "persistent_hits", "cold_start_s", "warm_start_s",
    ),
    "KernelStats": ("phases", "counters"),
    "ServeStats": (
        "submitted", "admitted", "shed", "completed", "expired",
        "errors", "late", "dedup_hits", "dedup_misses", "dispatches",
        "slots_used", "slots_paid", "promoted", "queue_depth_peak",
    ),
    "FaultStats": (
        "injected", "recovered_dispatches", "degraded_dispatches",
        "degraded_rows", "preemptions", "breaker_opens",
        "breaker_probes", "breaker_closes", "transitions",
    ),
    "GuardStats": (
        "watched", "stalls", "checked", "quarantined", "reasons",
        "stall_dumps", "inflight_cancelled", "barrier_timeouts",
        "heartbeats",
    ),
    "PrefixCacheStats": (
        "lookups", "hits", "hit_tokens", "prefill_tokens_total",
        "inserted_pages", "evicted_pages", "pages_in_use",
        "pages_total",
    ),
    "FleetStats": (
        "swap_s_hidden", "swap_s_exposed", "loads", "load_s",
        "weight_bytes_streamed", "prefetch_hits", "prefetch_misses",
        "cache_hits", "evictions", "resident_models", "resident_bytes",
        "model_swaps", "fleet_requests", "fleet_rows",
    ),
    "StreamStats": (
        "rows_folded", "dispatch_folds", "host_bytes_avoided",
        "accum_bytes", "checkpoints", "merges", "live_queries",
        "finalize_s",
    ),
    "RouterStats": (
        "routed", "routed_resident", "dedup_hits", "completed",
        "errors", "failovers", "re_admitted", "hedged", "hedge_wins",
        "hedge_losses", "zombie_payloads", "replica_errors",
        "replica_sheds", "no_replica_sheds", "kills", "revives",
        "per_replica",
    ),
    "MigrationStats": (
        "migrations", "prefill_ops", "pages_migrated", "bytes_streamed",
        "chunks_streamed", "migration_s_exposed", "migration_s_hidden",
        "refetch_fallbacks", "stalls", "corrupt_chunks",
        "cluster_tree_hits",
    ),
    "TierStats": (
        "demotions", "promotions", "pages_demoted", "pages_promoted",
        "bytes_spilled", "bytes_promoted", "restart_pages_reseeded",
        "restart_weights_reseeded", "checksum_refusals", "disk_stalls",
        "pin_refusals", "host_bytes", "disk_bytes",
    ),
    "LeaseStats": (
        "claims", "renews", "releases", "steals", "refused", "lost",
        "expired_seen", "shards_done", "refreshes",
    ),
    "SpecStats": (
        "drafted_tokens", "accepted_tokens", "rejected_tokens",
        "draft_tree", "draft_ngram", "draft_fleet", "accepted_tree",
        "accepted_ngram", "accepted_fleet", "decode_forwards",
        "seq_forwards", "dispatches_saved", "spec_dispatches",
        "spec_rows", "fallbacks",
    ),
    "CascadeStats": (
        "cascade_dispatches", "dense_fallbacks", "trunk_rows_deduped",
        "prefix_flops_saved", "cascade_decode_dispatches",
        "trunk_bytes_deduped",
    ),
    "MemStats": (
        "ledger_bytes", "budget_bytes", "pressure", "rung",
        "rung_downs", "rung_ups", "admits", "denials", "oom_events",
        "oom_reclaims", "oom_exhausted", "squeezes", "sheds",
    ),
}


def _json_safe(value, depth: int = 0):
    """Best-effort JSON sanitization: numpy scalars -> python, dataclass
    -> dict, tuples -> lists, non-finite floats -> None (strict-JSON
    clients must not choke on a NaN gauge), unknown objects -> repr.
    Copies containers first so concurrent counter mutation during a
    snapshot can at worst yield a momentarily-stale value, never a
    corrupt document."""
    import math

    if depth > 8:
        return repr(value)
    if value is None or isinstance(value, (bool, int, str)):
        return value
    if isinstance(value, float):
        return value if math.isfinite(value) else None
    if dataclasses.is_dataclass(value) and not isinstance(value, type):
        return {f.name: _json_safe(getattr(value, f.name), depth + 1)
                for f in dataclasses.fields(value)
                if not f.name.startswith("_")}
    if isinstance(value, dict):
        try:
            items = list(value.items())
        except RuntimeError:        # resized mid-iteration; retry once
            items = list(dict(value).items())
        return {str(k): _json_safe(v, depth + 1) for k, v in items}
    if isinstance(value, (list, tuple, set, frozenset)):
        return [_json_safe(v, depth + 1) for v in list(value)]
    if hasattr(value, "item") and getattr(value, "ndim", None) == 0:
        return _json_safe(value.item(), depth + 1)   # numpy scalar
    if hasattr(value, "tolist"):
        return _json_safe(value.tolist(), depth + 1)  # numpy array
    return repr(value)


class MetricsRegistry:
    """Named metrics sources + native counters/gauges, one snapshot.

    Sources are the existing ``*Stats`` objects (anything with public
    fields and/or a ``summary()`` method registers as-is — no adapter
    classes); native counters/gauges cover telemetry that has no stats
    object of its own (sentinel sweeps run, alerts raised, endpoint
    polls). Thread-safe throughout: supervisors, writer threads, and
    endpoint readers all touch it concurrently.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._sources: Dict[str, object] = {}  # guarded-by: _lock
        self._counters: Dict[str, float] = {}  # guarded-by: _lock
        self._gauges: Dict[str, object] = {}   # guarded-by: _lock

    # -- registration --------------------------------------------------------

    def register(self, name: str, stats: object) -> object:
        """Register a stats source under a stable name. Re-registering
        a name replaces it (servers rebuild sinks across resume)."""
        with self._lock:
            self._sources[str(name)] = stats
        return stats

    def unregister(self, name: str) -> None:
        with self._lock:
            self._sources.pop(str(name), None)

    def sources(self) -> Dict[str, object]:
        with self._lock:
            return dict(self._sources)

    # -- native metrics ------------------------------------------------------

    def counter(self, name: str, n: float = 1) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + n

    def gauge(self, name: str, value) -> None:
        with self._lock:
            self._gauges[name] = value

    # -- the canonical snapshot ----------------------------------------------

    def snapshot(self, device_memory: bool = True) -> Dict[str, object]:
        """One JSON-safe document covering every registered source:

        ``sources.<name>.fields`` — the raw public fields declared in
        :data:`STATS_SCHEMA` for the source's class (unknown classes
        fall back to their public dataclass/attribute fields);
        ``sources.<name>.summary`` — the object's own derived
        ``summary()`` when it has one; plus native ``counters`` /
        ``gauges`` and the per-device ``device_memory`` HBM gauges.
        """
        with self._lock:
            sources = dict(self._sources)
            counters = dict(self._counters)
            gauges = dict(self._gauges)
        doc: Dict[str, object] = {
            "schema_version": SNAPSHOT_VERSION,
            "time_s": time.time(),
            "counters": _json_safe(counters),
            "gauges": _json_safe(gauges),
            "sources": {},
        }
        for name, obj in sources.items():
            cls = type(obj).__name__
            fields = STATS_SCHEMA.get(cls)
            if fields is None:
                if dataclasses.is_dataclass(obj):
                    fields = tuple(f.name for f in dataclasses.fields(obj)
                                   if not f.name.startswith("_"))
                else:
                    fields = tuple(k for k in vars(obj)
                                   if not k.startswith("_"))
            entry: Dict[str, object] = {
                "type": cls,
                "fields": {f: _json_safe(getattr(obj, f, None))
                           for f in fields},
            }
            summarize = getattr(obj, "summary", None)
            if callable(summarize):
                try:
                    entry["summary"] = _json_safe(summarize())
                except Exception as err:  # noqa: BLE001 — one broken
                    # source must not take the whole endpoint down
                    entry["summary_error"] = repr(err)
            doc["sources"][name] = entry
        if device_memory:
            from ..utils.profiling import device_memory_stats

            doc["device_memory"] = _json_safe(device_memory_stats())
        return doc


def engine_registry(engine, sink=None,
                    registry: Optional[MetricsRegistry] = None
                    ) -> MetricsRegistry:
    """Register one ScoringEngine's stats objects (the per-sweep dump
    and the single-model server both use this): guard, compile, fault,
    kernel, prefix, occupancy when set, and the streaming sink's
    counters when a sink is attached."""
    reg = registry if registry is not None else MetricsRegistry()
    reg.register("guard", engine.guard_stats)
    reg.register("compile", engine.compile_stats)
    reg.register("faults", engine.fault_stats)
    if getattr(engine, "kernel_stats", None) is not None:
        reg.register("kernel", engine.kernel_stats)
    if getattr(engine, "prefix_stats", None) is not None:
        reg.register("prefix_cache", engine.prefix_stats)
    if getattr(engine, "occupancy", None) is not None:
        reg.register("occupancy", engine.occupancy)
    if getattr(engine, "spec_stats", None) is not None:
        reg.register("spec", engine.spec_stats)
    if getattr(engine, "cascade_stats", None) is not None:
        reg.register("cascade", engine.cascade_stats)
    if getattr(engine, "governor", None) is not None:
        # HBM-governor gauges (engine/hbm.py): ledger/pressure/rung
        # land in the snapshot next to device_memory_stats(), so budget
        # pressure is visible BEFORE anything OOMs.
        reg.register("mem", engine.governor.stats)
    if sink is not None and getattr(sink, "stats", None) is not None:
        reg.register("stream", sink.stats)
    return reg
