"""Human-survey analysis subsystem (reference: survey_analysis/*, C31-C43).

One load/clean/match pass feeding vectorized JAX statistics kernels; every
reference artifact schema is reproduced by `lir_tpu.survey.run`.
"""

from .loader import (
    all_question_cols,
    apply_exclusions,
    canonical_question_mapping,
    extract_question_text,
    group_question_ids,
    load_survey,
    load_survey_detailed,
    match_survey_to_llm_questions,
    survey_detailed,
    write_survey_detailed,
)
from .consolidated import (
    consolidated_results_payload,
    cross_prompt_difference_ci,
    format_report,
    human_cross_prompt_correlations,
    human_llm_correlation,
    human_responses_by_question,
    llm_cross_prompt_correlations,
    llm_responses_by_question,
    meta_correlation,
    run_consolidated_analysis,
    save_consolidated_results,
)
from .human_llm import (
    agreement_metrics,
    analyze_all_models,
    bootstrap_agreement_metrics,
    bootstrap_all_models,
    bootstrap_results_payload,
    difference_stats,
    human_averages_from_detailed,
    matched_pairs_analysis,
    relative_prob_series,
    write_agreement_analysis,
    write_bootstrap_results,
)
from .simulated import (
    individual_correlations,
    model_group_tensors,
    run_simulated_bootstrap,
    write_simulated_bootstrap,
)
from .family_differences import (
    analyze_family_differences,
    write_family_differences,
)
from .pvalues import (
    compare_correlation_distributions,
    human_correlations_with_pvalues,
    llm_correlations_with_pvalues,
    pearson_pvalues,
    run_pvalue_analysis,
    write_pvalue_analysis,
)
from .proportions import (
    run_proportion_analysis,
    write_proportion_analysis,
)
from .run import run_survey_pipeline
