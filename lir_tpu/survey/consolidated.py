"""Consolidated human-vs-LLM survey analysis (C31-C37).

Parity target: survey_analysis/survey_analysis_consolidated.py:128-990 —
per-question stats, human-LLM correlation with bootstrap CI, per-item
pairwise agreement, within-group cross-prompt rank-consistency correlations
with question-resampled bootstrap, the human-LLM difference CI, the
meta-correlation, the ~100-line stdout report, and the
``consolidated_analysis_results.json`` (D8) dump.

TPU-native redesign: the reference's hottest loop rebuilds a pandas
correlation matrix inside three nested Python loops (group x bootstrap x
respondent-pair; :352-703). Here each group's respondent matrix is resampled
once as a (n_boot, n_questions) index tensor and all bootstrap correlation
matrices are computed by a single vmapped masked-Pearson kernel; pair values
reduce to (sum, count) on device, so a 1000-iteration joint difference CI is
five kernel launches instead of ~10^7 scipy calls.
"""

from __future__ import annotations

import functools
import json
from pathlib import Path
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
import pandas as pd

from ..stats.bootstrap import bootstrap_correlation
from ..stats.core import resample_indices
from ..stats.correlations import masked_pearson_matrix
from ..stats.agreement import per_item_agreement
from .loader import GROUPS, group_question_ids


# ---------------------------------------------------------------------------
# Per-question response statistics (:128-160)
# ---------------------------------------------------------------------------


def human_responses_by_question(
    clean_df: pd.DataFrame, question_cols: List[str]
) -> Dict[str, Dict[str, object]]:
    stats: Dict[str, Dict[str, object]] = {}
    for q in question_cols:
        if q.endswith("_8"):
            continue
        responses = clean_df[q].dropna()
        if len(responses) > 0:
            stats[q] = {
                "mean": float(responses.mean()),
                "std": float(responses.std(ddof=0)),
                "n": int(len(responses)),
                "responses": responses.tolist(),
            }
    return stats


def llm_responses_by_question(llm_df: pd.DataFrame) -> Dict[str, Dict[str, object]]:
    stats: Dict[str, Dict[str, object]] = {}
    for prompt in llm_df["prompt"].unique():
        rel = llm_df.loc[llm_df["prompt"] == prompt, "relative_prob"]
        stats[prompt] = {
            "mean": float(rel.mean()),
            "std": float(rel.std(ddof=0)),
            "n": int(len(rel)),
            "model_responses": rel.tolist(),
        }
    return stats


def human_llm_correlation(
    human_stats, llm_stats, matches: Dict[str, str], key: jax.Array,
    n_bootstrap: int = 1000,
) -> Optional[Dict[str, object]]:
    """Pearson between per-question human means (0-1) and LLM mean relative
    probabilities, with percentile-bootstrap CI (:202-232)."""
    human_means, llm_means, matched = [], [], []
    for llm_prompt, survey_q in matches.items():
        if survey_q in human_stats and llm_prompt in llm_stats:
            h = human_stats[survey_q]["mean"] / 100.0
            m = llm_stats[llm_prompt]["mean"]
            human_means.append(h)
            llm_means.append(m)
            matched.append(
                {
                    "survey_question": survey_q,
                    "llm_prompt": llm_prompt,
                    "human_mean": h,
                    "llm_mean": m,
                }
            )
    if len(human_means) < 2:
        return None
    res = bootstrap_correlation(
        np.asarray(human_means), np.asarray(llm_means), key, n_boot=n_bootstrap
    )
    out = res.as_dict()
    out["n_questions"] = len(human_means)
    out["matched_questions"] = matched
    return out


# ---------------------------------------------------------------------------
# Cross-prompt (rank-consistency) correlations (:352-703)
# ---------------------------------------------------------------------------

MIN_ANSWERED = 5  # respondent must answer >= 5 of a group's questions (:382)


def _human_group_matrix(
    clean_df: pd.DataFrame, group: int
) -> Optional[np.ndarray]:
    """(n_respondents, 10) matrix of /100-scaled slider values for everyone
    who answered this group (gate: Q{g}_1 non-null, :363)."""
    gq = group_question_ids(group)
    respondents = clean_df[clean_df[f"Q{group}_1"].notna()]
    if len(respondents) < 2:
        return None
    return respondents[gq].to_numpy(dtype=float) / 100.0


def _llm_group_pivot(
    llm_df: pd.DataFrame, matches: Dict[str, str], group: int
) -> Optional[np.ndarray]:
    """(n_prompts, n_models) pivot of relative_prob for this group's matched
    prompts (:505-510)."""
    prompts = [
        p for p, q in matches.items() if int(q.split("_")[0][1:]) == group
    ]
    if len(prompts) < 2:
        return None
    data = llm_df[llm_df["prompt"].isin(prompts)]
    pivot = data.pivot_table(index="prompt", columns="model", values="relative_prob")
    if len(pivot) < 2:
        return None
    return pivot.to_numpy(dtype=float)


def _rater_pair_values(matrix: np.ndarray, min_answered: int = 0) -> np.ndarray:
    """Finite upper-triangle pairwise-complete correlations between raters.

    `matrix` is (items, raters) oriented as rows=raters for humans, so
    callers pass respondents-as-rows and we transpose internally; for the
    LLM pivot rows are already items.
    """
    x = np.asarray(matrix, dtype=float)
    if min_answered:
        valid = np.isfinite(x).sum(axis=1) >= min_answered
        x = np.where(valid[:, None], x, np.nan)
        corr = np.asarray(masked_pearson_matrix(jnp.asarray(x.T)))
    else:
        corr = np.asarray(masked_pearson_matrix(jnp.asarray(x)))
    iu = np.triu_indices(corr.shape[0], k=1)
    vals = corr[iu]
    return vals[np.isfinite(vals)]


@functools.partial(jax.jit, static_argnames=("min_answered",))
def _boot_pair_sums(x: jnp.ndarray, idx: jnp.ndarray, min_answered: int):
    """For each resample row of `idx` (question indices with replacement):
    correlation between raters over the sampled items, reduced to
    (sum of finite pair correlations, count). `x` is (raters, items)."""

    def one(ix):
        xs = x[:, ix]
        if min_answered:
            valid = jnp.isfinite(xs).sum(axis=1) >= min_answered
            xs = jnp.where(valid[:, None], xs, jnp.nan)
        corr = masked_pearson_matrix(xs.T)
        iu = jnp.triu_indices(xs.shape[0], k=1)
        vals = corr[iu]
        finite = jnp.isfinite(vals)
        return jnp.where(finite, vals, 0.0).sum(), finite.sum()

    return jax.vmap(one)(idx)


def _bootstrap_group_means(
    matrices: List[Optional[np.ndarray]],
    key: jax.Array,
    n_boot: int,
    min_answered: int,
) -> np.ndarray:
    """Per-iteration mean of the pooled (across groups) pair correlations —
    the quantity whose percentiles form the reference's CI (:417-470)."""
    sums = np.zeros(n_boot)
    counts = np.zeros(n_boot)
    for matrix in matrices:
        if matrix is None:
            continue
        key, sub = jax.random.split(key)
        idx = resample_indices(sub, n_boot, matrix.shape[1])
        s, c = _boot_pair_sums(jnp.asarray(matrix), idx, min_answered)
        sums += np.asarray(s)
        counts += np.asarray(c)
    with np.errstate(invalid="ignore"):
        return np.where(counts > 0, sums / counts, np.nan)


def human_cross_prompt_correlations(
    clean_df: pd.DataFrame, key: jax.Array, n_bootstrap: int = 100
) -> Dict[str, object]:
    """Within-group respondent-respondent correlations (:352-480)."""
    group_results: Dict[str, object] = {}
    all_corrs: List[float] = []
    matrices: List[Optional[np.ndarray]] = []
    for group in GROUPS:
        m = _human_group_matrix(clean_df, group)
        if m is None:
            matrices.append(None)
            continue
        vals = _rater_pair_values(m, min_answered=MIN_ANSWERED)
        n_valid = int((np.isfinite(m).sum(axis=1) >= MIN_ANSWERED).sum())
        if n_valid < 2:
            matrices.append(None)
            continue
        matrices.append(m)
        all_corrs.extend(vals.tolist())
        group_results[f"Group_{group}"] = {
            "n_respondents": n_valid,
            "n_pairs": int(vals.size),
            "mean_correlation": float(vals.mean()) if vals.size else 0.0,
            "correlations": vals.tolist(),
        }

    boot_means = _bootstrap_group_means(matrices, key, n_bootstrap, MIN_ANSWERED)
    finite = boot_means[np.isfinite(boot_means)]
    base_mean = float(np.mean(all_corrs)) if all_corrs else 0.0
    return {
        "group_results": group_results,
        "pairwise_correlations": all_corrs,
        "mean_correlation": base_mean,
        "std_correlation": float(np.std(all_corrs)) if all_corrs else 0.0,
        "n_pairs": len(all_corrs),
        "ci_lower": float(np.percentile(finite, 2.5)) if finite.size else base_mean,
        "ci_upper": float(np.percentile(finite, 97.5)) if finite.size else base_mean,
    }


def llm_cross_prompt_correlations(
    llm_df: pd.DataFrame,
    matches: Dict[str, str],
    key: jax.Array,
    n_bootstrap: int = 100,
) -> Dict[str, object]:
    """Within-group model-model correlations (:482-594). The rater axis is
    models; resampling is over the group's prompts."""
    group_results: Dict[str, object] = {}
    all_corrs: List[float] = []
    matrices: List[Optional[np.ndarray]] = []
    for group in GROUPS:
        pivot = _llm_group_pivot(llm_df, matches, group)
        if pivot is None:
            matrices.append(None)
            continue
        vals = _rater_pair_values(pivot)
        # Kernel orientation: (raters=models, items=prompts).
        matrices.append(pivot.T)
        all_corrs.extend(vals.tolist())
        group_results[f"Group_{group}"] = {
            "n_prompts": int(pivot.shape[0]),
            "n_models": int(pivot.shape[1]),
            "n_pairs": int(vals.size),
            "mean_correlation": float(vals.mean()) if vals.size else 0.0,
            "correlations": vals.tolist(),
        }

    boot_means = _bootstrap_group_means(matrices, key, n_bootstrap, 0)
    finite = boot_means[np.isfinite(boot_means)]
    base_mean = float(np.mean(all_corrs)) if all_corrs else 0.0
    return {
        "group_results": group_results,
        "pairwise_correlations": all_corrs,
        "mean_correlation": base_mean,
        "std_correlation": float(np.std(all_corrs)) if all_corrs else 0.0,
        "n_pairs": len(all_corrs),
        "ci_lower": float(np.percentile(finite, 2.5)) if finite.size else base_mean,
        "ci_upper": float(np.percentile(finite, 97.5)) if finite.size else base_mean,
    }


def cross_prompt_difference_ci(
    clean_df: pd.DataFrame,
    llm_df: pd.DataFrame,
    matches: Dict[str, str],
    key: jax.Array,
    n_bootstrap: int = 1000,
) -> Dict[str, object]:
    """Joint bootstrap of (human mean - LLM mean) cross-prompt correlation
    (:596-703) — both sides resampled independently inside each iteration."""
    human_mats = [_human_group_matrix(clean_df, g) for g in GROUPS]
    llm_mats = []
    for g in GROUPS:
        pivot = _llm_group_pivot(llm_df, matches, g)
        llm_mats.append(None if pivot is None else pivot.T)

    k_h, k_l = jax.random.split(key)
    h_means = _bootstrap_group_means(human_mats, k_h, n_bootstrap, MIN_ANSWERED)
    l_means = _bootstrap_group_means(llm_mats, k_l, n_bootstrap, 0)
    diffs = h_means - l_means
    diffs = diffs[np.isfinite(diffs)]
    if diffs.size == 0:
        return {
            "mean_difference": None,
            "ci_lower": None,
            "ci_upper": None,
            "n_bootstrap": 0,
        }
    return {
        "mean_difference": float(np.mean(diffs)),
        "ci_lower": float(np.percentile(diffs, 2.5)),
        "ci_upper": float(np.percentile(diffs, 97.5)),
        "n_bootstrap": int(diffs.size),
    }


# ---------------------------------------------------------------------------
# Meta-correlation (:705-748)
# ---------------------------------------------------------------------------


def meta_correlation(
    human_agreements, llm_agreements, matches: Dict[str, str], key: jax.Array,
    n_bootstrap: int = 1000,
) -> Dict[str, object]:
    """Correlation between per-item agreement patterns of humans and LLMs."""
    h_vals, l_vals = [], []
    for llm_prompt, survey_q in matches.items():
        if (
            survey_q in human_agreements["per_item"]
            and llm_prompt in llm_agreements["per_item"]
        ):
            h_vals.append(human_agreements["per_item"][survey_q]["mean_agreement"])
            l_vals.append(llm_agreements["per_item"][llm_prompt]["mean_agreement"])

    base = {
        "n_matched_items": len(h_vals),
        "human_mean_agreement": human_agreements["overall_mean"],
        "human_std_agreement": human_agreements["overall_std"],
        "llm_mean_agreement": llm_agreements["overall_mean"],
        "llm_std_agreement": llm_agreements["overall_std"],
    }
    if len(h_vals) < 2:
        return {
            "correlation": None,
            **base,
            "interpretation": "Insufficient matched items for correlation",
        }
    res = bootstrap_correlation(
        np.asarray(h_vals), np.asarray(l_vals), key, n_boot=n_bootstrap
    )
    return {
        "correlation": res.estimate,
        "p_value": res.p_value,
        "ci_lower": res.ci_lower,
        "ci_upper": res.ci_upper,
        **base,
        "interpretation": "Correlation between human and LLM per-item agreement patterns",
    }


# ---------------------------------------------------------------------------
# Orchestration + report + JSON (:750-990)
# ---------------------------------------------------------------------------


def _to_native(obj):
    if isinstance(obj, dict):
        return {k: _to_native(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_to_native(v) for v in obj]
    if isinstance(obj, (np.floating, np.integer)):
        return obj.item()
    if isinstance(obj, np.ndarray):
        return obj.tolist()
    if isinstance(obj, np.bool_):
        return bool(obj)
    return obj


def run_consolidated_analysis(
    clean_df: pd.DataFrame,
    question_cols: List[str],
    llm_df: pd.DataFrame,
    matches: Dict[str, str],
    exclusion_stats: Dict[str, float],
    key: jax.Array,
    n_bootstrap_standard: int = 1000,
    n_bootstrap_small: int = 100,
) -> Dict[str, object]:
    """The full consolidated pipeline (main(), :925-990), returning every
    intermediate block keyed as the reference's local variables."""
    keys = jax.random.split(key, 8)

    human_stats = human_responses_by_question(clean_df, question_cols)
    llm_stats = llm_responses_by_question(llm_df)
    human_llm_corr = human_llm_correlation(
        human_stats, llm_stats, matches, keys[0], n_bootstrap_standard
    )

    human_items = {
        q: np.asarray(clean_df[q].dropna(), dtype=float)
        for q in question_cols
        if not q.endswith("_8")
    }
    human_item_agreement = per_item_agreement(
        human_items, scale=100.0, key=keys[1], n_boot=n_bootstrap_standard,
        count_key="n_responses",
    )

    llm_items: Dict[str, np.ndarray] = {}
    models = llm_df["model"].unique()
    for prompt in llm_df["prompt"].unique():
        pdata = llm_df[llm_df["prompt"] == prompt]
        vals = []
        for model in models:
            probs = pdata.loc[pdata["model"] == model, "relative_prob"].values
            if len(probs) > 0 and not np.isnan(probs[0]):
                vals.append(float(probs[0]))
        llm_items[prompt] = np.asarray(vals)
    llm_item_agreement = per_item_agreement(
        llm_items, scale=1.0, key=keys[2], n_boot=n_bootstrap_standard,
        count_key="n_models",
    )

    human_cross = human_cross_prompt_correlations(
        clean_df, keys[3], n_bootstrap_small
    )
    llm_cross = llm_cross_prompt_correlations(
        llm_df, matches, keys[4], n_bootstrap_small
    )
    diff_ci = cross_prompt_difference_ci(
        clean_df, llm_df, matches, keys[5], n_bootstrap_standard
    )
    meta = meta_correlation(
        human_item_agreement, llm_item_agreement, matches, keys[6],
        n_bootstrap_standard,
    )

    return {
        "exclusion_stats": exclusion_stats,
        "human_stats": human_stats,
        "llm_stats": llm_stats,
        "matches": matches,
        "human_llm_correlation": human_llm_corr,
        "human_item_agreement": human_item_agreement,
        "llm_item_agreement": llm_item_agreement,
        "human_cross_prompt": human_cross,
        "llm_cross_prompt": llm_cross,
        "cross_prompt_difference": diff_ci,
        "meta_correlation": meta,
    }


def consolidated_results_payload(analysis: Dict[str, object]) -> Dict[str, object]:
    """The D8 ``consolidated_analysis_results.json`` schema (save_results,
    :857-918) built from `run_consolidated_analysis` output."""
    hc = analysis["human_llm_correlation"]
    hia = analysis["human_item_agreement"]
    lia = analysis["llm_item_agreement"]
    hcp = analysis["human_cross_prompt"]
    lcp = analysis["llm_cross_prompt"]
    dci = analysis["cross_prompt_difference"]
    meta = analysis["meta_correlation"]
    payload = {
        "exclusion_stats": analysis["exclusion_stats"],
        "matching_stats": {
            "n_human_questions": len(analysis["human_stats"]),
            "n_llm_prompts": len(analysis["llm_stats"]),
            "n_matched": len(analysis["matches"]),
            "matches": analysis["matches"],
        },
        "human_llm_correlation": {
            "correlation": hc["correlation"] if hc else None,
            "ci_lower": hc["ci_lower"] if hc else None,
            "ci_upper": hc["ci_upper"] if hc else None,
            "standard_error": hc["standard_error"] if hc else None,
            "p_value": hc["p_value"] if hc else None,
            "n_questions": hc["n_questions"] if hc else 0,
        },
        "per_item_agreement": {
            "human": {
                "overall_mean": hia["overall_mean"],
                "overall_mean_ci_lower": hia.get("overall_mean_ci_lower", 0),
                "overall_mean_ci_upper": hia.get("overall_mean_ci_upper", 0),
                "overall_std": hia["overall_std"],
                "n_items": hia["n_items"],
                "per_item_details": hia["per_item"],
            },
            "llm": {
                "overall_mean": lia["overall_mean"],
                "overall_mean_ci_lower": lia.get("overall_mean_ci_lower", 0),
                "overall_mean_ci_upper": lia.get("overall_mean_ci_upper", 0),
                "overall_std": lia["overall_std"],
                "n_items": lia["n_items"],
                "per_item_details": lia["per_item"],
            },
        },
        "meta_correlation": meta if meta else {},
        "cross_prompt_correlations": {
            "human": {
                "mean_correlation": hcp["mean_correlation"] if hcp else None,
                "ci_lower": hcp["ci_lower"] if hcp else None,
                "ci_upper": hcp["ci_upper"] if hcp else None,
                "std_correlation": hcp["std_correlation"] if hcp else None,
                "n_pairs": hcp["n_pairs"] if hcp else None,
            },
            "llm": {
                "mean_correlation": lcp["mean_correlation"] if lcp else None,
                "ci_lower": lcp["ci_lower"] if lcp else None,
                "ci_upper": lcp["ci_upper"] if lcp else None,
                "std_correlation": lcp["std_correlation"] if lcp else None,
                "n_pairs": lcp["n_pairs"] if lcp else None,
            },
            "difference": {
                "mean_difference": dci["mean_difference"] if dci else None,
                "ci_lower": dci["ci_lower"] if dci else None,
                "ci_upper": dci["ci_upper"] if dci else None,
                "n_bootstrap": dci["n_bootstrap"] if dci else None,
            },
        },
    }
    return _to_native(payload)


def save_consolidated_results(analysis: Dict[str, object], path: Path) -> None:
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(json.dumps(consolidated_results_payload(analysis), indent=2))


def format_report(analysis: Dict[str, object]) -> str:
    """The comprehensive stdout report (generate_comprehensive_report,
    :750-855), returned as a string so callers choose the sink."""
    ex = analysis["exclusion_stats"]
    hc = analysis["human_llm_correlation"]
    hia = analysis["human_item_agreement"]
    lia = analysis["llm_item_agreement"]
    hcp = analysis["human_cross_prompt"]
    lcp = analysis["llm_cross_prompt"]
    dci = analysis["cross_prompt_difference"]
    meta = analysis["meta_correlation"]

    lines = []
    bar = "=" * 80
    sub = "-" * 80
    lines += [
        "",
        bar,
        "CONSOLIDATED SURVEY ANALYSIS - HUMAN vs LLM ORDINARY MEANING AGREEMENT",
        bar,
        "",
        "EXCLUSION STATISTICS:",
        f"  Initial respondents: {ex['final_count'] + ex['total_excluded']}",
        f"  Excluded for short duration: {ex['duration_excluded']}",
        f"  Excluded for identical responses: {ex['identical_excluded']}",
        f"  Excluded for attention check failure: {ex['attention_failed']}",
        f"  Total excluded: {ex['total_excluded']}",
        f"  Final sample size: {ex['final_count']}",
        "",
        sub,
        "QUESTION MATCHING:",
        f"  Total survey questions: {len(analysis['human_stats'])}",
        f"  Total LLM prompts: {len(analysis['llm_stats'])}",
        f"  Successfully matched: {len(analysis['matches'])}",
        "",
        sub,
        "HUMAN-LLM CORRELATION (Question-Level Agreement):",
    ]
    if hc:
        lines += [
            f"  Pearson correlation: {hc['correlation']:.3f}",
            f"  95% CI: [{hc['ci_lower']:.3f}, {hc['ci_upper']:.3f}]",
            f"  Standard error: {hc['standard_error']:.3f}",
            f"  p-value: {hc['p_value']:.4f}",
            f"  Number of questions: {hc['n_questions']}",
        ]
    else:
        lines.append("  Insufficient matched questions for correlation")

    lines += [
        "",
        sub,
        "PER-ITEM AGREEMENT (Average agreement between raters for each item):",
        "",
        "  Human per-item agreement:",
        f"    Mean agreement across items: {hia['overall_mean']:.3f}",
        f"    95% CI: [{hia.get('overall_mean_ci_lower', 0):.3f}, "
        f"{hia.get('overall_mean_ci_upper', 0):.3f}]",
        f"    Std across items: {hia['overall_std']:.3f}",
        f"    Number of items: {hia['n_items']}",
        "",
        "  LLM per-item agreement:",
        f"    Mean agreement across items: {lia['overall_mean']:.3f}",
        f"    95% CI: [{lia.get('overall_mean_ci_lower', 0):.3f}, "
        f"{lia.get('overall_mean_ci_upper', 0):.3f}]",
        f"    Std across items: {lia['overall_std']:.3f}",
        f"    Number of items: {lia['n_items']}",
        "",
        sub,
        "CROSS-PROMPT CORRELATIONS (How similarly raters rank items):",
    ]
    if hcp:
        lines += [
            "",
            "  Human cross-prompt correlations (within groups):",
            f"    Mean correlation between respondent pairs: {hcp['mean_correlation']:.3f}",
            f"    95% CI: [{hcp['ci_lower']:.3f}, {hcp['ci_upper']:.3f}]",
            f"    Std of correlations: {hcp['std_correlation']:.3f}",
            f"    Number of respondent pairs: {hcp['n_pairs']}",
        ]
        for group, gstats in sorted(hcp["group_results"].items()):
            lines.append(
                f"    {group}: {gstats['n_respondents']} respondents, "
                f"mean corr = {gstats['mean_correlation']:.3f}"
            )
    if lcp:
        lines += [
            "",
            "  LLM cross-prompt correlations (within groups):",
            f"    Mean correlation between model pairs: {lcp['mean_correlation']:.3f}",
            f"    95% CI: [{lcp['ci_lower']:.3f}, {lcp['ci_upper']:.3f}]",
            f"    Std of correlations: {lcp['std_correlation']:.3f}",
            f"    Number of model pairs: {lcp['n_pairs']}",
        ]
        for group, gstats in sorted(lcp["group_results"].items()):
            lines.append(
                f"    {group}: {gstats['n_prompts']} prompts, "
                f"{gstats['n_models']} models, mean corr = "
                f"{gstats['mean_correlation']:.3f}"
            )
    if dci and dci["mean_difference"] is not None and hcp and lcp:
        lines += [
            "",
            "  Difference in cross-prompt correlations (Human - LLM):",
            f"    Mean difference: {dci['mean_difference']:.3f}",
            f"    95% CI: [{dci['ci_lower']:.3f}, {dci['ci_upper']:.3f}]",
            f"    Bootstrap iterations: {dci['n_bootstrap']}",
        ]

    lines += ["", sub, "META-CORRELATION (Agreement Pattern Comparison):"]
    if meta:
        if meta["correlation"] is not None:
            lines += [
                f"  Correlation between human and LLM per-item agreement "
                f"patterns: {meta['correlation']:.3f}",
                f"  95% CI: [{meta['ci_lower']:.3f}, {meta['ci_upper']:.3f}]",
                f"  p-value: {meta['p_value']:.4f}",
                f"  Number of matched items: {meta['n_matched_items']}",
            ]
        else:
            lines.append(f"  {meta['interpretation']}")
        lines += [
            "",
            f"  Human mean per-item agreement: {meta['human_mean_agreement']:.3f}",
            f"  LLM mean per-item agreement: {meta['llm_mean_agreement']:.3f}",
        ]

    lines += ["", sub, "INTERPRETATION:"]
    if hc:
        strength = (
            "strong"
            if abs(hc["correlation"]) > 0.7
            else "moderate"
            if abs(hc["correlation"]) > 0.4
            else "weak"
        )
        lines += [
            "",
            f"The correlation between average human and LLM responses is "
            f"{hc['correlation']:.3f},",
            f"indicating {strength} agreement",
            "between humans and LLMs on ordinary meaning judgments.",
        ]
    if meta:
        more = (
            "humans"
            if hia["overall_mean"] > lia["overall_mean"]
            else "LLMs"
        )
        lines += [
            "",
            "The per-item agreement patterns show that humans have",
            f"mean agreement of {hia['overall_mean']:.3f} compared to LLMs' "
            f"{lia['overall_mean']:.3f},",
            f"suggesting {more} are more consistent in their ordinary meaning "
            "judgments.",
        ]
    lines += ["", bar]
    return "\n".join(lines)
