"""Simulated-individual human-model correlation bootstrap (C38).

Parity target: survey_analysis/bootstrap_confidence_intervals.py:54-311 —
simulate individual humans from per-question (mean, std) as
clip(N(mu, sigma), 0, 1), correlate each simulated human with each model
over a random survey group, and bootstrap (10,000 iterations x 100 samples)
the base-vs-instruct mean correlation difference; plus per-model 1000-fold
CIs and six hard-coded family comparisons.

TPU-native redesign: the reference nests Python loops (bootstrap x sample x
question) around scipy.pearsonr — ~10^6 interpreter-level correlations per
model. Here all (n_iterations x n_samples) simulated humans for one model
are drawn as one (N, 10) tensor per sampled group, and the masked Pearson
against the model's group vector is a single vmapped kernel; the entire C38
analysis is a handful of XLA launches per model.

Sampling-validity semantics preserved exactly (:82-97): a (model, group)
pair contributes only when >= 8 of the group's questions are matched AND none
of the model's matched probabilities is NaN; otherwise every draw of that
group is rejected for that model, exactly as the reference's
``any(np.isnan(model_vals))`` rejection does.
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import pandas as pd

from ..stats.core import resample_indices
from .loader import GROUPS, group_question_ids


MIN_MATCHED_QUESTIONS = 8  # bootstrap_confidence_intervals.py:91


def model_group_tensors(
    model_df: pd.DataFrame,
    question_mapping: Dict[str, str],
    detailed: Dict[str, object],
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Per-group aligned tensors for one model.

    Returns (means, stds, model_vals, usable):
      means/stds: (5, 10) human per-question moments on the 0-1 scale
      model_vals: (5, 10) model relative probabilities (NaN where unmatched)
      usable:     (5,) bool — group passes the >=8-matched / no-NaN gate
    """
    by_q = detailed["results"]["by_question"]
    qid_to_prompt = {qid: p for p, qid in question_mapping.items()}
    rel_by_prompt: Dict[str, float] = {}
    for _, row in model_df.iterrows():
        if "relative_prob" in row.index:
            rel = row["relative_prob"]
        else:
            total = row["yes_prob"] + row["no_prob"]
            rel = row["yes_prob"] / total if total > 0 else float("nan")
        rel_by_prompt[row["prompt"]] = float(rel) if pd.notna(rel) else float("nan")

    n_g = len(GROUPS)
    means = np.full((n_g, 10), np.nan)
    stds = np.full((n_g, 10), np.nan)
    vals = np.full((n_g, 10), np.nan)
    matched = np.zeros((n_g, 10), dtype=bool)
    has_nan = np.zeros(n_g, dtype=bool)
    for gi, group in enumerate(GROUPS):
        for qi, qid in enumerate(group_question_ids(group)):
            prompt = qid_to_prompt.get(qid)
            if prompt is None or prompt not in rel_by_prompt or qid not in by_q:
                continue
            matched[gi, qi] = True
            means[gi, qi] = by_q[qid]["mean_response"] / 100.0
            stds[gi, qi] = by_q[qid]["std_response"] / 100.0
            v = rel_by_prompt[prompt]
            vals[gi, qi] = v
            if not np.isfinite(v):
                has_nan[gi] = True
    usable = (matched.sum(axis=1) >= MIN_MATCHED_QUESTIONS) & ~has_nan
    return means, stds, vals, usable


@jax.jit
def _simulated_correlations(key, means, stds, model_vals, usable):
    """(n_draws,) correlations between simulated humans and the model.

    Each draw: pick a uniform group, simulate clip(N(mean, std), 0, 1) per
    matched question, masked Pearson against the model's values. Draws whose
    group is unusable come back NaN (the caller drops them), mirroring the
    reference's rejected samples. `key` must be a batch of keys (one per
    draw); the draw count is the batch size.
    """

    def one(k):
        kg, kh = jax.random.split(k)
        g = jax.random.randint(kg, (), 0, means.shape[0])
        mu, sigma, mv = means[g], stds[g], model_vals[g]
        mask = jnp.isfinite(mv) & jnp.isfinite(mu)
        h = jnp.clip(mu + sigma * jax.random.normal(kh, mu.shape), 0.0, 1.0)
        mf = mask.astype(h.dtype)
        n = jnp.maximum(mf.sum(), 1.0)
        hm = (jnp.where(mask, h, 0.0)).sum() / n
        mm = (jnp.where(mask, mv, 0.0)).sum() / n
        dh = jnp.where(mask, h - hm, 0.0)
        dm = jnp.where(mask, mv - mm, 0.0)
        denom = jnp.sqrt((dh * dh).sum() * (dm * dm).sum())
        corr = jnp.where(denom > 0, (dh * dm).sum() / denom, jnp.nan)
        return jnp.where(usable[g], corr, jnp.nan)

    return jax.vmap(one)(key)


def individual_correlations(
    tensors: Dict[str, Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]],
    key: jax.Array,
    n_samples: int,
) -> Dict[str, np.ndarray]:
    """Per-model arrays of valid simulated-individual correlations
    (calculate_individual_correlations, :54-99)."""
    out: Dict[str, np.ndarray] = {}
    for model, (means, stds, vals, usable) in tensors.items():
        key, sub = jax.random.split(key)
        draws = _simulated_correlations(
            jax.random.split(sub, n_samples),
            jnp.asarray(means),
            jnp.asarray(stds),
            jnp.asarray(vals),
            jnp.asarray(usable),
        )
        arr = np.asarray(draws)
        out[model] = arr[np.isfinite(arr)]
    return out


def run_simulated_bootstrap(
    base_df: pd.DataFrame,
    question_mapping: Dict[str, str],
    detailed: Dict[str, object],
    key: jax.Array,
    n_base_samples: int = 500,
    n_bootstrap: int = 10_000,
    n_boot_samples: int = 100,
    n_per_model_boot: int = 1000,
    families: Optional[Dict[str, Dict[str, str]]] = None,
) -> Dict[str, object]:
    """The full C38 analysis. `base_df` is the D1 CSV (both base and
    instruct rows, distinguished by ``base_or_instruct``)."""
    model_types = {
        model: base_df.loc[base_df["model"] == model, "base_or_instruct"].iloc[0]
        for model in base_df["model"].unique()
    }
    tensors = {
        model: model_group_tensors(
            base_df[base_df["model"] == model], question_mapping, detailed
        )
        for model in base_df["model"].unique()
    }

    k_base, k_boot, k_model = jax.random.split(key, 3)

    # Base correlations (reference: n_samples=500, seed 42; :103).
    base_corrs = individual_correlations(tensors, k_base, n_base_samples)
    model_stats = {
        model: {
            "type": model_types[model],
            "mean_corr": float(np.mean(corrs)) if corrs.size else float("nan"),
            "std_corr": float(np.std(corrs)) if corrs.size else float("nan"),
            "n_correlations": int(corrs.size),
        }
        for model, corrs in base_corrs.items()
        if corrs.size
    }

    # Bootstrap: n_bootstrap iterations of fresh n_boot_samples draws per
    # model, pooled by type within each iteration (:126-148). All draws for
    # one model happen in a single kernel of n_bootstrap*n_boot_samples.
    sums = {"base": np.zeros(n_bootstrap), "instruct": np.zeros(n_bootstrap)}
    counts = {"base": np.zeros(n_bootstrap), "instruct": np.zeros(n_bootstrap)}
    for model, (means, stds, vals, usable) in tensors.items():
        k_boot, sub = jax.random.split(k_boot)
        draws = _simulated_correlations(
            jax.random.split(sub, n_bootstrap * n_boot_samples),
            jnp.asarray(means),
            jnp.asarray(stds),
            jnp.asarray(vals),
            jnp.asarray(usable),
        )
        arr = np.asarray(draws).reshape(n_bootstrap, n_boot_samples)
        finite = np.isfinite(arr)
        mtype = model_types[model]
        sums[mtype] += np.where(finite, arr, 0.0).sum(axis=1)
        counts[mtype] += finite.sum(axis=1)

    def _boot_means(mtype):
        c = counts[mtype]
        with np.errstate(invalid="ignore"):
            m = np.where(c > 0, sums[mtype] / c, np.nan)
        return m[np.isfinite(m)]

    base_means_boot = _boot_means("base")
    instruct_means_boot = _boot_means("instruct")

    def _pooled_mean(mtype):
        pooled = np.concatenate(
            [c for m, c in base_corrs.items() if model_types[m] == mtype]
            or [np.asarray([])]
        )
        return float(np.mean(pooled)) if pooled.size else float("nan")

    def _ci(samples):
        if len(samples) == 0:
            return (float("nan"), float("nan"))
        return (
            float(np.percentile(samples, 2.5)),
            float(np.percentile(samples, 97.5)),
        )

    base_mean = _pooled_mean("base")
    instruct_mean = _pooled_mean("instruct")
    base_ci = _ci(base_means_boot)
    instruct_ci = _ci(instruct_means_boot)

    n_common = min(len(base_means_boot), len(instruct_means_boot))
    diff_samples = base_means_boot[:n_common] - instruct_means_boot[:n_common]
    diff_ci = _ci(diff_samples)
    diff_mean = base_mean - instruct_mean

    # Per-model CIs: 1000 resamples of each model's base correlations (:211-230).
    per_model: Dict[str, Dict[str, object]] = {}
    for model, corrs in base_corrs.items():
        if corrs.size == 0:
            continue
        k_model, sub = jax.random.split(k_model)
        idx = np.asarray(resample_indices(sub, n_per_model_boot, corrs.size))
        boot_means = corrs[idx].mean(axis=1)
        lo, hi = _ci(boot_means)
        per_model[model] = {
            "type": model_types[model],
            "mean": model_stats[model]["mean_corr"],
            "ci_lower": lo,
            "ci_upper": hi,
        }

    families = families or DEFAULT_SIMULATED_FAMILIES
    family_rows = []
    for family, pair in families.items():
        b, i = pair.get("base"), pair.get("instruct")
        if b in per_model and i in per_model:
            bs, is_ = per_model[b], per_model[i]
            overlap = not (
                bs["ci_upper"] < is_["ci_lower"] or is_["ci_upper"] < bs["ci_lower"]
            )
            family_rows.append(
                {
                    "family": family,
                    "base_mean": bs["mean"],
                    "base_ci": [bs["ci_lower"], bs["ci_upper"]],
                    "instruct_mean": is_["mean"],
                    "instruct_ci": [is_["ci_lower"], is_["ci_upper"]],
                    "difference": bs["mean"] - is_["mean"],
                    "non_overlapping_ci": not overlap,
                }
            )

    return {
        "methodology": (
            "Bootstrap confidence intervals for individual human-model "
            "correlations"
        ),
        "n_bootstrap": n_bootstrap,
        "overall_results": {
            "base": {
                "mean": base_mean,
                "ci_lower": base_ci[0],
                "ci_upper": base_ci[1],
            },
            "instruct": {
                "mean": instruct_mean,
                "ci_lower": instruct_ci[0],
                "ci_upper": instruct_ci[1],
            },
            "difference": {
                "mean": diff_mean,
                "ci_lower": diff_ci[0],
                "ci_upper": diff_ci[1],
                "significant": bool(diff_ci[0] > 0 or diff_ci[1] < 0),
            },
        },
        "per_model_results": per_model,
        "family_comparisons": family_rows,
        "model_stats": model_stats,
    }


DEFAULT_SIMULATED_FAMILIES: Dict[str, Dict[str, str]] = {
    "t5": {"base": "google/t5-v1_1-base", "instruct": "google/flan-t5-base"},
    "falcon": {"base": "tiiuae/falcon-7b", "instruct": "tiiuae/falcon-7b-instruct"},
    "bloom": {"base": "bigscience/bloom-7b1", "instruct": "bigscience/bloomz-7b1"},
    "stablelm": {
        "base": "stabilityai/stablelm-base-alpha-7b",
        "instruct": "stabilityai/stablelm-tuned-alpha-7b",
    },
    "redpajama": {
        "base": "togethercomputer/RedPajama-INCITE-7B-Base",
        "instruct": "togethercomputer/RedPajama-INCITE-7B-Instruct",
    },
    "pythia": {"base": "EleutherAI/pythia-6.9b", "instruct": "databricks/dolly-v2-7b"},
}


def write_simulated_bootstrap(results: Dict[str, object], path: Path) -> None:
    """``bootstrap_confidence_intervals.json`` (:277-310)."""
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(json.dumps(results, indent=2))
