"""Model probabilities vs human yes-proportions + output-validity audit.

Parity target: survey_analysis/analyze_base_vs_instruct_vs_human.py:70-232 —
per-model Pearson/Spearman/MAE against the human ``proportion_yes`` from the
D7 detailed JSON, a Yes/No output-validity scan, and per-model probability
distribution statistics (with the same always-Yes / always-No warnings).
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Dict, List

import numpy as np
import pandas as pd
from scipy import stats as scipy_stats

from .human_llm import relative_prob_series


def human_proportions_from_detailed(
    detailed: Dict[str, object], question_mapping: Dict[str, str]
) -> Dict[str, float]:
    by_q = detailed["results"]["by_question"]
    return {
        prompt: by_q[qid]["proportion_yes"]
        for prompt, qid in question_mapping.items()
        if qid in by_q
    }


def model_vs_proportion_correlations(
    llm_df: pd.DataFrame,
    human_proportions: Dict[str, float],
    min_questions: int = 10,
) -> List[Dict[str, object]]:
    """Per-model agreement with human yes-proportions (:84-122), sorted by
    Pearson r descending."""
    rows = []
    df = llm_df.assign(_rel=relative_prob_series(llm_df))
    for model in df["model"].unique():
        mdata = df[df["model"] == model]
        h, m = [], []
        for _, row in mdata.iterrows():
            if row["prompt"] in human_proportions and pd.notna(row["_rel"]):
                h.append(human_proportions[row["prompt"]])
                m.append(float(row["_rel"]))
        if len(h) < min_questions:
            continue
        h_arr, m_arr = np.asarray(h), np.asarray(m)
        pr, pp = scipy_stats.pearsonr(h_arr, m_arr)
        sr, sp = scipy_stats.spearmanr(h_arr, m_arr)
        rows.append(
            {
                "model": model,
                "n_questions": len(h),
                "pearson_r": float(pr),
                "pearson_p": float(pp),
                "spearman_r": float(sr),
                "spearman_p": float(sp),
                "mae": float(np.mean(np.abs(h_arr - m_arr))),
            }
        )
    rows.sort(key=lambda r: -r["pearson_r"])
    return rows


def invalid_responses(llm_df: pd.DataFrame) -> List[Dict[str, str]]:
    """Outputs containing neither 'yes' nor 'no' (:130-141)."""
    out = []
    for _, row in llm_df.iterrows():
        text = str(row["model_output"]).lower()
        if "yes" not in text and "no" not in text:
            out.append(
                {
                    "model": row["model"],
                    "prompt": row["prompt"],
                    "output": row["model_output"],
                }
            )
    return out


def probability_distribution_stats(llm_df: pd.DataFrame) -> Dict[str, Dict[str, object]]:
    """Per-model relative-probability distribution summary with the
    bias warnings (:150-172)."""
    df = llm_df.assign(_rel=relative_prob_series(llm_df))
    out: Dict[str, Dict[str, object]] = {}
    for model in df["model"].unique():
        probs = df.loc[df["model"] == model, "_rel"].dropna()
        if len(probs) == 0:
            continue
        mean = float(probs.mean())
        warning = None
        if mean < 0.3:
            warning = "Model tends to answer 'No' (low mean probability)"
        elif mean > 0.7:
            warning = "Model tends to answer 'Yes' (high mean probability)"
        out[model] = {
            "mean": mean,
            "std": float(probs.std(ddof=0)),
            "min": float(probs.min()),
            "max": float(probs.max()),
            "warning": warning,
        }
    return out


def run_proportion_analysis(
    llm_df: pd.DataFrame,
    detailed: Dict[str, object],
    question_mapping: Dict[str, str],
) -> Dict[str, object]:
    props = human_proportions_from_detailed(detailed, question_mapping)
    return {
        "model_correlations": model_vs_proportion_correlations(llm_df, props),
        "invalid_responses": invalid_responses(llm_df),
        "probability_distributions": probability_distribution_stats(llm_df),
        "n_questions_with_human_data": len(props),
    }


def write_proportion_analysis(results: Dict[str, object], path: Path) -> None:
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(json.dumps(results, indent=2))
