"""Survey loading, cleaning, exclusion, and question matching (L2/L3 glue).

Parity targets in the reference:
  - load_and_clean_survey_data   survey_analysis/survey_analysis_consolidated.py:9-29
  - apply_exclusion_criteria     survey_analysis/survey_analysis_consolidated.py:36-85
  - extract_question_text        survey_analysis/survey_analysis_consolidated.py:87-103
  - match_survey_to_llm_questions survey_analysis/survey_analysis_consolidated.py:105-126

The reference applies the identical-slider and attention-check filters with
row-wise Python loops; here all three exclusion criteria are vectorized
column operations with byte-identical selection semantics (same ordering:
duration -> identical -> attention, each on the survivors of the previous).

This module also owns the D7 artifact ``survey_analysis_detailed.json``:
four survey scripts consume it (analyze_llm_human_agreement.py:15-16,
bootstrap_confidence_intervals.py:13-14, ...) but its producer is missing
from the reference tree (SURVEY.md §2.4 D7), so ``survey_detailed`` is the
in-tree replacement producer.
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Dict, List, Tuple

import numpy as np
import pandas as pd

from ..data.prompts import QUALTRICS_TO_QUESTION

# 5 groups x 11 sliders; column 8 is the attention check ("set slider to 100").
GROUPS = tuple(range(1, 6))


def group_question_ids(group: int) -> List[str]:
    """Substantive question columns of one survey group (attention Q*_8
    excluded) — the group structure shared by every survey script
    (e.g. bootstrap_confidence_intervals.py:46-52)."""
    return [f"Q{group}_{i}" for i in range(1, 12) if i != 8]


def all_question_cols(df: pd.DataFrame) -> List[str]:
    """Every Q{g}_{i} column present, attention checks included — the
    ``question_cols`` list of the reference loader."""
    cols = []
    for group in GROUPS:
        for question in range(1, 12):
            col = f"Q{group}_{question}"
            if col in df.columns:
                cols.append(col)
    return cols


def load_survey(path: Path) -> Tuple[pd.DataFrame, List[str]]:
    """Load the Qualtrics export, drop its two descriptive header rows, and
    numeric-coerce Duration plus every slider column."""
    df = pd.read_csv(path)
    df = df[2:].reset_index(drop=True)
    df["Duration (in seconds)"] = pd.to_numeric(
        df["Duration (in seconds)"], errors="coerce"
    )
    question_cols = all_question_cols(df)
    for col in question_cols:
        df[col] = pd.to_numeric(df[col], errors="coerce")
    return df, question_cols


def apply_exclusions(
    df: pd.DataFrame, question_cols: List[str]
) -> Tuple[pd.DataFrame, Dict[str, float]]:
    """Three exclusion criteria, applied in the reference's order.

    1. Duration < 20% of the (pre-filter) median completion time.
    2. All substantive sliders identical (attention Q*_8 not counted),
       among respondents who answered more than one substantive question.
    3. Any answered attention check != 100.
    """
    initial_count = len(df)
    stats: Dict[str, float] = {}

    duration = df["Duration (in seconds)"]
    median_duration = duration.median()
    min_duration = 0.2 * median_duration
    stats["duration_excluded"] = int((duration < min_duration).sum())
    stats["median_duration"] = float(median_duration)
    stats["min_duration_threshold"] = float(min_duration)
    df = df[duration >= min_duration]

    substantive = [c for c in question_cols if not c.endswith("_8")]
    vals = df[substantive]
    answered = vals.notna().sum(axis=1)
    # "All identical": nunique over answered sliders == 1, with > 1 answered.
    identical = (vals.nunique(axis=1, dropna=True) == 1) & (answered > 1)
    stats["identical_excluded"] = int(identical.sum())
    df = df[~identical]

    attention_cols = [f"Q{g}_8" for g in GROUPS if f"Q{g}_8" in df.columns]
    att = df[attention_cols]
    failed = (att.notna() & (att != 100)).any(axis=1)
    stats["attention_failed"] = int(failed.sum())
    df = df[~failed]

    stats["final_count"] = len(df)
    stats["total_excluded"] = initial_count - len(df)
    return df.reset_index(drop=True), stats


def extract_question_text(raw_path: Path) -> Dict[str, str]:
    """Column id -> question text, parsed from the Qualtrics header row
    (the text after the last " - " separator)."""
    df_raw = pd.read_csv(raw_path)
    headers = df_raw.iloc[0]
    mapping: Dict[str, str] = {}
    for col in df_raw.columns:
        if col.startswith("Q") and "_" in col:
            text = headers[col]
            if pd.notna(text) and isinstance(text, str) and " - " in text:
                mapping[col] = text.split(" - ")[-1].strip()
    return mapping


def match_survey_to_llm_questions(
    llm_df: pd.DataFrame, question_mapping: Dict[str, str]
) -> Dict[str, str]:
    """LLM prompt text -> Qualtrics question id, for prompts whose text
    matches a survey question exactly (attention checks excluded)."""
    prompt_to_question = {
        text: qid
        for qid, text in question_mapping.items()
        if not qid.endswith("_8")
    }
    return {
        prompt: prompt_to_question[prompt]
        for prompt in llm_df["prompt"].unique()
        if prompt in prompt_to_question
    }


def canonical_question_mapping() -> Dict[str, str]:
    """The static 50-question -> Qualtrics-id mapping (the dict copy-pasted
    across four reference survey scripts, e.g.
    analyze_llm_human_agreement.py:31-82) from the single prompt asset."""
    return {q: qid for qid, q in QUALTRICS_TO_QUESTION.items()}


def survey_detailed(
    clean_df: pd.DataFrame, question_cols: List[str]
) -> Dict[str, object]:
    """Produce the D7 ``survey_analysis_detailed.json`` payload.

    Schema (as consumed at analyze_llm_human_agreement.py:86-89 and
    bootstrap_confidence_intervals.py:82-89):
    ``results.by_question[Qx_y] = {mean_response, std_response,
    proportion_yes, n_responses}`` with mean/std on the 0-100 slider scale.
    ``proportion_yes`` is the fraction of respondents above the slider
    midpoint (> 50); the upstream producer is absent from the reference
    tree, so this definition is ours and is documented here.
    """
    by_question: Dict[str, Dict[str, float]] = {}
    for col in question_cols:
        if col.endswith("_8"):
            continue
        responses = clean_df[col].dropna().to_numpy(dtype=float)
        if responses.size == 0:
            continue
        by_question[col] = {
            "mean_response": float(np.mean(responses)),
            "std_response": float(np.std(responses)),
            "proportion_yes": float(np.mean(responses > 50.0)),
            "n_responses": int(responses.size),
        }
    return {"results": {"by_question": by_question}}


def write_survey_detailed(
    clean_df: pd.DataFrame, question_cols: List[str], path: Path
) -> Dict[str, object]:
    payload = survey_detailed(clean_df, question_cols)
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(json.dumps(payload, indent=2))
    return payload


def load_survey_detailed(path: Path) -> Dict[str, object]:
    return json.loads(Path(path).read_text())
