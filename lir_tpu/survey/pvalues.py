"""Correlation p-value suite: all-pairs LLM and human-rater correlations
with significance tests and distribution comparisons (C43).

Parity target: survey_analysis/calculate_correlation_pvalues.py:38-320 —
model-model Pearson+p over >10 common questions, rater-rater Pearson+p
within survey groups (>=3 common questions), and LLM-vs-human correlation
distribution comparison via Mann-Whitney U / KS / t-test / Cohen's d.

TPU-native redesign: the reference calls scipy.pearsonr inside an
O(raters^2) Python loop (~25k calls for ~100 raters x 5 groups). Here each
group's correlation matrix is one masked-Pearson kernel; p-values are then
computed in closed form from (r, n) exactly as pearsonr does
(t = r*sqrt((n-2)/(1-r^2)), two-sided t survival), vectorized over the
whole matrix.
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Dict, List

import jax.numpy as jnp
import numpy as np
import pandas as pd
from scipy import stats as scipy_stats

from ..stats.correlations import masked_pearson_matrix
from .loader import GROUPS, group_question_ids
from .human_llm import relative_prob_series


def pearson_pvalues(r: np.ndarray, n: np.ndarray) -> np.ndarray:
    """Two-sided p-values for Pearson r with n joint observations (the
    beta/t distribution used by scipy.stats.pearsonr)."""
    r = np.asarray(r, dtype=float)
    n = np.asarray(n, dtype=float)
    with np.errstate(divide="ignore", invalid="ignore"):
        t = r * np.sqrt((n - 2) / np.maximum(1e-300, 1 - r * r))
        p = 2 * scipy_stats.t.sf(np.abs(t), np.maximum(n - 2, 1))
    p = np.where(np.abs(r) >= 1.0, 0.0, p)
    return np.where(n > 2, p, np.nan)


def _joint_counts(x: np.ndarray) -> np.ndarray:
    m = np.isfinite(x).astype(float)
    return m.T @ m


def llm_correlations_with_pvalues(
    instruct_df: pd.DataFrame,
    base_df: pd.DataFrame,
    min_questions: int = 10,
) -> List[Dict[str, object]]:
    """All-pairs model-model correlations over common questions (:38-94).
    The reference requires strictly more than `min_questions` valid pairs.

    Defect fixed, not replicated: the reference concatenates the D1 and D2
    frames FIRST and then reads ``row['relative_prob']`` (:42,57-58), which
    is NaN for every D1 row after the concat — silently dropping all base
    models from an analysis that explicitly loads them. Here the readout is
    computed per-frame before concatenation, so base models participate via
    yes/(yes+no) as intended.
    """
    combined = pd.concat(
        [
            base_df.assign(_rel=relative_prob_series(base_df)),
            instruct_df.assign(_rel=relative_prob_series(instruct_df)),
        ],
        ignore_index=True,
    )
    # Models present in BOTH CSVs (e.g. Qwen-7B-Chat) have duplicate
    # (model, prompt) rows; the reference's dict build keeps the last one
    # (:55-65), so mirror that rather than pivot_table's mean-aggregation.
    combined = combined.drop_duplicates(subset=["model", "prompt"], keep="last")
    pivot = combined.pivot_table(index="prompt", columns="model", values="_rel")
    models = list(pivot.columns)
    x = pivot.to_numpy(dtype=float)

    corr = np.asarray(masked_pearson_matrix(jnp.asarray(x)))
    counts = _joint_counts(x)
    pvals = pearson_pvalues(corr, counts)

    out = []
    for i in range(len(models)):
        for j in range(i + 1, len(models)):
            n = int(counts[i, j])
            if n > min_questions:
                # Constant-input pairs keep their row with a NaN
                # correlation, exactly as the reference records them
                # (:83-92 appends pearsonr's NaN); every consumer filters
                # non-finite values (compare_correlation_distributions).
                finite = bool(np.isfinite(corr[i, j]))
                out.append(
                    {
                        "model1": models[i],
                        "model2": models[j],
                        "correlation": float(corr[i, j]),
                        "p_value": float(pvals[i, j]) if finite
                        else float("nan"),
                        "n_questions": n,
                        "significant": bool(finite and pvals[i, j] < 0.05),
                    }
                )
    return out


def apply_pvalue_exclusions(df: pd.DataFrame) -> pd.DataFrame:
    """The C43 script's own (lighter) exclusion pass (:217-227): duration
    < 20% of median, and answered attention checks != 100. No
    identical-slider filter."""
    duration = df["Duration (in seconds)"]
    df = df[duration >= 0.2 * duration.median()]
    for group in GROUPS:
        col = f"Q{group}_8"
        if col in df.columns:
            df = df[(df[col].isna()) | (df[col] == 100)]
    return df


def human_correlations_with_pvalues(
    clean_df: pd.DataFrame,
    min_questions: int = 3,
) -> List[Dict[str, object]]:
    """All-pairs rater-rater correlations within each group (:96-136)."""
    out = []
    for group in GROUPS:
        gq = group_question_ids(group)
        gdata = clean_df[clean_df[f"Q{group}_1"].notna()]
        if len(gdata) < 2:
            continue
        x = gdata[gq].to_numpy(dtype=float).T  # (questions, raters)
        corr = np.asarray(masked_pearson_matrix(jnp.asarray(x)))
        counts = _joint_counts(x)
        pvals = pearson_pvalues(corr, counts)
        n_r = x.shape[1]
        for i in range(n_r):
            for j in range(i + 1, n_r):
                n = int(counts[i, j])
                if n >= min_questions and np.isfinite(corr[i, j]):
                    out.append(
                        {
                            "group": group,
                            "rater1_idx": i,
                            "rater2_idx": j,
                            "correlation": float(corr[i, j]),
                            "p_value": float(pvals[i, j]),
                            "n_questions": n,
                            "significant": bool(pvals[i, j] < 0.05),
                        }
                    )
    return out


def compare_correlation_distributions(
    llm_correlations: List[Dict[str, object]],
    human_correlations: List[Dict[str, object]],
) -> Dict[str, object]:
    """LLM-vs-human correlation distribution tests (:138-204)."""
    llm_vals = np.asarray(
        [c["correlation"] for c in llm_correlations], dtype=float
    )
    human_vals = np.asarray(
        [c["correlation"] for c in human_correlations], dtype=float
    )
    llm_vals = llm_vals[np.isfinite(llm_vals)]
    human_vals = human_vals[np.isfinite(human_vals)]

    mw_stat, mw_p = scipy_stats.mannwhitneyu(
        llm_vals, human_vals, alternative="two-sided"
    )
    ks_stat, ks_p = scipy_stats.ks_2samp(llm_vals, human_vals)
    t_stat, t_p = scipy_stats.ttest_ind(llm_vals, human_vals)

    pooled_std = float(np.sqrt((llm_vals.std() ** 2 + human_vals.std() ** 2) / 2))
    cohens_d = float((llm_vals.mean() - human_vals.mean()) / pooled_std)

    def _stats_block(vals, rows):
        # Rates are over VALID (finite-correlation) rows, matching the
        # reference's valid_*_correlations denominators (:162-176).
        sig = sum(1 for c in rows if c["significant"])
        return {
            "mean": float(vals.mean()),
            "std": float(vals.std()),
            "median": float(np.median(vals)),
            "n_pairs": int(vals.size),
            "significant_pairs": sig,
            "proportion_significant": sig / int(vals.size) if vals.size else 0,
        }

    return {
        "llm_stats": _stats_block(llm_vals, llm_correlations),
        "human_stats": _stats_block(human_vals, human_correlations),
        "comparison_tests": {
            "mann_whitney": {
                "statistic": float(mw_stat),
                "p_value": float(mw_p),
                "significant": bool(mw_p < 0.05),
            },
            "kolmogorov_smirnov": {
                "statistic": float(ks_stat),
                "p_value": float(ks_p),
                "significant": bool(ks_p < 0.05),
            },
            "t_test": {
                "statistic": float(t_stat),
                "p_value": float(t_p),
                "significant": bool(t_p < 0.05),
            },
            "effect_size": {
                "cohens_d": cohens_d,
                "interpretation": (
                    "small"
                    if abs(cohens_d) < 0.5
                    else "medium"
                    if abs(cohens_d) < 0.8
                    else "large"
                ),
            },
        },
    }


def run_pvalue_analysis(
    instruct_df: pd.DataFrame,
    base_df: pd.DataFrame,
    survey_df: pd.DataFrame,
) -> Dict[str, object]:
    """End-to-end C43 (main, :206-320). `survey_df` is the loaded (not yet
    excluded) survey frame; this analysis applies its own exclusion rules."""
    clean = apply_pvalue_exclusions(survey_df)
    llm_corrs = llm_correlations_with_pvalues(instruct_df, base_df)
    human_corrs = human_correlations_with_pvalues(clean)
    comparison = compare_correlation_distributions(llm_corrs, human_corrs)
    return {
        "llm_correlations": llm_corrs,
        "human_correlations": human_corrs,
        "comparison": comparison,
    }


def write_pvalue_analysis(results: Dict[str, object], path: Path) -> None:
    """``correlation_pvalues_analysis.json`` (:312-319)."""
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(json.dumps(results, indent=2))
