"""Human-LLM agreement metrics: point estimates and question-resampled
bootstrap (C39/C41), plus the D9 ``llm_human_agreement_bootstrap.json`` writer.

Parity targets:
  - survey_analysis/analyze_llm_human_agreement.py:94-316 (point metrics:
    MAE/RMSE/MAPE/Pearson/Spearman per model, worst-disagreement questions,
    per-question across-model variance, ``llm_human_agreement_analysis.json``)
  - survey_analysis/analyze_llm_agreement_simple_bootstrap.py:90-480
    (question-resampled bootstrap, n=1000; overall base-vs-instruct
    comparison with 10,000-fold bootstrap CI and permutation p-value;
    matched-pairs normal-approximation test; D9 JSON)

The reference's broken respondent-resampling variant
(analyze_llm_human_agreement_bootstrap.py — references an undefined
``survey_df``, SURVEY.md §2.2 C40) is a known defect; its working semantics
are fully covered by this module.

TPU-native redesign: each bootstrap iteration in the reference re-walks the
model DataFrame row-by-row. Here each model is reduced once to aligned
(human, model, valid) vectors over the 50 canonical questions, and all 1000
resamples evaluate as one vmapped kernel. A reference quirk preserved
deliberately: membership of a question in a bootstrap sample is tested with
``in sampled_questions`` (analyze_llm_agreement_simple_bootstrap.py:101), so
duplicate draws do NOT up-weight a question — the resample acts as a random
subset. The kernel reproduces exactly that via a boolean membership mask.
"""

from __future__ import annotations

import functools
import json
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import pandas as pd
from scipy import stats as scipy_stats

from ..stats.bootstrap import bootstrap_mean_ci, permutation_test_difference
from ..stats.core import resample_indices


# ---------------------------------------------------------------------------
# Data alignment
# ---------------------------------------------------------------------------


def relative_prob_series(df: pd.DataFrame) -> pd.Series:
    """The unified readout: ``relative_prob`` when present (D2), else
    yes/(yes+no) with 0.5 fallback on zero mass (D1) — the column-handling
    branch at analyze_llm_human_agreement.py:102-106."""
    if "relative_prob" in df.columns:
        return df["relative_prob"].astype(float)
    total = df["yes_prob"].astype(float) + df["no_prob"].astype(float)
    with np.errstate(invalid="ignore", divide="ignore"):
        rel = df["yes_prob"].astype(float) / total
    return rel.where(total > 0, 0.5)


def human_averages_from_detailed(
    detailed: Dict[str, object], question_mapping: Dict[str, str]
) -> Dict[str, float]:
    """prompt -> human mean on the 0-1 scale (mean_response / 100)."""
    by_q = detailed["results"]["by_question"]
    return {
        prompt: by_q[qid]["mean_response"] / 100.0
        for prompt, qid in question_mapping.items()
        if qid in by_q
    }


def aligned_vectors(
    model_df: pd.DataFrame, human_averages: Dict[str, float]
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, List[str]]:
    """(human, model, valid) aligned over the canonical question order.

    `valid` marks questions the model answered with a finite probability.
    """
    questions = list(human_averages.keys())
    rel = relative_prob_series(model_df)
    by_prompt = dict(zip(model_df["prompt"], rel))
    h = np.asarray([human_averages[q] for q in questions], dtype=float)
    m = np.asarray(
        [by_prompt.get(q, np.nan) for q in questions], dtype=float
    )
    valid = np.isfinite(m)
    return h, m, valid, questions


# ---------------------------------------------------------------------------
# Point metrics (C39)
# ---------------------------------------------------------------------------


def agreement_metrics(
    model_df: pd.DataFrame,
    model_name: str,
    human_averages: Dict[str, float],
    min_questions: int = 10,
) -> Optional[Dict[str, object]]:
    """MAE/RMSE/MAPE/Pearson/Spearman between one model's relative
    probabilities and human averages (calculate_agreement_metrics,
    analyze_llm_human_agreement.py:94-148)."""
    h, m, valid, questions = aligned_vectors(model_df, human_averages)
    h, m = h[valid], m[valid]
    qs = [q for q, v in zip(questions, valid) if v]
    if h.size < min_questions:
        return None

    diff = np.abs(h - m)
    mae = float(diff.mean())
    rmse = float(np.sqrt(((h - m) ** 2).mean()))
    mape = float(np.mean(np.abs((h - m) / h)) * 100)
    pearson_r, pearson_p = scipy_stats.pearsonr(h, m)
    spearman_r, spearman_p = scipy_stats.spearmanr(h, m)

    order = np.argsort(-diff)
    worst = [
        {
            "prompt": qs[i],
            "human_avg": float(h[i]),
            "model_prob": float(m[i]),
            "difference": float(diff[i]),
        }
        for i in order[:5]
    ]
    return {
        "model": model_name,
        "n_questions": int(h.size),
        "mae": mae,
        "rmse": rmse,
        "mape": mape,
        "pearson_r": float(pearson_r),
        "pearson_p": float(pearson_p),
        "spearman_r": float(spearman_r),
        "spearman_p": float(spearman_p),
        "worst_questions": worst,
        "matched": {"human_avg": h, "model_prob": m, "prompts": qs},
    }


def analyze_all_models(
    human_averages: Dict[str, float],
    instruct_df: pd.DataFrame,
    base_df: Optional[pd.DataFrame] = None,
) -> List[Dict[str, object]]:
    """Per-model point metrics across both CSVs, sorted by MAE ascending."""
    results = []
    for model in instruct_df["model"].unique():
        r = agreement_metrics(
            instruct_df[instruct_df["model"] == model], model, human_averages
        )
        if r:
            r["model_type"] = "instruct"
            results.append(r)
    if base_df is not None:
        for model in base_df["model"].unique():
            r = agreement_metrics(
                base_df[base_df["model"] == model], model, human_averages
            )
            if r:
                r["model_type"] = "base"
                results.append(r)
    results.sort(key=lambda x: x["mae"])
    return results


def question_variance(
    all_results: List[Dict[str, object]], human_averages: Dict[str, float]
) -> Dict[str, Dict[str, float]]:
    """Across-model response variance per question
    (analyze_llm_human_agreement.py:265-288)."""
    out: Dict[str, Dict[str, float]] = {}
    for prompt, h_avg in human_averages.items():
        probs = []
        for r in all_results:
            matched = r["matched"]
            if prompt in matched["prompts"]:
                probs.append(matched["model_prob"][matched["prompts"].index(prompt)])
        if probs:
            out[prompt] = {
                "human_avg": float(h_avg),
                "model_mean": float(np.mean(probs)),
                "model_std": float(np.std(probs)),
                "n_models": len(probs),
            }
    return out


def write_agreement_analysis(
    all_results: List[Dict[str, object]],
    human_averages: Dict[str, float],
    path: Path,
) -> Dict[str, object]:
    """``llm_human_agreement_analysis.json`` (analyze_llm_human_agreement.py:
    291-310)."""
    payload = {
        "analysis_type": "llm_human_agreement",
        "description": "Comparison of LLM outputs to human average ratings per question",
        "model_results": [
            {
                "model": r["model"],
                "model_type": r["model_type"],
                "mae": r["mae"],
                "rmse": r["rmse"],
                "mape": r["mape"],
                "pearson_r": r["pearson_r"],
                "n_questions": r["n_questions"],
            }
            for r in all_results
        ],
        "question_variance": question_variance(all_results, human_averages),
    }
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(json.dumps(payload, indent=2))
    return payload


# ---------------------------------------------------------------------------
# Question-resampled bootstrap (C41)
# ---------------------------------------------------------------------------


@functools.partial(jax.jit, static_argnames=("n_questions",))
def _boot_metric_kernel(h, m, valid, idx, n_questions: int):
    """All bootstrap iterations at once. For each index row: select the
    UNIQUE sampled questions (membership semantics, see module docstring)
    intersected with `valid`, then compute (mae, mse, mape, pearson, n)."""

    def one(ix):
        sel = jnp.zeros((n_questions,), dtype=bool).at[ix].set(True) & valid
        n = sel.sum()
        w = sel / jnp.maximum(n, 1)
        d = jnp.where(sel, h - m, 0.0)
        mae = jnp.abs(d).sum() / jnp.maximum(n, 1)
        mse = (d * d).sum() / jnp.maximum(n, 1)

        ape = jnp.abs((h - m) / jnp.where(h == 0, jnp.nan, h))
        ape_ok = sel & jnp.isfinite(ape)
        n_ape = ape_ok.sum()
        mape = jnp.where(
            n_ape > 0,
            jnp.where(ape_ok, ape, 0.0).sum() / jnp.maximum(n_ape, 1) * 100.0,
            jnp.nan,
        )

        hm = (jnp.where(sel, h, 0.0)).sum() / jnp.maximum(n, 1)
        mm = (jnp.where(sel, m, 0.0)).sum() / jnp.maximum(n, 1)
        dh = jnp.where(sel, h - hm, 0.0)
        dm = jnp.where(sel, m - mm, 0.0)
        denom = jnp.sqrt((dh * dh).sum() * (dm * dm).sum())
        pearson = jnp.where(denom > 0, (dh * dm).sum() / denom, jnp.nan)
        return mae, mse, mape, pearson, n

    return jax.vmap(one)(idx)


def bootstrap_agreement_metrics(
    model_df: pd.DataFrame,
    human_averages: Dict[str, float],
    key: jax.Array,
    n_bootstrap: int = 1000,
    confidence: float = 0.95,
    min_questions: int = 10,
    min_successful: int = 100,
) -> Optional[Dict[str, float]]:
    """Bootstrap-over-questions CIs for one model's agreement metrics
    (analyze_llm_agreement_simple_bootstrap.py:151-212)."""
    h, m, valid, _ = aligned_vectors(model_df, human_averages)
    n_q = h.shape[0]
    idx = resample_indices(key, n_bootstrap, n_q)
    mae_s, mse_s, mape_s, r_s, n_s = (
        np.asarray(a)
        for a in _boot_metric_kernel(
            jnp.asarray(h), jnp.asarray(np.where(valid, m, 0.0)),
            jnp.asarray(valid), idx, n_q,
        )
    )
    ok = n_s >= min_questions
    if ok.sum() < min_successful:
        return None

    alpha = 1 - confidence
    metrics: Dict[str, float] = {"n_bootstrap": int(ok.sum())}
    for name, samples in (
        ("mae", mae_s), ("mse", mse_s), ("mape", mape_s), ("pearson_r", r_s)
    ):
        vals = samples[ok]
        vals = vals[np.isfinite(vals)]
        if vals.size:
            metrics[f"{name}_mean"] = float(np.mean(vals))
            metrics[f"{name}_ci_lower"] = float(np.percentile(vals, alpha / 2 * 100))
            metrics[f"{name}_ci_upper"] = float(
                np.percentile(vals, (1 - alpha / 2) * 100)
            )
            metrics[f"{name}_std"] = float(np.std(vals))
        else:
            for suffix in ("mean", "ci_lower", "ci_upper", "std"):
                metrics[f"{name}_{suffix}"] = float("nan")
    return metrics


def bootstrap_all_models(
    human_averages: Dict[str, float],
    instruct_df: pd.DataFrame,
    base_df: Optional[pd.DataFrame],
    key: jax.Array,
    n_bootstrap: int = 1000,
) -> List[Dict[str, object]]:
    """All models' bootstrap metrics, base models first (reference order:
    analyze_llm_agreement_simple_bootstrap.py:163-166), sorted by MAE."""
    jobs = []
    if base_df is not None:
        jobs += [(m, "base", base_df) for m in base_df["model"].unique()]
    jobs += [(m, "instruct", instruct_df) for m in instruct_df["model"].unique()]

    results = []
    # The reference demands >= 100 successful iterations (:187); scale the
    # gate down proportionally when running with reduced budgets.
    min_successful = min(100, max(1, n_bootstrap // 10))
    for model, model_type, src in jobs:
        key, sub = jax.random.split(key)
        metrics = bootstrap_agreement_metrics(
            src[src["model"] == model], human_averages, sub, n_bootstrap,
            min_successful=min_successful,
        )
        if metrics is None:
            continue
        results.append({"model": model, "model_type": model_type, **metrics})
    results.sort(key=lambda x: x["mae_mean"])
    return results


# ---------------------------------------------------------------------------
# Group difference statistics (C41 overall comparison)
# ---------------------------------------------------------------------------


def difference_stats(
    group1: Sequence[float],
    group2: Sequence[float],
    key: jax.Array,
    n_bootstrap: int = 10_000,
) -> Tuple[float, float, float, float]:
    """(observed diff, ci_lower, ci_upper, permutation p) for
    mean(group1) - mean(group2) — calculate_difference_stats
    (analyze_llm_agreement_simple_bootstrap.py:312-347). Composed from the
    shared bootstrap kernels in lir_tpu.stats."""
    a = np.asarray(group1, dtype=float)
    b = np.asarray(group2, dtype=float)

    k1, k2, k3 = jax.random.split(key, 3)
    means_a = bootstrap_mean_ci(a, k1, n_boot=n_bootstrap).samples
    means_b = bootstrap_mean_ci(b, k2, n_boot=n_bootstrap).samples
    diffs = means_a - means_b
    ci_lower = float(np.percentile(diffs, 2.5))
    ci_upper = float(np.percentile(diffs, 97.5))

    perm = permutation_test_difference(a, b, k3, n_perm=n_bootstrap)
    return perm["observed_difference"], ci_lower, ci_upper, perm["p_value"]


def matched_pairs_analysis(
    all_results: List[Dict[str, object]],
    families: Optional[Dict[str, Sequence[str]]] = None,
) -> Dict[str, Dict[str, float]]:
    """Paired instruct-base differences per family with a normal-approx
    paired test (analyze_llm_agreement_simple_bootstrap.py:392-444)."""
    pairs = []
    families = families or DEFAULT_FAMILIES
    for family, models in families.items():
        base = instruct = None
        for r in all_results:
            if r["model"] in models:
                if "instruct" in r["model"].lower() or "tuned" in r["model"].lower():
                    instruct = r
                else:
                    base = r
        if base and instruct:
            pairs.append({"family": family, "base": base, "instruct": instruct})

    out: Dict[str, Dict[str, float]] = {}
    for metric in ("mae", "mse", "mape"):
        diffs = [
            p["instruct"][f"{metric}_mean"] - p["base"][f"{metric}_mean"]
            for p in pairs
        ]
        if not diffs:
            continue
        mean_diff = float(np.mean(diffs))
        se = float(np.std(diffs) / np.sqrt(len(diffs)))
        t = mean_diff / se if se > 0 else 0.0
        p = float(2 * (1 - scipy_stats.norm.cdf(abs(t))))
        out[metric] = {
            "per_family": {
                pr["family"]: float(d) for pr, d in zip(pairs, diffs)
            },
            "mean_difference": mean_diff,
            "ci_lower": mean_diff - 1.96 * se,
            "ci_upper": mean_diff + 1.96 * se,
            "p_value": p,
        }
    return out


DEFAULT_FAMILIES: Dict[str, Tuple[str, str]] = {
    "Falcon": ("tiiuae/falcon-7b", "tiiuae/falcon-7b-instruct"),
    "StableLM": (
        "stabilityai/stablelm-base-alpha-7b",
        "stabilityai/stablelm-tuned-alpha-7b",
    ),
    "RedPajama": (
        "togethercomputer/RedPajama-INCITE-7B-Base",
        "togethercomputer/RedPajama-INCITE-7B-Instruct",
    ),
}


def bootstrap_results_payload(
    all_results: List[Dict[str, object]],
    key: jax.Array,
    n_bootstrap: int = 1000,
    n_diff_bootstrap: int = 10_000,
) -> Dict[str, object]:
    """The D9 ``llm_human_agreement_bootstrap.json`` schema
    (analyze_llm_agreement_simple_bootstrap.py:447-477)."""
    base = [r for r in all_results if r["model_type"] == "base"]
    instruct = [r for r in all_results if r["model_type"] == "instruct"]
    payload: Dict[str, object] = {
        "analysis_type": "llm_human_agreement_bootstrap_questions",
        "description": (
            "Comparison of LLM outputs to human average ratings with "
            "bootstrap confidence intervals (sampling questions)"
        ),
        "bootstrap_parameters": {
            "n_iterations": n_bootstrap,
            "confidence_level": 0.95,
            "bootstrap_method": "questions_with_replacement",
        },
        "model_results": [
            {k: v for k, v in r.items()} for r in all_results
        ],
        "overall_comparison": {
            "base_models_count": len(base),
            "instruct_models_count": len(instruct),
            "metrics": {},
        },
    }
    for metric in ("mae", "mse", "mape"):
        b_vals = [
            r[f"{metric}_mean"] for r in base if np.isfinite(r[f"{metric}_mean"])
        ]
        i_vals = [
            r[f"{metric}_mean"]
            for r in instruct
            if np.isfinite(r[f"{metric}_mean"])
        ]
        if not b_vals or not i_vals:
            continue
        key, sub = jax.random.split(key)
        diff, lo, hi, p = difference_stats(b_vals, i_vals, sub, n_diff_bootstrap)
        payload["overall_comparison"]["metrics"][metric] = {
            "base_mean": float(np.mean(b_vals)),
            "base_ci": [
                float(np.percentile(b_vals, 2.5)),
                float(np.percentile(b_vals, 97.5)),
            ],
            "instruct_mean": float(np.mean(i_vals)),
            "instruct_ci": [
                float(np.percentile(i_vals, 2.5)),
                float(np.percentile(i_vals, 97.5)),
            ],
            "difference": diff,
            "difference_ci": [lo, hi],
            "p_value": p,
        }
    return payload


def write_bootstrap_results(payload: Dict[str, object], path: Path) -> None:
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(json.dumps(payload, indent=2))


def llm_prompt_estimates_from_accum(acc, n_boot: int = 1000,
                                    confidence: float = 0.95
                                    ) -> Dict[int, Dict[str, float]]:
    """Axis-3 entry point consuming the streaming accumulator DIRECTLY
    (engine/stream_stats.py via stats/streaming.HostAccum): per-prompt
    mean relative probability + seeded bootstrap CI — the LLM side of
    the human-vs-LLM comparison, available live mid-sweep without a
    results.csv reload. The resample key is the accumulator's recorded
    manifest seed, so estimates are reproducible across resume and
    match a csv-reload replay (stats.streaming.accum_from_rows)."""
    from ..stats import streaming as streaming_mod

    out: Dict[int, Dict[str, float]] = {}
    for p in range(acc.filled.shape[0]):
        values = streaming_mod.prompt_values(acc, "rel", p)
        if values.size == 0:
            continue
        entry: Dict[str, float] = {
            "estimate": float(values.mean()),
            "n": int(values.size),
        }
        entry.update(streaming_mod.bootstrap_mean_ci_seeded(
            values, acc.seed, p, n_boot, confidence))
        out[p] = entry
    return out
