"""End-to-end survey analysis pipeline driver.

Replaces the reference's seven standalone survey scripts (each re-loading and
re-cleaning the same CSVs, SURVEY.md §2.3) with one orchestrated pass that
loads/cleans once and emits every artifact:

  survey_analysis_detailed.json        (D7 - producer missing upstream)
  consolidated_analysis_results.json   (D8)
  llm_human_agreement_analysis.json    (C39)
  llm_human_agreement_bootstrap.json   (D9, C41)
  bootstrap_confidence_intervals.json  (C38)
  family_differences.json              (C42)
  correlation_pvalues_analysis.json    (C43)
  proportion_analysis.json             (analyze_base_vs_instruct_vs_human)

Usage:
  python -m lir_tpu.survey.run --survey data/word_meaning_survey_results.csv \\
      --instruct data/instruct_model_comparison_results.csv \\
      --base data/model_comparison_results.csv --out results/survey
"""

from __future__ import annotations

import argparse
from pathlib import Path
from typing import Dict, Optional

import jax
import pandas as pd

from ..utils.logging import get_logger
from . import consolidated, family_differences, human_llm, loader, proportions
from . import pvalues as pvalues_mod
from . import simulated

log = get_logger(__name__)


def run_survey_pipeline(
    survey_csv: Path,
    instruct_csv: Path,
    base_csv: Optional[Path],
    out_dir: Path,
    seed: int = 42,
    n_bootstrap_standard: int = 1000,
    n_bootstrap_small: int = 100,
    n_bootstrap_large: int = 10_000,
    run_simulated_individuals: bool = True,
) -> Dict[str, object]:
    """Run every survey analysis and write all artifacts into `out_dir`.

    Returns the in-memory results keyed by artifact name.
    """
    out_dir = Path(out_dir)
    out_dir.mkdir(parents=True, exist_ok=True)
    key = jax.random.PRNGKey(seed)
    keys = jax.random.split(key, 6)

    log.info("Loading survey data from %s", survey_csv)
    survey_df, question_cols = loader.load_survey(survey_csv)
    clean_df, exclusion_stats = loader.apply_exclusions(survey_df, question_cols)
    log.info(
        "Exclusions: %d -> %d respondents",
        exclusion_stats["final_count"] + exclusion_stats["total_excluded"],
        exclusion_stats["final_count"],
    )

    instruct_df = pd.read_csv(instruct_csv)
    base_df = pd.read_csv(base_csv) if base_csv else None

    question_mapping_text = loader.extract_question_text(survey_csv)
    matches = loader.match_survey_to_llm_questions(
        instruct_df, question_mapping_text
    )
    canonical = loader.canonical_question_mapping()

    # D7 — the detailed per-question stats the downstream scripts assume.
    detailed = loader.write_survey_detailed(
        clean_df, question_cols, out_dir / "survey_analysis_detailed.json"
    )

    # D8 — consolidated analysis.
    log.info("Running consolidated analysis")
    analysis = consolidated.run_consolidated_analysis(
        clean_df, question_cols, instruct_df, matches, exclusion_stats,
        keys[0], n_bootstrap_standard, n_bootstrap_small,
    )
    consolidated.save_consolidated_results(
        analysis, out_dir / "consolidated_analysis_results.json"
    )
    (out_dir / "consolidated_report.txt").write_text(
        consolidated.format_report(analysis)
    )

    # C39 — point agreement metrics + figures.
    log.info("Running human-LLM agreement metrics")
    human_avgs = human_llm.human_averages_from_detailed(detailed, canonical)
    point_results = human_llm.analyze_all_models(human_avgs, instruct_df, base_df)
    human_llm.write_agreement_analysis(
        point_results, human_avgs, out_dir / "llm_human_agreement_analysis.json"
    )
    from ..report import survey_figures

    survey_figures.best_worst_agreement_plot(
        point_results, out_dir / "best_worst_model_agreement.png"
    )
    survey_figures.mae_comparison_plot(
        point_results, out_dir / "model_mae_comparison.png"
    )

    # C41 / D9 — question-resampled bootstrap.
    log.info("Running question-resampled bootstrap (n=%d)", n_bootstrap_standard)
    boot_results = human_llm.bootstrap_all_models(
        human_avgs, instruct_df, base_df, keys[1], n_bootstrap_standard
    )
    d9_payload = human_llm.bootstrap_results_payload(
        boot_results, keys[2], n_bootstrap_standard, n_bootstrap_large
    )
    # Matched-pairs analysis (reference stdout, :392-444) rides along in the
    # D9 JSON under an extra key — consumers read model_results only.
    d9_payload["matched_pairs"] = human_llm.matched_pairs_analysis(boot_results)
    human_llm.write_bootstrap_results(
        d9_payload, out_dir / "llm_human_agreement_bootstrap.json"
    )

    # C42 — family differences from D9.
    fam = family_differences.analyze_family_differences(d9_payload, keys[3])
    family_differences.write_family_differences(
        fam, out_dir / "family_differences.json"
    )

    results: Dict[str, object] = {
        "detailed": detailed,
        "consolidated": analysis,
        "agreement_points": point_results,
        "agreement_bootstrap": d9_payload,
        "family_differences": fam,
    }

    # C38 — simulated-individual bootstrap (heavy; needs the D1 CSV).
    if run_simulated_individuals and base_df is not None:
        log.info("Running simulated-individual bootstrap (n=%d)", n_bootstrap_large)
        sim = simulated.run_simulated_bootstrap(
            base_df, canonical, detailed, keys[4],
            n_bootstrap=n_bootstrap_large,
        )
        simulated.write_simulated_bootstrap(
            sim, out_dir / "bootstrap_confidence_intervals.json"
        )
        results["simulated_bootstrap"] = sim

    # C43 — correlation p-values (own exclusion rules, raw survey frame).
    if base_df is not None:
        log.info("Running correlation p-value analysis")
        pv = pvalues_mod.run_pvalue_analysis(instruct_df, base_df, survey_df)
        pvalues_mod.write_pvalue_analysis(
            pv, out_dir / "correlation_pvalues_analysis.json"
        )
        from ..report import survey_figures

        survey_figures.correlation_pvalue_panel(
            pv["llm_correlations"], pv["human_correlations"],
            out_dir / "correlation_pvalue_distributions.png",
        )
        results["pvalues"] = pv

    # Proportion-based comparison + validity audit.
    prop = proportions.run_proportion_analysis(instruct_df, detailed, canonical)
    proportions.write_proportion_analysis(
        prop, out_dir / "proportion_analysis.json"
    )
    results["proportions"] = prop

    log.info("Survey pipeline complete; artifacts in %s", out_dir)
    return results


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--survey", type=Path, required=True)
    parser.add_argument("--instruct", type=Path, required=True)
    parser.add_argument("--base", type=Path, default=None)
    parser.add_argument("--out", type=Path, default=Path("results/survey"))
    parser.add_argument("--seed", type=int, default=42)
    parser.add_argument("--quick", action="store_true",
                        help="reduced bootstrap budgets for smoke runs")
    args = parser.parse_args()

    kwargs = {}
    if args.quick:
        kwargs = dict(
            n_bootstrap_standard=50,
            n_bootstrap_small=20,
            n_bootstrap_large=200,
            run_simulated_individuals=True,
        )
    run_survey_pipeline(
        args.survey, args.instruct, args.base, args.out, seed=args.seed,
        **kwargs,
    )


if __name__ == "__main__":
    main()
