"""Per-family base-vs-instruct difference analysis (C42).

Parity target: survey_analysis/analyze_model_family_differences.py:1-232 —
consumes the D9 bootstrap JSON and, for each model family and each of
MAE/MSE/MAPE, reports the instruct-minus-base difference with:
  method 1: propagated-std 1.96*SE CI (:63-72)
  method 2: combined CI-range CI (:74-82)
  method 3: 10,000-draw normal-approximation Monte Carlo with a two-tailed
            p-value (:169-230) — vectorized via normal_approx_mc_difference.
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Dict, Optional

import jax
import numpy as np

from ..stats.bootstrap import normal_approx_mc_difference

DEFAULT_FAMILIES: Dict[str, Dict[str, str]] = {
    "Falcon": {
        "base": "tiiuae/falcon-7b",
        "instruct": "tiiuae/falcon-7b-instruct",
    },
    "StableLM": {
        "base": "stabilityai/stablelm-base-alpha-7b",
        "instruct": "stabilityai/stablelm-tuned-alpha-7b",
    },
    "RedPajama": {
        "base": "togethercomputer/RedPajama-INCITE-7B-Base",
        "instruct": "togethercomputer/RedPajama-INCITE-7B-Instruct",
    },
}

METRICS = ("mae", "mse", "mape")


def analyze_family_differences(
    bootstrap_payload: Dict[str, object],
    key: jax.Array,
    families: Optional[Dict[str, Dict[str, str]]] = None,
    n_mc: int = 10_000,
) -> Dict[str, object]:
    """Differences for every (family, metric) with all three CI methods."""
    families = families or DEFAULT_FAMILIES
    by_model = {r["model"]: r for r in bootstrap_payload["model_results"]}

    out: Dict[str, object] = {}
    for family, pair in families.items():
        base = by_model.get(pair["base"])
        instruct = by_model.get(pair["instruct"])
        if base is None or instruct is None:
            out[family] = {"missing": True}
            continue
        fam: Dict[str, object] = {}
        for metric in METRICS:
            b_mean = base[f"{metric}_mean"]
            i_mean = instruct[f"{metric}_mean"]
            diff = i_mean - b_mean

            # Method 1: independence-propagated std.
            se = float(np.sqrt(base[f"{metric}_std"] ** 2
                               + instruct[f"{metric}_std"] ** 2))
            m1 = (diff - 1.96 * se, diff + 1.96 * se)

            # Method 2: combined CI ranges.
            b_range = base[f"{metric}_ci_upper"] - base[f"{metric}_ci_lower"]
            i_range = (
                instruct[f"{metric}_ci_upper"] - instruct[f"{metric}_ci_lower"]
            )
            combined = float(np.sqrt(b_range**2 + i_range**2))
            m2 = (diff - combined / 2, diff + combined / 2)

            # Method 3: normal-approximation MC (instruct - base).
            key, sub = jax.random.split(key)
            mc = normal_approx_mc_difference(
                i_mean, instruct[f"{metric}_std"],
                b_mean, base[f"{metric}_std"],
                sub, n_draws=n_mc,
            )

            fam[metric] = {
                "base_mean": b_mean,
                "base_ci": [
                    base[f"{metric}_ci_lower"], base[f"{metric}_ci_upper"]
                ],
                "instruct_mean": i_mean,
                "instruct_ci": [
                    instruct[f"{metric}_ci_lower"],
                    instruct[f"{metric}_ci_upper"],
                ],
                "difference": diff,
                "relative_change_pct": (diff / b_mean) * 100 if b_mean else None,
                "ci_propagated_std": list(m1),
                "ci_combined_range": list(m2),
                "significant_combined_range": bool(m2[0] * m2[1] > 0),
                "mc_difference": mc,
            }
        out[family] = fam
    return out


def write_family_differences(results: Dict[str, object], path: Path) -> None:
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(json.dumps(results, indent=2))
