"""Guard layer: catching the failures that never raise.

PR 4's recovery machinery (retry -> AOT->lazy degrade -> bisection
ladder -> circuit breaker) triggers on an EXCEPTION. The failure modes
that actually dominate long TPU-pod runs are silent:

- a dispatch or collective that hangs forever (a dead peer host parks
  every live host inside ``process_allgather``; a wedged runtime parks
  the dispatch thread in C++) — nothing raises, the run just stops;
- numerics corruption — NaN/Inf logits flowing through the score
  readouts land in results.csv as plausible-looking confidences, the
  exact reliability artifact the paper measures.

Two guards close the gap:

- watchdog.DispatchWatchdog: every device dispatch runs on a watched
  executor whose deadline derives from the SAME ``scheduler.
  bucket_cost()`` price model the planners use (calibrated multiple +
  floor, ``RuntimeConfig.watchdog_multiple``/``watchdog_floor_s``).
  On expiry it dumps every thread stack, abandons the dispatch, and
  surfaces a synthetic :class:`DispatchStalled` into the EXISTING
  recovery machinery (ladder retry -> breaker) — a hang costs one
  deadline instead of the run.
- numerics.check_values: a validation boundary at score-extraction
  time (logits finite, P(Yes)+P(No) renormalization sane, confidence
  in range) that quarantines offending rows as ``error:numerics``,
  mirroring the ladder's poison-row isolation, instead of writing
  garbage. Counters land in profiling.GuardStats per site.

The multihost liveness guard (timeout-bounded barrier + per-host
heartbeat allgather) lives in parallel/multihost.py and reuses
watchdog.watch_call to bound the collectives.
"""

from .numerics import NUMERICS_ERROR, check_payload, check_values
from .watchdog import (DispatchStalled, DispatchWatchdog,
                       dump_thread_stacks, watch_call)

__all__ = [
    "DispatchStalled", "DispatchWatchdog", "watch_call",
    "dump_thread_stacks",
    "NUMERICS_ERROR", "check_values", "check_payload",
]
