"""Numerics guard: the validation boundary at score-extraction time.

An SDC-prone chip, a corrupt executable, or a miscompiled kernel does
not crash — it emits NaN/Inf logits, and those flow through the softmax
readouts and ``_parse_confidence`` into results.csv as plausible-looking
numbers. For a framework whose HEADLINE measurement is confidence
reliability, silently recording corrupt confidences is the worst
possible failure, so every row crosses this boundary before it is
written (offline sweep) or resolved (serve):

- P(yes) / P(no) finite and inside [0, 1] (softmax outputs — anything
  else is corruption, not rounding);
- renormalization sanity: P(yes) + P(no) <= 1 (+ float slop);
- weighted confidence finite and inside [0, 100] (E[v] over the digit
  set cannot leave it);
- the top-20 log-probability map free of NaN and never positive
  (log-softmax is <= 0 by construction);
- the parsed confidence integer inside [0, 100] (belt-and-braces: the
  parse itself now rejects out-of-range integers).

Offending rows are QUARANTINED as ``error:numerics`` — the offline row
keeps its cell identity with every measurement field nulled, the serve
request resolves status "error" with a numerics note — mirroring the
degradation ladder's poison-row isolation: neighbors score bitwise
identical to a clean run, only the corrupt row is withheld. Counters
land in profiling.GuardStats per site ("sweep" / "serve").
"""

from __future__ import annotations

import json
import math
from typing import Optional, Sequence

import numpy as np

NUMERICS_ERROR = "error:numerics"

# Float32 readouts round-trip through host floats; these are slop for
# rounding, not tolerance for corruption (a real softmax output can miss
# the exact bound by an ulp, never by a percent).
_P_EPS = 1e-4
_SUM_EPS = 1e-3
_CONF_EPS = 1e-3


def check_values(token_1_prob, token_2_prob,
                 weighted_confidence=None,
                 logprob_values: Optional[Sequence[float]] = None,
                 confidence_value: Optional[int] = None) -> Optional[str]:
    """Validate one row's device-derived readouts. Returns None when the
    row is sane, else a short human-readable reason (the quarantine
    note). Impossible-for-valid-softmax conditions only: a clean row can
    NEVER trip this, so quarantine implies corruption."""
    for name, v in (("P(yes)", token_1_prob), ("P(no)", token_2_prob)):
        if v is None:
            return f"{name} missing"
        v = float(v)
        if not math.isfinite(v):
            return f"{name} not finite ({v!r})"
        if v < -_P_EPS or v > 1.0 + _P_EPS:
            return f"{name}={v:.6g} outside [0,1]"
    s = float(token_1_prob) + float(token_2_prob)
    if s > 1.0 + _SUM_EPS:
        return f"P(yes)+P(no)={s:.6g} > 1 (renormalization insane)"
    if weighted_confidence is not None:
        w = float(weighted_confidence)
        if not math.isfinite(w):
            return f"weighted confidence not finite ({w!r})"
        if w < -_CONF_EPS or w > 100.0 + _CONF_EPS:
            return f"weighted confidence={w:.6g} outside [0,100]"
    if confidence_value is not None and not 0 <= confidence_value <= 100:
        return f"confidence value {confidence_value} outside [0,100]"
    if logprob_values is not None:
        arr = np.asarray(logprob_values, dtype=np.float64)
        if arr.size:
            if np.isnan(arr).any():
                return "log-probability map contains NaN"
            if (arr > _P_EPS).any():
                return "log-probability map contains positive logprobs"
    return None


def check_payload(payload: dict) -> Optional[str]:
    """:func:`check_values` over a serve measurement payload (the dict
    ``batcher.score`` returns per row). The stringified log-prob map is
    parsed back — 20 entries, negligible next to the dispatch — so an
    injected NaN that only reaches the map is still caught."""
    lp = None
    s = payload.get("log_probabilities")
    if s:
        try:
            lp = list(json.loads(s).values())
        except ValueError:
            return "log-probability map unparseable"
    return check_values(payload.get("token_1_prob"),
                        payload.get("token_2_prob"),
                        payload.get("weighted_confidence"), lp,
                        payload.get("confidence_value"))
