"""Dispatch watchdog: stall detection for calls that never return.

A wedged device runtime (dead ICI link, stuck collective, runaway
kernel) parks the dispatching thread inside a C++ call that no signal
short of SIGKILL interrupts — ``except Exception`` recovery never runs
because nothing ever raises. :func:`watch_call` runs the call on a
disposable worker thread and polls it against a deadline from the
caller's thread; on expiry it dumps every live thread's stack (the
post-mortem a hung run otherwise never yields), ABANDONS the worker,
and raises :class:`DispatchStalled` — an ordinary ``RuntimeError`` so
the existing recovery machinery (sweep ladder retry, serve retry ->
degradation ladder -> breaker) treats a hang exactly like a raised
device fault: one deadline lost, not the run.

Deadlines come from :class:`DispatchWatchdog`, which prices each
dispatch through the SAME ``scheduler.bucket_cost()`` row-token model
the offline planner and online batcher use: the first successful
dispatch calibrates seconds-per-cost-unit (EWMA thereafter), and the
deadline is ``floor + multiple * predicted_seconds``
(``RuntimeConfig.watchdog_floor_s`` / ``watchdog_multiple``). Until
calibrated the watchdog observes without enforcing — a legitimate
first-dispatch compile can take minutes and must never be shot.

Abandonment is safe by construction: the only injected hang mode
(faults.SiteSchedule kind="hang") sleeps BEFORE touching the engine
and raises on release, so an abandoned worker never mutates the
KV-cache donation chain behind a live retry; a real wedged runtime
call is already beyond help and the recovery path's
``degrade_to_lazy()`` resets the donation chain anyway.
"""

from __future__ import annotations

import sys
import threading
import time
import traceback
from typing import Callable, Optional

from ..utils.logging import get_logger
from ..utils.profiling import GuardStats

log = get_logger(__name__)

DEFAULT_TICK_S = 0.05


class DispatchStalled(RuntimeError):
    """A watched call outlived its watchdog deadline. Synthetic on
    purpose: a real hang raises nothing, so this stands in for the
    device error the recovery machinery (ladder/breaker) expects."""


def dump_thread_stacks() -> str:
    """Every live thread's current stack, formatted — the post-mortem a
    hung process otherwise never produces. Pure introspection
    (sys._current_frames), safe to call from any thread."""
    frames = sys._current_frames()
    names = {t.ident: t for t in threading.enumerate()}
    parts = []
    for ident, frame in frames.items():
        t = names.get(ident)
        label = (f"{t.name} (daemon={t.daemon})" if t is not None
                 else f"ident={ident}")
        parts.append(f"--- thread {label} ---\n"
                     + "".join(traceback.format_stack(frame)))
    return "\n".join(parts)


def watch_call(fn: Callable, deadline_s: Optional[float],
               label: str = "call",
               on_tick: Optional[Callable[[], None]] = None,
               tick_s: float = DEFAULT_TICK_S):
    """Run ``fn()`` on a disposable daemon thread, polling every
    ``tick_s`` seconds from the caller's thread.

    - result / exception propagate to the caller (BaseException
      included — an injected preemption must unwind here exactly as it
      would inline);
    - ``on_tick`` runs on the CALLER's thread at every poll (the serve
      supervisor uses it to resolve in-flight rows whose deadline
      passed mid-dispatch — partial results immediately instead of
      waiting out the device call);
    - ``deadline_s=None`` waits forever (ticks still fire);
    - on expiry: dump all thread stacks to the log, abandon the worker
      (its eventual result or error is dropped and logged at INFO),
      raise :class:`DispatchStalled`.
    """
    done = threading.Event()
    box: dict = {}
    state = {"abandoned": False}

    def _run():
        try:
            box["result"] = fn()
        except BaseException as err:  # noqa: BLE001 — re-raised by caller
            box["error"] = err
            if state["abandoned"]:
                log.info("abandoned %s eventually raised: %r", label, err)
        finally:
            if state["abandoned"] and "error" not in box:
                log.info("abandoned %s eventually completed; result "
                         "dropped", label)
            done.set()

    worker = threading.Thread(target=_run, name=f"watched:{label}",
                              daemon=True)
    start = time.monotonic()
    worker.start()
    while not done.wait(tick_s):
        if on_tick is not None:
            on_tick()
        if (deadline_s is not None
                and time.monotonic() - start >= deadline_s):
            state["abandoned"] = True
            log.error(
                "watchdog: %s exceeded its %.2fs deadline — abandoning "
                "the dispatch and surfacing DispatchStalled into the "
                "recovery path. Thread stacks:\n%s",
                label, deadline_s, dump_thread_stacks())
            raise DispatchStalled(
                f"{label} exceeded its {deadline_s:.2f}s watchdog "
                f"deadline (dispatch abandoned, thread stacks dumped)")
    if "error" in box:
        raise box["error"]
    return box["result"]


class DispatchWatchdog:
    """Deadline policy + calibration + counters for watched dispatches.

    ``multiple <= 0`` disables the watchdog entirely (every watch() is
    a plain call). Deadlines: ``floor_s + multiple * predicted``, where
    ``predicted`` is the calibrated seconds-per-cost-unit times the
    dispatch's ``bucket_cost`` (or, with no cost given, the EWMA of raw
    dispatch seconds). The floor is a hard minimum safety margin so a
    noisy calibration can never produce a hair-trigger deadline.
    """

    def __init__(self, multiple: float = 20.0, floor_s: float = 30.0,
                 stats: Optional[GuardStats] = None,
                 tick_s: float = DEFAULT_TICK_S,
                 seed_headroom: Optional[float] = None):
        self.multiple = float(multiple)
        self.floor_s = float(floor_s)
        self.stats = stats if stats is not None else GuardStats()
        self.tick_s = float(tick_s)
        # EWMA seed headroom, read from the scheduler's decode-floor
        # constants (scheduler.watchdog_seed_headroom — the fused/unfused
        # kernel spread): the FIRST calibration sample is inflated by
        # this ratio, so a deadline seeded on fast fused-kernel
        # dispatches never fires spuriously when a later dispatch
        # legitimately runs the slower dense decode path (a shape the
        # kernel can't fuse, or --no-fused-decode mid-fleet). The EWMA
        # tightens back within a few dispatches (0.7 decay).
        if seed_headroom is None:
            from ..engine import scheduler as _sched

            seed_headroom = _sched.watchdog_seed_headroom()
        self.seed_headroom = max(float(seed_headroom), 1.0)
        # Calibration EWMAs: observed from every dispatching thread
        # (sweep main thread, serve supervisor, AOT-wait paths), so
        # mutations hold the lock (enforced by lint/locks.py).
        self._rate: Optional[float] = None   # guarded-by: _lock
        self._flat: Optional[float] = None   # guarded-by: _lock
        self._lock = threading.Lock()

    @property
    def enabled(self) -> bool:
        return self.multiple > 0

    @property
    def calibrated(self) -> bool:
        with self._lock:
            return self._flat is not None

    def deadline_for(self, cost: Optional[float]) -> Optional[float]:
        """Seconds this dispatch may take before it counts as stalled,
        or None while uncalibrated (observe-only: the first dispatch of
        a fresh engine may legitimately compile for minutes)."""
        if not self.enabled:
            return None
        with self._lock:
            rate, flat = self._rate, self._flat
        if cost is not None and rate is not None:
            return self.floor_s + self.multiple * rate * max(float(cost),
                                                             1.0)
        if flat is not None:
            return self.floor_s + self.multiple * flat
        return None

    def observe(self, cost: Optional[float], elapsed: float) -> None:
        """Fold one successful dispatch into the calibration (EWMA,
        0.7 old / 0.3 new — adapts within a few dispatches but one
        outlier can't crater the deadline)."""
        with self._lock:
            if cost is not None and cost > 0:
                r = elapsed / max(float(cost), 1.0)
                self._rate = (r * self.seed_headroom if self._rate is None
                              else 0.7 * self._rate + 0.3 * r)
            self._flat = (elapsed * self.seed_headroom
                          if self._flat is None
                          else 0.7 * self._flat + 0.3 * elapsed)

    def watch(self, fn: Callable, cost: Optional[float] = None,
              site: str = "dispatch", label: str = "",
              on_tick: Optional[Callable[[], None]] = None):
        """Run one dispatch under the watchdog. Successful calls feed
        the calibration; expiries count into ``stats.stalls[site]`` and
        raise DispatchStalled for the caller's recovery machinery."""
        if not self.enabled:
            return fn()
        deadline = self.deadline_for(cost)
        if deadline is None and on_tick is None:
            # Uncalibrated and nobody needs ticks: run inline (no
            # thread), observe, enforce from the next dispatch on.
            t0 = time.monotonic()
            out = fn()
            self.observe(cost, time.monotonic() - t0)
            return out
        self.stats.site("watched", site)
        t0 = time.monotonic()
        try:
            out = watch_call(fn, deadline, label=label or site,
                             on_tick=on_tick, tick_s=self.tick_s)
        except DispatchStalled:
            self.stats.site("stalls", site)
            self.stats.count("stall_dumps")
            raise
        self.observe(cost, time.monotonic() - t0)
        return out
