"""Unified command-line interface for the framework.

The reference is driven by running eleven standalone scripts with hard-coded
personal paths (SURVEY.md §5 config: "no argparse anywhere"). Here every
experiment and analysis is one subcommand of ``python -m lir_tpu``:

  sweep        word-meaning model-comparison sweep -> D1/D2 CSVs
  perturb      perturbation grid sweep (with resume) -> D6 workbook
  serve        online scoring service (continuous batching, JSONL io)
  rephrase     generate/refresh perturbations.json with a local model
  analyze      all statistical analyses over existing artifacts
  survey       human-survey pipeline -> every survey JSON artifact
  bench        the prompts/sec/chip benchmark (end-to-end sweep path)
  precompile   warm the persistent compile cache for a model/ladder
  lint         graft-lint static analysis (JAX/XLA invariants, seconds)
  concat-shards  merge per-host .hostN sweep shards into the final artifact

Every command runs with the persistent XLA compilation cache ON (compiled
executables survive process restarts — utils/compile_cache.py; dir from
--compile-cache-dir > $LIR_TPU_COMPILE_CACHE > ~/.cache/lir_tpu/xla;
--no-compile-cache opts out).

Model weights must be local checkpoint directories (zero egress); pass
--checkpoints pointing at a root containing ``<org>__<name>`` dirs.
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path
from typing import List, Optional

from .utils.logging import get_logger

log = get_logger(__name__)


def _add_multihost_flag(p) -> None:
    p.add_argument("--multihost", action="store_true",
                   help="bring up jax.distributed for a multi-host pod "
                        "before loading models; each host then sweeps its "
                        "shard (perturb: grid cells, sweep: models) into "
                        "per-host .hostN artifacts that concatenate "
                        "row-wise; errors if bring-up fails rather than "
                        "silently degrading")


def _maybe_init_multihost(args) -> None:
    if getattr(args, "multihost", False):
        from .parallel import multihost

        multihost.initialize(required=True)


def _add_sweep(sub) -> None:
    p = sub.add_parser("sweep", help="word-meaning model comparison (D1/D2)")
    p.add_argument("--checkpoints", type=Path, required=True)
    p.add_argument("--models", nargs="+", required=True,
                   help="repo ids; suffix ':base' or ':instruct' "
                        "(default instruct)")
    p.add_argument("--out", type=Path, default=Path("results/comparison"))
    p.add_argument("--sweep-kind", choices=["base_vs_instruct", "instruct_only"],
                   default="base_vs_instruct")
    p.add_argument("--batch-size", type=int, default=32)
    p.add_argument("--mesh", type=str, default=None,
                   help="dataxmodel[xseq], e.g. 1x8 for 8-way tensor "
                        "parallel, 1x1x8 for sequence-parallel prefill "
                        "(long prompts)")
    p.add_argument("--param-cache", type=Path, default=None,
                   help="orbax cache root: convert HF weights once, restore "
                        "fast afterwards")
    p.add_argument("--int8", action="store_true",
                   help="weight-only int8 quantization (7B fits one chip)")
    p.add_argument("--int8-dynamic", action="store_true",
                   help="with --int8: quantize activations per token and "
                        "run s8xs8 MXU matmuls (LLM.int8()-style vector-"
                        "wise mode, no outlier decomposition)")
    p.add_argument("--kv-cache-int8", action="store_true",
                   help="store the KV cache int8 with per-vector scales: "
                        "half the cache HBM (longer contexts / bigger "
                        "batches on one chip), s8 decode attention dots")
    _add_fleet_flags(p, with_models=False)
    _add_multihost_flag(p)


def _positive_int(text: str) -> int:
    """argparse type for decode budgets: a 0/negative budget would run an
    empty decode scan whose position-0 readout is silently garbage."""
    value = int(text)
    if value < 1:
        raise argparse.ArgumentTypeError(f"{value} is not >= 1")
    return value


def _add_perturb(sub) -> None:
    p = sub.add_parser("perturb", help="perturbation grid sweep (D6)")
    p.add_argument("--checkpoints", type=Path, required=True)
    p.add_argument("--model", required=True)
    p.add_argument("--perturbations", type=Path,
                   default=Path("perturbations.json"))
    p.add_argument("--out", type=Path,
                   default=Path("results/perturbation_results.xlsx"))
    p.add_argument("--subset-size", type=int, default=None)
    p.add_argument("--batch-size", type=int, default=32)
    p.add_argument("--mesh", type=str, default=None)
    p.add_argument("--param-cache", type=Path, default=None)
    p.add_argument("--int8", action="store_true")
    p.add_argument("--int8-dynamic", action="store_true")
    p.add_argument("--kv-cache-int8", action="store_true")
    p.add_argument("--full-completions", action="store_true",
                   help="decode the reference's full 50-token Model "
                        "Response / Model Confidence Response text per "
                        "cell instead of the short 4/8-token budgets — "
                        "exact D6 text parity at ~1/4 the throughput "
                        "(measured 5.8 vs 23.9 p/s/chip; use "
                        "--batch-size 24, batch 40 OOMs with the larger "
                        "cache). Disables the early stops")
    p.add_argument("--sweep-decode-tokens", type=_positive_int,
                   default=None,
                   help="binary-format decode budget per cell (default 4; "
                        "the numeric readout consumes position 0 only)")
    p.add_argument("--sweep-confidence-tokens", type=_positive_int,
                   default=None,
                   help="confidence-format decode budget per cell "
                        "(default 8 — covers the measured answer "
                        "positions, SCALE.md; with the early stop armed a "
                        "generous budget costs actual response length, "
                        "so size this for the WORST answer)")
    p.add_argument("--no-early-stop", action="store_true",
                   help="disable the digit/EOS early stops and always "
                        "decode the full budgets (stops change no "
                        "recorded value — PARITY.md; this flag exists "
                        "for measurement, not correctness)")
    p.add_argument("--prefix-cache", action="store_true",
                   help="enable the cross-request radix prefix cache for "
                        "the OFFLINE sweep (paged KV pool + radix tree; "
                        "serving enables it by default): repeated grids "
                        "on one engine resume shared prefixes from the "
                        "page pool, bitwise-identical results")
    p.add_argument("--no-row-artifact", action="store_true",
                   help="with streaming stats ON, skip materializing "
                        "the per-row csv/xlsx artifact entirely: the "
                        "sweep transfers NO per-row payloads through "
                        "the host — distributions come straight off "
                        "the device accumulator (resume runs on the "
                        "manifest + accumulator checkpoint). CSV stays "
                        "the schema-parity default (DEPLOY.md §1j)")
    p.add_argument("--lease-shards", action="store_true",
                   help="lease-based work-stealing shards instead of "
                        "the static host split: shard ownership rides "
                        "lease records ({holder, expiry} __meta__ "
                        "lines in a shared <results>.leases.jsonl), "
                        "renewed at every flush; a live host steals "
                        "shards whose lease expired, so a slow or "
                        "dead host rebalances instead of strangling "
                        "the shard fence (DEPLOY.md §1m; pair with "
                        "--no-row-artifact on pods)")
    p.add_argument("--lease-ttl", type=float, default=None,
                   help="shard-lease time-to-live in wall-clock "
                        "seconds (default 300): a lease older than "
                        "this is stealable — size it a few flush "
                        "intervals above the slowest healthy shard")
    p.add_argument("--lease-cells", type=int, default=None,
                   help="grid cells per leased shard (the stealing "
                        "granularity; default 0 derives ~4 shards per "
                        "host)")
    _add_prefix_pool_flags(p)
    _add_engine_tuning_flags(p)
    _add_guard_flags(p)
    _add_governor_flags(p)
    _add_kernel_flags(p)
    _add_spec_flags(p)
    _add_cascade_flags(p)
    _add_trace_flags(p)
    p.add_argument("--barrier-timeout", type=float, default=None,
                   help="multihost liveness bound in seconds: a shard-"
                        "boundary barrier a peer never reaches raises "
                        "HostDesyncError (resumable exit) instead of "
                        "hanging forever (default 900; <= 0 restores "
                        "unbounded barriers)")
    _add_multihost_flag(p)


def _add_prefix_pool_flags(p) -> None:
    """Page-pool sizing knobs for the cross-request prefix cache
    (models/paged.py + engine/prefix_tree.py), shared by perturb and
    serve."""
    p.add_argument("--prefix-cache-pages", type=_positive_int, default=None,
                   help="KV page pool size in pages (default 512; each "
                        "page holds --prefix-page-size token positions "
                        "and costs models/paged.kv_page_bytes of HBM — "
                        "DEPLOY.md §1g sizing arithmetic)")
    p.add_argument("--prefix-page-size", type=_positive_int, default=None,
                   help="token positions per KV page (default 16; also "
                        "the radix tree's edge granularity — prefixes "
                        "cache in full pages, tails recompute)")


def _prefix_rt_kw(args, rt_kw: dict) -> None:
    if getattr(args, "prefix_cache", False):
        rt_kw["prefix_cache"] = True
    if getattr(args, "prefix_cache_pages", None) is not None:
        rt_kw["prefix_cache_pages"] = args.prefix_cache_pages
    if getattr(args, "prefix_page_size", None) is not None:
        rt_kw["prefix_page_size"] = args.prefix_page_size


def _add_engine_tuning_flags(p) -> None:
    """Engine-shape knobs (RuntimeConfig) shared by perturb and serve —
    surfaced so no config field needs a source edit to change
    (lint/configdrift.py enforces the coverage)."""
    p.add_argument("--max-seq-len", type=_positive_int, default=None,
                   help="prompt-length ceiling in tokens (default 1024): "
                        "tops the bucket ladder and sizes every KV "
                        "cache; legal prompt + format is ≲700 tokens")
    p.add_argument("--max-new-tokens", type=_positive_int, default=None,
                   help="full-completion decode budget (default 50; the "
                        "short sweep budgets are --sweep-decode-tokens/"
                        "--sweep-confidence-tokens — this one gates "
                        "--full-completions text parity and rephrasing)")
    p.add_argument("--no-ragged-scheduler", action="store_true",
                   help="disable the ragged bucket-ladder scheduler and "
                        "restore legacy todo-order batching (every "
                        "mixed-length batch pads to its longest row — "
                        "the bench's single-bucket baseline; results "
                        "identical per cell)")
    p.add_argument("--sweep-group-min-prefix", type=_positive_int,
                   default=None,
                   help="cross-cell prefix grouping: minimum shared "
                        "leading tokens (default 16; see DEPLOY.md §1b)")
    p.add_argument("--sweep-group-min-cells", type=int, default=None,
                   help="cross-cell prefix grouping: minimum cells per "
                        "group (default 4; 0 disables grouping)")
    p.add_argument("--no-aot-precompile", action="store_true",
                   help="disable background AOT precompilation of the "
                        "planned dispatch shapes (every shape then pays "
                        "lazy trace-on-first-call inside the sweep)")
    p.add_argument("--precompile-workers", type=int, default=None,
                   help="AOT precompile thread count (default 0 = one "
                        "per CPU core, capped at the shape count)")
    p.add_argument("--dtype", default=None,
                   choices=["bfloat16", "float32", "float16"],
                   help="parameter/activation dtype on device (default "
                        "bfloat16; float32 for parity audits — "
                        "DEPLOY.md §1a)")
    p.add_argument("--logits-dtype", default=None,
                   choices=["float32", "bfloat16"],
                   help="final-logits accumulation dtype (default "
                        "float32; the softmax readouts assume fp32 "
                        "accuracy — lower only for measurement)")
    p.add_argument("--scan-positions", type=_positive_int, default=None,
                   help="generated positions scanned for the yes/no "
                        "top-k match (default 10 = the reference's "
                        "MAX_LOOK_AHEAD; the D6 sweep reads position 0 "
                        "regardless)")
    p.add_argument("--topk-match", type=_positive_int, default=None,
                   help="top-k membership rule for the scan-position "
                        "readout (default 2 = the reference's top-2 "
                        "rule)")
    p.add_argument("--remat", action="store_true",
                   help="jax.checkpoint the decoder blocks "
                        "(rematerialize activations — slower, fits "
                        "bigger models per chip)")
    p.add_argument("--no-streaming-stats", action="store_true",
                   help="disable the device-resident streaming-"
                        "statistics sink (per-dispatch accumulator "
                        "fold, live percentile/kappa estimates, "
                        "accumulator checkpoints); analysis then runs "
                        "only off the row artifact (DEPLOY.md §1j)")


def _engine_rt_kw(args, rt_kw: dict) -> None:
    if getattr(args, "max_seq_len", None) is not None:
        rt_kw["max_seq_len"] = args.max_seq_len
    if getattr(args, "max_new_tokens", None) is not None:
        rt_kw["max_new_tokens"] = args.max_new_tokens
    if getattr(args, "no_ragged_scheduler", False):
        rt_kw["ragged_scheduler"] = False
    if getattr(args, "sweep_group_min_prefix", None) is not None:
        rt_kw["sweep_group_min_prefix"] = args.sweep_group_min_prefix
    if getattr(args, "sweep_group_min_cells", None) is not None:
        rt_kw["sweep_group_min_cells"] = args.sweep_group_min_cells
    if getattr(args, "no_aot_precompile", False):
        rt_kw["aot_precompile"] = False
    if getattr(args, "precompile_workers", None) is not None:
        rt_kw["precompile_workers"] = args.precompile_workers
    if getattr(args, "dtype", None) is not None:
        rt_kw["dtype"] = args.dtype
    if getattr(args, "logits_dtype", None) is not None:
        rt_kw["logits_dtype"] = args.logits_dtype
    if getattr(args, "scan_positions", None) is not None:
        rt_kw["scan_positions"] = args.scan_positions
    if getattr(args, "topk_match", None) is not None:
        rt_kw["topk_match"] = args.topk_match
    if getattr(args, "remat", False):
        rt_kw["remat"] = True
    if getattr(args, "no_streaming_stats", False):
        rt_kw["streaming_stats"] = False


def _add_fleet_flags(p, with_models: bool) -> None:
    """Multi-model fleet knobs (config.FleetConfig — engine/fleet.py
    over models/weights.py; DEPLOY.md §1k)."""
    if with_models:
        p.add_argument("--fleet-models", default=None,
                       help="comma-separated model ids to serve as a "
                            "FLEET: all models co-resident up to the "
                            "weight-cache budget, per-model dispatch "
                            "queues, and the {\"op\": \"fleet_score\"} "
                            "request class — one question scored under "
                            "every model, answered with per-model "
                            "P(yes)/P(no) plus pairwise kappa/"
                            "disagreement (DEPLOY.md §1k)")
        p.add_argument("--fleet-deadline", type=float, default=None,
                       help="default deadline in seconds for fleet_score "
                            "fan-outs (default 60; per-request "
                            "\"deadline_s\" overrides)")
    p.add_argument("--weight-cache-gb", type=float, default=None,
                   help="HBM budget for co-resident model weights in the "
                        "fleet's LRU weight cache (default 0 = "
                        "unbounded; size it so budget >= largest model, "
                        "see DEPLOY.md §1k arithmetic)")
    p.add_argument("--no-weight-prefetch", action="store_true",
                   help="disable async weight streaming: every model "
                        "swap then serializes its host->device load "
                        "with compute (the pre-fleet drop-and-reload "
                        "behavior; measurement baseline)")


def _add_kernel_flags(p) -> None:
    """Fused-kernel knobs (ops/flash_decode + piggybacking), shared by
    perturb and serve (precompile follows the serving defaults)."""
    p.add_argument("--no-fused-decode", action="store_true",
                   help="disable the fused Pallas flash-decode kernel and "
                        "restore the dense decode-attention lowering "
                        "exactly (the pre-PR7 path; greedy results are "
                        "argmax-identical either way)")
    p.add_argument("--no-piggyback", action="store_true",
                   help="disable chunked prefill/decode piggybacking "
                        "(each dispatch then runs its own prefill + "
                        "decode call; results identical)")


def _kernel_rt_kw(args, rt_kw: dict) -> None:
    if getattr(args, "no_fused_decode", False):
        rt_kw["fused_decode"] = False
    if getattr(args, "no_piggyback", False):
        rt_kw["piggyback_prefill"] = False


def _add_spec_flags(p) -> None:
    """Speculative-decode knobs (engine/spec.py + RuntimeConfig.
    spec_decode/spec_k/spec_draft_model, Config.spec SpecConfig),
    shared by perturb and serve."""
    p.add_argument("--no-spec-decode", action="store_true",
                   help="disable speculative scoring decode (draft k "
                        "tokens, verify in one multi-query forward; ON "
                        "by default for self-drafting — consumed "
                        "results are bitwise either way, DEPLOY.md §1n)")
    p.add_argument("--spec-k", type=_positive_int, default=None,
                   help="speculative verify window: tokens checked per "
                        "verify forward (1 emission + up to k-1 drafts; "
                        "default 4, < 2 disables)")
    p.add_argument("--spec-draft-model", type=str, default=None,
                   help="fleet model id that DRAFTS for the scored "
                        "model (same tokenizer required; acquired "
                        "through the weight cache so drafting never "
                        "evicts the verifier). Empty = self-drafting "
                        "(radix-tree + n-gram prompt lookup)")
    p.add_argument("--spec-ngram", type=_positive_int, default=None,
                   help="n-gram match length for the prompt-lookup "
                        "fallback drafter (default 2)")
    p.add_argument("--no-spec-tree-probe", action="store_true",
                   help="skip the radix prefix tree's token-history "
                        "continuation probe when drafting (n-gram "
                        "lookup only)")
    p.add_argument("--spec-tree-tails", type=_positive_int, default=None,
                   help="continuation tails recorded per radix node for "
                        "drafting, LRU beyond this (default 32; host "
                        "memory only)")


def _spec_rt_kw(args, rt_kw: dict) -> None:
    if getattr(args, "no_spec_decode", False):
        rt_kw["spec_decode"] = False
    if getattr(args, "spec_k", None) is not None:
        rt_kw["spec_k"] = args.spec_k
    if getattr(args, "spec_draft_model", None) is not None:
        rt_kw["spec_draft_model"] = args.spec_draft_model


def _spec_config_from_args(args):
    from .config import SpecConfig

    kw = {}
    if getattr(args, "spec_ngram", None) is not None:
        kw["ngram"] = args.spec_ngram
    if getattr(args, "no_spec_tree_probe", False):
        kw["tree_probe"] = False
    if getattr(args, "spec_tree_tails", None) is not None:
        kw["tree_tails_per_node"] = args.spec_tree_tails
    return SpecConfig(**kw)


def _add_cascade_flags(p) -> None:
    """Shared-prefix cascade-prefill knobs (ops/cascade_prefill +
    RuntimeConfig.cascade_prefill, Config.cascade CascadeConfig),
    shared by perturb and serve (DEPLOY.md §1q)."""
    p.add_argument("--no-cascade-prefill", action="store_true",
                   help="disable shared-prefix cascade prefill and "
                        "restore the dense shared-dispatch path exactly "
                        "(cascade results are argmax-identical; dense is "
                        "the measurement baseline)")
    p.add_argument("--cascade-min-trunk", type=_positive_int,
                   default=None,
                   help="shortest shared trunk (tokens, post-snap) worth "
                        "the cascade split; shorter trunks dispatch "
                        "densely (default 32 — below it the extra "
                        "launch + merge beats the deduped prefill)")
    p.add_argument("--cascade-trunk-quantum", type=_positive_int,
                   default=None,
                   help="trunk lengths snap DOWN to this multiple so "
                        "near-identical prefixes share one compiled "
                        "cascade shape (default 16)")
    p.add_argument("--cascade-min-rows", type=_positive_int,
                   default=None,
                   help="fewest real rows sharing the trunk before "
                        "cascade engages (default 2; one row has "
                        "nothing to dedupe)")
    p.add_argument("--cascade-int8-qk", action="store_true",
                   help="quantize the cascade prefix leg's QK^T to int8 "
                        "inside the kernel (models/quant.py scales; "
                        "softmax + PV stay fp32 — tolerance-bound, "
                        "argmax-identical in tests)")
    p.add_argument("--no-cascade-decode", action="store_true",
                   help="disable the trunk-aware flash-decode split "
                        "dedup and restore the flat decode kernels "
                        "exactly (cascade-decode payloads are BITWISE "
                        "the flat kernels'; flat is the measurement "
                        "baseline — DEPLOY.md §1r)")
    p.add_argument("--no-cascade-fused-suffix", action="store_true",
                   help="run the cascade prefill as two kernel launches "
                        "plus an HBM merge round-trip instead of the "
                        "fused single-kernel path (bitwise-identical "
                        "results; the two-leg path is the fused "
                        "kernel's verification baseline)")


def _cascade_rt_kw(args, rt_kw: dict) -> None:
    if getattr(args, "no_cascade_prefill", False):
        rt_kw["cascade_prefill"] = False
    if getattr(args, "no_cascade_decode", False):
        rt_kw["cascade_decode"] = False
    if getattr(args, "no_cascade_fused_suffix", False):
        rt_kw["cascade_fused_suffix"] = False


def _cascade_config_from_args(args):
    from .config import CascadeConfig

    kw = {}
    if getattr(args, "cascade_min_trunk", None) is not None:
        kw["min_trunk"] = args.cascade_min_trunk
    if getattr(args, "cascade_trunk_quantum", None) is not None:
        kw["trunk_quantum"] = args.cascade_trunk_quantum
    if getattr(args, "cascade_min_rows", None) is not None:
        kw["min_rows"] = args.cascade_min_rows
    if getattr(args, "cascade_int8_qk", False):
        kw["int8_qk"] = True
    return CascadeConfig(**kw)


def _add_trace_flags(p) -> None:
    """Structured-tracing knobs (lir_tpu/observe/tracing.py), shared by
    perturb and serve."""
    p.add_argument("--trace-out", type=Path, default=None,
                   help="record per-request/per-dispatch trace spans "
                        "(admit -> queue -> batch-form -> dispatch -> "
                        "readout -> resolve, weight swaps, stream "
                        "folds) and write Chrome/Perfetto trace-event "
                        "JSON here at exit — open in chrome://tracing "
                        "or ui.perfetto.dev; span names match the "
                        "jax.profiler device-trace annotations")
    p.add_argument("--trace-buffer", type=int, default=None,
                   help="trace-span ring capacity (default 65536; "
                        "oldest spans drop beyond it, drops counted in "
                        "the metrics snapshot)")


def _add_router_flags(p) -> None:
    """Elastic multi-replica router knobs (config.RouterConfig —
    serve/router.py; DEPLOY.md §1m)."""
    p.add_argument("--replicas", type=int, default=None,
                   help="run N in-process replica servers behind the "
                        "failover router (single-model serving): "
                        "queue-depth/breaker-aware placement, "
                        "exactly-once failover of a dead replica's "
                        "in-flight requests, deadline-whisker hedging "
                        "(default 1 = no router)")
    p.add_argument("--hedge-threshold", type=float, default=None,
                   help="hedge whisker in seconds: an in-flight "
                        "request this close to its deadline is "
                        "duplicated onto a second replica, first "
                        "payload wins (default 0 = hedging off)")
    p.add_argument("--replica-failure-threshold", type=int, default=None,
                   help="consecutive error results from one replica "
                        "before its router-side breaker opens "
                        "(default 2)")
    p.add_argument("--replica-cooldown", type=float, default=None,
                   help="router-side replica breaker open->half-open "
                        "cooldown in seconds (default 5; monotonic-"
                        "clocked — wall steps can't hold it open)")
    p.add_argument("--residency-bonus", type=float, default=None,
                   help="placement bonus (queue-row equivalents) for a "
                        "replica whose WeightCache already holds the "
                        "request's model (default 8)")
    p.add_argument("--pressure-weight", type=float, default=None,
                   help="placement penalty (queue-row equivalents) per "
                        "unit of a replica's HBM-governor pressure — "
                        "memory as a routing signal (default 6; "
                        "0 disables)")
    p.add_argument("--slo-wait-weight", type=float, default=None,
                   help="SLO placement term: weight on a replica's "
                        "oldest queued-row wait relative to the "
                        "request's remaining deadline (default 4; "
                        "0 disables)")
    p.add_argument("--router-tick", type=float, default=None,
                   help="router supervisor tick in seconds (hedging "
                        "scans + breaker promotion; default 0.02)")
    p.add_argument("--router-cache-entries", type=int, default=None,
                   help="router-level content-addressed dedup cache "
                        "capacity — the exactly-once backstop against "
                        "zombie-replica payloads (default 4096; "
                        "0 disables)")


def _router_cfg(args):
    """RouterConfig from the flags (None = dataclass default)."""
    from .config import RouterConfig

    kw = {}
    if getattr(args, "replicas", None) is not None:
        kw["replicas"] = args.replicas
    if getattr(args, "hedge_threshold", None) is not None:
        kw["hedge_s"] = args.hedge_threshold
    if getattr(args, "replica_failure_threshold", None) is not None:
        kw["replica_failure_threshold"] = args.replica_failure_threshold
    if getattr(args, "replica_cooldown", None) is not None:
        kw["replica_cooldown_s"] = args.replica_cooldown
    if getattr(args, "residency_bonus", None) is not None:
        kw["residency_bonus"] = args.residency_bonus
    if getattr(args, "slo_wait_weight", None) is not None:
        kw["slo_wait_weight"] = args.slo_wait_weight
    if getattr(args, "pressure_weight", None) is not None:
        kw["pressure_weight"] = args.pressure_weight
    if getattr(args, "router_tick", None) is not None:
        kw["tick_s"] = args.router_tick
    if getattr(args, "router_cache_entries", None) is not None:
        kw["cache_entries"] = args.router_cache_entries
    return RouterConfig(**kw)


def _add_migrate_flags(p) -> None:
    """Disaggregated prefill/decode serving knobs
    (config.MigrationConfig — serve/migrate.py; DEPLOY.md §1p)."""
    p.add_argument("--no-migrate", action="store_true",
                   help="disable KV-page migration + disaggregated "
                        "placement entirely (MigrationConfig.enabled; "
                        "restores the role-less replica router)")
    p.add_argument("--migrate-prefill-replicas", type=int, default=None,
                   help="of --replicas N, dedicate the first K to the "
                        "PREFILL role: long prompts prefill there and "
                        "their KV pages migrate to decode-role "
                        "replicas (default 0 = colocated)")
    p.add_argument("--migrate-chunk-pages", type=int, default=None,
                   help="KV pages per transfer chunk of the double-"
                        "buffered page migration (default 8)")
    p.add_argument("--migrate-inflight-chunks", type=int, default=None,
                   help="transfer chunks kept in flight (default 2 = "
                        "double buffering)")
    p.add_argument("--migrate-min-prefix", type=int, default=None,
                   help="minimum tokenized shared-prefix length worth "
                        "a remote prefill + migration; shorter prompts "
                        "score colocated (default 32)")
    p.add_argument("--migrate-page-bonus", type=float, default=None,
                   help="placement bonus (queue-row equivalents) per "
                        "cluster-index-matched page a replica already "
                        "holds for the request's prefix (default 0.5)")
    p.add_argument("--no-migrate-verify", action="store_true",
                   help="skip the per-chunk transfer checksums "
                        "(MigrationConfig.verify) — corruption then "
                        "lands undetected; only for measurement")
    p.add_argument("--migrate-timeout", type=float, default=None,
                   help="wall-clock budget in seconds for one whole "
                        "migration chain before the router falls back "
                        "to local re-prefill (default 30)")


def _migrate_cfg(args):
    """MigrationConfig from the flags (None = dataclass default)."""
    from .config import MigrationConfig

    kw = {}
    if getattr(args, "no_migrate", False):
        kw["enabled"] = False
    if getattr(args, "migrate_prefill_replicas", None) is not None:
        kw["prefill_replicas"] = args.migrate_prefill_replicas
    if getattr(args, "migrate_chunk_pages", None) is not None:
        kw["chunk_pages"] = args.migrate_chunk_pages
    if getattr(args, "migrate_inflight_chunks", None) is not None:
        kw["inflight_chunks"] = args.migrate_inflight_chunks
    if getattr(args, "migrate_min_prefix", None) is not None:
        kw["min_prefix_tokens"] = args.migrate_min_prefix
    if getattr(args, "migrate_page_bonus", None) is not None:
        kw["page_bonus"] = args.migrate_page_bonus
    if getattr(args, "no_migrate_verify", False):
        kw["verify"] = False
    if getattr(args, "migrate_timeout", None) is not None:
        kw["timeout_s"] = args.migrate_timeout
    return MigrationConfig(**kw)


def _add_tier_flags(p) -> None:
    """Tiered-memory knobs (config.TierConfig — serve/tiers.py;
    DEPLOY.md §1s)."""
    p.add_argument("--tiered", action="store_true",
                   help="enable the tiered memory ladder "
                        "(TierConfig.enabled): the HBM governor's "
                        "reclaim rungs demote KV radix pages and idle "
                        "fleet weights to pinned host DRAM and local "
                        "disk instead of deleting them; promotes ride "
                        "the checksummed paged-warm import (bitwise)")
    p.add_argument("--tier-host-mb", type=float, default=None,
                   help="host-DRAM tier budget in MiB "
                        "(TierConfig.host_budget_mb, default 256); "
                        "overflow spills to the disk tier, LRU first")
    p.add_argument("--tier-disk-dir", type=str, default=None,
                   help="local directory for the disk tier "
                        "(TierConfig.disk_dir; empty = host tier only, "
                        "no spill and no restart-warm)")
    p.add_argument("--tier-disk-mb", type=float, default=None,
                   help="disk tier budget in MiB "
                        "(TierConfig.disk_budget_mb, default 1024); "
                        "oldest entries drop at the budget")
    p.add_argument("--tier-demote-pages", type=int, default=None,
                   help="max KV pages one evict_pages rung engagement "
                        "demotes (TierConfig.demote_pages_per_step, "
                        "default 32)")
    p.add_argument("--no-tier-verify", action="store_true",
                   help="skip promote-side chunk checksums "
                        "(TierConfig.verify) — tier corruption then "
                        "lands undetected; only for measurement")
    p.add_argument("--tier-disk-timeout", type=float, default=None,
                   help="seconds a disk-tier promote may take before "
                        "the store abandons it and the request "
                        "re-prefills (TierConfig.disk_timeout_s, "
                        "default 10)")
    p.add_argument("--no-restart-warm", action="store_true",
                   help="do NOT reseed the radix tree / weight cache "
                        "from the disk tier at server construction "
                        "(TierConfig.restart_warm)")
    p.add_argument("--tier-host-bonus", type=float, default=None,
                   help="placement price of one host-tier page in "
                        "HBM-page equivalents (TierConfig.host_bonus, "
                        "default 0.5)")
    p.add_argument("--tier-disk-bonus", type=float, default=None,
                   help="placement price of one disk-tier page in "
                        "HBM-page equivalents (TierConfig.disk_bonus, "
                        "default 0.25)")


def _tier_cfg(args):
    """TierConfig from the flags (None = dataclass default)."""
    from .config import TierConfig

    kw = {}
    if getattr(args, "tiered", False):
        kw["enabled"] = True
    if getattr(args, "tier_host_mb", None) is not None:
        kw["host_budget_mb"] = args.tier_host_mb
    if getattr(args, "tier_disk_dir", None) is not None:
        kw["disk_dir"] = args.tier_disk_dir
    if getattr(args, "tier_disk_mb", None) is not None:
        kw["disk_budget_mb"] = args.tier_disk_mb
    if getattr(args, "tier_demote_pages", None) is not None:
        kw["demote_pages_per_step"] = args.tier_demote_pages
    if getattr(args, "no_tier_verify", False):
        kw["verify"] = False
    if getattr(args, "tier_disk_timeout", None) is not None:
        kw["disk_timeout_s"] = args.tier_disk_timeout
    if getattr(args, "no_restart_warm", False):
        kw["restart_warm"] = False
    if getattr(args, "tier_host_bonus", None) is not None:
        kw["host_bonus"] = args.tier_host_bonus
    if getattr(args, "tier_disk_bonus", None) is not None:
        kw["disk_bonus"] = args.tier_disk_bonus
    return TierConfig(**kw)


def _add_observatory_flags(p) -> None:
    """Reliability-observatory knobs (lir_tpu/observe; fleet serving
    only — the sentinel grid fans across every fleet model)."""
    p.add_argument("--sentinels", type=Path, default=None,
                   help="JSONL sentinel grid ({\"prompt\": ...} or "
                        "{\"binary_prompt\", \"confidence_prompt\"}, "
                        "optional \"targets\") re-scored across the "
                        "whole fleet on --sentinel-interval and on any "
                        "weight-cache residency change; per-window "
                        "kappa/CI/mean drift alerts ride the stats "
                        "endpoint (DEPLOY.md §1l)")
    p.add_argument("--sentinel-interval", type=float, default=None,
                   help="seconds between scheduled sentinel sweeps "
                        "(default 60)")
    p.add_argument("--sentinel-window", type=float, default=None,
                   help="drift-window width in seconds (default 600): "
                        "sweeps in one window fold into one "
                        "accumulator lattice; kappa/CI/mean compare "
                        "ACROSS windows")
    p.add_argument("--sentinel-max-sweeps", type=int, default=None,
                   help="lattice capacity in sweeps per window "
                        "(default 32; a full window skips further "
                        "sweeps loudly rather than overwriting slots)")
    p.add_argument("--drift-sigma", type=float, default=None,
                   help="alert threshold: |window metric - baseline "
                        "mean| > sigma * max(std, floor) (default 3)")
    p.add_argument("--drift-min-windows", type=int, default=None,
                   help="clean windows required before drift detection "
                        "arms (default 2)")
    p.add_argument("--observe-history", type=int, default=None,
                   help="window lattices kept on device / summaries "
                        "queryable (default 64; oldest drop beyond it)")


def _observe_cfg(args):
    """ObserveConfig from the flags (None = dataclass default)."""
    from .config import ObserveConfig

    kw = {}
    if getattr(args, "sentinel_interval", None) is not None:
        kw["sentinel_interval_s"] = args.sentinel_interval
    if getattr(args, "sentinel_window", None) is not None:
        kw["sentinel_window_s"] = args.sentinel_window
    if getattr(args, "sentinel_max_sweeps", None) is not None:
        kw["max_sweeps_per_window"] = args.sentinel_max_sweeps
    if getattr(args, "drift_sigma", None) is not None:
        kw["drift_sigma"] = args.drift_sigma
    if getattr(args, "drift_min_windows", None) is not None:
        kw["drift_min_windows"] = args.drift_min_windows
    if getattr(args, "observe_history", None) is not None:
        kw["history_windows"] = args.observe_history
    if getattr(args, "trace_buffer", None) is not None:
        kw["trace_buffer"] = args.trace_buffer
    return ObserveConfig(**kw)


def _maybe_start_tracing(args):
    """Install the process trace recorder under --trace-out; returns it
    (or None). The caller exports at exit."""
    if getattr(args, "trace_out", None) is None:
        return None
    from .observe import tracing

    rec = tracing.TraceRecorder(capacity=_observe_cfg(args).trace_buffer)
    tracing.set_recorder(rec)
    return rec


def _finish_tracing(rec, args) -> None:
    if rec is None:
        return
    rec.export_chrome(args.trace_out)
    log.info("trace: wrote %d spans (%d dropped) -> %s", len(rec),
             rec.dropped, args.trace_out)


def _add_governor_flags(p) -> None:
    """Unified HBM-governor knobs (config.GovernorConfig —
    engine/hbm.py; DEPLOY.md §1o), shared by perturb and serve."""
    p.add_argument("--no-hbm-governor", action="store_true",
                   help="disable the unified HBM governor (enabled): "
                        "no ledger, no degradation ladder, OOMs "
                        "re-raise raw — the pre-governor baseline")
    p.add_argument("--hbm-budget-gb", type=float, default=None,
                   help="governed HBM budget in GiB (hbm_budget_gb; "
                        "default 0 derives it from the device "
                        "bytes_limit minus the reserve; on CPU 0 "
                        "means unbounded — the ladder never engages)")
    p.add_argument("--hbm-reserve-frac", type=float, default=None,
                   help="fraction of the device limit held back from "
                        "a derived budget (hbm_reserve_frac, default "
                        "0.08 — runtime scratch + fragmentation slack)")
    p.add_argument("--hbm-engage-pressure", type=float, default=None,
                   help="ledger/budget pressure at which the "
                        "degradation ladder engages its next rung "
                        "(engage_pressure, default 0.9)")
    p.add_argument("--hbm-hysteresis", type=float, default=None,
                   help="release band below the engage pressure "
                        "(hysteresis, default 0.15): rungs re-arm "
                        "below engage - hysteresis, so the ladder "
                        "can never flap on one threshold")
    p.add_argument("--hbm-sustain-ticks", type=int, default=None,
                   help="consecutive over-pressure dispatch ticks "
                        "before a rung engages (sustain_ticks, "
                        "default 2 — spikes don't walk the ladder, "
                        "sustained pressure does)")
    p.add_argument("--hbm-evict-pages", type=int, default=None,
                   help="radix pages evicted per evict_pages rung "
                        "engagement (evict_pages_per_step, default 32)")


def _governor_cfg(args):
    """GovernorConfig from the flags (None = dataclass default)."""
    from .config import GovernorConfig

    kw = {}
    if getattr(args, "no_hbm_governor", False):
        kw["enabled"] = False
    if getattr(args, "hbm_budget_gb", None) is not None:
        kw["hbm_budget_gb"] = args.hbm_budget_gb
    if getattr(args, "hbm_reserve_frac", None) is not None:
        kw["hbm_reserve_frac"] = args.hbm_reserve_frac
    if getattr(args, "hbm_engage_pressure", None) is not None:
        kw["engage_pressure"] = args.hbm_engage_pressure
    if getattr(args, "hbm_hysteresis", None) is not None:
        kw["hysteresis"] = args.hbm_hysteresis
    if getattr(args, "hbm_sustain_ticks", None) is not None:
        kw["sustain_ticks"] = args.hbm_sustain_ticks
    if getattr(args, "hbm_evict_pages", None) is not None:
        kw["evict_pages_per_step"] = args.hbm_evict_pages
    return GovernorConfig(**kw)


def _add_guard_flags(p) -> None:
    """Guard-layer knobs (lir_tpu/guard) shared by perturb and serve."""
    p.add_argument("--watchdog-multiple", type=float, default=None,
                   help="dispatch watchdog deadline = floor + multiple x "
                        "predicted dispatch seconds (bucket_cost-priced, "
                        "self-calibrated; default 20). <= 0 disables "
                        "stall detection")
    p.add_argument("--watchdog-floor", type=float, default=None,
                   help="hard minimum watchdog deadline in seconds "
                        "(default 30) — the safety margin a noisy "
                        "calibration can never undercut")
    p.add_argument("--no-numerics-guard", action="store_true",
                   help="disable the score-extraction numerics guard "
                        "(NaN/Inf/out-of-range rows are then written "
                        "verbatim instead of quarantined as "
                        "error:numerics — measurement only)")


def _add_precompile(sub) -> None:
    p = sub.add_parser(
        "precompile",
        help="warm the compile cache for a model/ladder ahead of serving: "
             "AOT-compile every bucket-ladder executable (in parallel) "
             "into the persistent cache, so the serving process — or "
             "every restarted/autoscaled worker — deserializes instead "
             "of compiling. Run once per host (caches are per-host).")
    p.add_argument("--checkpoints", type=Path, required=True)
    p.add_argument("--model", required=True)
    p.add_argument("--batch-size", type=int, default=32)
    p.add_argument("--mesh", type=str, default=None)
    p.add_argument("--param-cache", type=Path, default=None)
    p.add_argument("--int8", action="store_true")
    p.add_argument("--int8-dynamic", action="store_true")
    p.add_argument("--kv-cache-int8", action="store_true")
    p.add_argument("--sweep-decode-tokens", type=_positive_int, default=None)
    p.add_argument("--sweep-confidence-tokens", type=_positive_int,
                   default=None)
    p.add_argument("--sfx-buckets", default="8,16",
                   help="suffix bucket edges to warm per ladder edge "
                        "(default 8,16 — the edges short sweep format "
                        "instructions land in)")
    p.add_argument("--workers", type=int, default=0,
                   help="parallel compile threads (0 = one per core)")


def _add_serve(sub) -> None:
    p = sub.add_parser(
        "serve",
        help="online scoring service: continuous-batching request queue "
             "over the bucket ladder (lir_tpu/serve). Reads JSONL "
             "requests from --requests (default stdin), writes one JSONL "
             "result per line to stdout, ServeStats to stderr on exit. "
             "Request lines: {\"id\", \"binary_prompt\", "
             "\"confidence_prompt\"} or {\"prompt\"} with optional "
             "\"response_format\"/\"confidence_format\", plus optional "
             "\"targets\": [t1, t2], \"class\", \"deadline_s\". With "
             "--fleet-models, lines score under EVERY fleet model "
             "({\"op\": \"fleet_score\"} or any line without a "
             "\"model\" key) and return per-model P(yes)/P(no) plus "
             "pairwise kappa/disagreement; a \"model\" key routes a "
             "line to that one model's dispatch queue")
    p.add_argument("--checkpoints", type=Path, required=True)
    p.add_argument("--model", default=None,
                   help="single-model serving (the full ScoringServer: "
                        "breaker/ladder/checkpoint); exactly one of "
                        "--model / --fleet-models is required")
    p.add_argument("--batch-size", type=int, default=32)
    p.add_argument("--mesh", type=str, default=None)
    p.add_argument("--param-cache", type=Path, default=None)
    p.add_argument("--int8", action="store_true")
    p.add_argument("--int8-dynamic", action="store_true")
    p.add_argument("--kv-cache-int8", action="store_true")
    p.add_argument("--sweep-decode-tokens", type=_positive_int, default=None)
    p.add_argument("--sweep-confidence-tokens", type=_positive_int,
                   default=None)
    p.add_argument("--requests", type=str, default="-",
                   help="JSONL request file, or '-' for stdin (default)")
    p.add_argument("--queue-depth", type=int, default=256,
                   help="admission-control bound; a submit into a full "
                        "queue sheds the least-urgent request")
    p.add_argument("--linger-ms", type=float, default=20.0,
                   help="continuous-batching window: a partial bucket "
                        "dispatches once its oldest request waited this "
                        "long")
    p.add_argument("--cache-entries", type=int, default=4096,
                   help="content-addressed result cache capacity "
                        "(0 disables dedup)")
    p.add_argument("--deadline", action="append", default=None,
                   metavar="CLASS=SECONDS",
                   help="deadline class override, repeatable (default: "
                        "interactive=10, batch=300)")
    p.add_argument("--no-precompile", action="store_true",
                   help="skip the boot AOT precompile of every "
                        "(ladder, suffix, batch) executable")
    p.add_argument("--breaker-cooldown", type=float, default=30.0,
                   help="circuit-breaker open->half-open cooldown in "
                        "seconds: after max_consecutive_failures the "
                        "server sheds for this long, then probes the "
                        "device with one dispatch and recovers on "
                        "success (DEPLOY.md §1e)")
    p.add_argument("--state-checkpoint", type=Path, default=None,
                   help="crash-consistent state file: SIGTERM stops the "
                        "supervisor and atomically writes every "
                        "unresolved request here; on boot, an existing "
                        "file is re-submitted (dedup-deduplicated "
                        "against anything already served)")
    p.add_argument("--no-prefix-cache", action="store_true",
                   help="disable the cross-request radix prefix cache "
                        "(serving default ON: arriving requests pay "
                        "prefill only for their unshared suffix, results "
                        "bitwise-identical; OFF restores PR-3 exact-"
                        "match dedup only)")
    p.add_argument("--no-pad-full", action="store_true",
                   help="pad serve dispatches to the offline sweep's "
                        "power-of-two tail instead of the full batch "
                        "(saves tail FLOPs, costs extra executables and "
                        "slow tiny-batch programs — DEPLOY.md §1d)")
    p.add_argument("--no-degrade-ladder", action="store_true",
                   help="on a dispatch that exhausts its retries, error "
                        "the whole batch instead of degrading to lazy "
                        "jit and bisecting out poison rows")
    p.add_argument("--max-consecutive-failures", type=_positive_int,
                   default=None,
                   help="full dispatch failures in a row before the "
                        "circuit breaker opens (default 3)")
    p.add_argument("--stream-window", type=int, default=None,
                   help="live streaming-statistics ring size (default "
                        "4096): a JSONL request line {\"op\": "
                        "\"stats\"} returns in-progress percentile/"
                        "kappa estimates over the last N served rows "
                        "without touching the device; 0 disables "
                        "(DEPLOY.md §1j)")
    _add_prefix_pool_flags(p)
    _add_engine_tuning_flags(p)
    _add_guard_flags(p)
    _add_governor_flags(p)
    _add_kernel_flags(p)
    _add_spec_flags(p)
    _add_cascade_flags(p)
    _add_trace_flags(p)
    _add_observatory_flags(p)
    _add_router_flags(p)
    _add_migrate_flags(p)
    _add_tier_flags(p)
    _add_fleet_flags(p, with_models=True)


def _add_rephrase(sub) -> None:
    p = sub.add_parser("rephrase", help="generate perturbations.json locally")
    p.add_argument("--checkpoints", type=Path, required=True)
    p.add_argument("--model", required=True,
                   help="instruct model acting as the rephraser")
    p.add_argument("--out", type=Path, default=Path("perturbations.json"))
    p.add_argument("--sessions", type=int, default=100)
    p.add_argument("--per-session", type=int, default=20)


def _add_analyze(sub) -> None:
    p = sub.add_parser("analyze", help="statistical analyses over artifacts")
    p.add_argument("--perturbation-results", type=Path, default=None,
                   help="D6 workbook -> perturbation distribution suite")
    p.add_argument("--base-csv", type=Path, default=None,
                   help="D1 -> base-vs-instruct deltas")
    p.add_argument("--instruct-csv", type=Path, default=None,
                   help="D2 -> model graph suite (+ kappa combiner when the "
                        "D6 workbook is also given)")
    p.add_argument("--out", type=Path, default=Path("results/analysis"))
    p.add_argument("--no-figures", action="store_true")
    p.add_argument("--n-simulations", type=int, default=100_000)


def _add_repro(sub) -> None:
    p = sub.add_parser(
        "repro",
        help="regenerate the full published analysis from a reference-style "
             "data directory (D1/D2/D3 CSVs) in one shot",
    )
    p.add_argument("--data", type=Path, required=True,
                   help="directory holding model_comparison_results.csv, "
                        "instruct_model_comparison_results.csv, "
                        "word_meaning_survey_results.csv")
    p.add_argument("--perturbation-results", type=Path, default=None,
                   help="optional D6 workbook for the perturbation suite")
    p.add_argument("--out", type=Path, default=Path("results/repro"))
    p.add_argument("--quick", action="store_true")
    p.add_argument("--no-figures", action="store_true")


def _add_lint(sub) -> None:
    from .lint import cli as lint_cli

    p = sub.add_parser(
        "lint",
        help="graft-lint: AST static analysis proving the engine's "
             "JAX/XLA invariants — donation-safety, trace-hazard, "
             "host-sync, lock-discipline, config-drift. Zero new "
             "findings outside tools/lint_baseline.json or exit 1 "
             "(DEPLOY.md §1i). Runs in seconds; wired into `make "
             "verify` and the pre-push hook.")
    lint_cli.build_parser(p)


def cmd_lint(args) -> None:
    from .lint import cli as lint_cli

    sys.exit(lint_cli.run(args))


def _add_survey(sub) -> None:
    p = sub.add_parser("survey", help="human-survey analysis pipeline")
    p.add_argument("--survey", type=Path, required=True)
    p.add_argument("--instruct", type=Path, required=True)
    p.add_argument("--base", type=Path, default=None)
    p.add_argument("--out", type=Path, default=Path("results/survey"))
    p.add_argument("--quick", action="store_true")


def _parse_mesh(spec: Optional[str]):
    if not spec:
        return None
    from .config import MeshConfig

    dims = [int(x) for x in spec.lower().split("x")]
    if len(dims) == 2:
        dims.append(1)
    if len(dims) != 3:
        raise SystemExit(
            f"--mesh must be DATAxMODEL or DATAxMODELxSEQ, got {spec!r}")
    data, model, seq = dims
    return MeshConfig(data=data, model=model, seq=seq)


def _parse_models(items: List[str]):
    from .engine.multi import ModelSpec

    specs = []
    for item in items:
        name, _, kind = item.partition(":")
        specs.append(ModelSpec(name, kind or "instruct"))
    return specs


def cmd_sweep(args) -> None:
    _maybe_init_multihost(args)
    from .config import RuntimeConfig
    from .engine.multi import run_model_comparison_sweep
    from .models.factory import engine_factory

    factory = engine_factory(
        args.checkpoints, RuntimeConfig(batch_size=args.batch_size),
        _parse_mesh(args.mesh), cache_root=args.param_cache,
        quantize_int8=args.int8, int8_dynamic=args.int8_dynamic,
        kv_cache_int8=args.kv_cache_int8,
    )
    run_model_comparison_sweep(
        _parse_models(args.models), factory, args.out,
        sweep_kind=args.sweep_kind,
        weight_prefetch=not args.no_weight_prefetch,
        weight_cache_bytes=(int(args.weight_cache_gb * 2**30)
                            if args.weight_cache_gb else None),
    )


def _guard_rt_kw(args, rt_kw: dict) -> None:
    """Fold the guard-layer flags into a RuntimeConfig kwargs dict."""
    if getattr(args, "watchdog_multiple", None) is not None:
        rt_kw["watchdog_multiple"] = args.watchdog_multiple
    if getattr(args, "watchdog_floor", None) is not None:
        rt_kw["watchdog_floor_s"] = args.watchdog_floor
    if getattr(args, "no_numerics_guard", False):
        rt_kw["numerics_guard"] = False


def cmd_perturb(args) -> None:
    _maybe_init_multihost(args)
    from .config import RuntimeConfig
    from .data.prompts import LEGAL_PROMPTS
    from .engine.rephrase import load_or_generate_perturbations
    from .engine.sweep import run_perturbation_sweep
    from .models.factory import engine_factory

    if args.full_completions and (args.sweep_decode_tokens is not None
                                  or args.sweep_confidence_tokens is not None):
        raise SystemExit(
            "--full-completions decodes the reference's full 50-token "
            "responses unconditionally; it cannot combine with "
            "--sweep-decode-tokens / --sweep-confidence-tokens")
    rt_kw = dict(batch_size=args.batch_size,
                 sweep_full_completions=args.full_completions,
                 sweep_early_stop=not args.no_early_stop)
    if args.sweep_decode_tokens is not None:
        rt_kw["sweep_decode_tokens"] = args.sweep_decode_tokens
    if args.sweep_confidence_tokens is not None:
        rt_kw["sweep_confidence_tokens"] = args.sweep_confidence_tokens
    _engine_rt_kw(args, rt_kw)
    _guard_rt_kw(args, rt_kw)
    _kernel_rt_kw(args, rt_kw)
    _spec_rt_kw(args, rt_kw)
    _cascade_rt_kw(args, rt_kw)
    _prefix_rt_kw(args, rt_kw)
    if args.no_row_artifact:
        rt_kw["row_artifact"] = False
    if args.barrier_timeout is not None:
        rt_kw["barrier_timeout_s"] = args.barrier_timeout
    if args.lease_shards:
        rt_kw["lease_shards"] = True
    if args.lease_ttl is not None:
        rt_kw["lease_ttl_s"] = args.lease_ttl
    if args.lease_cells is not None:
        rt_kw["lease_cells_per_shard"] = args.lease_cells
    factory = engine_factory(
        args.checkpoints,
        RuntimeConfig(**rt_kw),
        _parse_mesh(args.mesh), cache_root=args.param_cache,
        quantize_int8=args.int8, int8_dynamic=args.int8_dynamic,
        kv_cache_int8=args.kv_cache_int8,
        spec_config=_spec_config_from_args(args),
        governor_config=_governor_cfg(args),
        cascade_config=_cascade_config_from_args(args),
    )
    entries = load_or_generate_perturbations(
        args.perturbations, LEGAL_PROMPTS, None
    )
    perturbations = [rephrasings for _, rephrasings in entries]
    rec = _maybe_start_tracing(args)
    engine = factory(args.model)
    try:
        rows = run_perturbation_sweep(
            engine, args.model, LEGAL_PROMPTS, perturbations, args.out,
            subset_size=args.subset_size,
        )
    finally:
        _finish_tracing(rec, args)
    log.info("perturbation sweep wrote %d rows", len(rows))


def cmd_serve(args) -> None:
    import json

    from .config import RuntimeConfig, ServeConfig
    from .data.prompts import LEGAL_PROMPTS
    from .models.factory import engine_factory
    from .serve import ScoringServer, ServeRequest

    rt_kw = dict(batch_size=args.batch_size)
    if args.sweep_decode_tokens is not None:
        rt_kw["sweep_decode_tokens"] = args.sweep_decode_tokens
    if args.sweep_confidence_tokens is not None:
        rt_kw["sweep_confidence_tokens"] = args.sweep_confidence_tokens
    _engine_rt_kw(args, rt_kw)
    _guard_rt_kw(args, rt_kw)
    _kernel_rt_kw(args, rt_kw)
    _spec_rt_kw(args, rt_kw)
    _cascade_rt_kw(args, rt_kw)
    _prefix_rt_kw(args, rt_kw)
    classes = dict(ServeConfig().classes)
    for spec in args.deadline or ():
        name, sep, secs = spec.partition("=")
        try:
            classes[name] = float(secs)
        except ValueError:
            sep = ""
        if not sep or not name:
            raise SystemExit(f"--deadline {spec!r} must be CLASS=SECONDS")
    serve_kw = {}
    if args.max_consecutive_failures is not None:
        serve_kw["max_consecutive_failures"] = args.max_consecutive_failures
    if args.stream_window is not None:
        serve_kw["stream_window"] = args.stream_window
    serve_cfg = ServeConfig(
        queue_depth=args.queue_depth, classes=tuple(classes.items()),
        linger_s=args.linger_ms / 1000.0,
        cache_entries=args.cache_entries,
        breaker_cooldown_s=args.breaker_cooldown,
        prefix_cache=not args.no_prefix_cache,
        pad_full=not args.no_pad_full,
        degrade_ladder=not args.no_degrade_ladder, **serve_kw)
    if bool(args.model) == bool(args.fleet_models):
        raise SystemExit("serve needs exactly one of --model (single-"
                         "model) or --fleet-models (multiplexed fleet)")
    n_replicas = args.replicas if args.replicas is not None else 1
    if n_replicas > 1 and args.fleet_models:
        raise SystemExit("--replicas fronts single-model replica "
                         "servers; combine it with --model (fleet "
                         "replicas: run N fleet serve processes behind "
                         "an external router)")
    if n_replicas > 1 and args.state_checkpoint is not None:
        raise SystemExit("--state-checkpoint is per-server state; with "
                         "--replicas the router's failover replaces it "
                         "(a dead replica's in-flight work re-admits "
                         "to survivors)")
    n_prefill = args.migrate_prefill_replicas or 0
    if n_prefill and n_prefill >= n_replicas:
        raise SystemExit("--migrate-prefill-replicas must leave at "
                         "least one decode-role replica (got "
                         f"{n_prefill} of {n_replicas})")
    if args.sentinels is not None and not args.fleet_models:
        raise SystemExit("--sentinels needs --fleet-models: the "
                         "observatory re-scores the sentinel grid "
                         "across a fleet (single-model drift has no "
                         "agreement axis to watch)")
    # Install the trace recorder BEFORE server construction so the
    # server registers it as a metrics source.
    rec = _maybe_start_tracing(args)
    factory = engine_factory(
        args.checkpoints, RuntimeConfig(**rt_kw), _parse_mesh(args.mesh),
        cache_root=args.param_cache, quantize_int8=args.int8,
        int8_dynamic=args.int8_dynamic, kv_cache_int8=args.kv_cache_int8,
        spec_config=_spec_config_from_args(args),
        governor_config=_governor_cfg(args),
        cascade_config=_cascade_config_from_args(args))
    if args.fleet_models:
        try:
            _run_fleet_serve(args, serve_cfg, factory)
        finally:
            _finish_tracing(rec, args)
        return
    if n_replicas > 1:
        try:
            _run_router_serve(args, serve_cfg, factory, n_replicas)
        finally:
            _finish_tracing(rec, args)
        return
    engine = factory(args.model)
    server = ScoringServer(engine, args.model, serve_cfg,
                           precompile=not args.no_precompile,
                           tiers=_tier_cfg(args)).start()

    futures = []
    if args.state_checkpoint is not None:
        import signal

        def _on_sigterm(signum, frame):
            n = server.shutdown_checkpoint(args.state_checkpoint)
            log.warning("SIGTERM: checkpointed %d pending requests -> %s"
                        "; exiting", n, args.state_checkpoint)
            sys.exit(0)

        signal.signal(signal.SIGTERM, _on_sigterm)
        if args.state_checkpoint.exists():
            # Resume the previous incarnation's unresolved requests
            # BEFORE reading new traffic (their results print first).
            futures.extend(server.resume_from_checkpoint(
                args.state_checkpoint))

    # Default formats: the canonical legal-prompt pair, so a bare
    # {"prompt": ...} line scores exactly like a sweep cell.
    default_rf = LEGAL_PROMPTS[0].response_format
    default_cf = LEGAL_PROMPTS[0].confidence_format
    stream = (sys.stdin if args.requests == "-"
              else open(args.requests, encoding="utf-8"))
    try:
        for i, line in enumerate(stream):
            line = line.strip()
            if not line:
                continue
            obj = json.loads(line)
            if obj.get("op") == "stats":
                # Live streaming-statistics readout: in-progress
                # percentile/kappa estimates over the served window,
                # answered immediately from the host-side ring (no
                # device work, no queueing).
                print(json.dumps({"op": "stats",
                                  "stats": server.stream_summary()}),
                      flush=True)
                continue
            if obj.get("op") == "metrics":
                # The unified metrics snapshot (observe/registry):
                # every registered *Stats source + HBM gauges, live.
                print(json.dumps({"op": "metrics",
                                  "metrics": server.metrics.snapshot()}),
                      flush=True)
                continue
            prompt = obj.get("prompt")
            req = ServeRequest(
                binary_prompt=obj.get(
                    "binary_prompt",
                    f"{prompt} {obj.get('response_format', default_rf)}"),
                confidence_prompt=obj.get(
                    "confidence_prompt",
                    f"{prompt} {obj.get('confidence_format', default_cf)}"),
                targets=tuple(obj.get("targets", ("Yes", "No"))),
                klass=obj.get("class", serve_cfg.default_class),
                deadline_s=obj.get("deadline_s"),
                request_id=str(obj.get("id", i)))
            futures.append(server.submit(req))
    finally:
        if stream is not sys.stdin:
            stream.close()
    for fut in futures:
        r = fut.result()
        print(json.dumps({k: v for k, v in vars(r).items()
                          if not k.startswith("_")}), flush=True)
    server.stop()
    _finish_tracing(rec, args)
    if args.state_checkpoint is not None and args.state_checkpoint.exists():
        args.state_checkpoint.unlink()   # clean drain: nothing pending
    log.info("serve stats: %s", json.dumps(server.stats.summary()))
    # Exit metrics snapshot — includes the per-device HBM gauges, so
    # WeightCache/page-pool budget pressure is on the record even when
    # nothing ever OOMed.
    log.info("serve metrics: %s", json.dumps(server.metrics.snapshot()))
    if server.stream is not None:
        log.info("serve stream stats: %s",
                 json.dumps(server.stream_summary()))
    if engine.prefix_cache is not None:
        log.info("serve prefix cache: %s",
                 json.dumps(engine.prefix_stats.summary()))
    log.info("serve faults: %s", json.dumps(server.faults.summary()))
    if not server.healthy:
        sys.exit(1)


def _run_router_serve(args, serve_cfg, factory, n_replicas: int) -> None:
    """Elastic serving loop (``serve --model X --replicas N``): N
    in-process replica ScoringServers behind a ReplicaRouter
    (serve/router.py) — queue-depth/breaker-aware placement,
    exactly-once failover of a dead replica's in-flight requests, and
    deadline-whisker hedging. The JSONL surface is the single-model
    one; {"op": "stats"} answers the router's per-replica health view
    (DEPLOY.md §1m)."""
    import json

    from .data.prompts import LEGAL_PROMPTS
    from .serve import ReplicaRouter, ScoringServer, ServeRequest

    servers = []
    tcfg = _tier_cfg(args)
    for i in range(n_replicas):
        engine = factory(args.model)
        # Each in-process replica owns its own disk-tier directory —
        # the on-disk index is per-store, never shared.
        rep_tiers = tcfg
        if tcfg.enabled and tcfg.disk_dir:
            import dataclasses as _dc
            rep_tiers = _dc.replace(
                tcfg, disk_dir=str(Path(tcfg.disk_dir) / f"r{i}"))
        servers.append(ScoringServer(
            engine, args.model, serve_cfg,
            precompile=not args.no_precompile, tiers=rep_tiers).start())
    # Disaggregated roles (serve/migrate.py; DEPLOY.md §1p): the first
    # --migrate-prefill-replicas servers take the prefill role, the
    # rest decode; 0 keeps every replica colocated ("both").
    n_prefill = getattr(args, "migrate_prefill_replicas", None) or 0
    roles = {f"r{i}": ("prefill" if i < n_prefill else "decode")
             for i in range(n_replicas)} if n_prefill else None
    router = ReplicaRouter(
        [(f"r{i}", s) for i, s in enumerate(servers)],
        config=_router_cfg(args), roles=roles,
        migrate=_migrate_cfg(args)).start()
    log.info("router: %d replica servers for %s (%d prefill-role)",
             n_replicas, args.model, n_prefill)
    default_rf = LEGAL_PROMPTS[0].response_format
    default_cf = LEGAL_PROMPTS[0].confidence_format
    stream = (sys.stdin if args.requests == "-"
              else open(args.requests, encoding="utf-8"))
    futures = []
    try:
        for i, line in enumerate(stream):
            line = line.strip()
            if not line:
                continue
            obj = json.loads(line)
            if obj.get("op") == "stats":
                print(json.dumps({"op": "stats",
                                  **router.stats_summary()}),
                      flush=True)
                continue
            if obj.get("op") == "metrics":
                print(json.dumps({"op": "metrics",
                                  "metrics": router.metrics.snapshot()}),
                      flush=True)
                continue
            prompt = obj.get("prompt")
            futures.append(router.submit(ServeRequest(
                binary_prompt=obj.get(
                    "binary_prompt",
                    f"{prompt} {obj.get('response_format', default_rf)}"),
                confidence_prompt=obj.get(
                    "confidence_prompt",
                    f"{prompt} {obj.get('confidence_format', default_cf)}"),
                targets=tuple(obj.get("targets", ("Yes", "No"))),
                klass=obj.get("class", serve_cfg.default_class),
                deadline_s=obj.get("deadline_s"),
                request_id=str(obj.get("id", i)))))
    finally:
        if stream is not sys.stdin:
            stream.close()
    for fut in futures:
        r = fut.result()
        print(json.dumps({k: v for k, v in vars(r).items()
                          if not k.startswith("_")}), flush=True)
    router.stop()
    for s in servers:
        s.stop()
    log.info("router stats: %s", json.dumps(router.stats_summary()))
    log.info("router metrics: %s",
             json.dumps(router.metrics.snapshot()))
    if not router.alive_replicas():
        sys.exit(1)


def _run_fleet_serve(args, serve_cfg, factory) -> None:
    """Fleet serving loop (``serve --fleet-models``): every JSONL line
    without a "model" key (or with {"op": "fleet_score"}) fans across
    all fleet models and prints one aggregated agreement payload —
    per-model P(yes)/P(no)/decision, pairwise kappa/disagreement
    through the stats/streaming contingency path; a "model" key routes
    the line to that one model's dispatch queue (DEPLOY.md §1k)."""
    import json

    from .data.prompts import LEGAL_PROMPTS
    from .engine.fleet import ModelFleet
    from .serve import FleetScoringServer, ServeRequest

    if args.state_checkpoint is not None:
        raise SystemExit(
            "--state-checkpoint is not supported with --fleet-models; "
            "run fleet serving behind an external retry layer")
    models = [m for m in args.fleet_models.split(",") if m]
    if not models:
        raise SystemExit("--fleet-models needs at least one model id")
    # Engines load at boot (tokenizer/buckets are submit-time state);
    # WEIGHT residency is the cache's call from here on — under a
    # budget, boot itself evicts down to what fits and later acquires
    # re-stream from the pinned host staging.
    fleet = ModelFleet.from_engines(
        [(m, factory(m)) for m in models],
        cache_budget_bytes=(int(args.weight_cache_gb * 2**30)
                            if args.weight_cache_gb else None),
        prefetch=not args.no_weight_prefetch)
    server = FleetScoringServer(
        fleet, serve_cfg,
        fleet_deadline_s=(args.fleet_deadline
                          if args.fleet_deadline is not None else 60.0),
        tiers=_tier_cfg(args),
    ).start()
    default_rf = LEGAL_PROMPTS[0].response_format
    default_cf = LEGAL_PROMPTS[0].confidence_format
    scheduler = None
    if args.sentinels is not None:
        from .observe import SentinelScheduler

        sentinels = _load_sentinels(args.sentinels, default_rf,
                                    default_cf)
        scheduler = SentinelScheduler(server, sentinels,
                                      cfg=_observe_cfg(args))
        server.attach_observatory(scheduler)
        scheduler.start()
        log.info("observatory: %d sentinels every %.0fs, %.0fs windows,"
                 " %.1f-sigma alerts", len(sentinels),
                 scheduler.cfg.sentinel_interval_s,
                 scheduler.cfg.sentinel_window_s,
                 scheduler.cfg.drift_sigma)
    stream = (sys.stdin if args.requests == "-"
              else open(args.requests, encoding="utf-8"))
    futures = []
    try:
        for i, line in enumerate(stream):
            line = line.strip()
            if not line:
                continue
            obj = json.loads(line)
            if obj.get("op") == "stats":
                # Serve + fleet counters, plus the observatory's window
                # history and drift alerts when a sentinel grid runs.
                print(json.dumps({"op": "stats",
                                  **server.stats_summary()}),
                      flush=True)
                continue
            if obj.get("op") == "metrics":
                print(json.dumps({"op": "metrics",
                                  "metrics": server.metrics.snapshot()}),
                      flush=True)
                continue
            prompt = obj.get("prompt")
            req = ServeRequest(
                binary_prompt=obj.get(
                    "binary_prompt",
                    f"{prompt} {obj.get('response_format', default_rf)}"),
                confidence_prompt=obj.get(
                    "confidence_prompt",
                    f"{prompt} {obj.get('confidence_format', default_cf)}"),
                targets=tuple(obj.get("targets", ("Yes", "No"))),
                klass=obj.get("class", serve_cfg.default_class),
                deadline_s=obj.get("deadline_s"),
                request_id=str(obj.get("id", i)))
            if obj.get("model"):
                futures.append(("single",
                                server.submit(req, obj["model"])))
            else:
                futures.append(("fleet", server.submit_fleet(req)))
    finally:
        if stream is not sys.stdin:
            stream.close()
    for kind, fut in futures:
        r = fut.result()
        print(json.dumps(r if kind == "fleet"
                         else {k: v for k, v in vars(r).items()
                               if not k.startswith("_")}), flush=True)
    if scheduler is not None:
        # Stop sentinel traffic first, then drain client traffic; the
        # final partial window finalizes so a drift that landed minutes
        # before shutdown still alerts.
        scheduler.stop()
    server.stop()
    fleet.shutdown()
    log.info("serve stats: %s", json.dumps(server.stats.summary()))
    log.info("fleet stats: %s", json.dumps(server.fleet_summary()))
    log.info("serve metrics: %s", json.dumps(server.metrics.snapshot()))
    if scheduler is not None:
        obs = scheduler.summary()
        log.info("observatory: %d sweeps over %d finalized windows, "
                 "%d drift alert(s)", obs["sweeps"], len(obs["windows"]),
                 len(obs["alerts"]))
        for alert in obs["alerts"]:
            log.warning("drift alert: %s", json.dumps(alert))


def _load_sentinels(path: Path, default_rf: str, default_cf: str):
    """Sentinel grid from a JSONL file (request-line schema minus the
    serving metadata)."""
    import json

    from .serve import ServeRequest

    sentinels = []
    for i, line in enumerate(path.read_text(encoding="utf-8")
                             .splitlines()):
        line = line.strip()
        if not line:
            continue
        obj = json.loads(line)
        prompt = obj.get("prompt")
        sentinels.append(ServeRequest(
            binary_prompt=obj.get(
                "binary_prompt",
                f"{prompt} {obj.get('response_format', default_rf)}"),
            confidence_prompt=obj.get(
                "confidence_prompt",
                f"{prompt} {obj.get('confidence_format', default_cf)}"),
            targets=tuple(obj.get("targets", ("Yes", "No"))),
            request_id=f"sentinel-{i}"))
    if not sentinels:
        raise SystemExit(f"--sentinels {path}: no sentinel lines found")
    return sentinels


def cmd_precompile(args) -> None:
    import time

    from .config import RuntimeConfig
    from .engine import compile_plan
    from .models.factory import engine_factory

    rt_kw = dict(batch_size=args.batch_size)
    if args.sweep_decode_tokens is not None:
        rt_kw["sweep_decode_tokens"] = args.sweep_decode_tokens
    if args.sweep_confidence_tokens is not None:
        rt_kw["sweep_confidence_tokens"] = args.sweep_confidence_tokens
    try:
        sfx = tuple(int(b) for b in args.sfx_buckets.split(","))
    except ValueError:
        sfx = ()
    if not sfx or any(b <= 0 for b in sfx):
        raise SystemExit(f"--sfx-buckets {args.sfx_buckets!r} must be "
                         "comma-separated positive ints (e.g. 8,16)")
    factory = engine_factory(
        args.checkpoints, RuntimeConfig(**rt_kw), _parse_mesh(args.mesh),
        cache_root=args.param_cache, quantize_int8=args.int8,
        int8_dynamic=args.int8_dynamic, kv_cache_int8=args.kv_cache_int8,
        spec_config=_spec_config_from_args(args),
        cascade_config=_cascade_config_from_args(args))
    engine = factory(args.model)
    specs = compile_plan.sweep_specs_for_ladder(engine, sfx_buckets=sfx)
    t0 = time.perf_counter()
    registry = compile_plan.precompile_async(engine, specs,
                                             max_workers=args.workers)
    ok = registry.wait()
    stats = engine.compile_stats
    log.info("precompiled %d/%d executables in %.1fs wall "
             "(%.1fs compile total; manifest %s); per-shape: %s",
             ok, len(specs), time.perf_counter() - t0, stats.compile_s,
             registry.manifest_key,
             {k: round(v, 2) for k, v in sorted(stats.shapes.items())})
    if ok < len(specs):
        sys.exit(1)


def cmd_rephrase(args) -> None:
    import jax

    from .data.prompts import LEGAL_PROMPTS
    from .engine.rephrase import (
        load_or_generate_perturbations,
        rephraser_from_engine,
    )
    from .models.factory import engine_factory

    engine = engine_factory(args.checkpoints)(args.model)
    load_or_generate_perturbations(
        args.out, LEGAL_PROMPTS, rephraser_from_engine(engine),
        jax.random.PRNGKey(42),
        sessions_per_prompt=args.sessions,
        rephrasings_per_session=args.per_session,
    )


def cmd_analyze(args) -> None:
    from .utils.profiling import ensure_cpu_backend

    ensure_cpu_backend()  # host statistics: never run over a tunneled TPU
    ran = False
    if args.perturbation_results:
        from .analysis.perturbation import analyze_all_models

        analyze_all_models(
            args.perturbation_results, args.out / "perturbation",
            n_simulations=args.n_simulations,
            make_figures=not args.no_figures,
        )
        ran = True
    if args.base_csv:
        from .analysis.base_vs_instruct import run_base_vs_instruct_analysis

        run_base_vs_instruct_analysis(
            args.base_csv, args.out / "base_vs_instruct",
            make_figures=not args.no_figures,
        )
        ran = True
    if args.instruct_csv:
        from .analysis.model_graph import run_model_graph_analysis

        run_model_graph_analysis(
            args.instruct_csv, args.out / "model_graph",
            make_figures=not args.no_figures,
        )
        ran = True
        if args.perturbation_results:
            from .analysis.kappa_combined import run_kappa_analysis

            run_kappa_analysis(
                args.instruct_csv, args.perturbation_results,
                args.out / "kappa", make_figures=not args.no_figures,
            )
    if not ran:
        log.error("analyze: give at least one of --perturbation-results, "
                  "--base-csv, --instruct-csv")
        sys.exit(2)


def cmd_repro(args) -> None:
    """Survey pipeline + every CSV-driven analysis in one pass."""
    from .utils.profiling import ensure_cpu_backend

    ensure_cpu_backend()
    from .analysis.base_vs_instruct import run_base_vs_instruct_analysis
    from .analysis.model_graph import run_model_graph_analysis
    from .survey.run import run_survey_pipeline

    data = args.data
    base_csv = data / "model_comparison_results.csv"
    instruct_csv = data / "instruct_model_comparison_results.csv"
    survey_csv = data / "word_meaning_survey_results.csv"
    figures = not args.no_figures

    kwargs = {}
    if args.quick:
        kwargs = dict(n_bootstrap_standard=50, n_bootstrap_small=20,
                      n_bootstrap_large=200)
    run_survey_pipeline(
        survey_csv, instruct_csv,
        base_csv if base_csv.exists() else None,
        args.out / "survey", **kwargs,
    )
    if base_csv.exists():
        run_base_vs_instruct_analysis(
            base_csv, args.out / "base_vs_instruct", make_figures=figures)
    run_model_graph_analysis(
        instruct_csv, args.out / "model_graph",
        n_bootstrap=50 if args.quick else 1000, make_figures=figures)
    if args.perturbation_results:
        from .analysis.kappa_combined import run_kappa_analysis
        from .analysis.perturbation import analyze_all_models

        analyze_all_models(
            args.perturbation_results, args.out / "perturbation",
            n_simulations=2000 if args.quick else 100_000,
            make_figures=figures,
        )
        run_kappa_analysis(
            instruct_csv, args.perturbation_results, args.out / "kappa",
            n_bootstrap=100 if args.quick else 1000, make_figures=figures,
        )
    log.info("repro complete; artifacts under %s", args.out)


def cmd_survey(args) -> None:
    from .utils.profiling import ensure_cpu_backend

    ensure_cpu_backend()  # host statistics: never run over a tunneled TPU
    from .survey.run import run_survey_pipeline

    kwargs = {}
    if args.quick:
        kwargs = dict(n_bootstrap_standard=50, n_bootstrap_small=20,
                      n_bootstrap_large=200)
    run_survey_pipeline(args.survey, args.instruct, args.base, args.out,
                        **kwargs)


def cmd_concat_shards(args) -> None:
    """Merge per-host .hostN result shards into the final artifact — the
    manual gather for pods WITHOUT a shared filesystem (copy every host's
    shard + manifest next to --results first; with a shared filesystem the
    sweep's host 0 runs this merge automatically after its barrier)."""
    from .data import schemas

    # Pod hosts and the merge machine may disagree on openpyxl (shards are
    # written in the POD's resolved container) — probe the requested
    # suffix, then the alternate, before declaring the shards missing.
    candidates = [args.results]
    if args.results.suffix in (".xlsx", ".csv"):
        candidates.append(args.results.with_suffix(
            ".csv" if args.results.suffix == ".xlsx" else ".xlsx"))
    merged = out = None
    for cand in candidates:
        merged = schemas.concat_host_shards(cand, n_hosts=args.hosts)
        if merged is not None:
            out = schemas.resolve_results_path(cand)
            break
    if merged is None:
        probed = ", ".join(
            str(schemas.resolve_results_path(c).with_name(
                f"{schemas.resolve_results_path(c).stem}.host0"
                f"{schemas.resolve_results_path(c).suffix}"))
            for c in candidates)
        raise SystemExit(
            f"no mergeable shards for {args.results} — expected "
            f"{args.hosts or 'host0..hostN'} consecutive shard files "
            f"(probed: {probed}, ...)")
    manifest = out.with_suffix(".manifest.jsonl")
    manifest_note = (
        f"(+ union manifest {manifest.name})" if manifest.exists() else
        "(WARNING: no shard manifests found next to the shards — resume "
        "state NOT merged; copy the .hostN.manifest.jsonl files too)")
    print(f"merged {len(merged)} rows -> {out} {manifest_note}")


def cmd_bench(args) -> None:
    import runpy

    # bench.py parses sys.argv itself; hand it a clean argv so the CLI's
    # own subcommand tokens don't reach its parser.
    bench_path = Path(__file__).resolve().parent.parent / "bench.py"
    fwd = []
    if getattr(args, "allow_ungated", False):
        fwd.append("--allow-ungated")
    fwd += getattr(args, "bench_extra", [])
    old_argv = sys.argv
    sys.argv = [str(bench_path)] + fwd
    try:
        runpy.run_path(str(bench_path), run_name="__main__")
    finally:
        sys.argv = old_argv


def main(argv: Optional[List[str]] = None) -> None:
    parser = argparse.ArgumentParser(prog="lir_tpu", description=__doc__)
    parser.add_argument("--compile-cache-dir", type=Path, default=None,
                        help="persistent XLA compile cache directory "
                             "(default: $LIR_TPU_COMPILE_CACHE or "
                             "~/.cache/lir_tpu/xla)")
    parser.add_argument("--no-compile-cache", action="store_true",
                        help="disable the persistent compile cache (every "
                             "process then recompiles from scratch)")
    sub = parser.add_subparsers(dest="command", required=True)
    _add_sweep(sub)
    _add_perturb(sub)
    _add_serve(sub)
    _add_precompile(sub)
    _add_rephrase(sub)
    _add_analyze(sub)
    _add_repro(sub)
    _add_survey(sub)
    _add_lint(sub)
    bench_p = sub.add_parser(
        "bench", help="prompts/sec/chip benchmark (end-to-end sweep path); "
                      "unrecognized flags are forwarded to bench.py "
                      "verbatim (--model, --sweep-batches, ... — see "
                      "`python bench.py --help`)")
    bench_p.add_argument("--allow-ungated", action="store_true",
                         help="report even when the chip kind has no MFU "
                              "peak-table entry (default: abort)")

    cs = sub.add_parser(
        "concat-shards",
        help="merge per-host .hostN sweep shards + manifests into the "
             "final results artifact (manual gather for pods without a "
             "shared filesystem)")
    cs.add_argument("--results", type=Path, required=True,
                    help="the FINAL results path the sweep was given "
                         "(shards live next to it as <stem>.hostN.<ext>)")
    cs.add_argument("--hosts", type=int, default=None,
                    help="expected shard count (default: walk host0, "
                         "host1, ... until the first gap)")

    # bench.py owns its flag surface (it parses sys.argv itself); unknown
    # flags on the bench subcommand are forwarded verbatim instead of
    # hand-mirroring every bench.py option here. Every other subcommand
    # still rejects unknowns — and so does anything typed BEFORE the
    # `bench` subcommand (a typo of the CLI's own flags must fail with
    # THIS parser's usage message, not bench.py's; ADVICE r5, cli.py:470).
    args, extra = parser.parse_known_args(argv)
    if extra:
        argv_seq = list(sys.argv[1:] if argv is None else argv)
        pre_bench = (argv_seq[:argv_seq.index("bench")]
                     if args.command == "bench" else argv_seq)
        bad = [t for t in extra if t in pre_bench]
        if args.command != "bench" or bad:
            parser.error("unrecognized arguments: "
                         f"{' '.join(bad or extra)}")
    args.bench_extra = extra
    if getattr(args, "int8_dynamic", False) and not getattr(args, "int8", False):
        parser.error("--int8-dynamic requires --int8 (it selects HOW int8 "
                     "matmuls run, not whether weights are quantized)")
    if not args.no_compile_cache and args.command != "lint":
        # lint is pure host-side ast analysis — never touch jax (the
        # pre-push hook runs it in containers without an accelerator).
        from .utils import compile_cache

        compile_cache.enable_persistent_cache(args.compile_cache_dir)
    {
        "sweep": cmd_sweep,
        "perturb": cmd_perturb,
        "serve": cmd_serve,
        "precompile": cmd_precompile,
        "rephrase": cmd_rephrase,
        "analyze": cmd_analyze,
        "repro": cmd_repro,
        "survey": cmd_survey,
        "lint": cmd_lint,
        "bench": cmd_bench,
        "concat-shards": cmd_concat_shards,
    }[args.command](args)


if __name__ == "__main__":
    main()
