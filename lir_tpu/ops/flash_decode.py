"""Pallas fused flash-decode: single-query attention over the KV cache.

The decode phase of the scoring step is where the 36% MFU plateau lives
(BENCH_r02-r05): each greedy step attends ONE query per row over the whole
cache, and XLA's dense lowering materializes the (B, H, 1, T) score row,
the fp32 softmax, and the probability row as separate HBM round-trips
between three kernels. This kernel is the Flash-Decoding treatment (Dao
et al.): because the query axis is a single position, parallelism must
come from the KEY axis — the cache's sequence dimension is split into
blocks, each grid program reduces its block with an online softmax into a
partial (o, m, l) triple, and the partials combine with one log-sum-exp
reduction. Scores, exponentials, and probability-weighted sums never
leave VMEM; HBM traffic drops to the cache read plus O(B*H*hd) partials.

Layout contract matches the decode path exactly (models/decoder.
_attention_cached): q is (B, H, hd) — one post-RoPE query per row — and
k/v arrive in the CACHE layout (K, T, B, hd) (head-major/batch-minor, the
order the decode while-loop carries), un-repeated for GQA/MQA: grouped
query heads contract against their kv head inside the kernel, so the
cache is never copied K -> H. Masking semantics equal the dense path's
additive bias: a key is valid iff its mask bit is set AND its mask-aware
position does not exceed the query's; ALiBi families add
``slope_h * key_position`` exactly as ``decoder._causal_bias`` does.

Block sizes align to the flash_attention edges (DEFAULT_BLOCK_K): the
split width is the largest divisor of T no wider than the requested
block (preferring sublane-aligned multiples of 8), falling back to a
single full-width split — every cache extent the bucket ladder plans
(bucket + suffix + decode budget) therefore lowers without padding or
out-of-bounds tail blocks. ``interpret=True`` runs the kernel in the
Pallas interpreter so tier-1 exercises it on CPU (tests/test_kernels.py);
production CPU runs keep the dense path (models/decoder.FUSED_DECODE_
INTERPRET_ON_CPU is the test hook, mirroring FLASH_INTERPRET_ON_CPU).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .flash_attention import DEFAULT_BLOCK_K
from .lse import merge_partials


def pick_split(total: int, want: int = DEFAULT_BLOCK_K) -> int:
    """Split width for a cache of ``total`` slots: the largest divisor of
    ``total`` that is <= ``want``, preferring sublane-aligned multiples of
    8; ``total`` itself (one split) when nothing smaller divides. Exact
    division — never a padded or out-of-bounds tail block."""
    want = min(int(want), int(total))
    for b in range(want, 7, -1):
        if total % b == 0 and b % 8 == 0:
            return b
    for b in range(want, 0, -1):
        if total % b == 0:
            return b
    return int(total)


def _decode_kernel(qpos_ref, slope_ref, mask_ref, kpos_ref, q_ref, k_ref,
                   v_ref, o_ref, m_ref, l_ref, *, sm_scale: float,
                   alibi: bool, n_groups: int):
    b = pl.program_id(0)
    kh = pl.program_id(1)
    q = q_ref[0, 0].astype(jnp.float32) * sm_scale        # (G, hd)
    k = k_ref[0, :, 0, :].astype(jnp.float32)             # (bs, hd)
    v = v_ref[0, :, 0, :].astype(jnp.float32)
    s = jnp.dot(q, k.T, preferred_element_type=jnp.float32)  # (G, bs)
    kmask = mask_ref[0, 0] > 0                            # (bs,)
    kp = kpos_ref[0, 0]                                   # (bs,)
    qp = qpos_ref[b, 0]
    if alibi:
        # Per-head slopes for this kv head's query group (h = kh*G + g).
        slope = slope_ref[pl.ds(kh * n_groups, n_groups), 0]  # (G,)
        s = s + slope[:, None] * kp.astype(jnp.float32)[None, :]
    valid = (kmask & (kp <= qp))[None, :]                 # (1, bs)
    s = jnp.where(valid, s, -jnp.inf)

    m = s.max(axis=-1)                                    # (G,)
    p = jnp.exp(s - m[:, None])
    p = jnp.where(jnp.isfinite(s), p, 0.0)                # all-masked split
    o_ref[0, 0, 0] = jnp.dot(p, v, preferred_element_type=jnp.float32)
    m_ref[0, 0, 0] = m
    l_ref[0, 0, 0] = p.sum(axis=-1)


@functools.partial(jax.jit, static_argnames=("block_k", "interpret"))
def flash_decode(
    q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
    q_positions: jnp.ndarray,
    key_mask: jnp.ndarray,
    key_positions: jnp.ndarray | None = None,
    alibi_slopes: jnp.ndarray | None = None,
    block_k: int = DEFAULT_BLOCK_K,
    interpret: bool = False,
) -> jnp.ndarray:
    """One decode step of attention, fused. Returns (B, H, hd) in q's dtype.

    ``q``: (B, H, hd) single query per row (post-RoPE). ``k``/``v``:
    (K, T, B, hd) cache layout, K the kv-head count (un-repeated GQA/MQA).
    ``q_positions``: (B,) mask-aware query positions. ``key_mask``: (B, T)
    {0,1} validity over cache slots (any pattern). ``key_positions``:
    (B, T) mask-aware slot positions (decoder.mask_positions of the cache
    mask); defaults to the mask's own cumsum. ``alibi_slopes``: optional
    (H,) per-head slopes (bloom) added as ``slope * key_position``.

    Grid is (B, K, T / split): each program owns one key split in VMEM and
    emits a partial (o, m, l); the final output is the log-sum-exp
    combination of the splits — exact attention, any split count.
    """
    B, H, hd = q.shape
    K, T = k.shape[0], k.shape[1]
    G = H // K
    sm_scale = 1.0 / np.sqrt(hd)
    alibi = alibi_slopes is not None
    if key_positions is None:
        key_positions = jnp.maximum(jnp.cumsum(key_mask, axis=-1) - 1, 0)
    key_mask = jnp.asarray(key_mask, jnp.int32)
    key_positions = jnp.asarray(key_positions, jnp.int32)
    if alibi_slopes is None:
        slopes = jnp.zeros((H, 1), jnp.float32)
    else:
        slopes = jnp.asarray(alibi_slopes, jnp.float32).reshape(H, 1)

    split = pick_split(T, block_k)
    n_splits = T // split
    qg = q.reshape(B, K, G, hd)

    kernel = functools.partial(_decode_kernel, sm_scale=sm_scale,
                               alibi=alibi, n_groups=G)
    f32 = jnp.float32
    o_p, m_p, l_p = pl.pallas_call(
        kernel,
        grid=(B, K, n_splits),
        in_specs=[
            # Per-row query position: whole (B, 1) array in SMEM (TPU
            # lowering wants full-array blocks for tiny scalars — same
            # pattern as flash_attention's first-valid index).
            pl.BlockSpec(index_map=lambda b, h, j: (0, 0),
                         memory_space=pltpu.SMEM),
            # Per-head ALiBi slopes, whole (H, 1) array in SMEM.
            pl.BlockSpec(index_map=lambda b, h, j: (0, 0),
                         memory_space=pltpu.SMEM),
            # Key mask / positions as (B, 1, T): one split per program.
            pl.BlockSpec((1, 1, split), lambda b, h, j: (b, 0, j)),
            pl.BlockSpec((1, 1, split), lambda b, h, j: (b, 0, j)),
            # Query group (1, 1, G, hd); cache splits (1, split, 1, hd).
            pl.BlockSpec((1, 1, G, hd), lambda b, h, j: (b, h, 0, 0)),
            pl.BlockSpec((1, split, 1, hd), lambda b, h, j: (h, j, b, 0)),
            pl.BlockSpec((1, split, 1, hd), lambda b, h, j: (h, j, b, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, 1, G, hd), lambda b, h, j: (b, h, j, 0, 0)),
            pl.BlockSpec((1, 1, 1, G), lambda b, h, j: (b, h, j, 0)),
            pl.BlockSpec((1, 1, 1, G), lambda b, h, j: (b, h, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, K, n_splits, G, hd), f32),
            jax.ShapeDtypeStruct((B, K, n_splits, G), f32),
            jax.ShapeDtypeStruct((B, K, n_splits, G), f32),
        ],
        interpret=interpret,
    )(q_positions[:, None].astype(jnp.int32), slopes,
      key_mask[:, None, :], key_positions[:, None, :], qg, k, v)

    # Log-sum-exp combine across splits (ops/lse.merge_partials, shared
    # with the cascade-prefill merge): renormalize each partial by the
    # global row max, then sum the weighted accumulators and weights. A
    # fully-masked split carries m = -inf and weight exactly 0.
    out = merge_partials(o_p, m_p, l_p, axis=2)           # (B, K, G, hd)
    return out.reshape(B, H, hd).astype(q.dtype)


def _trunk_decode_kernel(qpos_ref, slope_ref, mask_ref, kpos_ref, q_ref,
                         k_ref, v_ref, o_ref, m_ref, l_ref, *,
                         sm_scale: float, alibi: bool, n_groups: int):
    """Trunk-split sibling of :func:`_decode_kernel` for shared-prefix
    cascade decode: every row of a shared dispatch attends the SAME
    trunk KV (the cascade cache broadcasts the trunk into every batch
    row), so a split that lies fully inside the trunk reads its K/V
    block from cache row 0 ONLY — once per (kv head, split) instead of
    once per row — and batches ALL rows' queries into one MXU GEMM.
    Per-(row, group) arithmetic is exactly the single-row kernel's (the
    batched dot never mixes rows, masks/positions stay per-row), which
    is what keeps the merged output bitwise the flat kernel's."""
    kh = pl.program_id(0)
    q = q_ref[0].astype(jnp.float32) * sm_scale           # (B, G, hd)
    B, G, hd = q.shape
    k = k_ref[0, :, 0, :].astype(jnp.float32)             # (bs, hd) row 0
    v = v_ref[0, :, 0, :].astype(jnp.float32)
    s = jnp.dot(q.reshape(B * G, hd), k.T,
                preferred_element_type=jnp.float32)       # (B*G, bs)
    s = s.reshape(B, G, -1)
    kmask = mask_ref[0] > 0                               # (B, bs)
    kp = kpos_ref[0]                                      # (B, bs)
    qp = qpos_ref[:, 0]                                   # (B,)
    if alibi:
        slope = slope_ref[pl.ds(kh * n_groups, n_groups), 0]  # (G,)
        s = s + slope[None, :, None] * kp.astype(jnp.float32)[:, None, :]
    valid = (kmask & (kp <= qp[:, None]))[:, None, :]     # (B, 1, bs)
    s = jnp.where(valid, s, -jnp.inf)

    m = s.max(axis=-1)                                    # (B, G)
    p = jnp.exp(s - m[..., None])
    p = jnp.where(jnp.isfinite(s), p, 0.0)                # all-masked split
    o = jnp.dot(p.reshape(B * G, -1), v,
                preferred_element_type=jnp.float32)
    o_ref[0, 0] = o.reshape(B, G, hd)
    m_ref[0, 0] = m
    l_ref[0, 0] = p.sum(axis=-1)


@functools.partial(jax.jit,
                   static_argnames=("trunk_len", "block_k", "interpret"))
def flash_decode_trunk(
    q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
    q_positions: jnp.ndarray,
    key_mask: jnp.ndarray,
    key_positions: jnp.ndarray | None = None,
    alibi_slopes: jnp.ndarray | None = None,
    trunk_len: int = 0,
    block_k: int = DEFAULT_BLOCK_K,
    interpret: bool = False,
) -> jnp.ndarray:
    """Trunk-aware decode step for shared-prefix (cascade) dispatches.

    Arguments as :func:`flash_decode` plus static ``trunk_len``: the
    leading cache extent whose KV is bitwise-identical across the batch
    (the shared trunk a cascade/shared dispatch broadcast or prefilled
    into every row). The split ladder is the flat kernel's exactly —
    ``pick_split(T)`` over the WHOLE cache extent — but the splits that
    lie fully inside the trunk run as one batched GEMM per kv head
    against row 0's K/V (HBM loads the trunk tiles once per step, not
    once per row), while the tail splits run the unmodified per-row
    kernel. The two partial sets concatenate in original split order
    and merge through the same :func:`~lir_tpu.ops.lse.merge_partials`
    reduction, so the result is BITWISE the flat kernel's (pinned by
    tests/test_cascade_decode) — trunk dedup is a pure HBM-traffic
    lever. Per step and layer it saves ``2 * K * nt*split * hd *
    itemsize * (B - 1)`` trunk bytes, nt the trunk split count.
    """
    B, H, hd = q.shape
    K, T = k.shape[0], k.shape[1]
    G = H // K
    split = pick_split(T, block_k)
    nt = max(0, min(int(trunk_len), T - 1)) // split
    if nt == 0:
        # No full split fits inside the trunk: the flat kernel verbatim.
        return flash_decode(q, k, v, q_positions, key_mask, key_positions,
                            alibi_slopes, block_k, interpret)
    sm_scale = 1.0 / np.sqrt(hd)
    alibi = alibi_slopes is not None
    if key_positions is None:
        key_positions = jnp.maximum(jnp.cumsum(key_mask, axis=-1) - 1, 0)
    key_mask = jnp.asarray(key_mask, jnp.int32)
    key_positions = jnp.asarray(key_positions, jnp.int32)
    if alibi_slopes is None:
        slopes = jnp.zeros((H, 1), jnp.float32)
    else:
        slopes = jnp.asarray(alibi_slopes, jnp.float32).reshape(H, 1)

    n_splits = T // split
    qg = q.reshape(B, K, G, hd)
    f32 = jnp.float32
    qpos2 = q_positions[:, None].astype(jnp.int32)

    # Trunk leg: grid (K, nt); K/V blocks index row 0 only — the dedup.
    kernel_t = functools.partial(_trunk_decode_kernel, sm_scale=sm_scale,
                                 alibi=alibi, n_groups=G)
    o_t, m_t, l_t = pl.pallas_call(
        kernel_t,
        grid=(K, nt),
        in_specs=[
            pl.BlockSpec(index_map=lambda h, j: (0, 0),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec(index_map=lambda h, j: (0, 0),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((1, B, split), lambda h, j: (0, 0, j)),
            pl.BlockSpec((1, B, split), lambda h, j: (0, 0, j)),
            pl.BlockSpec((1, B, G, hd), lambda h, j: (h, 0, 0, 0)),
            pl.BlockSpec((1, split, 1, hd), lambda h, j: (h, j, 0, 0)),
            pl.BlockSpec((1, split, 1, hd), lambda h, j: (h, j, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, B, G, hd), lambda h, j: (h, j, 0, 0, 0)),
            pl.BlockSpec((1, 1, B, G), lambda h, j: (h, j, 0, 0)),
            pl.BlockSpec((1, 1, B, G), lambda h, j: (h, j, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((K, nt, B, G, hd), f32),
            jax.ShapeDtypeStruct((K, nt, B, G), f32),
            jax.ShapeDtypeStruct((K, nt, B, G), f32),
        ],
        interpret=interpret,
    )(qpos2, slopes, key_mask[None], key_positions[None],
      qg.transpose(1, 0, 2, 3), k, v)

    # Suffix leg: the unmodified per-row kernel over only the tail
    # splits (index maps offset by nt — no cache slicing or copies).
    ns = n_splits - nt
    kernel_s = functools.partial(_decode_kernel, sm_scale=sm_scale,
                                 alibi=alibi, n_groups=G)
    o_s, m_s, l_s = pl.pallas_call(
        kernel_s,
        grid=(B, K, ns),
        in_specs=[
            pl.BlockSpec(index_map=lambda b, h, j: (0, 0),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec(index_map=lambda b, h, j: (0, 0),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((1, 1, split), lambda b, h, j: (b, 0, j + nt)),
            pl.BlockSpec((1, 1, split), lambda b, h, j: (b, 0, j + nt)),
            pl.BlockSpec((1, 1, G, hd), lambda b, h, j: (b, h, 0, 0)),
            pl.BlockSpec((1, split, 1, hd),
                         lambda b, h, j: (h, j + nt, b, 0)),
            pl.BlockSpec((1, split, 1, hd),
                         lambda b, h, j: (h, j + nt, b, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, 1, G, hd), lambda b, h, j: (b, h, j, 0, 0)),
            pl.BlockSpec((1, 1, 1, G), lambda b, h, j: (b, h, j, 0)),
            pl.BlockSpec((1, 1, 1, G), lambda b, h, j: (b, h, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, K, ns, G, hd), f32),
            jax.ShapeDtypeStruct((B, K, ns, G), f32),
            jax.ShapeDtypeStruct((B, K, ns, G), f32),
        ],
        interpret=interpret,
    )(qpos2, slopes, key_mask[:, None, :], key_positions[:, None, :],
      qg, k, v)

    # Concatenate in original split order, then the flat kernel's merge:
    # every partial equals the flat kernel's for its split, so the
    # reduction — and the output — are bitwise-identical.
    o_p = jnp.concatenate([o_t.transpose(2, 0, 1, 3, 4), o_s], axis=2)
    m_p = jnp.concatenate([m_t.transpose(2, 0, 1, 3), m_s], axis=2)
    l_p = jnp.concatenate([l_t.transpose(2, 0, 1, 3), l_s], axis=2)
    out = merge_partials(o_p, m_p, l_p, axis=2)           # (B, K, G, hd)
    return out.reshape(B, H, hd).astype(q.dtype)


def _decode_kernel_mq(qpos_ref, slope_ref, mask_ref, kpos_ref, q_ref, k_ref,
                      v_ref, o_ref, m_ref, l_ref, *, sm_scale: float,
                      alibi: bool, n_groups: int):
    """Multi-query sibling of :func:`_decode_kernel` for the speculative
    verify pass: S teacher-forced queries per row, each with its OWN
    mask-aware position, reduced with exactly the single-query kernel's
    per-row ops — every (query, group) row's score/softmax/weighted-sum
    arithmetic is independent of S, which is what keeps a verified
    position bitwise the sequential decode step's."""
    b = pl.program_id(0)
    kh = pl.program_id(1)
    q = q_ref[0, 0].astype(jnp.float32) * sm_scale        # (S, G, hd)
    S, G, hd = q.shape
    k = k_ref[0, :, 0, :].astype(jnp.float32)             # (bs, hd)
    v = v_ref[0, :, 0, :].astype(jnp.float32)
    s = jnp.dot(q.reshape(S * G, hd), k.T,
                preferred_element_type=jnp.float32)       # (S*G, bs)
    s = s.reshape(S, G, -1)
    kmask = mask_ref[0, 0] > 0                            # (bs,)
    kp = kpos_ref[0, 0]                                   # (bs,)
    qp = qpos_ref[b]                                      # (S,)
    if alibi:
        slope = slope_ref[pl.ds(kh * n_groups, n_groups), 0]  # (G,)
        s = s + slope[None, :, None] * kp.astype(jnp.float32)[None, None, :]
    valid = (kmask[None, :] & (kp[None, :] <= qp[:, None]))[:, None, :]
    s = jnp.where(valid, s, -jnp.inf)                     # (S, G, bs)

    m = s.max(axis=-1)                                    # (S, G)
    p = jnp.exp(s - m[..., None])
    p = jnp.where(jnp.isfinite(s), p, 0.0)                # all-masked split
    o = jnp.dot(p.reshape(S * G, -1), v,
                preferred_element_type=jnp.float32)
    o_ref[0, 0, 0] = o.reshape(S, G, hd)
    m_ref[0, 0, 0] = m
    l_ref[0, 0, 0] = p.sum(axis=-1)


@functools.partial(jax.jit, static_argnames=("block_k", "interpret"))
def flash_decode_mq(
    q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
    q_positions: jnp.ndarray,
    key_mask: jnp.ndarray,
    key_positions: jnp.ndarray | None = None,
    alibi_slopes: jnp.ndarray | None = None,
    block_k: int = DEFAULT_BLOCK_K,
    interpret: bool = False,
) -> jnp.ndarray:
    """Multi-query fused decode attention: S queries per row over the KV
    cache in ONE kernel launch — the speculative-decode verify path
    (ROADMAP item 3: the k drafted positions verify in one dispatch).

    ``q``: (B, S, H, hd) post-RoPE queries — the teacher-forced draft
    window, already written into the cache at their slots. ``q_positions``:
    (B, S) per-query mask-aware positions: causality (``kp <= qp`` per
    query) is what keeps a query from seeing later drafts, exactly as
    ``decoder._causal_bias`` orders the dense path. Other arguments as
    :func:`flash_decode`. Per-query results are bitwise the single-query
    kernel's for the same cache state (pinned by tests/test_spec_decode):
    the per-(query, group) row reductions never mix queries, and the
    split ladder is chosen from T alone.
    """
    B, S, H, hd = q.shape
    K, T = k.shape[0], k.shape[1]
    G = H // K
    sm_scale = 1.0 / np.sqrt(hd)
    alibi = alibi_slopes is not None
    if key_positions is None:
        key_positions = jnp.maximum(jnp.cumsum(key_mask, axis=-1) - 1, 0)
    key_mask = jnp.asarray(key_mask, jnp.int32)
    key_positions = jnp.asarray(key_positions, jnp.int32)
    if alibi_slopes is None:
        slopes = jnp.zeros((H, 1), jnp.float32)
    else:
        slopes = jnp.asarray(alibi_slopes, jnp.float32).reshape(H, 1)

    split = pick_split(T, block_k)
    n_splits = T // split
    qg = q.reshape(B, S, K, G, hd).transpose(0, 2, 1, 3, 4)  # (B, K, S, G, hd)

    kernel = functools.partial(_decode_kernel_mq, sm_scale=sm_scale,
                               alibi=alibi, n_groups=G)
    f32 = jnp.float32
    o_p, m_p, l_p = pl.pallas_call(
        kernel,
        grid=(B, K, n_splits),
        in_specs=[
            pl.BlockSpec(index_map=lambda b, h, j: (0, 0),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec(index_map=lambda b, h, j: (0, 0),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((1, 1, split), lambda b, h, j: (b, 0, j)),
            pl.BlockSpec((1, 1, split), lambda b, h, j: (b, 0, j)),
            pl.BlockSpec((1, 1, S, G, hd), lambda b, h, j: (b, h, 0, 0, 0)),
            pl.BlockSpec((1, split, 1, hd), lambda b, h, j: (h, j, b, 0)),
            pl.BlockSpec((1, split, 1, hd), lambda b, h, j: (h, j, b, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, 1, S, G, hd),
                         lambda b, h, j: (b, h, j, 0, 0, 0)),
            pl.BlockSpec((1, 1, 1, S, G), lambda b, h, j: (b, h, j, 0, 0)),
            pl.BlockSpec((1, 1, 1, S, G), lambda b, h, j: (b, h, j, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, K, n_splits, S, G, hd), f32),
            jax.ShapeDtypeStruct((B, K, n_splits, S, G), f32),
            jax.ShapeDtypeStruct((B, K, n_splits, S, G), f32),
        ],
        interpret=interpret,
    )(q_positions.astype(jnp.int32), slopes,
      key_mask[:, None, :], key_positions[:, None, :], qg, k, v)

    # Same log-sum-exp combine as flash_decode, with the query axis along.
    out = merge_partials(o_p, m_p, l_p, axis=2)           # (B, K, S, G, hd)
    return out.transpose(0, 2, 1, 3, 4).reshape(B, S, H, hd).astype(q.dtype)


def _trunk_decode_kernel_mq(qpos_ref, slope_ref, mask_ref, kpos_ref, q_ref,
                            k_ref, v_ref, o_ref, m_ref, l_ref, *,
                            sm_scale: float, alibi: bool, n_groups: int):
    """Trunk-split sibling of :func:`_decode_kernel_mq`: all rows' verify
    windows (B*S queries) batch into one GEMM per (kv head, trunk
    split), K/V read from cache row 0 only — speculative verify rides
    the same trunk dedup as the single-query step, with identical
    per-(row, query, group) arithmetic."""
    kh = pl.program_id(0)
    q = q_ref[0].astype(jnp.float32) * sm_scale           # (B, S, G, hd)
    B, S, G, hd = q.shape
    k = k_ref[0, :, 0, :].astype(jnp.float32)             # (bs, hd) row 0
    v = v_ref[0, :, 0, :].astype(jnp.float32)
    s = jnp.dot(q.reshape(B * S * G, hd), k.T,
                preferred_element_type=jnp.float32)
    s = s.reshape(B, S, G, -1)
    kmask = mask_ref[0] > 0                               # (B, bs)
    kp = kpos_ref[0]                                      # (B, bs)
    qp = qpos_ref[:]                                      # (B, S)
    if alibi:
        slope = slope_ref[pl.ds(kh * n_groups, n_groups), 0]  # (G,)
        s = s + (slope[None, None, :, None]
                 * kp.astype(jnp.float32)[:, None, None, :])
    valid = (kmask[:, None, :]
             & (kp[:, None, :] <= qp[:, :, None]))[:, :, None, :]
    s = jnp.where(valid, s, -jnp.inf)                     # (B, S, G, bs)

    m = s.max(axis=-1)                                    # (B, S, G)
    p = jnp.exp(s - m[..., None])
    p = jnp.where(jnp.isfinite(s), p, 0.0)                # all-masked split
    o = jnp.dot(p.reshape(B * S * G, -1), v,
                preferred_element_type=jnp.float32)
    o_ref[0, 0] = o.reshape(B, S, G, hd)
    m_ref[0, 0] = m
    l_ref[0, 0] = p.sum(axis=-1)


@functools.partial(jax.jit,
                   static_argnames=("trunk_len", "block_k", "interpret"))
def flash_decode_mq_trunk(
    q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
    q_positions: jnp.ndarray,
    key_mask: jnp.ndarray,
    key_positions: jnp.ndarray | None = None,
    alibi_slopes: jnp.ndarray | None = None,
    trunk_len: int = 0,
    block_k: int = DEFAULT_BLOCK_K,
    interpret: bool = False,
) -> jnp.ndarray:
    """Trunk-aware multi-query decode: :func:`flash_decode_mq` with the
    :func:`flash_decode_trunk` split dedup, so speculative verify
    windows in a shared-trunk dispatch load the trunk KV once per
    (kv head, split) per verify pass instead of once per row. Bitwise
    the flat mq kernel's output (same split ladder, same per-element
    arithmetic, same merge)."""
    B, S, H, hd = q.shape
    K, T = k.shape[0], k.shape[1]
    G = H // K
    split = pick_split(T, block_k)
    nt = max(0, min(int(trunk_len), T - 1)) // split
    if nt == 0:
        return flash_decode_mq(q, k, v, q_positions, key_mask,
                               key_positions, alibi_slopes, block_k,
                               interpret)
    sm_scale = 1.0 / np.sqrt(hd)
    alibi = alibi_slopes is not None
    if key_positions is None:
        key_positions = jnp.maximum(jnp.cumsum(key_mask, axis=-1) - 1, 0)
    key_mask = jnp.asarray(key_mask, jnp.int32)
    key_positions = jnp.asarray(key_positions, jnp.int32)
    if alibi_slopes is None:
        slopes = jnp.zeros((H, 1), jnp.float32)
    else:
        slopes = jnp.asarray(alibi_slopes, jnp.float32).reshape(H, 1)

    n_splits = T // split
    qg = q.reshape(B, S, K, G, hd).transpose(0, 2, 1, 3, 4)  # (B, K, S, G, hd)
    f32 = jnp.float32
    qpos = q_positions.astype(jnp.int32)

    kernel_t = functools.partial(_trunk_decode_kernel_mq, sm_scale=sm_scale,
                                 alibi=alibi, n_groups=G)
    o_t, m_t, l_t = pl.pallas_call(
        kernel_t,
        grid=(K, nt),
        in_specs=[
            pl.BlockSpec(index_map=lambda h, j: (0, 0),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec(index_map=lambda h, j: (0, 0),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((1, B, split), lambda h, j: (0, 0, j)),
            pl.BlockSpec((1, B, split), lambda h, j: (0, 0, j)),
            pl.BlockSpec((1, B, S, G, hd), lambda h, j: (h, 0, 0, 0, 0)),
            pl.BlockSpec((1, split, 1, hd), lambda h, j: (h, j, 0, 0)),
            pl.BlockSpec((1, split, 1, hd), lambda h, j: (h, j, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, B, S, G, hd),
                         lambda h, j: (h, j, 0, 0, 0, 0)),
            pl.BlockSpec((1, 1, B, S, G), lambda h, j: (h, j, 0, 0, 0)),
            pl.BlockSpec((1, 1, B, S, G), lambda h, j: (h, j, 0, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((K, nt, B, S, G, hd), f32),
            jax.ShapeDtypeStruct((K, nt, B, S, G), f32),
            jax.ShapeDtypeStruct((K, nt, B, S, G), f32),
        ],
        interpret=interpret,
    )(qpos, slopes, key_mask[None], key_positions[None],
      qg.transpose(1, 0, 2, 3, 4), k, v)

    ns = n_splits - nt
    kernel_s = functools.partial(_decode_kernel_mq, sm_scale=sm_scale,
                                 alibi=alibi, n_groups=G)
    o_s, m_s, l_s = pl.pallas_call(
        kernel_s,
        grid=(B, K, ns),
        in_specs=[
            pl.BlockSpec(index_map=lambda b, h, j: (0, 0),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec(index_map=lambda b, h, j: (0, 0),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((1, 1, split), lambda b, h, j: (b, 0, j + nt)),
            pl.BlockSpec((1, 1, split), lambda b, h, j: (b, 0, j + nt)),
            pl.BlockSpec((1, 1, S, G, hd), lambda b, h, j: (b, h, 0, 0, 0)),
            pl.BlockSpec((1, split, 1, hd),
                         lambda b, h, j: (h, j + nt, b, 0)),
            pl.BlockSpec((1, split, 1, hd),
                         lambda b, h, j: (h, j + nt, b, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, 1, S, G, hd),
                         lambda b, h, j: (b, h, j, 0, 0, 0)),
            pl.BlockSpec((1, 1, 1, S, G), lambda b, h, j: (b, h, j, 0, 0)),
            pl.BlockSpec((1, 1, 1, S, G), lambda b, h, j: (b, h, j, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, K, ns, S, G, hd), f32),
            jax.ShapeDtypeStruct((B, K, ns, S, G), f32),
            jax.ShapeDtypeStruct((B, K, ns, S, G), f32),
        ],
        interpret=interpret,
    )(qpos, slopes, key_mask[:, None, :], key_positions[:, None, :],
      qg, k, v)

    o_p = jnp.concatenate([o_t.transpose(2, 0, 1, 3, 4, 5), o_s], axis=2)
    m_p = jnp.concatenate([m_t.transpose(2, 0, 1, 3, 4), m_s], axis=2)
    l_p = jnp.concatenate([l_t.transpose(2, 0, 1, 3, 4), l_s], axis=2)
    out = merge_partials(o_p, m_p, l_p, axis=2)           # (B, K, S, G, hd)
    return out.transpose(0, 2, 1, 3, 4).reshape(B, S, H, hd).astype(q.dtype)
