"""Log-sum-exp combination of partial attention reductions.

Flash-style attention kernels split the key axis — into cache splits
(ops/flash_decode's split-K grid) or into legs (ops/cascade_prefill's
shared-trunk prefix leg + per-row suffix leg) — and each partition
reduces independently into a partial ``(o, m, l)`` triple: the
probability-weighted value accumulator, the running score max, and the
softmax normalizer, all computed against the partition's LOCAL max.
Combining partials is the one numerically delicate step, and before this
module it lived inline in two places of flash_decode.py (the single- and
multi-query kernels) with the cascade merge about to make three; the
arithmetic must stay IDENTICAL everywhere or a resumed/split path drifts
from the dense reference. This helper is now that single source
(ISSUE-16 satellite: the refactor is pinned bitwise against the
pre-refactor combine by tests/test_cascade.py).
"""

from __future__ import annotations

import jax.numpy as jnp


def merge_partials(o_p: jnp.ndarray, m_p: jnp.ndarray, l_p: jnp.ndarray,
                   axis: int) -> jnp.ndarray:
    """Combine partial flash reductions along ``axis`` — exact attention.

    ``o_p``: partial weighted-value accumulators, shaped like the final
    output with an extra partition axis at ``axis`` and the head-dim
    last. ``m_p``/``l_p``: the matching per-partition score maxima and
    normalizers (``o_p`` without the head-dim axis). Each partial is
    renormalized by the GLOBAL max across partitions, then the weighted
    accumulators and weights sum; a fully-masked partition carries
    ``m = -inf`` and weight exactly 0, so empty splits are no-ops. The
    ``1e-30`` floor only engages when EVERY partition is empty (an
    all-masked row), where the convention is an all-zero output row.
    """
    m = m_p.max(axis=axis)
    w = jnp.where(jnp.isfinite(m_p),
                  jnp.exp(m_p - jnp.expand_dims(m, axis)), 0.0)
    l = (w * l_p).sum(axis=axis)
    o = (w[..., None] * o_p).sum(axis=axis)
    return o / jnp.maximum(l, 1e-30)[..., None]
