"""Pallas flash attention: exact attention without the (S, S) score matrix.

The single-chip complement to parallel/ring_attention.py: within one chip,
XLA's default attention materializes the (B, H, S, S) score tensor in HBM
(O(S^2) memory); this kernel streams K/V blocks through VMEM with an online
softmax, so peak memory is O(S * hd) and the score tile lives entirely
on-chip. Use when a long sequence fits one chip's weights but not its
attention scores; shard over the mesh's ``seq`` axis (ring attention) when
it doesn't.

Layout contract matches models/decoder.py and parallel/ring_attention.py:
(B, S, H, hd), causal or full, with an optional per-row key validity mask
(any pattern — masking semantics equal the dense path's additive bias for
every real-token position; masked-query rows come back 0 and are ignored
downstream, exactly like the dense path's uniform-garbage pad rows).

Kernel design (pallas_guide.md patterns):
  grid = (B, H, S / BLOCK_Q); each program owns one query tile in VMEM and
  fori_loops over K/V tiles with ``pl.ds`` dynamic slices, carrying the
  (m, l, acc) online-softmax state as loop values. Causal programs stop at
  the diagonal block, and the loop starts at the row's first valid key
  block (both traced fori_loop bounds), so left-pad and upper-triangle work
  is skipped. Matmuls request fp32 accumulation (preferred_element_type).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

DEFAULT_BLOCK_Q = 128
DEFAULT_BLOCK_K = 128


def _flash_kernel(start_ref, slope_ref, mask_ref, kpos_ref, q_ref, k_ref,
                  v_ref, o_ref, *, causal: bool, block_q: int, block_k: int,
                  sm_scale: float, alibi: bool):
    b = pl.program_id(0)
    h = pl.program_id(1)
    qi = pl.program_id(2)
    q = q_ref[0, 0].astype(jnp.float32) * sm_scale          # (bq, hd)
    seq_len = k_ref.shape[2]
    n_kblocks = seq_len // block_k
    first_valid = start_ref[b, 0]  # index of the row's first valid key

    q_pos = qi * block_q + lax.broadcasted_iota(
        jnp.int32, (block_q, 1), 0)[:, 0]

    m0 = jnp.full((block_q,), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((block_q,), jnp.float32)
    acc0 = jnp.zeros((block_q, q.shape[-1]), jnp.float32)

    def body(j, carry):
        m, l, acc = carry
        k = k_ref[0, 0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, 0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32)  # (bq, bk)
        kmask = mask_ref[0, 0, pl.ds(j * block_k, block_k)] > 0  # (bk,)
        if alibi:
            # ALiBi: + slope_h * mask-aware key position (bloom). Matches
            # decoder._causal_bias exactly — positions come in precomputed.
            kp = kpos_ref[0, 0, pl.ds(j * block_k, block_k)]      # (bk,)
            s = s + slope_ref[h, 0] * kp.astype(jnp.float32)[None, :]
        valid = kmask[None, :]
        if causal:
            k_pos = j * block_k + lax.broadcasted_iota(
                jnp.int32, (1, block_k), 1)
            valid = valid & (q_pos[:, None] >= k_pos)
        s = jnp.where(valid, s, -jnp.inf)

        m_new = jnp.maximum(m, s.max(axis=-1))
        alpha = jnp.where(jnp.isfinite(m), jnp.exp(m - m_new), 0.0)
        p = jnp.exp(s - m_new[:, None])
        p = jnp.where(jnp.isfinite(s), p, 0.0)
        l = l * alpha + p.sum(axis=-1)
        acc = acc * alpha[:, None] + jnp.dot(
            p, v, preferred_element_type=jnp.float32)
        return m_new, l, acc

    # Blocks before the row's first valid key contribute nothing; causal
    # programs additionally stop at their diagonal block.
    lower = first_valid // block_k
    if causal:
        upper = lax.min(
            jnp.int32(n_kblocks),
            (qi * block_q + block_q + block_k - 1) // block_k,
        )
    else:
        upper = n_kblocks
    m, l, acc = lax.fori_loop(lower, upper, body, (m0, l0, acc0))
    o_ref[0, 0] = (acc / jnp.maximum(l, 1e-30)[:, None]).astype(o_ref.dtype)


@functools.partial(
    jax.jit, static_argnames=("causal", "block_q", "block_k", "interpret"))
def flash_attention(
    q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
    causal: bool = True,
    key_mask: jnp.ndarray | None = None,
    alibi_slopes: jnp.ndarray | None = None,
    key_positions: jnp.ndarray | None = None,
    block_q: int = DEFAULT_BLOCK_Q,
    block_k: int = DEFAULT_BLOCK_K,
    interpret: bool = False,
) -> jnp.ndarray:
    """Exact attention, (B, S, H, hd) layout, O(S*hd) memory.

    ``key_mask``: optional (B, S) {0,1} validity mask over key positions —
    any pattern (left pad, right pad, holes). Equivalent to the dense
    path's additive key-mask bias for every valid query position; rows of
    fully-masked queries return 0.
    ``alibi_slopes``: optional (H,) per-head ALiBi slopes (bloom). Adds
    ``slope_h * key_position`` to the scores; ``key_positions`` (B, S)
    mask-aware positions must be given with it (decoder.mask_positions).
    S must be divisible by the block sizes (blocks shrink automatically for
    short sequences). ``interpret=True`` runs the kernel in the Pallas
    interpreter (CPU tests).
    """
    B, S, H, hd = q.shape
    block_q = min(block_q, S)
    block_k = min(block_k, S)
    if S % block_q or S % block_k:
        raise ValueError(
            f"seq len {S} must be divisible by blocks ({block_q}, {block_k})"
        )
    alibi = alibi_slopes is not None
    if alibi and key_positions is None:
        raise ValueError("alibi_slopes requires key_positions")
    sm_scale = 1.0 / np.sqrt(hd)
    if key_mask is None:
        key_mask = jnp.ones((B, S), jnp.int32)
    key_mask = jnp.asarray(key_mask, jnp.int32)
    if key_positions is None:
        key_positions = jnp.zeros((B, S), jnp.int32)
    key_positions = jnp.asarray(key_positions, jnp.int32)
    if alibi_slopes is None:
        slopes = jnp.zeros((H, 1), jnp.float32)
    else:
        slopes = jnp.asarray(alibi_slopes, jnp.float32).reshape(H, 1)
    # First valid key index per row (loop lower bound; 0 when all-masked —
    # such rows are garbage on every path).
    first_valid = jnp.argmax(key_mask, axis=-1).astype(jnp.int32)

    # Kernel-friendly layout: (B, H, S, hd).
    qt = jnp.swapaxes(q, 1, 2)
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)

    kernel = functools.partial(
        _flash_kernel, causal=causal, block_q=block_q, block_k=block_k,
        sm_scale=sm_scale, alibi=alibi)

    out = pl.pallas_call(
        kernel,
        grid=(B, H, S // block_q),
        in_specs=[
            # Per-row first-valid index: whole (B, 1) array in SMEM (TPU
            # lowering wants full-array blocks for tiny scalars); programs
            # index it by their batch id.
            pl.BlockSpec(index_map=lambda b, h, i: (0, 0),
                         memory_space=pltpu.SMEM),
            # Per-head ALiBi slopes, whole (H, 1) array in SMEM.
            pl.BlockSpec(index_map=lambda b, h, i: (0, 0),
                         memory_space=pltpu.SMEM),
            # Key mask as (B, 1, S): one (1, 1, S) block per program.
            pl.BlockSpec((1, 1, S), lambda b, h, i: (b, 0, 0)),
            # Mask-aware key positions, same layout as the mask.
            pl.BlockSpec((1, 1, S), lambda b, h, i: (b, 0, 0)),
            pl.BlockSpec((1, 1, block_q, hd), lambda b, h, i: (b, h, i, 0)),
            pl.BlockSpec((1, 1, S, hd), lambda b, h, i: (b, h, 0, 0)),
            pl.BlockSpec((1, 1, S, hd), lambda b, h, i: (b, h, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, block_q, hd),
                               lambda b, h, i: (b, h, i, 0)),
        out_shape=jax.ShapeDtypeStruct(qt.shape, q.dtype),
        interpret=interpret,
    )(first_valid[:, None], slopes, key_mask[:, None, :],
      key_positions[:, None, :], qt, kt, vt)
    return jnp.swapaxes(out, 1, 2)
