"""Custom kernels: the single entry point for every attention kernel in
the framework.

- ``flash_attention`` (Pallas): prefill/full-sequence attention without
  the (S, S) score matrix — O(S * hd) memory, online softmax.
- ``flash_decode`` (Pallas): fused single-query decode attention over the
  KV cache — K-split online softmax + log-sum-exp combine, the decode-
  phase complement of ``flash_attention`` (ROADMAP item 2's MFU floor).
- ``cascade_attention`` (Pallas): shared-trunk prefill decomposition —
  the trunk's attention once per dispatch as dense MXU matmuls (optional
  in-kernel s8×s8 QK^T) plus per-row suffix attention, merged by
  ``merge_partials`` (ROADMAP item 1's prefill plateau).
- ``merge_partials`` (``ops/lse.py``): the one log-sum-exp partial-merge
  both the decode split-K reduction and the cascade trunk/suffix merge
  reduce through.
- ``ring_attention`` / ``ulysses_attention`` (explicit collectives): the
  multi-chip sequence-parallel kernels, re-exported from
  parallel/ring_attention.py so kernel consumers import ONE surface;
  ``reference_attention`` is the dense single-device ground truth every
  kernel is pinned against in tests.

SURVEY.md §2.5: none were *required* for reference parity; flash
attention extends the long-context ceiling and flash decode attacks the
decode-phase MFU plateau.
"""

from .flash_attention import (  # noqa: F401
    DEFAULT_BLOCK_K,
    DEFAULT_BLOCK_Q,
    flash_attention,
)
from .cascade_prefill import (  # noqa: F401
    cascade_attention,
    pick_block_n,
)
from .flash_decode import (flash_decode, flash_decode_mq,  # noqa: F401
                           pick_split)
from .lse import merge_partials  # noqa: F401
from ..parallel.ring_attention import (  # noqa: F401
    reference_attention,
    ring_attention,
    ulysses_attention,
)
