"""Custom Pallas TPU kernels for ops where XLA's default lowering is
memory-bound (SURVEY.md §2.5: none were *required* for reference parity;
flash attention extends the framework's long-context ceiling)."""

from .flash_attention import flash_attention  # noqa: F401
