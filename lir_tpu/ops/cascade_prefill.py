"""Shared-prefix cascade attention for the prefill phase (ROADMAP item 1).

The 36% MFU plateau of the isolated scoring step (BENCH_r02-r05) is a
PREFILL problem as much as a decode one: the paper's axis-1 workload asks
thousands of rephrasings of ~5 long legal-prompt trunks, so every
shared-trunk dispatch recomputes trunk attention once PER ROW even though
each row's queries see byte-identical trunk KV. This module is the
Hydragen-style decomposition (Juravsky et al.): attention over a
dispatch's cache splits into

- a PREFIX leg — every (row, position, head) query attends the ONE
  shared trunk KV block. Because the trunk KV carries no batch axis, the
  whole dispatch's queries flatten into a single (N, hd) x (hd, Tt)
  dense matmul per kv head (inter-query batching): one MXU-saturating
  GEMM instead of B batched thin ones, and a warm trunk gathered from
  the radix page pool costs zero recompute;
- a per-row SUFFIX leg — each rephrasing's tail attends its own
  remainder KV with ordinary causal masking;

merged by the same log-sum-exp combination the Flash-Decoding split-K
kernel uses (ops/lse.merge_partials — lifted out of flash_decode's
inline combines so all three fused paths share one reduction). The split
is exact: trunk keys all precede every suffix query, so the prefix leg
needs neither mask nor causality, and the merge reproduces softmax over
the full key axis bitwise-stably (parity vs the dense path is pinned at
every ladder extent by tests/test_cascade.py).

The prefix leg optionally fuses int8 QK^T INSIDE the kernel
(models/quant.py's dynamic rule — the same per-vector machinery
``shared_quant``/``QuantActivation`` apply around matmuls, here applied
to q and trunk-k blocks in VMEM): scores run s8 x s8 -> s32 on the MXU
at half the VMEM read traffic, scales fold on the s32 scores, softmax
and the PV contraction stay fp32. ``interpret=True`` runs the kernel in
the Pallas interpreter so tier-1 exercises it on CPU; production CPU
keeps the dense path (models/decoder.CASCADE_INTERPRET_ON_CPU is the
test hook, mirroring FUSED_DECODE_INTERPRET_ON_CPU).
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..models.quant import dynamic_quant
from .lse import merge_partials

# Flattened-query block edge: one MXU-shaped tile of inter-query-batched
# rows per grid program (the lane width; same edge family as
# flash_attention's DEFAULT_BLOCK_Q/K).
DEFAULT_BLOCK_N = 128


def pick_block_n(n: int, want: int = DEFAULT_BLOCK_N) -> int:
    """Query-block edge for N flattened rows: ``want`` when N reaches it
    (the padded tail block is masked by construction — pad rows are
    sliced off after the kernel), else N rounded up to a sublane
    multiple of 8 so tiny dispatches lower without relayout."""
    if n >= want:
        return int(want)
    return max(8 * ((int(n) + 7) // 8), 8)


def _prefix_kernel(slope_ref, q_ref, k_ref, v_ref, o_ref, m_ref, l_ref, *,
                   sm_scale: float, alibi: bool, int8_qk: bool):
    """One (kv head, query block) program of the prefix leg.

    q block: (bn, hd) flattened (row, position, group) queries; k/v: the
    WHOLE (Tt, hd) trunk for this kv head in VMEM — the trunk is one
    block on purpose (a bucket-ladder trunk at hd <= 128 is <= 512 KiB
    per side, and one block keeps the online-softmax state scalar per
    query row). Every trunk key precedes every query and every trunk
    slot is real, so there is no mask and no causal term; the partial
    (o, m, l) triple is always finite.
    """
    k = k_ref[0]                                          # (Tt, hd)
    if int8_qk:
        # models/quant.dynamic_quant INSIDE the kernel: per-query-row /
        # per-key-row int8 with fp32 scales, s8 x s8 -> s32 on the MXU,
        # scales (and the softmax 1/sqrt(hd)) folded on the s32 scores.
        qq, qs = dynamic_quant(q_ref[0])
        kq, ks = dynamic_quant(k)
        s32 = jnp.dot(qq, kq.T, preferred_element_type=jnp.int32)
        s = s32.astype(jnp.float32) * (qs.astype(jnp.float32)
                                       * sm_scale)[:, None] * ks[None, :]
    else:
        q = q_ref[0].astype(jnp.float32) * sm_scale       # (bn, hd)
        s = jnp.dot(q, k.astype(jnp.float32).T,
                    preferred_element_type=jnp.float32)   # (bn, Tt)
    if alibi:
        # ALiBi bias depends on the KEY position only (decoder.
        # _causal_bias) and trunk slot t IS position t, so the bias is
        # slope_row * iota — no position array needs to ride along.
        kp = jax.lax.broadcasted_iota(jnp.float32, s.shape, 1)
        s = s + slope_ref[0][:, None] * kp

    m = s.max(axis=-1)                                    # (bn,)
    p = jnp.exp(s - m[:, None])
    o_ref[0] = jnp.dot(p, v_ref[0].astype(jnp.float32),
                       preferred_element_type=jnp.float32)
    m_ref[0] = m
    l_ref[0] = p.sum(axis=-1)


def _prefix_partials(q, trunk_k, trunk_v, slopes, int8_qk: bool,
                     block_n: int, interpret: bool):
    """Prefix-leg partials: (o, m, l) shaped (B, K, R, G, hd) / (B, K, R, G).

    Inter-query batching: q (B, R, H, hd) flattens to (K, N, hd) with
    N = B*R*G — the whole dispatch is one dense GEMM per kv head against
    the single-row trunk — padded to a block multiple host-side (pad
    rows compute garbage partials that are sliced off before the merge).
    """
    B, R, H, hd = q.shape
    K, Tt = trunk_k.shape[0], trunk_k.shape[1]
    G = H // K
    N = B * R * G
    sm_scale = 1.0 / math.sqrt(hd)
    bn = pick_block_n(N, block_n)
    n_pad = -N % bn
    qf = (q.reshape(B, R, K, G, hd).transpose(2, 0, 1, 3, 4)
          .reshape(K, N, hd))
    qf = jnp.pad(qf, ((0, 0), (0, n_pad), (0, 0)))
    alibi = slopes is not None
    if alibi:
        # Per-flattened-row slope: row n = (b*R + r)*G + g belongs to
        # query head h = kh*G + g.
        sl = jnp.broadcast_to(
            jnp.asarray(slopes, jnp.float32).reshape(K, 1, G),
            (K, B * R, G)).reshape(K, N)
    else:
        sl = jnp.zeros((K, N), jnp.float32)
    sl = jnp.pad(sl, ((0, 0), (0, n_pad)))
    npad = N + n_pad

    kernel = functools.partial(_prefix_kernel, sm_scale=sm_scale,
                               alibi=alibi, int8_qk=int8_qk)
    f32 = jnp.float32
    o_p, m_p, l_p = pl.pallas_call(
        kernel,
        grid=(K, npad // bn),
        in_specs=[
            pl.BlockSpec((1, bn), lambda h, i: (h, i)),
            pl.BlockSpec((1, bn, hd), lambda h, i: (h, i, 0)),
            # The whole trunk per program (see _prefix_kernel).
            pl.BlockSpec((1, Tt, hd), lambda h, i: (h, 0, 0)),
            pl.BlockSpec((1, Tt, hd), lambda h, i: (h, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bn, hd), lambda h, i: (h, i, 0)),
            pl.BlockSpec((1, bn), lambda h, i: (h, i)),
            pl.BlockSpec((1, bn), lambda h, i: (h, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((K, npad, hd), f32),
            jax.ShapeDtypeStruct((K, npad), f32),
            jax.ShapeDtypeStruct((K, npad), f32),
        ],
        interpret=interpret,
    )(sl, qf, trunk_k, trunk_v)

    def unflat(x):
        x = x[:, :N]
        x = x.reshape((K, B, R, G) + x.shape[2:])
        return jnp.moveaxis(x, 0, 1)                      # (B, K, R, G, ...)

    return unflat(o_p), unflat(m_p), unflat(l_p)


def _suffix_partials(q, sfx_k, sfx_v, suffix_mask, q_positions, slopes):
    """Suffix-leg partials over each row's OWN remainder KV: causal
    within the window (key position <= query position, mask-aware — the
    exact ``decoder._causal_bias`` rule, so ragged right-padded and
    left-padded windows both behave like unpadded rows), ALiBi on key
    positions, grouped GQA contraction against un-repeated k/v. Plain
    XLA on purpose: the per-row window is short (R x R) and batched thin
    — there is no (S, T) tile to save, exactly why decode steps stay
    dense too. A fully-masked (pad) query row yields m = -inf / l = 0
    and defers entirely to the prefix leg in the merge."""
    B, R, H, hd = q.shape
    K = sfx_k.shape[2]
    G = H // K
    sm_scale = 1.0 / math.sqrt(hd)
    qg = q.reshape(B, R, K, G, hd).astype(jnp.float32) * sm_scale
    s = jnp.einsum("brkgd,btkd->bkrgt", qg, sfx_k.astype(jnp.float32))
    kp = q_positions.astype(jnp.float32)                  # keys = queries
    if slopes is not None:
        sl = jnp.asarray(slopes, jnp.float32).reshape(K, G)
        s = s + sl[None, :, None, :, None] * kp[:, None, None, None, :]
    valid = ((suffix_mask[:, None, :] > 0)
             & (q_positions[:, None, :] <= q_positions[:, :, None]))
    s = jnp.where(valid[:, None, :, None, :], s, -jnp.inf)
    m = s.max(axis=-1)                                    # (B, K, R, G)
    p = jnp.exp(s - m[..., None])
    p = jnp.where(jnp.isfinite(s), p, 0.0)
    o = jnp.einsum("bkrgt,btkd->bkrgd", p, sfx_v.astype(jnp.float32))
    return o, m, p.sum(axis=-1)


def _fused_cascade_kernel(slope_ref, qpos_ref, smask_ref, q_ref, sk_ref,
                          sv_ref, tk_ref, tv_ref, o_ref, *, sm_scale: float,
                          alibi: bool, n_groups: int):
    """One (kv head, batch row) program of the FULLY-FUSED cascade:
    prefix leg + suffix leg + log-sum-exp merge in a single kernel, so
    the partial (o, m, l) triples never round-trip through HBM. Every
    per-element op mirrors the two-leg path exactly — the prefix block
    is :func:`_prefix_kernel`'s arithmetic, the suffix block is
    :func:`_suffix_partials`' (per (row, kv head) slice), and the merge
    is :func:`~lir_tpu.ops.lse.merge_partials`' stacked-sum order — so
    the fused output is BITWISE the two-leg path's (pinned across the
    cascade matrix by tests/test_cascade.py)."""
    G = n_groups
    q = q_ref[0, 0].astype(jnp.float32) * sm_scale        # (R*G, hd)
    RG, hd = q.shape
    R = RG // G
    # Per-flattened-row slopes arrive HOST-built (like _prefix_partials'
    # flattened slope array): building them in-kernel from a (G,) block
    # lets XLA contract the bias mul+add into an FMA, a 1-ulp drift off
    # the two-leg lowering.
    slope_rg = slope_ref[0]                               # (RG,)

    # Prefix leg (== _prefix_kernel, non-int8): no mask, no causality.
    tk = tk_ref[0]                                        # (Tt, hd)
    s = jnp.dot(q, tk.astype(jnp.float32).T,
                preferred_element_type=jnp.float32)       # (RG, Tt)
    if alibi:
        kp_t = jax.lax.broadcasted_iota(jnp.float32, s.shape, 1)
        s = s + slope_rg[:, None] * kp_t
    m_t = s.max(axis=-1)                                  # (RG,)
    p = jnp.exp(s - m_t[:, None])
    o_t = jnp.dot(p, tv_ref[0].astype(jnp.float32),
                  preferred_element_type=jnp.float32)
    l_t = p.sum(axis=-1)

    # Suffix leg (== _suffix_partials for this (b, kh) slice): causal
    # within the window, mask-aware, ALiBi on absolute key positions.
    sk = sk_ref[0, 0].astype(jnp.float32)                 # (R, hd)
    s2 = jnp.dot(q, sk.T, preferred_element_type=jnp.float32)  # (RG, R)
    qp = qpos_ref[0]                                      # (R,)
    if alibi:
        s2 = s2 + slope_rg[:, None] * qp.astype(jnp.float32)[None, :]
    valid = (smask_ref[0] > 0)[None, :] & (qp[None, :] <= qp[:, None])
    valid = jnp.broadcast_to(valid[:, None, :], (R, G, R)).reshape(RG, R)
    s2 = jnp.where(valid, s2, -jnp.inf)
    m_s = s2.max(axis=-1)
    p2 = jnp.exp(s2 - m_s[:, None])
    p2 = jnp.where(jnp.isfinite(s2), p2, 0.0)             # all-masked row
    o_s = jnp.dot(p2, sv_ref[0, 0].astype(jnp.float32),
                  preferred_element_type=jnp.float32)
    l_s = p2.sum(axis=-1)

    # In-VMEM merge: merge_partials' exact stacked-reduction order over
    # the two partials, trunk first.
    m_p = jnp.stack([m_t, m_s])
    m = m_p.max(axis=0)
    w = jnp.where(jnp.isfinite(m_p), jnp.exp(m_p - m[None]), 0.0)
    l = (w * jnp.stack([l_t, l_s])).sum(axis=0)
    o = (w[..., None] * jnp.stack([o_t, o_s])).sum(axis=0)
    o_ref[0, 0] = o / jnp.maximum(l, 1e-30)[..., None]


def _cascade_fused(q, sfx_k, sfx_v, trunk_k, trunk_v, suffix_mask,
                   q_positions, slopes, interpret: bool):
    """Single-launch cascade attention: grid (K, B), each program owns
    one row's R*G flattened queries against the whole trunk plus the
    row's own suffix window, merged in VMEM — one kernel, zero HBM
    round-trips for the partials."""
    B, R, H, hd = q.shape
    K, Tt = trunk_k.shape[0], trunk_k.shape[1]
    G = H // K
    RG = R * G
    sm_scale = 1.0 / math.sqrt(hd)
    alibi = slopes is not None
    if alibi:
        sl = jnp.broadcast_to(
            jnp.asarray(slopes, jnp.float32).reshape(K, 1, G),
            (K, R, G)).reshape(K, RG)
    else:
        sl = jnp.zeros((K, RG), jnp.float32)
    qf = (q.reshape(B, R, K, G, hd).transpose(0, 2, 1, 3, 4)
          .reshape(B, K, RG, hd))
    skt = sfx_k.transpose(0, 2, 1, 3)                     # (B, K, R, hd)
    svt = sfx_v.transpose(0, 2, 1, 3)
    kernel = functools.partial(_fused_cascade_kernel, sm_scale=sm_scale,
                               alibi=alibi, n_groups=G)
    out = pl.pallas_call(
        kernel,
        grid=(K, B),
        in_specs=[
            pl.BlockSpec((1, RG), lambda h, b: (h, 0)),
            pl.BlockSpec((1, R), lambda h, b: (b, 0)),
            pl.BlockSpec((1, R), lambda h, b: (b, 0)),
            pl.BlockSpec((1, 1, RG, hd), lambda h, b: (b, h, 0, 0)),
            pl.BlockSpec((1, 1, R, hd), lambda h, b: (b, h, 0, 0)),
            pl.BlockSpec((1, 1, R, hd), lambda h, b: (b, h, 0, 0)),
            pl.BlockSpec((1, Tt, hd), lambda h, b: (h, 0, 0)),
            pl.BlockSpec((1, Tt, hd), lambda h, b: (h, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, RG, hd), lambda h, b: (b, h, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((B, K, RG, hd), jnp.float32),
        interpret=interpret,
    )(sl, jnp.asarray(q_positions, jnp.int32),
      jnp.asarray(suffix_mask, jnp.int32), qf, skt, svt, trunk_k, trunk_v)
    out = out.reshape(B, K, R, G, hd).transpose(0, 2, 1, 3, 4)
    return out.reshape(B, R, H, hd).astype(q.dtype)


@functools.partial(jax.jit,
                   static_argnames=("int8_qk", "block_n", "interpret",
                                    "fused_suffix"))
def cascade_attention(q, sfx_k, sfx_v, trunk_k, trunk_v, suffix_mask,
                      q_positions, alibi_slopes=None, int8_qk: bool = False,
                      block_n: int = DEFAULT_BLOCK_N,
                      interpret: bool = False,
                      fused_suffix: bool = True) -> jnp.ndarray:
    """Shared-trunk cascade attention for one layer's remainder window.

    ``q``: (B, R, H, hd) post-RoPE queries at the dispatch's remainder
    positions. ``sfx_k``/``sfx_v``: (B, R, K, hd) the window's own
    post-RoPE k/v (un-repeated GQA). ``trunk_k``/``trunk_v``:
    (K, Tt, hd) the SHARED trunk KV — one row, no batch axis; slot t is
    position t and every slot is real. ``suffix_mask``: (B, R) validity
    of the remainder positions; ``q_positions``: (B, R) mask-aware
    ABSOLUTE positions (trunk_len + window-local). Returns (B, R, H, hd)
    in q's dtype — softmax over trunk + window keys, exact.

    ``fused_suffix`` (default ON, RuntimeConfig.cascade_fused_suffix)
    runs prefix + suffix + merge as ONE Pallas launch with the partials
    merged in VMEM — bitwise the two-leg path below. The int8-QK^T
    variant keeps the two-leg split (its prefix leg quantizes in-kernel
    over flattened query blocks; --no-cascade-fused-suffix restores the
    two-leg path for float too).
    """
    if fused_suffix and not int8_qk:
        return _cascade_fused(q, sfx_k, sfx_v, trunk_k, trunk_v,
                              suffix_mask, q_positions, alibi_slopes,
                              interpret)
    B, R, H, hd = q.shape
    o_t, m_t, l_t = _prefix_partials(q, trunk_k, trunk_v, alibi_slopes,
                                     int8_qk, block_n, interpret)
    o_s, m_s, l_s = _suffix_partials(q, sfx_k, sfx_v, suffix_mask,
                                     q_positions, alibi_slopes)
    out = merge_partials(jnp.stack([o_t, o_s], axis=2),
                         jnp.stack([m_t, m_s], axis=2),
                         jnp.stack([l_t, l_s], axis=2),
                         axis=2)                          # (B, K, R, G, hd)
    return out.transpose(0, 2, 1, 3, 4).reshape(B, R, H, hd).astype(q.dtype)
