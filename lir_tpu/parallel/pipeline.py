"""Pipeline parallelism (GPipe-style) over a ``pipe`` mesh axis.

The reference runs on one GPU and has no pipeline story at all; this is
the TPU-native completion of the parallelism matrix (dp x tp x sp x PP):
the layer stack is split into P contiguous stages (the stacked (L, ...)
param arrays shard on axis 0), the batch splits into M microbatches, and
activations flow stage-to-stage with ``lax.ppermute`` — ONE (B/M, S, D)
transfer per stage boundary per microbatch, instead of tensor
parallelism's two all-reduces per LAYER. That trade makes PP the right
axis when interconnect is the scarce resource (multi-slice DCN, or long
chains of chips), while TP stays right within an ICI-rich slice; the two
compose (a stage can itself be TP-sharded) but v1 keeps the pipe mesh
one-dimensional.

Scope: the full-sequence FORWARD (prefill / capture scoring path). The
KV-cached decode loop stays on the dp/tp/sp axes — a token-level decode
pipeline would add a bubble per generated token, which at our 4-16-token
decode budgets can never amortize (the classic GPipe bubble argument:
utilization = M / (M + P - 1) needs M >> P, and decode's M is 1).

Schedule: plain GPipe fill-drain over M + P - 1 ticks. Every stage runs
its layer chunk every tick (bubble ticks compute on garbage and are
discarded — on SPMD hardware predicating the work away saves nothing),
stage 0 injects microbatch t, stage P-1 collects microbatch t-(P-1).
Utilization M/(M+P-1); pick n_micro >= ~4x the stage count.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ._compat import shard_map

from ..models import decoder
from ..models.registry import ModelConfig

Params = Any


def build_pipe_mesh(n_stages: int, devices=None) -> Mesh:
    """A 1-axis ('pipe',) mesh of n_stages devices."""
    if devices is None:
        devices = jax.devices()
    if n_stages > len(devices):
        raise ValueError(f"pipeline needs {n_stages} devices, "
                         f"have {len(devices)}")
    return Mesh(np.asarray(devices[:n_stages]), ("pipe",))


def _layer_spec_tree(layer_params: Params):
    """PartitionSpec tree: every stacked (L, ...) leaf shards its LAYER
    axis over 'pipe' (QuantTensor payload/scale leaves included — the
    layer axis leads both)."""
    return jax.tree.map(
        lambda leaf: P("pipe", *([None] * (leaf.ndim - 1))), layer_params)


def shard_params_pipelined(params: Params, cfg: ModelConfig,
                           mesh: Mesh) -> Params:
    """Place the param tree for pipeline execution: layer stacks split
    across stages (axis 0 over 'pipe'), embeddings/norms/head replicated
    (stage 0 embeds, stage P-1 unembeds; replication keeps v1 simple and
    costs one vocab matrix per chip)."""
    P_ = mesh.shape["pipe"]
    if cfg.n_layers % P_:
        raise ValueError(
            f"n_layers={cfg.n_layers} must divide into {P_} pipeline stages")
    placed = {}
    for key, sub in params.items():
        if key == "layers":
            placed[key] = jax.tree.map(
                lambda leaf, spec: jax.device_put(
                    leaf, NamedSharding(mesh, spec)),
                sub, _layer_spec_tree(sub))
        else:
            placed[key] = jax.tree.map(
                lambda leaf: jax.device_put(leaf, NamedSharding(mesh, P())),
                sub)
    return placed


def forward_pipelined(params: Params, cfg: ModelConfig, tokens: jax.Array,
                      attn_mask: Optional[jax.Array] = None,
                      mesh: Optional[Mesh] = None,
                      n_micro: int = 4) -> jax.Array:
    """Pipeline-parallel full-sequence causal forward.

    Semantics match ``decoder.forward`` exactly (left-pad masks, RoPE /
    learned / ALiBi positions, fp32 logits (B, S, V)); parity is pinned in
    tests/test_pipeline_parallel.py. ``tokens``/``attn_mask``: (B, S) with
    B % n_micro == 0.
    """
    if mesh is None:
        mesh = build_pipe_mesh(jax.device_count())
    n_stages = mesh.shape["pipe"]
    B, S = tokens.shape
    if B % n_micro:
        raise ValueError(f"batch {B} must divide into {n_micro} microbatches")
    if cfg.n_layers % n_stages:
        raise ValueError(f"n_layers={cfg.n_layers} must divide into "
                         f"{n_stages} pipeline stages")
    if attn_mask is None:
        attn_mask = jnp.ones_like(tokens)
    Bm = B // n_micro

    layer_params = params["layers"]
    other = {k: v for k, v in params.items() if k != "layers"}

    def kernel(layers_local, other_p, toks, mask):
        stage = lax.axis_index("pipe")
        last = n_stages - 1
        full = dict(other_p)
        full["layers"] = layers_local

        # Per-microbatch views: (M, Bm, S)
        toks_mb = toks.reshape(n_micro, Bm, S)
        mask_mb = mask.reshape(n_micro, Bm, S)

        def chunk(x, mb_idx):
            """Run this stage's layer chunk on activations x (Bm, S, D)
            for microbatch mb_idx (positions/bias derived per microbatch —
            every stage needs them, not just stage 0)."""
            m = lax.dynamic_index_in_dim(mask_mb, mb_idx, 0, keepdims=False)
            positions = decoder.mask_positions(m)
            sin = cos = None
            if cfg.pos_embedding == "rotary":
                sin, cos = decoder._rope_sincos(positions, cfg.rotary_dim,
                                                cfg.rope_theta)
            bias = decoder._causal_bias(m, positions, cfg)
            x, _ = decoder._scan_blocks(full, cfg, x, sin, cos, bias,
                                        key_mask=m)
            return x

        def embed_mb(mb_idx):
            t = lax.dynamic_index_in_dim(toks_mb, mb_idx, 0, keepdims=False)
            m = lax.dynamic_index_in_dim(mask_mb, mb_idx, 0, keepdims=False)
            return decoder._embed(full, cfg, t, decoder.mask_positions(m))

        # Embeddings are never quantized (quant.py excludes tok_embed), so
        # the leaf's own shape/dtype describe the activations directly.
        D = full["tok_embed"].shape[-1]
        act_dtype = full["tok_embed"].dtype

        def tick(carry, t):
            buf, outs = carry
            # Which microbatch this stage processes at tick t (clamped in
            # the bubble; the result is discarded then).
            mb = jnp.clip(t - stage, 0, n_micro - 1)
            x_in = jnp.where(stage == 0, embed_mb(mb), buf)
            y = chunk(x_in, mb)
            # Hand to the next stage. No (last -> 0) edge: stage 0's
            # incoming buffer is zeros, and it never reads it.
            buf = lax.ppermute(y, "pipe",
                               [(i, i + 1) for i in range(n_stages - 1)])
            # Last stage banks finished microbatches (valid ticks only).
            out_idx = jnp.clip(t - last, 0, n_micro - 1)
            valid = (stage == last) & (t >= last)
            outs = jnp.where(
                valid,
                lax.dynamic_update_slice(outs, y[None],
                                         (out_idx, 0, 0, 0)),
                outs)
            return (buf, outs), None

        buf0 = jnp.zeros((Bm, S, D), act_dtype)
        outs0 = jnp.zeros((n_micro, Bm, S, D), act_dtype)
        (_, outs), _ = lax.scan(tick, (buf0, outs0),
                                jnp.arange(n_micro + n_stages - 1))

        # psum the (B, S, D) HIDDEN STATES (non-last stages contribute
        # zeros), then unembed on every stage: the collective moves D-wide
        # activations, not the V-wide fp32 logits — ~V/D (often 10-70x)
        # less traffic on exactly the slow links PP is chosen for. The
        # redundant unembed compute is replicated work XLA already
        # schedules locally.
        hidden = lax.psum(
            jnp.where(stage == last, outs, jnp.zeros_like(outs)), "pipe")
        return decoder._unembed(full, cfg, hidden.reshape(B, S, -1))

    in_specs = (_layer_spec_tree(layer_params),
                jax.tree.map(lambda _: P(), other), P(), P())
    return shard_map(kernel, mesh=mesh, in_specs=in_specs, out_specs=P(),
                     check_vma=False)(layer_params, other, tokens, attn_mask)
