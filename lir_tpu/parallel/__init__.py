"""Distributed layer: device mesh, parameter sharding, sequence parallelism.

No hand-written communication code on the tensor-parallel path — sharding
annotations let XLA emit the ICI collectives (SURVEY.md §5). The explicit
collectives live in ring_attention.py (ppermute ring, all_to_all Ulysses)
where the schedule IS the algorithm.

ATTENTION-KERNEL SURFACE: ``lir_tpu.ops`` is the single kernel entry
point — it re-exports ``reference_attention`` / ``ring_attention`` /
``ulysses_attention`` alongside the Pallas ``flash_attention`` and
``flash_decode`` kernels. The re-exports below remain for backward
compatibility with existing ``lir_tpu.parallel`` importers; new code
should import kernels from ``lir_tpu.ops`` and keep this package for
the mesh/sharding machinery (sharding, seq_forward, multihost,
pipeline).
"""

from . import sharding  # noqa: F401
from .ring_attention import (  # noqa: F401
    reference_attention,
    ring_attention,
    seq_sharded,
    ulysses_attention,
)
from .seq_forward import (  # noqa: F401
    forward_seq_parallel,
    make_seq_attn_impl,
    prefill_seq_parallel,
    seq_batch_sharding,
)
from .multihost import (  # noqa: F401
    barrier,
    gather_rows,
    host_shard,
    is_multiprocess,
)
from .pipeline import (  # noqa: F401
    build_pipe_mesh,
    forward_pipelined,
    shard_params_pipelined,
)
