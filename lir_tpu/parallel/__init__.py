"""Distributed layer: device mesh, parameter sharding, sequence parallelism.

No hand-written communication code on the tensor-parallel path — sharding
annotations let XLA emit the ICI collectives (SURVEY.md §5). The explicit
collectives live in ring_attention.py (ppermute ring, all_to_all Ulysses)
where the schedule IS the algorithm.
"""

from . import sharding  # noqa: F401
from .ring_attention import (  # noqa: F401
    reference_attention,
    ring_attention,
    seq_sharded,
    ulysses_attention,
)
from .seq_forward import (  # noqa: F401
    forward_seq_parallel,
    make_seq_attn_impl,
    prefill_seq_parallel,
    seq_batch_sharding,
)
from .multihost import (  # noqa: F401
    barrier,
    gather_rows,
    host_shard,
    is_multiprocess,
)
from .pipeline import (  # noqa: F401
    build_pipe_mesh,
    forward_pipelined,
    shard_params_pipelined,
)
