"""Multi-host result gathering, process coordination, and liveness.

SURVEY.md §5 names the mechanism for collecting sweep results across hosts:
``jax.experimental.multihost_utils.process_allgather`` over ICI/DCN — the
TPU-native replacement for the reference's "download the batch output file"
step (perturb_prompts.py:332-345). On a single-process run (one host, any
number of chips) every helper degrades to the identity, so sweep drivers
call them unconditionally.

LIVENESS (lir_tpu/guard): a collective is also the pod's deadliest
failure mode — one dead or wedged peer parks every LIVE host inside
``process_allgather``/``sync_global_devices`` forever, with no exception
for the recovery machinery to catch. :func:`barrier` therefore accepts a
timeout (the collective runs on a watched thread — guard/watchdog) and
:func:`liveness_barrier` fronts it with a per-host heartbeat allgather,
so at every sweep shard boundary the survivors learn which peers are
alive, how far each got, and — when a peer is gone — exit with
:class:`HostDesyncError` while their shard artifacts and manifests are
already flushed (resumable), rather than hanging in ICI/DCN.
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np

from ..utils.logging import get_logger

log = get_logger(__name__)


class HostDesyncError(RuntimeError):
    """A multihost collective outlived its liveness timeout: a peer host
    is presumed dead or wedged. Raised on the SURVIVORS — their shard
    results and manifests are flushed before every guarded barrier, so
    the correct response is to exit and resume, not to wait."""


def initialize(coordinator_address: str | None = None,
               num_processes: int | None = None,
               process_id: int | None = None,
               required: bool = False) -> bool:
    """Bring up the JAX distributed runtime for a multi-host pod.

    On a real TPU pod slice ``jax.distributed.initialize()`` auto-detects
    the coordinator and process topology from the TPU metadata; the three
    arguments exist for manual bring-up (CPU/GPU clusters, DCN-connected
    multislice). Collectives then ride ICI within a slice and DCN across
    slices — the jobs themselves never change, because every helper in
    this module (and ``host_shard``/``gather_rows`` in the sweep drivers)
    keys off ``jax.process_count()``.

    Returns True when the distributed runtime came up, False when running
    single-process (no cluster detected / already initialized) — callers
    proceed either way. ``required=True`` (what the CLI's explicit
    ``--multihost`` passes) turns a failed bring-up into a hard error
    instead: a host that silently fell back to process_count()==1 would
    take the ENTIRE grid via host_shard while its peers sweep shards —
    duplicate scoring and conflicting manifest writes.
    """
    try:
        jax.distributed.initialize(coordinator_address, num_processes,
                                   process_id)
    except Exception as err:  # noqa: BLE001 — single-host is a normal path
        # A launcher (or an earlier call) may have brought the runtime up
        # already; that is a SUCCESSFUL multi-host state, not a bring-up
        # failure (ADVICE r2 #2). jax raises a RuntimeError whose text
        # varies by version, so probe the outcome instead of the message.
        try:
            if jax.process_count() > 1:
                log.info("jax.distributed already up: process %d of %d",
                         jax.process_index(), jax.process_count())
                return True
        except Exception:  # noqa: BLE001 — no runtime at all
            pass
        if required:
            # Distinguish "runtime is up but reports one process" (a
            # launcher pre-initialized a single-process topology — the
            # bring-up itself SUCCEEDED; the topology is what's wrong) from
            # a genuine bring-up failure, so --multihost users see the real
            # state instead of a misattributed error (ADVICE r3 #3).
            already_up = False
            try:
                already_up = jax.distributed.is_initialized()
            except Exception:  # noqa: BLE001 — probe only
                pass
            if already_up:
                raise RuntimeError(
                    "--multihost requested but the distributed runtime was "
                    "already initialized with a SINGLE-process topology "
                    "(process_count()==1) — this host would take the entire "
                    "grid while any peers sweep shards. Fix the launcher's "
                    "coordinator/num_processes settings rather than the "
                    f"bring-up call. (initialize() said: {err})") from err
            raise RuntimeError(
                f"--multihost requested but distributed bring-up failed: "
                f"{err}") from err
        log.info("single-process mode (distributed init unavailable: %s)",
                 err)
        return False
    if required and jax.process_count() == 1:
        # Bring-up "succeeded" but found no peers (e.g. a lone TPU VM whose
        # coordinator config is missing): under --multihost this host would
        # take the ENTIRE grid via host_shard while any correctly-configured
        # peers sweep shards — the same duplicate-scoring hazard as the
        # pre-initialized single-process case above, so it must be as loud.
        raise RuntimeError(
            "--multihost requested but jax.distributed came up with a "
            "SINGLE-process topology (process_count()==1) — no peers were "
            "found. Check the coordinator address / pod slice "
            "configuration.")
    log.info("jax.distributed up: process %d of %d, %d local devices",
             jax.process_index(), jax.process_count(),
             jax.local_device_count())
    return True


def is_multiprocess() -> bool:
    return jax.process_count() > 1


def gather_rows(local_rows: np.ndarray) -> np.ndarray:
    """All-gather per-host result rows to every host.

    `local_rows`: (n_local, ...) numeric array of this host's scored rows
    (row order within a host is preserved; hosts are concatenated in
    process-index order). Single-process: returns the input unchanged.
    """
    if not is_multiprocess():
        return np.asarray(local_rows)
    from jax.experimental import multihost_utils

    gathered = multihost_utils.process_allgather(np.asarray(local_rows))
    return np.reshape(gathered, (-1,) + np.asarray(local_rows).shape[1:])


def _bounded(fn, name: str, timeout_s: float):
    """Run one collective on a watched thread (guard/watchdog.watch_call)
    with a hard deadline. On expiry the collective is abandoned (the
    worker thread stays parked in the C++ call — the process is exiting
    anyway) and HostDesyncError carries the diagnosis."""
    from ..guard.watchdog import DispatchStalled, watch_call

    try:
        return watch_call(fn, timeout_s, label=f"multihost:{name}")
    except DispatchStalled as err:
        raise HostDesyncError(
            f"multihost collective {name!r} did not complete within "
            f"{timeout_s:.0f}s — a peer host is presumed dead or wedged "
            f"(process {jax.process_index()} of {jax.process_count()} "
            f"reporting). This host's shard artifacts and manifest are "
            f"already flushed; exit and re-launch to resume.") from err


def barrier(name: str, timeout_s: Optional[float] = None) -> None:
    """Synchronize hosts at a named point (e.g. before a manifest flush so
    one host's resume view can't run ahead of another's writes).
    ``timeout_s`` bounds the wait: a barrier a peer never reaches raises
    HostDesyncError instead of hanging forever (None/<=0 keeps the
    legacy unbounded wait)."""
    if not is_multiprocess():
        return
    from jax.experimental import multihost_utils

    if timeout_s is None or timeout_s <= 0:
        multihost_utils.sync_global_devices(name)
        return
    _bounded(lambda: multihost_utils.sync_global_devices(name), name,
             timeout_s)


def heartbeat(name: str, payload: int = 0,
              timeout_s: Optional[float] = None) -> np.ndarray:
    """All-gather one ``(process_index, payload)`` beat per host —
    liveness plus progress (the sweep sends its flushed row count) in a
    single cheap collective. Returns the (n_hosts, 2) table, int64, in
    process order. Single-process: the identity (this host's beat)."""
    beat = np.asarray([[jax.process_index(), int(payload)]], np.int64)
    if not is_multiprocess():
        return beat
    from jax.experimental import multihost_utils

    fn = lambda: multihost_utils.process_allgather(beat)  # noqa: E731
    gathered = (fn() if timeout_s is None or timeout_s <= 0
                else _bounded(fn, f"heartbeat:{name}", timeout_s))
    return np.reshape(np.asarray(gathered), (-1, 2))


def liveness_barrier(name: str, timeout_s: Optional[float] = None,
                     payload: int = 0, stats=None):
    """The guarded shard-boundary fence: heartbeat allgather (who is
    alive, how far each host got) then a timeout-bounded barrier. Either
    step expiring raises HostDesyncError on the survivors; the heartbeat
    table is logged first so the operator can see WHICH peer went dark
    on the next boundary. ``stats`` (profiling.GuardStats) counts
    heartbeats and barrier timeouts. Single-process: identity, returns
    this host's beat."""
    if not is_multiprocess():
        return heartbeat(name, payload)
    try:
        beats = heartbeat(name, payload, timeout_s)
        if stats is not None:
            stats.count("heartbeats")
        log.info("liveness %s: %d/%d hosts beating — %s", name,
                 beats.shape[0], jax.process_count(),
                 "; ".join(f"host{int(h)}={int(p)}" for h, p in beats))
        barrier(name, timeout_s)
        return beats
    except HostDesyncError:
        if stats is not None:
            stats.count("barrier_timeouts")
        log.error("liveness %s: collective timed out — exiting resumable "
                  "rather than hanging on a dead peer", name)
        raise


def lease_fence(name: str, all_done, work,
                timeout_s: Optional[float] = None,
                poll_s: float = 0.05, payload: int = 0, stats=None):
    """The LEASE-AWARE shard fence (engine/lease.py): instead of
    parking at the barrier while a slow or dead peer sits on a static
    shard, a host that finished its own shards DRAINS the lease log
    first — ``work()`` steals-and-scores one expired shard per call
    (returning True when it did anything) — and only enters the
    ordinary liveness barrier once ``all_done()`` reports every shard
    completed. A straggler thus costs at most one lease TTL of the
    fleet's time (its shards get stolen), not the whole fence.

    ``timeout_s`` bounds the WHOLE drain + barrier: if shards stay
    unfinished with nothing stealable past the bound (a live peer
    renewing a lease it never finishes), HostDesyncError fires with the
    same resumable-exit contract as :func:`liveness_barrier`."""
    import time as _time

    deadline = (None if timeout_s is None or timeout_s <= 0
                else _time.monotonic() + timeout_s)
    waited_logged = False
    while not all_done():
        if work():
            continue
        if deadline is not None and _time.monotonic() > deadline:
            if stats is not None:
                stats.count("barrier_timeouts")
            raise HostDesyncError(
                f"lease fence {name!r}: shards still unfinished after "
                f"{timeout_s:.0f}s with nothing left to steal — a peer "
                f"holds a live lease it never completes. This host's "
                f"shard artifacts and manifest are flushed; exit and "
                f"re-launch to resume.")
        if not waited_logged:
            waited_logged = True
            log.info("lease fence %s: own shards done; waiting on "
                     "live foreign leases (stealing any that expire)",
                     name)
        _time.sleep(poll_s)
    return liveness_barrier(name, timeout_s=timeout_s, payload=payload,
                            stats=stats)


def gather_stacked(arr: np.ndarray) -> np.ndarray:
    """All-gather one equal-shape array per host, stacked on a new
    leading host axis: returns (n_hosts, *shape) in process-index order.
    The streaming-statistics fence merge rides this (every host's shard
    accumulator is the same (P, R) lattice; the merge is a slot-wise
    union of the stack — stats/streaming.merge_accums). Single-process:
    the input under a length-1 leading axis."""
    arr = np.asarray(arr)
    if not is_multiprocess():
        return arr[None]
    from jax.experimental import multihost_utils

    gathered = multihost_utils.process_allgather(arr)
    return np.reshape(np.asarray(gathered), (-1,) + arr.shape)


def host_shard(items, process_index: int | None = None,
               process_count: int | None = None):
    """Deterministic round-robin split of a work list across hosts: host i
    takes items[i::N]. Complementary to gather_rows: every host sweeps its
    shard, then rows are all-gathered (grid order is restored by the
    manifest keys, not list position)."""
    i = jax.process_index() if process_index is None else process_index
    n = jax.process_count() if process_count is None else process_count
    return list(items)[i::n]
