"""Multi-host result gathering and process coordination.

SURVEY.md §5 names the mechanism for collecting sweep results across hosts:
``jax.experimental.multihost_utils.process_allgather`` over ICI/DCN — the
TPU-native replacement for the reference's "download the batch output file"
step (perturb_prompts.py:332-345). On a single-process run (one host, any
number of chips) every helper degrades to the identity, so sweep drivers
call them unconditionally.
"""

from __future__ import annotations

import jax
import numpy as np

from ..utils.logging import get_logger

log = get_logger(__name__)


def initialize(coordinator_address: str | None = None,
               num_processes: int | None = None,
               process_id: int | None = None,
               required: bool = False) -> bool:
    """Bring up the JAX distributed runtime for a multi-host pod.

    On a real TPU pod slice ``jax.distributed.initialize()`` auto-detects
    the coordinator and process topology from the TPU metadata; the three
    arguments exist for manual bring-up (CPU/GPU clusters, DCN-connected
    multislice). Collectives then ride ICI within a slice and DCN across
    slices — the jobs themselves never change, because every helper in
    this module (and ``host_shard``/``gather_rows`` in the sweep drivers)
    keys off ``jax.process_count()``.

    Returns True when the distributed runtime came up, False when running
    single-process (no cluster detected / already initialized) — callers
    proceed either way. ``required=True`` (what the CLI's explicit
    ``--multihost`` passes) turns a failed bring-up into a hard error
    instead: a host that silently fell back to process_count()==1 would
    take the ENTIRE grid via host_shard while its peers sweep shards —
    duplicate scoring and conflicting manifest writes.
    """
    try:
        jax.distributed.initialize(coordinator_address, num_processes,
                                   process_id)
    except Exception as err:  # noqa: BLE001 — single-host is a normal path
        # A launcher (or an earlier call) may have brought the runtime up
        # already; that is a SUCCESSFUL multi-host state, not a bring-up
        # failure (ADVICE r2 #2). jax raises a RuntimeError whose text
        # varies by version, so probe the outcome instead of the message.
        try:
            if jax.process_count() > 1:
                log.info("jax.distributed already up: process %d of %d",
                         jax.process_index(), jax.process_count())
                return True
        except Exception:  # noqa: BLE001 — no runtime at all
            pass
        if required:
            # Distinguish "runtime is up but reports one process" (a
            # launcher pre-initialized a single-process topology — the
            # bring-up itself SUCCEEDED; the topology is what's wrong) from
            # a genuine bring-up failure, so --multihost users see the real
            # state instead of a misattributed error (ADVICE r3 #3).
            already_up = False
            try:
                already_up = jax.distributed.is_initialized()
            except Exception:  # noqa: BLE001 — probe only
                pass
            if already_up:
                raise RuntimeError(
                    "--multihost requested but the distributed runtime was "
                    "already initialized with a SINGLE-process topology "
                    "(process_count()==1) — this host would take the entire "
                    "grid while any peers sweep shards. Fix the launcher's "
                    "coordinator/num_processes settings rather than the "
                    f"bring-up call. (initialize() said: {err})") from err
            raise RuntimeError(
                f"--multihost requested but distributed bring-up failed: "
                f"{err}") from err
        log.info("single-process mode (distributed init unavailable: %s)",
                 err)
        return False
    if required and jax.process_count() == 1:
        # Bring-up "succeeded" but found no peers (e.g. a lone TPU VM whose
        # coordinator config is missing): under --multihost this host would
        # take the ENTIRE grid via host_shard while any correctly-configured
        # peers sweep shards — the same duplicate-scoring hazard as the
        # pre-initialized single-process case above, so it must be as loud.
        raise RuntimeError(
            "--multihost requested but jax.distributed came up with a "
            "SINGLE-process topology (process_count()==1) — no peers were "
            "found. Check the coordinator address / pod slice "
            "configuration.")
    log.info("jax.distributed up: process %d of %d, %d local devices",
             jax.process_index(), jax.process_count(),
             jax.local_device_count())
    return True


def is_multiprocess() -> bool:
    return jax.process_count() > 1


def gather_rows(local_rows: np.ndarray) -> np.ndarray:
    """All-gather per-host result rows to every host.

    `local_rows`: (n_local, ...) numeric array of this host's scored rows
    (row order within a host is preserved; hosts are concatenated in
    process-index order). Single-process: returns the input unchanged.
    """
    if not is_multiprocess():
        return np.asarray(local_rows)
    from jax.experimental import multihost_utils

    gathered = multihost_utils.process_allgather(np.asarray(local_rows))
    return np.reshape(gathered, (-1,) + np.asarray(local_rows).shape[1:])


def barrier(name: str) -> None:
    """Synchronize hosts at a named point (e.g. before a manifest flush so
    one host's resume view can't run ahead of another's writes)."""
    if not is_multiprocess():
        return
    from jax.experimental import multihost_utils

    multihost_utils.sync_global_devices(name)


def host_shard(items, process_index: int | None = None,
               process_count: int | None = None):
    """Deterministic round-robin split of a work list across hosts: host i
    takes items[i::N]. Complementary to gather_rows: every host sweeps its
    shard, then rows are all-gathered (grid order is restored by the
    manifest keys, not list position)."""
    i = jax.process_index() if process_index is None else process_index
    n = jax.process_count() if process_count is None else process_count
    return list(items)[i::n]
