"""Multi-host result gathering and process coordination.

SURVEY.md §5 names the mechanism for collecting sweep results across hosts:
``jax.experimental.multihost_utils.process_allgather`` over ICI/DCN — the
TPU-native replacement for the reference's "download the batch output file"
step (perturb_prompts.py:332-345). On a single-process run (one host, any
number of chips) every helper degrades to the identity, so sweep drivers
call them unconditionally.
"""

from __future__ import annotations

import jax
import numpy as np

from ..utils.logging import get_logger

log = get_logger(__name__)


def is_multiprocess() -> bool:
    return jax.process_count() > 1


def gather_rows(local_rows: np.ndarray) -> np.ndarray:
    """All-gather per-host result rows to every host.

    `local_rows`: (n_local, ...) numeric array of this host's scored rows
    (row order within a host is preserved; hosts are concatenated in
    process-index order). Single-process: returns the input unchanged.
    """
    if not is_multiprocess():
        return np.asarray(local_rows)
    from jax.experimental import multihost_utils

    gathered = multihost_utils.process_allgather(np.asarray(local_rows))
    return np.reshape(gathered, (-1,) + np.asarray(local_rows).shape[1:])


def barrier(name: str) -> None:
    """Synchronize hosts at a named point (e.g. before a manifest flush so
    one host's resume view can't run ahead of another's writes)."""
    if not is_multiprocess():
        return
    from jax.experimental import multihost_utils

    multihost_utils.sync_global_devices(name)


def host_shard(items, process_index: int | None = None,
               process_count: int | None = None):
    """Deterministic round-robin split of a work list across hosts: host i
    takes items[i::N]. Complementary to gather_rows: every host sweeps its
    shard, then rows are all-gathered (grid order is restored by the
    manifest keys, not list position)."""
    i = jax.process_index() if process_index is None else process_index
    n = jax.process_count() if process_count is None else process_count
    return list(items)[i::n]
