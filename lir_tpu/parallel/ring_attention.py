"""Sequence/context parallelism: ring attention and Ulysses all-to-all.

The reference never exceeds ~700-token prompts (SURVEY.md §5 "long-context:
absent"), but this framework treats long-context as first-class: when a
sequence no longer fits one chip's HBM, shard it over the mesh's ``seq``
axis and compute exact attention with either

  - ``ring_attention``: K/V blocks rotate around the ring via
    ``lax.ppermute`` while each device holds its Q shard, accumulating with
    an online (flash-style) softmax — communication overlaps compute and
    peak memory is O(S/N) per device. (Liu et al., Ring Attention with
    Blockwise Transformers, 2023.)
  - ``ulysses_attention``: two ``lax.all_to_all`` reshards (seq-sharded ->
    head-sharded and back) around a plain local attention — cheaper when
    n_heads >= n_seq_shards and the full sequence fits once per device.
    (Jacobs et al., DeepSpeed-Ulysses, 2023.)

Both are exact: outputs match single-device softmax attention to float
tolerance (verified against ``reference_attention`` in tests on a virtual
8-device mesh). Layout matches models/decoder.py: (B, S, H, hd), with the S
axis sharded over ``seq``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from ._compat import shard_map


def reference_attention(
    q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray, causal: bool = True,
    q_positions: jnp.ndarray | None = None,
    kv_positions: jnp.ndarray | None = None,
    key_mask: jnp.ndarray | None = None,
    alibi_slopes: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """Plain softmax attention, (B, S, H, hd) layout — the single-device
    ground truth the parallel kernels must match.

    Optional mask semantics mirror ``models/decoder._causal_bias``: causality
    compares mask-aware positions (``kv_positions <= q_positions``), pads are
    excluded via ``key_mask``, and ALiBi adds ``slope * kv_position``.
    """
    B, S, H, hd = q.shape
    scale = 1.0 / np.sqrt(hd)
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    if q_positions is not None or key_mask is not None:
        if q_positions is None:
            q_positions = jnp.broadcast_to(jnp.arange(S), (B, S))
        if kv_positions is None:
            kv_positions = q_positions
        allowed = jnp.ones((B, S, k.shape[1]), bool)
        if causal:
            allowed = kv_positions[:, None, :] <= q_positions[:, :, None]
        if key_mask is not None:
            allowed = allowed & (key_mask[:, None, :] > 0)
        s = jnp.where(allowed[:, None], s, -jnp.inf)
    elif causal:
        mask = jnp.tril(jnp.ones((S, S), bool))
        s = jnp.where(mask[None, None], s, -jnp.inf)
    if alibi_slopes is not None:
        kp = (kv_positions if kv_positions is not None
              else jnp.broadcast_to(jnp.arange(k.shape[1]), (B, k.shape[1])))
        s = s + (alibi_slopes[None, :, None, None]
                 * kp.astype(jnp.float32)[:, None, None, :])
    # Fully-masked rows (query pads): softmax over all -inf is NaN; zero them.
    finite = jnp.isfinite(s).any(axis=-1, keepdims=True)
    p = jax.nn.softmax(jnp.where(finite, s, 0.0), axis=-1)
    p = jnp.where(finite, p, 0.0).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)


def _repeat_kv(q, k, v):
    """Repeat K/V heads up to the query head count (GQA/MQA callers)."""
    H, K = q.shape[2], k.shape[2]
    if K != H:
        k = jnp.repeat(k, H // K, axis=2)
        v = jnp.repeat(v, H // K, axis=2)
    return k, v


def _ring_kernel(q, k, v, q_index, axis_name: str, axis_size: int,
                 causal: bool, q_pos=None, k_pos=None, k_valid=None,
                 slopes=None):
    """Per-device ring body. q/k/v: (B, Sl, H, hd) local shards; q_index is
    this device's position on the ring (its global block offset / Sl).

    Optional mask-aware mode (all shapes (B, Sl), local shards): ``q_pos`` /
    ``k_pos`` are positions with decoder._causal_bias semantics (causality =
    ``k_pos <= q_pos``), ``k_valid`` masks out pad keys, ``slopes`` (H,) adds
    ALiBi ``slope * k_pos``. The k-side arrays rotate around the ring with
    their K/V blocks.
    """
    B, Sl, H, hd = q.shape
    scale = 1.0 / np.sqrt(hd)
    qf = q.astype(jnp.float32) * scale

    o0 = jnp.zeros((B, Sl, H, hd), jnp.float32)
    m0 = jnp.full((B, H, Sl), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((B, H, Sl), jnp.float32)

    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]
    masked = q_pos is not None
    if not masked:
        q_pos = jnp.broadcast_to(q_index * Sl + jnp.arange(Sl), (B, Sl))
        k_pos = jnp.broadcast_to(
            (q_index * Sl + jnp.arange(Sl))[None], (B, Sl))
    if k_valid is None:
        k_valid = jnp.ones((B, Sl), jnp.int32)

    def step(j, carry):
        o, m, l, k_blk, v_blk, kp_blk, kv_blk = carry
        src = (q_index - j) % axis_size          # block's origin device
        if not masked:
            # Dense mode: block positions are derivable from the ring index;
            # recompute instead of rotating (saves two ppermutes' latency).
            kp = jnp.broadcast_to(src * Sl + jnp.arange(Sl)[None], (B, Sl))
        else:
            kp = kp_blk

        s = jnp.einsum("bqhd,bkhd->bhqk", qf, k_blk.astype(jnp.float32))
        allowed = kv_blk[:, None, :] > 0
        if causal:
            allowed = allowed & (kp[:, None, :] <= q_pos[:, :, None])
        s = jnp.where(allowed[:, None], s, -jnp.inf)
        if slopes is not None:
            s = s + (slopes[None, :, None, None]
                     * kp.astype(jnp.float32)[:, None, None, :])

        m_new = jnp.maximum(m, s.max(axis=-1))
        # exp(-inf - -inf) guard: a fully-masked row keeps m = -inf.
        alpha = jnp.where(jnp.isfinite(m), jnp.exp(m - m_new), 0.0)
        p = jnp.exp(s - m_new[..., None])
        p = jnp.where(jnp.isfinite(s), p, 0.0)

        l = l * alpha + p.sum(axis=-1)
        pv = jnp.einsum("bhqk,bkhd->bqhd", p, v_blk.astype(jnp.float32))
        o = o * alpha.transpose(0, 2, 1)[..., None] + pv

        k_blk = lax.ppermute(k_blk, axis_name, perm)
        v_blk = lax.ppermute(v_blk, axis_name, perm)
        if masked:
            kp_blk = lax.ppermute(kp_blk, axis_name, perm)
            kv_blk = lax.ppermute(kv_blk, axis_name, perm)
        return (o, m_new, l, k_blk, v_blk, kp_blk, kv_blk)

    o, m, l, *_ = lax.fori_loop(
        0, axis_size, step, (o0, m0, l0, k, v, k_pos, k_valid))
    denom = jnp.maximum(l, 1e-30).transpose(0, 2, 1)[..., None]
    return (o / denom).astype(q.dtype)


def ring_attention(
    q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
    mesh: Mesh, causal: bool = True, axis_name: str = "seq",
    q_positions: jnp.ndarray | None = None,
    kv_positions: jnp.ndarray | None = None,
    key_mask: jnp.ndarray | None = None,
    alibi_slopes: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """Exact attention with the sequence axis sharded over `axis_name`.

    q/k/v: (B, S, H, hd) GLOBAL shapes (S divisible by the axis size).
    GQA/MQA K/V (fewer heads than q) are repeated internally. Returns
    (B, S, H, hd) with the same sharding as q.

    Mask-aware mode (for the seq-sharded MODEL forward, parallel/seq_forward):
    ``q_positions``/``kv_positions``/``key_mask`` are (B, S) global arrays
    sharded like the sequence axis, with decoder._causal_bias semantics;
    ``alibi_slopes`` (H,) enables bloom's position bias in-ring.
    """
    k, v = _repeat_kv(q, k, v)
    axis_size = mesh.shape[axis_name]
    spec = P(None, axis_name, None, None)
    pspec = P(None, axis_name)

    if q_positions is not None:
        kv_positions = q_positions if kv_positions is None else kv_positions

        def kernel(q, k, v, qp, kp, kvalid):
            idx = lax.axis_index(axis_name)
            return _ring_kernel(q, k, v, idx, axis_name, axis_size, causal,
                                q_pos=qp, k_pos=kp, k_valid=kvalid,
                                slopes=alibi_slopes)

        if key_mask is None:
            key_mask = jnp.ones(q.shape[:2], jnp.int32)
        return shard_map(
            kernel, mesh=mesh,
            in_specs=(spec, spec, spec, pspec, pspec, pspec), out_specs=spec,
            check_vma=False,
        )(q, k, v, q_positions, kv_positions, key_mask)

    def kernel(q, k, v):
        idx = lax.axis_index(axis_name)
        return _ring_kernel(q, k, v, idx, axis_name, axis_size, causal,
                            slopes=alibi_slopes)

    return shard_map(
        kernel, mesh=mesh,
        in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False,
    )(q, k, v)


def ulysses_attention(
    q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
    mesh: Mesh, causal: bool = True, axis_name: str = "seq",
    q_positions: jnp.ndarray | None = None,
    kv_positions: jnp.ndarray | None = None,
    key_mask: jnp.ndarray | None = None,
    alibi_slopes: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """All-to-all sequence parallelism: reshard (S/N, H) -> (S, H/N), run
    plain local attention over the full sequence, reshard back.

    Requires H % axis_size == 0. Same global layout and mask contract as
    ring_attention; per-head ALiBi slopes are sliced to each device's head
    shard after the all-to-all.
    """
    k, v = _repeat_kv(q, k, v)
    axis_size = mesh.shape[axis_name]
    H = q.shape[2]
    if H % axis_size != 0:
        raise ValueError(
            f"ulysses needs n_heads ({H}) divisible by seq shards ({axis_size})"
        )
    spec = P(None, axis_name, None, None)
    pspec = P(None, axis_name)
    masked = q_positions is not None
    if masked:
        kv_positions = q_positions if kv_positions is None else kv_positions
        if key_mask is None:
            key_mask = jnp.ones(q.shape[:2], jnp.int32)

    def kernel(q, k, v, *pos):
        # (B, Sl, H, hd) -> (B, S, H/N, hd): split heads, gather sequence.
        def to_heads(x):
            return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                                  tiled=True)

        def to_seq(x):
            return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                                  tiled=True)

        slopes = alibi_slopes
        if slopes is not None:
            # Heads are sharded after the all-to-all: take this device's rows.
            idx = lax.axis_index(axis_name)
            h_local = H // axis_size
            slopes = lax.dynamic_slice_in_dim(slopes, idx * h_local, h_local)
        qh, kh, vh = to_heads(q), to_heads(k), to_heads(v)
        if masked:
            qp, kp, kvalid = (
                lax.all_gather(x, axis_name, axis=1, tiled=True) for x in pos)
            out = reference_attention(
                qh, kh, vh, causal=causal, q_positions=qp, kv_positions=kp,
                key_mask=kvalid, alibi_slopes=slopes)
        else:
            out = reference_attention(qh, kh, vh, causal=causal,
                                      alibi_slopes=slopes)
        return to_seq(out)

    in_specs = (spec, spec, spec) + ((pspec, pspec, pspec) if masked else ())
    args = (q, k, v) + ((q_positions, kv_positions, key_mask) if masked else ())
    return shard_map(
        kernel, mesh=mesh,
        in_specs=in_specs, out_specs=spec,
        check_vma=False,
    )(*args)


def seq_sharded(mesh: Mesh, axis_name: str = "seq") -> NamedSharding:
    """NamedSharding for (B, S, H, hd) activations with S over `axis_name`."""
    return NamedSharding(mesh, P(None, axis_name, None, None))
