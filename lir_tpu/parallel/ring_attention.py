"""Sequence/context parallelism: ring attention and Ulysses all-to-all.

The reference never exceeds ~700-token prompts (SURVEY.md §5 "long-context:
absent"), but this framework treats long-context as first-class: when a
sequence no longer fits one chip's HBM, shard it over the mesh's ``seq``
axis and compute exact attention with either

  - ``ring_attention``: K/V blocks rotate around the ring via
    ``lax.ppermute`` while each device holds its Q shard, accumulating with
    an online (flash-style) softmax — communication overlaps compute and
    peak memory is O(S/N) per device. (Liu et al., Ring Attention with
    Blockwise Transformers, 2023.)
  - ``ulysses_attention``: two ``lax.all_to_all`` reshards (seq-sharded ->
    head-sharded and back) around a plain local attention — cheaper when
    n_heads >= n_seq_shards and the full sequence fits once per device.
    (Jacobs et al., DeepSpeed-Ulysses, 2023.)

Both are exact: outputs match single-device softmax attention to float
tolerance (verified against ``reference_attention`` in tests on a virtual
8-device mesh). Layout matches models/decoder.py: (B, S, H, hd), with the S
axis sharded over ``seq``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from jax import shard_map


def reference_attention(
    q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray, causal: bool = True
) -> jnp.ndarray:
    """Plain softmax attention, (B, S, H, hd) layout — the single-device
    ground truth the parallel kernels must match."""
    B, S, H, hd = q.shape
    scale = 1.0 / np.sqrt(hd)
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        mask = jnp.tril(jnp.ones((S, S), bool))
        s = jnp.where(mask[None, None], s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)


def _repeat_kv(q, k, v):
    """Repeat K/V heads up to the query head count (GQA/MQA callers)."""
    H, K = q.shape[2], k.shape[2]
    if K != H:
        k = jnp.repeat(k, H // K, axis=2)
        v = jnp.repeat(v, H // K, axis=2)
    return k, v


def _ring_kernel(q, k, v, q_index, axis_name: str, axis_size: int,
                 causal: bool):
    """Per-device ring body. q/k/v: (B, Sl, H, hd) local shards; q_index is
    this device's position on the ring (its global block offset / Sl)."""
    B, Sl, H, hd = q.shape
    scale = 1.0 / np.sqrt(hd)
    qf = q.astype(jnp.float32) * scale

    o0 = jnp.zeros((B, Sl, H, hd), jnp.float32)
    m0 = jnp.full((B, H, Sl), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((B, H, Sl), jnp.float32)

    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]
    q_pos = q_index * Sl + jnp.arange(Sl)

    def step(j, carry):
        o, m, l, k_blk, v_blk = carry
        src = (q_index - j) % axis_size          # block's origin device
        k_pos = src * Sl + jnp.arange(Sl)

        s = jnp.einsum("bqhd,bkhd->bhqk", qf, k_blk.astype(jnp.float32))
        if causal:
            allowed = q_pos[:, None] >= k_pos[None, :]
            s = jnp.where(allowed[None, None], s, -jnp.inf)

        m_new = jnp.maximum(m, s.max(axis=-1))
        # exp(-inf - -inf) guard: a fully-masked row keeps m = -inf.
        alpha = jnp.where(jnp.isfinite(m), jnp.exp(m - m_new), 0.0)
        p = jnp.exp(s - m_new[..., None])
        p = jnp.where(jnp.isfinite(s), p, 0.0)

        l = l * alpha + p.sum(axis=-1)
        pv = jnp.einsum("bhqk,bkhd->bqhd", p, v_blk.astype(jnp.float32))
        o = o * alpha.transpose(0, 2, 1)[..., None] + pv

        k_blk = lax.ppermute(k_blk, axis_name, perm)
        v_blk = lax.ppermute(v_blk, axis_name, perm)
        return (o, m_new, l, k_blk, v_blk)

    o, m, l, _, _ = lax.fori_loop(0, axis_size, step, (o0, m0, l0, k, v))
    denom = jnp.maximum(l, 1e-30).transpose(0, 2, 1)[..., None]
    return (o / denom).astype(q.dtype)


def ring_attention(
    q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
    mesh: Mesh, causal: bool = True, axis_name: str = "seq",
) -> jnp.ndarray:
    """Exact attention with the sequence axis sharded over `axis_name`.

    q/k/v: (B, S, H, hd) GLOBAL shapes (S divisible by the axis size).
    GQA/MQA K/V (fewer heads than q) are repeated internally. Returns
    (B, S, H, hd) with the same sharding as q.
    """
    k, v = _repeat_kv(q, k, v)
    axis_size = mesh.shape[axis_name]
    spec = P(None, axis_name, None, None)

    def kernel(q, k, v):
        idx = lax.axis_index(axis_name)
        return _ring_kernel(q, k, v, idx, axis_name, axis_size, causal)

    return shard_map(
        kernel, mesh=mesh,
        in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False,
    )(q, k, v)


def ulysses_attention(
    q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
    mesh: Mesh, causal: bool = True, axis_name: str = "seq",
) -> jnp.ndarray:
    """All-to-all sequence parallelism: reshard (S/N, H) -> (S, H/N), run
    plain local attention over the full sequence, reshard back.

    Requires H % axis_size == 0. Same global layout contract as
    ring_attention.
    """
    k, v = _repeat_kv(q, k, v)
    axis_size = mesh.shape[axis_name]
    H = q.shape[2]
    if H % axis_size != 0:
        raise ValueError(
            f"ulysses needs n_heads ({H}) divisible by seq shards ({axis_size})"
        )
    spec = P(None, axis_name, None, None)

    def kernel(q, k, v):
        # (B, Sl, H, hd) -> (B, S, H/N, hd): split heads, gather sequence.
        def to_heads(x):
            return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                                  tiled=True)

        def to_seq(x):
            return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                                  tiled=True)

        qh, kh, vh = to_heads(q), to_heads(k), to_heads(v)
        out = reference_attention(qh, kh, vh, causal=causal)
        return to_seq(out)

    return shard_map(
        kernel, mesh=mesh,
        in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False,
    )(q, k, v)


def seq_sharded(mesh: Mesh, axis_name: str = "seq") -> NamedSharding:
    """NamedSharding for (B, S, H, hd) activations with S over `axis_name`."""
    return NamedSharding(mesh, P(None, axis_name, None, None))
