"""Device mesh + parameter sharding rules.

The reference's only intra-model parallelism is accelerate's
``device_map="auto"`` layer offloading (compare_base_vs_instruct.py:424-435);
its only "communication backend" is the OpenAI Batch REST API (SURVEY.md §5).
The TPU-native replacement is declarative: build a ``jax.sharding.Mesh`` over
the slice, annotate params/activations with ``NamedSharding``, and let XLA
emit the all-gather/reduce-scatter/psum collectives over ICI.

Axes (scaling-book convention):
- ``data``  — the perturbation/question grid (batch) axis.
- ``model`` — tensor parallelism: attention heads / MLP columns / vocab.
- ``seq``   — sequence (context) parallelism for the long-context path
  (parallel/ring_attention.py).

Megatron-style rules: qkv projections are column-parallel (heads), the
attention output and MLP down projection row-parallel, embeddings sharded on
the hidden axis, the LM head on vocab. Families whose head counts don't
divide the mesh (falcon-7b MQA: 71 q heads, 1 kv head) degrade gracefully to
replicated attention + sharded MLP rather than failing.
"""

from __future__ import annotations

import re
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..config import MeshConfig
from ..models.registry import ModelConfig

Params = Dict[str, Any]

# (regex over '/'-joined param paths, PartitionSpec) — the rule shape of
# the fleet's per-model registry (SNIPPETS.md [2] match_partition_rules
# is the exemplar). First match wins; scalar leaves always replicate.
PartitionRules = Sequence[Tuple[str, P]]


def build_mesh(cfg: MeshConfig, devices=None) -> Mesh:
    """Create a (data, model, seq) mesh. Works on real TPU slices and on
    virtual CPU devices (XLA_FLAGS=--xla_force_host_platform_device_count=N)."""
    if devices is None:
        devices = jax.devices()
    n = cfg.n_devices
    if n > len(devices):
        raise ValueError(f"mesh needs {n} devices, have {len(devices)}")
    arr = np.asarray(devices[:n]).reshape(cfg.shape)
    return Mesh(arr, cfg.axis_names)


def single_device_mesh() -> Mesh:
    return Mesh(np.asarray(jax.devices()[:1]).reshape(1, 1, 1),
                ("data", "model", "seq"))


def decoder_param_specs(cfg: ModelConfig, mesh: Mesh) -> Params:
    """PartitionSpec tree matching models/decoder.py's param layout.

    Head-sharded attention requires n_heads % model_size == 0 AND
    n_kv_heads % model_size == 0 (MQA/odd-head families replicate attention
    instead); MLP sharding requires intermediate_size % model_size == 0.
    """
    m = mesh.shape["model"]
    shard_attn = (cfg.n_heads % m == 0) and (cfg.n_kv_heads % m == 0)
    shard_mlp = cfg.intermediate_size % m == 0
    shard_vocab = cfg.vocab_size % m == 0
    shard_hidden = cfg.hidden_size % m == 0

    A = "model" if shard_attn else None    # qkv output / wo input axis
    F = "model" if shard_mlp else None     # MLP hidden axis
    V = "model" if shard_vocab else None   # vocab axis

    layers: Params = {
        "ln1": {"scale": P(None, None)},
        "wq": P(None, None, A), "wk": P(None, None, A), "wv": P(None, None, A),
        "wo": P(None, A, None),
        "w_up": P(None, None, F), "w_down": P(None, F, None),
    }
    if cfg.norm == "layernorm":
        layers["ln1"]["bias"] = P(None, None)
    if not cfg.shared_block_ln:
        layers["ln2"] = dict(layers["ln1"])
    if cfg.gated_mlp:
        layers["w_gate"] = P(None, None, F)
    if cfg.qkv_bias:
        layers.update({"bq": P(None, A), "bk": P(None, A), "bv": P(None, A)})
    if cfg.attn_out_bias:
        layers["bo"] = P(None, None)
    if cfg.mlp_bias:
        layers.update({"b_up": P(None, F), "b_down": P(None, None)})

    specs: Params = {
        # Embedding sharded on hidden: the take() stays local, layer 0's
        # first matmul all-gathers activations (cheap at these batch sizes).
        "tok_embed": P(None, "model" if shard_hidden else None),
        "layers": layers,
    }
    if cfg.pos_embedding == "learned":
        specs["pos_embed"] = P(None, "model" if shard_hidden else None)
    if cfg.embedding_norm:
        specs["embed_ln"] = {"scale": P(None), "bias": P(None)}
    if cfg.final_norm:
        specs["final_ln"] = {"scale": P(None)}
        if cfg.norm == "layernorm":
            specs["final_ln"]["bias"] = P(None)
    if not cfg.tie_embeddings:
        specs["lm_head"] = P(None, V)
    return specs


def encdec_param_specs(cfg, mesh: Mesh) -> Params:
    """PartitionSpec tree matching models/encdec.py's T5 param layout —
    Megatron-style: attention head projections column-parallel (output
    axis on 'model'), their output projections row-parallel, MLP columns
    on 'model'; relative-attention bucket embeddings shard on the HEAD
    axis so the per-head bias lives with its heads. Same divisibility
    degradations as decoder_param_specs (non-dividing axes replicate).

    Closes the round-2 gap where `--mesh` was silently ignored for
    encoder-decoder checkpoints (models/factory.py; the reference runs
    T0-3B/tk-instruct-3b 8-bit on one GPU,
    compare_instruct_models.py:145-166,471-475 — at bf16 they need the
    slice)."""
    m = mesh.shape["model"]
    shard_attn = cfg.n_heads % m == 0
    A = "model" if shard_attn else None
    F = "model" if cfg.intermediate_size % m == 0 else None

    def stack(cross: bool) -> Params:
        p: Params = {
            "ln_attn": P(None, None),
            "wq": P(None, None, A), "wk": P(None, None, A),
            "wv": P(None, None, A), "wo": P(None, A, None),
            "ln_mlp": P(None, None),
            "wo_mlp": P(None, F, None),
        }
        if cfg.gated_mlp:
            p.update({"wi_0": P(None, None, F), "wi_1": P(None, None, F)})
        else:
            p["wi"] = P(None, None, F)
        if cross:
            p.update({
                "ln_cross": P(None, None),
                "cq": P(None, None, A), "ck": P(None, None, A),
                "cv": P(None, None, A), "co": P(None, A, None),
            })
        return p

    specs: Params = {
        "shared_embed": P(None, "model" if cfg.hidden_size % m == 0 else None),
        "enc_rel_embed": P(None, A),
        "dec_rel_embed": P(None, A),
        "encoder": stack(cross=False),
        "enc_final_ln": P(None),
        "decoder": stack(cross=True),
        "dec_final_ln": P(None),
    }
    if not cfg.tie_embeddings:
        specs["lm_head"] = P(
            None, "model" if cfg.vocab_size % m == 0 else None)
    return specs


# ---------------------------------------------------------------------------
# Per-model partition-rule registry (the fleet layer's seam)
# ---------------------------------------------------------------------------

# Model-name pattern -> rules factory. A factory takes (cfg, mesh) and
# returns EITHER a full PartitionSpec pytree matching the param tree, OR
# a PartitionRules sequence to be matched against '/'-joined param paths
# (match_partition_rules). Registered rules win over the structural
# defaults (decoder_param_specs / encdec_param_specs), so one
# odd-architecture model in a fleet can shard its own way without
# forking shard_params — and the weight streamer (models/weights.py)
# places every chunk under the SAME registry, so streamed and monolithic
# loads can never disagree on placement.
_PARTITION_RULE_REGISTRY: List[
    Tuple[str, Callable[[Any, Mesh], Any]]] = []


def register_partition_rules(
        name_pattern: str,
        rules_fn: Callable[[Any, Mesh], Any]) -> None:
    """Register per-model partition rules: ``name_pattern`` is a regex
    matched (re.search) against ``cfg.name``. Later registrations win
    over earlier ones (override in tests / deployment preludes)."""
    _PARTITION_RULE_REGISTRY.insert(0, (str(name_pattern), rules_fn))


def unregister_partition_rules(name_pattern: str) -> None:
    _PARTITION_RULE_REGISTRY[:] = [
        (p, f) for p, f in _PARTITION_RULE_REGISTRY if p != name_pattern]


def registered_rules_for(cfg) -> Optional[Callable[[Any, Mesh], Any]]:
    name = str(getattr(cfg, "name", ""))
    for pattern, fn in _PARTITION_RULE_REGISTRY:
        if re.search(pattern, name):
            return fn
    return None


def _tree_with_paths(params: Params) -> List[Tuple[str, Any]]:
    """('/'-joined path, leaf) pairs; QuantTensor is a leaf (its q/scale
    split is derived, not matched)."""
    from ..models.quant import QuantTensor

    flat = jax.tree_util.tree_flatten_with_path(
        params, is_leaf=lambda x: isinstance(x, QuantTensor))[0]
    out = []
    for path, leaf in flat:
        parts = []
        for k in path:
            parts.append(str(getattr(k, "key", getattr(k, "idx", k))))
        out.append(("/".join(parts), leaf))
    return out


def match_partition_rules(rules: PartitionRules, params: Params) -> Params:
    """PartitionSpec pytree for ``params`` from (regex, spec) rules —
    the SNIPPETS.md [2] exemplar adapted to this engine's dict pytrees:
    first re.search match on the '/'-joined path wins, scalar leaves
    always replicate, and an unmatched non-scalar leaf is a loud error
    (a silently replicated 7B matrix is an OOM at 3am, not a default).
    """
    from ..models.quant import QuantTensor

    def spec_for(name: str, leaf) -> P:
        shape = tuple(getattr(leaf, "shape", ()))
        if len(shape) == 0 or int(np.prod(shape)) == 1:
            return P()
        for rule, spec in rules:
            if re.search(rule, name) is not None:
                return spec
        raise ValueError(f"partition rule not found for param: {name}")

    leaves = [spec_for(name, leaf) for name, leaf in _tree_with_paths(params)]
    treedef = jax.tree_util.tree_structure(
        params, is_leaf=lambda x: isinstance(x, QuantTensor))
    return jax.tree_util.tree_unflatten(treedef, leaves)


def spec_tree_for(cfg, mesh: Mesh, params: Optional[Params] = None
                  ) -> Params:
    """The PartitionSpec pytree for one model on one mesh — registry
    first (per-model rules), structural defaults otherwise. This is the
    ONE resolution path: shard_params (monolithic load) and
    models/weights.stream_params (chunked fleet load) both call it, so
    a model's placement cannot depend on how its weights arrived."""
    from ..models.registry import T5Config

    fn = registered_rules_for(cfg)
    if fn is not None:
        rules = fn(cfg, mesh)
        if isinstance(rules, (list, tuple)):
            if params is None:
                raise ValueError(
                    "rule-list partition rules need the param tree to "
                    "match against (pass params=)")
            return match_partition_rules(rules, params)
        return rules
    return (encdec_param_specs(cfg, mesh) if isinstance(cfg, T5Config)
            else decoder_param_specs(cfg, mesh))


def quant_scale_spec(spec: P) -> P:
    """Spec for a QuantTensor's per-output-channel scale, derived from the
    dense weight's spec: keep the leading (layer-stack) axes, keep the OUTPUT
    axis. Column-parallel weights (output axis sharded on 'model') get
    model-sharded scales; row-parallel weights (input axis sharded) have
    per-output scales that are replicated — exactly the bitsandbytes-on-
    multi-GPU composition the reference ran (compare_base_vs_instruct.py:
    424-435: load_in_8bit + device_map='auto')."""
    return P(*spec[:-2], spec[-1])


def shard_params(params: Params, cfg: ModelConfig, mesh: Mesh) -> Params:
    """device_put every param with its NamedSharding (single host).

    int8 trees compose: a QuantTensor's payload takes the dense weight's
    spec, its scale the derived output-axis spec (quant_scale_spec).
    Resolution goes through spec_tree_for — per-model registry rules
    first, then the structural defaults (T5Config trees get the enc-dec
    specs)."""
    from ..models.quant import QuantTensor

    specs = spec_tree_for(cfg, mesh, params)

    def place(leaf, spec):
        if isinstance(leaf, QuantTensor):
            return QuantTensor(
                q=jax.device_put(leaf.q, NamedSharding(mesh, spec)),
                scale=jax.device_put(
                    leaf.scale, NamedSharding(mesh, quant_scale_spec(spec))),
                dynamic=leaf.dynamic,
            )
        return jax.device_put(leaf, NamedSharding(mesh, spec))

    return jax.tree.map(place, params, specs,
                        is_leaf=lambda x: isinstance(x, QuantTensor))


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Inputs: grid/batch axis over 'data', sequence axis replicated."""
    return NamedSharding(mesh, P("data", None))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
