"""Sequence-parallel MODEL forward: the full decoder with its attention
routed through ring / Ulysses kernels over the mesh's ``seq`` axis.

This is the long-context production path (VERDICT r1 weak #4): everything
outside attention — norms, QKV/MLP matmuls with replicated (or
tensor-sharded) weights, RoPE, the unembed — partitions trivially along the
sequence axis, so we leave it to XLA via sharding constraints and swap ONLY
the attention op for an explicit-collective kernel (``ppermute`` ring or
``all_to_all`` Ulysses). No (S, T) bias tensor is ever materialized: the
kernels derive causality/padding/ALiBi from (B, S) position arrays, so peak
activation memory is O(S/N) per device.

The reference never exceeds ~700-token prompts (SURVEY.md §5 "long-context
absent"); this module is the capability the TPU framework adds on top.
Semantics match ``decoder.forward`` / ``decoder.prefill`` exactly (left-pad
masks, mask-aware positions, bloom's ALiBi) — parity is pinned by
tests/test_sequence_parallel.py on a virtual 8-device mesh.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models import decoder
from ..models.registry import ModelConfig
from .ring_attention import ring_attention, ulysses_attention


def seq_batch_sharding(mesh: Mesh, axis_name: str = "seq") -> NamedSharding:
    """Sharding for (B, S) token/mask arrays with S over the seq axis."""
    return NamedSharding(mesh, P(None, axis_name))


def make_seq_attn_impl(cfg: ModelConfig, mesh: Mesh, impl: str = "ring",
                       axis_name: str = "seq"):
    """Build the ``attn_impl`` hook for ``decoder.forward``/``prefill``.

    Returns ``fn(q, k, v, key_mask) -> (B, S, H*hd)`` computing exact causal
    attention with the sequence axis sharded over ``axis_name``. Causality
    and padding follow decoder._causal_bias semantics via mask-aware
    positions; ALiBi families (bloom) pass their slopes into the kernel.
    """
    if impl not in ("ring", "ulysses"):
        raise ValueError(f"unknown sequence-parallel impl: {impl!r}")
    kernel = ring_attention if impl == "ring" else ulysses_attention
    slopes = (decoder.alibi_slopes(cfg.n_heads)
              if cfg.pos_embedding == "alibi" else None)

    def attn_impl(q, k, v, key_mask):
        B, S, H, hd = q.shape
        if key_mask is None:
            key_mask = jnp.ones((B, S), jnp.int32)
        positions = decoder.mask_positions(key_mask)
        # Pad queries get position 0 (mask_positions), so like the dense
        # path they attend to the first real token — finite garbage rows,
        # bit-matching decoder._causal_bias semantics; readouts ignore them.
        out = kernel(q, k, v, mesh, causal=True, axis_name=axis_name,
                     q_positions=positions, kv_positions=positions,
                     key_mask=key_mask, alibi_slopes=slopes)
        return out.reshape(B, S, H * hd)

    return attn_impl


def forward_seq_parallel(params, cfg: ModelConfig, tokens: jax.Array,
                         attn_mask: Optional[jax.Array] = None,
                         mesh: Optional[Mesh] = None, impl: str = "ring",
                         axis_name: str = "seq") -> jax.Array:
    """``decoder.forward`` with the sequence axis sharded over the mesh.

    tokens/attn_mask: (B, S) global shapes, S divisible by the seq-axis
    size. Returns fp32 logits (B, S, V) sharded like the inputs.
    """
    if mesh is None:
        raise ValueError("forward_seq_parallel needs a mesh with a seq axis")
    sb = seq_batch_sharding(mesh, axis_name)
    tokens = lax.with_sharding_constraint(tokens, sb)
    if attn_mask is None:
        attn_mask = jnp.ones_like(tokens)
    attn_mask = lax.with_sharding_constraint(attn_mask, sb)
    attn_impl = make_seq_attn_impl(cfg, mesh, impl, axis_name)
    return decoder.forward(params, cfg, tokens, attn_mask,
                           attn_impl=attn_impl)


def prefill_seq_parallel(params, cfg: ModelConfig, tokens: jax.Array,
                         attn_mask: jax.Array, max_len: int,
                         mesh: Optional[Mesh] = None, impl: str = "ring",
                         axis_name: str = "seq"):
    """``decoder.prefill`` with the quadratic prompt phase seq-sharded.

    The returned KV cache is constrained off the seq axis (replicated along
    T) so the subsequent decode loop — one query position, O(T) memory —
    runs the ordinary dense path unchanged. This is the long-prompt recipe:
    shard the O(S^2) prefill, gather K/V once, decode cheap.
    """
    if mesh is None:
        raise ValueError("prefill_seq_parallel needs a mesh with a seq axis")
    sb = seq_batch_sharding(mesh, axis_name)
    tokens = lax.with_sharding_constraint(tokens, sb)
    attn_mask = lax.with_sharding_constraint(attn_mask, sb)
    attn_impl = make_seq_attn_impl(cfg, mesh, impl, axis_name)
    logits, cache, next_pos = decoder.prefill(
        params, cfg, tokens, attn_mask, max_len, attn_impl=attn_impl)

    def unshard(x):
        return lax.with_sharding_constraint(
            x, NamedSharding(mesh, P(*([None] * x.ndim))))

    return logits, jax.tree.map(unshard, cache), next_pos
