"""jax version compatibility for the parallel package.

The repo targets current jax (top-level ``jax.shard_map`` with the
``check_vma`` kwarg); older runtimes keep shard_map under
``jax.experimental`` with the kwarg's previous name ``check_rep``. This
shim resolves both so every parallel module imports one symbol.
"""

from __future__ import annotations

import inspect

try:  # jax >= 0.5 exports shard_map at top level
    from jax import shard_map as _shard_map
except ImportError:  # older jax keeps it under experimental
    from jax.experimental.shard_map import shard_map as _shard_map

if "check_vma" in inspect.signature(_shard_map).parameters:
    shard_map = _shard_map
else:
    def shard_map(*args, check_vma=None, **kwargs):
        if check_vma is not None:
            kwargs["check_rep"] = check_vma
        return _shard_map(*args, **kwargs)
